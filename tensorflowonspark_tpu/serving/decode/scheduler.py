"""Iteration-level continuous batcher for autoregressive decode.

No reference counterpart (the reference delegates all inference to TF
Serving, SURVEY.md §2.2); this is the Orca-style iteration-level
scheduler the serving tier mounts behind
:class:`~tensorflowonspark_tpu.serving.replicas.ReplicaPool`:

- requests admit into free KV-cache slots **mid-flight** — there is no
  generation-boundary barrier; a new prompt joins the very next engine
  iteration after a slot frees up;
- each iteration runs (1) prefill for newly admitted prompts
  (sequence- and row-bucketed so compile count stays
  ``O(log slots · log max_seq)``), then (2) ONE fused decode step over
  every occupied slot;
- a finished sequence (EOS or ``max_tokens``) retires its slot
  immediately and the slot is eligible for re-admission in the same
  loop pass.

Three engine upgrades ride the same loop (all default-on via env,
all token-exact against the full-recompute oracle):

- **Block-paged KV with prefix sharing** (``TFOS_DECODE_PAGED``):
  the cache is a :class:`~.kvcache.PagedKVCache`; admission matches
  each prompt against the resident prefix trie and maps the shared
  blocks (refcount bump) instead of re-prefilling them — only the
  unmatched tail runs ``models/transformer.prefill_extend``.
- **Seeded sampling** (per-session temperature/top-k/top-p/seed,
  ``serving/decode/sampling.py``): logits come back to the host and
  the token is a pure function of ``(logits, params, index)``, so a
  failover replay re-draws the identical stream.
- **Speculative decoding** (``spec_window`` + a draft model): the
  draft proposes K-1 tokens, the verify step is ONE windowed
  ``decode_step_paged`` over the K-token window, and a draft token is
  accepted iff it EQUALS the target's seeded sample at that index —
  so speculative output is byte-identical to non-speculative at the
  same seed, not merely distribution-preserving.

Tokens stream back through the resolve-once machinery the predict path
already uses (batcher.PendingResult semantics): the driver-side
:class:`PendingSession` keys its token ledger by index, so a failover
replay after a replica SIGKILL (greedy and seeded-sampled decode are
both deterministic) re-delivers identical ``(index, token)`` pairs —
first arrival wins, ``_set``/``_fail`` resolve once, zero drop and
zero dup by construction.

Module import stays stdlib + numpy (driver-importable); jax and the
model only load inside :class:`DecodeEngine`'s replica-side thread.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

import numpy as np

from tensorflowonspark_tpu.actors.ledger import IndexLedger, ResolveOnce
from tensorflowonspark_tpu.serving import batcher as _batcher
from tensorflowonspark_tpu.serving.decode import sampling as _sampling
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

SLOTS_ENV = "TFOS_DECODE_SLOTS"
QUEUE_MAX_ENV = "TFOS_DECODE_QUEUE_MAX"
MAX_TOKENS_ENV = "TFOS_DECODE_MAX_TOKENS"
PAGED_ENV = "TFOS_DECODE_PAGED"
BLOCK_ENV = "TFOS_DECODE_BLOCK"
PREFIX_SHARING_ENV = "TFOS_DECODE_PREFIX_SHARING"
SPEC_WINDOW_ENV = "TFOS_DECODE_SPEC_WINDOW"


def slots_default():
    return int(os.environ.get(SLOTS_ENV, "8"))


def queue_max_default():
    return int(os.environ.get(QUEUE_MAX_ENV, "64"))


def max_tokens_default():
    return int(os.environ.get(MAX_TOKENS_ENV, "64"))


def paged_default():
    return os.environ.get(PAGED_ENV, "1") != "0"


def block_size_default():
    return int(os.environ.get(BLOCK_ENV, "16"))


def prefix_sharing_default():
    return os.environ.get(PREFIX_SHARING_ENV, "1") != "0"


def spec_window_default():
    return int(os.environ.get(SPEC_WINDOW_ENV, "4"))


class DecodeSpec:
    """The decode tier's picklable config, carried to replicas inside
    the ModelSpec payload (replicas.ModelSpec(..., decode=...)).

    ``cfg`` is a ``models/transformer.Config``; ``slots`` sizes the
    KV cache; ``eos_id``/``max_tokens`` are per-session defaults a
    request may override (``max_tokens`` is always clamped to the
    cache page, ``max_seq - len(prompt)``).

    Paged-cache knobs (defaults from env): ``paged`` selects
    :class:`~.kvcache.PagedKVCache` over the legacy
    :class:`~.kvcache.SlotKVCache`; ``block_size``/``num_blocks`` size
    it; ``prefix_sharing`` arms the prefix trie.  Speculative decoding
    arms when BOTH ``draft_params`` (a transformer params pytree) and
    ``draft_cfg`` are given: the draft proposes ``spec_window - 1``
    tokens per iteration and one windowed verify step scores them
    (paged mode only — the verify step is ``decode_step_paged``).
    """

    def __init__(self, cfg, slots=None, eos_id=None, max_tokens=None,
                 paged=None, block_size=None, num_blocks=None,
                 prefix_sharing=None, draft_params=None, draft_cfg=None,
                 spec_window=None):
        self.cfg = cfg
        self.slots = int(slots or slots_default())
        self.eos_id = eos_id
        self.max_tokens = int(max_tokens or max_tokens_default())
        self.paged = paged_default() if paged is None else bool(paged)
        self.block_size = int(block_size or block_size_default())
        self.num_blocks = num_blocks
        self.prefix_sharing = (prefix_sharing_default()
                               if prefix_sharing is None
                               else bool(prefix_sharing))
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec_window = int(spec_window or spec_window_default())
        if self.spec_window < 2:
            raise ValueError(
                f"spec_window must be >= 2, got {self.spec_window}")
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError(
                "speculative decoding needs BOTH draft_params and "
                "draft_cfg (or neither)")
        if draft_params is not None and not self.paged:
            raise ValueError(
                "speculative decoding requires paged=True (the verify "
                "step is decode_step_paged)")

    @property
    def speculative(self):
        return self.draft_params is not None


class PendingSession(ResolveOnce):
    """One decode session's future: a streaming token ledger plus the
    resolve-once result, mirroring ``batcher.PendingResult``.  Both
    pieces come from ``actors.ledger``.

    The :class:`~tensorflowonspark_tpu.actors.ledger.IndexLedger` keys
    on token INDEX: after a replica SIGKILL the session re-prefills on a
    survivor and decode re-streams the same ``(index, token)`` pairs
    (greedy is deterministic; seeded sampling is a pure function of
    ``(logits, params, index)``, and the ``sampling`` dict — seed
    included — rides the dispatch blob, so the replay draws the same
    variates) — the first arrival of an index wins (its timestamp
    included, so TTFT/per-token stats survive failover), and a
    duplicate ``gen_done`` is swallowed by the resolve-once gate.
    """

    __slots__ = ("id", "prompt", "max_tokens", "eos_id", "sampling",
                 "trace", "route_id", "t_submit", "_ledger")

    def __init__(self, sid, prompt, max_tokens, eos_id, sampling=None,
                 trace=None, route_id=None):
        super().__init__()
        self.id = sid
        self.prompt = [int(t) for t in prompt]
        self.max_tokens = int(max_tokens)
        self.eos_id = eos_id
        self.sampling = sampling
        self.route_id = route_id   # session-affinity key: a fabric
        # router pins returning sessions to the replica whose paged KV
        # cache still holds their prefix blocks (serving/fabric)
        self.trace = trace         # W3C traceparent string (or None);
        # rides the dispatch blob so replica-side decode spans join the
        # request's trace tree (docs/telemetry.md "Causal tracing")
        self.t_submit = time.perf_counter()
        self._ledger = IndexLedger()   # index -> token, first arrival wins

    def tokens_so_far(self):
        return [int(t) for t in self._ledger.values()]

    def result(self, timeout=None):
        """Block for the session result dict (``tokens``, ``ttft_ms``,
        ``token_ms`` gaps, ``total_ms`` + engine meta); raises the
        session's error or TimeoutError."""
        timeout = (_batcher.request_timeout_default()
                   if timeout is None else timeout)
        return self.wait(timeout, "decode session not done")

    # -- resolve-once plumbing (pool._collect calls these) ------------------
    def _token(self, index, token):
        self._ledger.record(index, int(token))

    def _set(self, tokens, meta):
        if self.done():
            return
        now = time.perf_counter()
        times = self._ledger.times()
        gaps = []
        order = sorted(times)
        for a, b in zip(order, order[1:]):
            if b == a + 1:  # only adjacent indices time a real gap
                gaps.append((times[b] - times[a]) * 1e3)
        self.resolve({
            "tokens": [int(t) for t in tokens],
            "ttft_ms": (round((times[0] - self.t_submit) * 1e3, 3)
                        if 0 in times else None),
            "token_ms": [round(g, 3) for g in gaps],
            "total_ms": round((now - self.t_submit) * 1e3, 3),
            **(meta or {}),
        })

    def _fail(self, exc):
        self.reject(exc)


class _Slot:
    """Replica-side per-slot generation state."""

    __slots__ = ("sid", "prompt_len", "generated", "max_tokens", "eos_id",
                 "sampling", "trace", "last", "t_admit")

    def __init__(self, sid, prompt_len, max_tokens, eos_id, first_token,
                 sampling=None, trace=None):
        self.sid = sid
        self.prompt_len = prompt_len
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.sampling = sampling
        self.trace = trace
        self.generated = [first_token]
        self.last = first_token
        self.t_admit = time.perf_counter()


class DecodeEngine:
    """The replica-side continuous-batching loop.

    ``emit(kind, sid, *payload)`` is the wire back to the pool
    (replicas._make_replica_task routes it onto the manager out-queue):
    ``("token", sid, index, token)`` per generated token,
    ``("done", sid, tokens, meta)`` at retirement,
    ``("error", sid, message)`` on a per-session failure.

    jax, the transformer model and the KV cache are imported/built on
    the engine thread — constructing a DecodeEngine never touches jax,
    so driver-side imports stay cheap and axon-hook-safe.
    """

    def __init__(self, params, spec, emit, replica=0):
        self._params = params
        self._spec = spec
        self._emit = emit
        self._replica = replica
        self._q = collections.deque()
        self._qlock = threading.Lock()
        self._sids = set()          # sids queued or active (dedupe)
        self._active = {}           # slot index -> _Slot
        self._cache = None          # engine-thread cache, read by stats()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self._started = threading.Event()
        self._init_error = None
        self.iterations = 0
        self.prefills = 0
        self.retired = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self, timeout=120.0):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tfos-decode-engine", daemon=True)
            self._thread.start()
        if not self._started.wait(timeout):
            raise TimeoutError("decode engine did not start")
        if self._init_error is not None:
            raise self._init_error
        return self

    def stop(self, timeout=10.0):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def set_params(self, params):
        """Hot-reload hook: swap params between iterations.  In-flight
        sessions finish against their already-cached K/V (old params)
        plus new-param compute for the remaining tokens — same in-band,
        no-drop semantics as the predict path's reload."""
        self._params = params

    def submit(self, sid, prompt, max_tokens=None, eos_id=None,
               sampling=None, trace=None):
        """Queue one session; admission happens at the next iteration.
        Rejections (prompt too long, duplicate sid) are emitted as
        session errors, not raised — submit is called from the replica's
        message loop which must keep draining.  ``trace`` (a W3C
        traceparent string) links replica-side admit/retire telemetry
        into the originating request's trace."""
        cfg = self._spec.cfg
        prompt = [int(t) for t in prompt]
        if not prompt or len(prompt) > cfg.max_seq - 1:
            self._emit("error", sid,
                       f"prompt length {len(prompt)} not in [1, "
                       f"{cfg.max_seq - 1}] (max_seq {cfg.max_seq})")
            return
        with self._qlock:
            if sid in self._sids:
                return              # failover re-send of a live session
            self._sids.add(sid)
            self._q.append({
                "sid": sid, "prompt": prompt,
                "max_tokens": int(max_tokens or self._spec.max_tokens),
                "eos_id": self._spec.eos_id if eos_id is None else eos_id,
                "sampling": sampling, "trace": trace,
                "t_queued": time.perf_counter(),
            })
        self._wake.set()

    def stats(self):
        with self._qlock:
            queued = len(self._q)
        out = {
            "iterations": self.iterations,
            "prefills": self.prefills,
            "retired": self.retired,
            "active": len(self._active),
            "queued": queued,
            "slots": self._spec.slots,
            "paged": self._spec.paged,
        }
        if self._spec.paged:
            cache = self._cache
            out["prefix_hits"] = self.prefix_hits
            out["prefix_tokens_saved"] = self.prefix_tokens_saved
            out["blocks_in_use"] = (cache.blocks_in_use
                                    if cache is not None else 0)
        if self._spec.speculative:
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
            out["spec_accept_rate"] = round(
                self.spec_accepted / max(1, self.spec_proposed), 4)
        return out

    # -- engine thread ------------------------------------------------------
    def _build_caches(self):
        spec = self._spec
        if spec.paged:
            cache = self._kvcache_mod.PagedKVCache(
                spec.cfg, spec.slots, block_size=spec.block_size,
                num_blocks=spec.num_blocks,
                prefix_sharing=spec.prefix_sharing)
        else:
            cache = self._kvcache_mod.SlotKVCache(spec.cfg, spec.slots)
        dcache = None
        if spec.speculative:
            dcache = self._kvcache_mod.SlotKVCache(
                spec.draft_cfg, spec.slots)
        self._cache = cache
        return cache, dcache

    def _run(self):
        try:
            import jax
            import jax.numpy as jnp  # noqa: F401 - jit closure imports

            from tensorflowonspark_tpu.models import transformer
            from tensorflowonspark_tpu.serving.decode import kvcache

            spec = self._spec
            cfg = spec.cfg

            def _prefill(p, toks, lens):
                return transformer.prefill(p, toks, cfg, lengths=lens)

            self._prefill_jit = jax.jit(_prefill)
            if spec.paged:
                def _extend(p, toks, pk, pv, ptab, plens, lens):
                    return transformer.prefill_extend(
                        p, toks, cfg, pk, pv, ptab, plens, lengths=lens)

                def _pstep(p, toks, pk, pv, tables, lens):
                    return transformer.decode_step_paged(
                        p, toks, cfg, pk, pv, tables, lens)

                self._extend_jit = jax.jit(_extend)
                self._pstep_jit = jax.jit(_pstep)
            else:
                def _step(p, toks, ck, cv, lens):
                    return transformer.decode_step(
                        p, toks, cfg, ck, cv, lens)

                self._step_jit = jax.jit(_step)
            if spec.speculative:
                dcfg = spec.draft_cfg

                def _dprefill(p, toks, lens):
                    return transformer.prefill(p, toks, dcfg, lengths=lens)

                def _dstep(p, toks, ck, cv, lens):
                    return transformer.decode_step(
                        p, toks, dcfg, ck, cv, lens)

                self._dprefill_jit = jax.jit(_dprefill)
                self._dstep_jit = jax.jit(_dstep)
            self._kvcache_mod = kvcache
            cache, dcache = self._build_caches()
        except BaseException as e:  # noqa: BLE001 - surface via start()
            self._init_error = e
            self._started.set()
            return
        self._started.set()
        while not self._stop.is_set():
            try:
                faults.check("decode.step", replica=self._replica)
                self._admit(cache, dcache)
                if not self._active:
                    self._wake.wait(0.02)
                    self._wake.clear()
                    continue
                if self._spec.paged:
                    self._iterate_paged(cache, dcache)
                else:
                    self._iterate(cache)
            except BaseException as e:  # noqa: BLE001 - fail the cohort,
                # rebuild the caches, keep the replica serving
                logger.exception("decode engine iteration failed")
                self._fail_all(repr(e))
                cache, dcache = self._build_caches()

    # -- admission ----------------------------------------------------------
    def _admit(self, cache, dcache=None):
        """Move queued sessions into free slots.

        Paged mode: each prompt is first matched against the prefix
        trie; a hit maps the shared blocks (refcount bump) and only the
        unmatched tail runs ``prefill_extend`` — grouped by (tail
        bucket, prefix-block bucket) so compile count stays
        logarithmic.  Misses (and slot mode) run the plain bucketed
        ``prefill``.  Every admitted prompt's whole-block prefix is then
        offered to the trie, so the FIRST request of a prefix populates
        it for all followers.  The first token comes from the prefill
        logits either way (sampled at index 0).
        """
        batch = []
        with self._qlock:
            while self._q and len(batch) < cache.free_slots:
                batch.append(self._q.popleft())
        if not batch:
            return
        cfg = self._spec.cfg
        paged = self._spec.paged
        plain, matched = [], []
        for req in batch:
            shared, mlen = (cache.match_prefix(req["prompt"])
                            if paged else ([], 0))
            if mlen > 0:
                matched.append((req, shared, mlen))
            else:
                plain.append(req)

        admitted = []  # (req, logits_row [vocab], k_i, v_i, shared, mlen)
        # -- plain bucketed prefill (whole prompt) --------------------------
        groups = {}
        for req in plain:
            t = _batcher.bucket_seq(len(req["prompt"]), cfg.max_seq)
            groups.setdefault(t, []).append(req)
        for t, members in groups.items():
            rows = _batcher.bucket_size(len(members), self._spec.slots)
            toks = np.stack([
                _batcher.pad_seq(np.asarray(m["prompt"], np.int32), t)
                for m in members])
            lens = np.asarray([len(m["prompt"]) for m in members], np.int32)
            toks = _batcher.pad_rows(toks, rows)
            lens = _batcher.pad_rows(lens, rows)
            logits, k, v = self._prefill_jit(self._params, toks, lens)
            logits = np.asarray(logits)
            self.prefills += 1
            for i, req in enumerate(members):
                admitted.append((req, logits[i], k[i], v[i], [], 0))
        # -- prefix-hit tail prefill ----------------------------------------
        groups = {}
        for req, shared, mlen in matched:
            tail = len(req["prompt"]) - mlen
            key = (_batcher.bucket_seq(tail, cfg.max_seq),
                   _batcher.bucket_size(len(shared),
                                        cache.blocks_per_slot))
            groups.setdefault(key, []).append((req, shared, mlen))
        for (t, nbp), members in groups.items():
            rows = _batcher.bucket_size(len(members), self._spec.slots)
            toks = np.stack([
                _batcher.pad_seq(
                    np.asarray(m[0]["prompt"][m[2]:], np.int32), t)
                for m in members])
            lens = np.asarray(
                [len(m[0]["prompt"]) - m[2] for m in members], np.int32)
            ptab = np.zeros((len(members), nbp), np.int32)
            for i, (_req, shared, _mlen) in enumerate(members):
                ptab[i, :len(shared)] = shared
            plens = np.asarray([m[2] for m in members], np.int32)
            toks = _batcher.pad_rows(toks, rows)
            lens = _batcher.pad_rows(lens, rows)
            ptab = _batcher.pad_rows(ptab, rows)
            plens = _batcher.pad_rows(plens, rows)
            logits, k, v = self._extend_jit(
                self._params, toks, cache.k, cache.v, ptab, plens, lens)
            logits = np.asarray(logits)
            self.prefills += 1
            for i, (req, shared, mlen) in enumerate(members):
                admitted.append((req, logits[i], k[i], v[i], shared, mlen))
                self.prefix_hits += 1
                self.prefix_tokens_saved += mlen
                metrics_registry.inc("tfos_decode_prefix_hits")
        # -- draft prefill (speculative mode: full prompt, own cache) -------
        draft_kv = {}  # sid -> (k_i, v_i)
        if dcache is not None:
            groups = {}
            for req in batch:
                t = _batcher.bucket_seq(len(req["prompt"]),
                                        self._spec.draft_cfg.max_seq)
                groups.setdefault(t, []).append(req)
            for t, members in groups.items():
                rows = _batcher.bucket_size(len(members), self._spec.slots)
                toks = np.stack([
                    _batcher.pad_seq(np.asarray(m["prompt"], np.int32), t)
                    for m in members])
                lens = np.asarray(
                    [len(m["prompt"]) for m in members], np.int32)
                toks = _batcher.pad_rows(toks, rows)
                lens = _batcher.pad_rows(lens, rows)
                _lg, dk, dv = self._dprefill_jit(
                    self._spec.draft_params, toks, lens)
                for i, req in enumerate(members):
                    draft_kv[req["sid"]] = (dk[i], dv[i])

        # -- slot installation + first-token emission -----------------------
        for req, logits_row, k_i, v_i, shared, mlen in admitted:
            plen = len(req["prompt"])
            slot = cache.alloc()
            # cannot be None: admission is bounded by free_slots
            if paged:
                bs = cache.block_size
                own = cache.alloc_blocks(-(-(plen - mlen) // bs))
                cache.map_session(slot, shared, own, plen)
                cache.insert_tail(slot, k_i, v_i, mlen, plen - mlen)
                cache.register_prompt(slot, req["prompt"])
            else:
                cache.insert(slot, k_i, v_i, plen)
            if dcache is not None:
                dk, dv = draft_kv[req["sid"]]
                dcache.insert(slot, dk, dv, plen)
            first = _sampling.sample_token(logits_row, req["sampling"], 0)
            mt = min(req["max_tokens"], cache.max_seq - plen)
            st = _Slot(req["sid"], plen, max(1, mt), req["eos_id"], first,
                       req["sampling"], trace=req.get("trace"))
            self._active[slot] = st
            with telemetry.activate(st.trace):
                telemetry.event(
                    telemetry.DECODE_ADMIT, sid=st.sid, slot=slot,
                    prompt_len=plen, prefix_hit_len=mlen,
                    queue_ms=round((time.perf_counter()
                                    - req.get("t_queued", time.perf_counter()))
                                   * 1e3, 3))
            self._emit("token", st.sid, 0, first)
            if (st.eos_id is not None and first == st.eos_id) \
                    or st.max_tokens <= 1:
                self._retire(cache, slot)
        metrics_registry.set_gauge("tfos_decode_slot_occupancy",
                                   cache.occupancy)
        if paged:
            metrics_registry.set_gauge("tfos_decode_blocks_in_use",
                                       cache.blocks_in_use)

    # -- iteration: legacy slot-paged path ----------------------------------
    def _iterate(self, cache):
        """One fused decode step over every occupied slot."""
        tokens = np.zeros((cache.slots,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.last
        logits, cache.k, cache.v = self._step_jit(
            self._params, tokens, cache.k, cache.v, cache.lengths)
        logits = np.asarray(logits)
        self.iterations += 1
        for slot in list(self._active):
            st = self._active[slot]
            cache.lengths[slot] += 1
            tok = _sampling.sample_token(logits[slot], st.sampling,
                                         len(st.generated))
            st.generated.append(tok)
            st.last = tok
            self._emit("token", st.sid, len(st.generated) - 1, tok)
            if (st.eos_id is not None and tok == st.eos_id) \
                    or len(st.generated) >= st.max_tokens \
                    or cache.lengths[slot] >= cache.max_seq:
                self._retire(cache, slot)
        metrics_registry.set_gauge("tfos_decode_slot_occupancy",
                                   cache.occupancy)

    # -- iteration: paged path (plain W=1 or speculative W=K) ---------------
    def _iterate_paged(self, cache, dcache):
        """One fused windowed step over every occupied slot.

        Without a draft model the window is 1 token — the plain paged
        step.  With one, the draft proposes ``K-1`` tokens host-sampled
        at their future indices, the window ``[last, d_1 .. d_{K-1}]``
        runs ONE ``decode_step_paged`` verify, and draft token ``d_j``
        is accepted iff it equals the target's seeded sample at index
        ``base+j-1`` — every emitted token is exactly the target
        sample conditioned on a correct history, so speculative output
        matches non-speculative token-for-token.  The draft ingests the
        full window (K steps) so its cache stays aligned; rejection
        rolls both cursors back by assignment, and the stale K/V past
        the cursor is unreachable (masked) until a later correct write
        lands on it.
        """
        spec = self._spec
        k_win = spec.spec_window if dcache is not None else 1
        window = np.zeros((cache.slots, k_win), np.int32)
        for slot, st in self._active.items():
            window[slot, 0] = st.last
        n0 = cache.lengths.copy()
        if dcache is not None:
            for j in range(k_win):
                dlogits, dcache.k, dcache.v = self._dstep_jit(
                    spec.draft_params, window[:, j], dcache.k, dcache.v,
                    dcache.lengths)
                for slot in self._active:
                    dcache.lengths[slot] += 1
                if j < k_win - 1:
                    dlogits = np.asarray(dlogits)
                    for slot, st in self._active.items():
                        window[slot, j + 1] = _sampling.sample_token(
                            dlogits[slot], st.sampling,
                            len(st.generated) + j)
        for slot in self._active:
            cache.ensure_capacity(slot, int(n0[slot]) + k_win)
        logits, cache.k, cache.v = self._pstep_jit(
            self._params, window, cache.k, cache.v,
            cache.block_tables, n0)
        logits = np.asarray(logits)           # [slots, K, vocab]
        self.iterations += 1
        for slot in list(self._active):
            st = self._active[slot]
            n = int(n0[slot])
            base = len(st.generated)
            # rows past max_seq wrote their token's k/v to the sentinel,
            # so their logits miss history — never emit from them
            valid = min(k_win, cache.max_seq - n)
            emitted = []
            for j in range(valid):
                if j > 0 and int(window[slot, j]) != emitted[j - 1]:
                    break               # draft diverged; later rows stale
                if j > 0:
                    self.spec_accepted += 1
                emitted.append(_sampling.sample_token(
                    logits[slot, j], st.sampling, base + j))
            if dcache is not None:
                self.spec_proposed += k_win - 1
            done = False
            for tok in emitted:
                st.generated.append(tok)
                st.last = tok
                cache.lengths[slot] += 1
                self._emit("token", st.sid, len(st.generated) - 1, tok)
                if (st.eos_id is not None and tok == st.eos_id) \
                        or len(st.generated) >= st.max_tokens:
                    done = True
                    break
            if dcache is not None:
                # roll the draft cursor back onto the accepted prefix
                dcache.lengths[slot] = cache.lengths[slot]
            if done or cache.lengths[slot] >= cache.max_seq:
                self._retire(cache, slot)
        metrics_registry.set_gauge("tfos_decode_slot_occupancy",
                                   cache.occupancy)
        metrics_registry.set_gauge("tfos_decode_blocks_in_use",
                                   cache.blocks_in_use)
        if dcache is not None:
            metrics_registry.set_gauge(
                "tfos_decode_spec_accept",
                round(self.spec_accepted / max(1, self.spec_proposed), 4))

    def _retire(self, cache, slot):
        st = self._active.pop(slot)
        cache.retire(slot)
        with self._qlock:
            self._sids.discard(st.sid)
        self.retired += 1
        metrics_registry.inc("tfos_decode_retired_total")
        gen_ms = round((time.perf_counter() - st.t_admit) * 1e3, 3)
        with telemetry.activate(st.trace):
            telemetry.record_span(
                telemetry.DECODE_RETIRE, gen_ms / 1e3, sid=st.sid,
                tokens=len(st.generated), prompt_len=st.prompt_len,
                replica=self._replica)
        self._emit("done", st.sid, list(st.generated), {
            "replica": self._replica,
            "prompt_len": st.prompt_len,
            "gen_ms": gen_ms,
        })

    def _fail_all(self, message):
        with self._qlock:
            queued = list(self._q)
            self._q.clear()
            self._sids.clear()
        for req in queued:
            self._emit("error", req["sid"], message)
        for st in self._active.values():
            self._emit("error", st.sid, message)
        self._active.clear()
