"""Continuous-batching autoregressive decode tier (docs/serving.md,
"Autoregressive decode").

No reference equivalent — the reference delegates all inference to TF
Serving (SURVEY.md §2.2); this package gives the framework an
in-framework LLM decode path on the existing serving runtime:

  - :mod:`~tensorflowonspark_tpu.serving.decode.kvcache` — preallocated
    slot-paged KV cache, one page per session;
  - :mod:`~tensorflowonspark_tpu.serving.decode.scheduler` —
    iteration-level continuous batcher (mid-flight admission, one fused
    decode step per iteration, immediate slot retirement);
  - :mod:`~tensorflowonspark_tpu.serving.decode.loadgen` — open-loop
    Poisson load generator for TTFT / per-token SLOs.

The model half lives in ``models/transformer.py`` (``prefill``,
``decode_step``, ``greedy_decode_reference``); the frontend half in
``serving/server.py`` (``Server.generate``, ``POST /v1/generate``).
"""

from tensorflowonspark_tpu.serving.decode.loadgen import (  # noqa: F401
    run_open_loop,
)
from tensorflowonspark_tpu.serving.decode.scheduler import (  # noqa: F401
    DecodeEngine,
    DecodeSpec,
    PendingSession,
)
