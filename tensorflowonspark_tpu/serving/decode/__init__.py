"""Continuous-batching autoregressive decode tier (docs/serving.md,
"Autoregressive decode").

No reference equivalent — the reference delegates all inference to TF
Serving (SURVEY.md §2.2); this package gives the framework an
in-framework LLM decode path on the existing serving runtime:

  - :mod:`~tensorflowonspark_tpu.serving.decode.kvcache` — the
    block-paged :class:`~.kvcache.PagedKVCache` (ref-counted prefix
    sharing through a prompt trie) plus the legacy slot-paged
    :class:`~.kvcache.SlotKVCache`, one page per session;
  - :mod:`~tensorflowonspark_tpu.serving.decode.scheduler` —
    iteration-level continuous batcher (mid-flight admission, one fused
    decode step per iteration, immediate slot retirement; prefix-hit
    admission, seeded sampling and draft-model speculative decoding
    ride the same loop);
  - :mod:`~tensorflowonspark_tpu.serving.decode.sampling` — seeded
    temperature/top-k/top-p sampling, pure in ``(logits, params,
    index)`` so failover replay and speculative verify are token-exact;
  - :mod:`~tensorflowonspark_tpu.serving.decode.loadgen` — open-loop
    Poisson load generator for TTFT / per-token SLOs, plus the
    shared-prefix traffic mix for the prefix-reuse bench lane.

The model half lives in ``models/transformer.py`` (``prefill``,
``prefill_extend``, ``decode_step``, ``decode_step_paged``,
``greedy_decode_reference``); the frontend half in
``serving/server.py`` (``Server.generate``, ``POST /v1/generate``).
"""

from tensorflowonspark_tpu.serving.decode.loadgen import (  # noqa: F401
    run_open_loop,
    session_route_ids,
    shared_prefix_prompts,
)
from tensorflowonspark_tpu.serving.decode.sampling import (  # noqa: F401
    sample_token,
)
from tensorflowonspark_tpu.serving.decode.scheduler import (  # noqa: F401
    DecodeEngine,
    DecodeSpec,
    PendingSession,
)
