"""Dynamic micro-batcher: coalesce concurrent requests into padded,
power-of-two shape-bucket batches.

No reference equivalent — the reference stack stops at offline batch
inference (Inference.scala:27-79, pipeline.py:585-644 `_run_model_tf2`);
this extends the batched-predict idea of our `pipeline.yield_batch`
(reference pipeline.py:688-710) to an *online* request path.

Why buckets: a jitted predict compiles once per distinct input shape.
Concurrent requests arrive in arbitrary counts, so a naive batcher would
present every batch size from 1..max and compile each one.  Rounding the
batch up to the next power of two (capped at ``TFOS_SERVE_MAX_BATCH``)
and padding the rows bounds the number of executables at
``log2(max_batch)+1`` per input signature — compile once per bucket,
never per request.

Latency contract: the first queued request waits at most
``TFOS_SERVE_MAX_DELAY_MS`` for co-batchable traffic before the batch is
flushed (deadline flush); a full batch flushes immediately.

Admission control: once the number of queued-but-unbatched requests
exceeds ``TFOS_SERVE_QUEUE_MAX``, ``submit`` sheds load by raising
:class:`Overloaded` (the HTTP frontend maps it to 503 + Retry-After)
instead of growing the queue without bound.

Pure stdlib + numpy: importable by engine executors and the driver alike
(never pulls jax — the replica side owns compilation, see replicas.py).
"""

from __future__ import annotations

import itertools
import logging
import os
import queue as _queue
import threading
import time

import numpy as np

from tensorflowonspark_tpu.actors.ledger import OnceGate, ResolveOnce
from tensorflowonspark_tpu.utils import metrics_registry

logger = logging.getLogger(__name__)

MAX_BATCH_ENV = "TFOS_SERVE_MAX_BATCH"
MAX_DELAY_ENV = "TFOS_SERVE_MAX_DELAY_MS"
QUEUE_MAX_ENV = "TFOS_SERVE_QUEUE_MAX"
TIMEOUT_ENV = "TFOS_SERVE_TIMEOUT"


def max_batch_default():
    return int(os.environ.get(MAX_BATCH_ENV, "64"))


def max_delay_ms_default():
    return float(os.environ.get(MAX_DELAY_ENV, "10"))


def queue_max_default():
    return int(os.environ.get(QUEUE_MAX_ENV, "1024"))


def request_timeout_default():
    return float(os.environ.get(TIMEOUT_ENV, "30"))


def bucket_size(n, cap=None):
    """Smallest power of two >= n, capped at ``cap`` (default
    TFOS_SERVE_MAX_BATCH).  The cap itself is always a legal bucket even
    when it is not a power of two — a full batch pads nothing."""
    cap = max_batch_default() if cap is None else int(cap)
    if n >= cap:
        return cap
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def bucket_seq(n, cap):
    """Smallest power of two >= n, capped at ``cap`` (the cap itself is
    always a legal bucket).  Same rounding as :func:`bucket_size` but on
    a sequence axis: variable-length prompts compile one executable per
    bucket instead of one per distinct length (or all at max_seq)."""
    cap = int(cap)
    if n >= cap:
        return cap
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def pad_rows(arr, target):
    """Pad ``arr`` along axis 0 up to ``target`` rows by edge-replication
    (real rows repeated, so padded compute stays numerically in-domain —
    no NaN-able zeros into normalization layers)."""
    arr = np.asarray(arr)
    n = arr.shape[0] if arr.ndim else 0
    if arr.ndim == 0:
        raise ValueError("pad_rows needs at least one (batch) axis")
    if n == target:
        return arr
    if n > target:
        raise ValueError(f"cannot pad {n} rows down to {target}")
    if n == 0:
        raise ValueError("cannot pad an empty batch (no row to replicate)")
    widths = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, mode="edge")


def pad_seq(arr, target, axis=0):
    """Pad ``arr`` along a SEQUENCE axis up to ``target`` by
    edge-replication (the last real step repeated, so padded positions
    stay in-domain — token ids remain valid vocabulary entries).

    Attention-mask safety is split with the model: edge values keep the
    compute finite/in-domain, and the consumer masks padded positions
    using the true lengths the batcher ships alongside
    (``MicroBatcher(seq_axis=...)`` adds a ``_seq_len`` column;
    ``transformer.prefill(lengths=...)`` reads its final REAL position
    and never attends past it causally)."""
    arr = np.asarray(arr)
    if arr.ndim <= axis:
        raise ValueError(f"array rank {arr.ndim} has no axis {axis}")
    n = arr.shape[axis]
    if n == target:
        return arr
    if n > target:
        raise ValueError(f"cannot pad seq {n} down to {target}")
    if n == 0:
        raise ValueError("cannot pad an empty sequence")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths, mode="edge")


def pad_columns(cols, target):
    """Pad every column of a batch (dict, tuple or list of arrays) up to
    ``target`` rows; returns the same container type.  Shared by the
    online batcher and the offline pipeline partial-batch path
    (pipeline._run_model)."""
    if isinstance(cols, dict):
        return {k: pad_rows(v, target) for k, v in cols.items()}
    return type(cols)(pad_rows(c, target) for c in cols)


class Overloaded(RuntimeError):
    """Admission control rejection: the pending-request queue is full.

    ``retry_after`` (seconds) is advisory backoff for the client; the
    HTTP frontend surfaces it as a ``Retry-After`` header on the 503.
    """

    def __init__(self, depth, limit, retry_after=0.1):
        super().__init__(
            f"serving queue full ({depth} pending > {limit}); retry in "
            f"{retry_after:.2f}s")
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after


class PendingResult(ResolveOnce):
    """One request's future: resolved by the batch that absorbed it.
    Resolve-once semantics come from ``actors.ledger.ResolveOnce`` —
    the first complete()/fail() of any batch attempt wins."""

    __slots__ = ("example", "attrs", "t_submit")

    def __init__(self, example):
        super().__init__()
        self.example = example
        self.attrs = None            # timing attrs, set on resolve
        self.t_submit = time.perf_counter()

    def result(self, timeout=None):
        """Block for the outputs row ({tensor_name: ndarray}); raises the
        batch's error, or TimeoutError after ``timeout`` seconds."""
        timeout = request_timeout_default() if timeout is None else timeout
        return self.wait(timeout, "request not served")

    def _set(self, value, attrs):
        self.attrs = attrs
        self.resolve(value)

    def _fail(self, exc):
        self.reject(exc)


class Batch:
    """A padded device batch plus the requests it will resolve.

    ``complete``/``fail`` are idempotent (first call wins): a batch
    re-dispatched after a replica death may be answered twice, and the
    duplicate must be a no-op rather than a double-resolve.
    """

    def __init__(self, batch_id, requests, inputs, bucket, assembly_ms,
                 observer=None, batch_observer=None):
        self.id = batch_id
        self.requests = requests
        self.inputs = inputs          # {tensor_name: [bucket, ...] ndarray}
        self.n_valid = len(requests)
        self.bucket = bucket
        self.assembly_ms = assembly_ms
        self.t_assembled = time.perf_counter()
        self._observer = observer
        self._batch_observer = batch_observer
        self._gate = OnceGate()

    def _claim(self):
        return self._gate.claim()

    def complete(self, outputs, meta=None):
        """Resolve every request with its row of ``outputs`` (padded rows
        beyond ``n_valid`` are discarded)."""
        if not self._claim():
            return False
        meta = meta or {}
        now = time.perf_counter()
        device_ms = float(meta.get("device_ms") or 0.0)
        for i, req in enumerate(self.requests):
            row = {k: v[i] for k, v in outputs.items()}
            attrs = {
                "queue_ms": max(
                    0.0, (self.t_assembled - req.t_submit) * 1e3
                    - self.assembly_ms),
                "batch_ms": self.assembly_ms,
                "device_ms": device_ms,
                "total_ms": (now - req.t_submit) * 1e3,
                "batch": self.n_valid,
                "bucket": self.bucket,
            }
            # replica-reported provenance: which params version answered
            # (canary rollouts split SLO telemetry by this, docs/deployment.md)
            if "version" in meta:
                attrs["version"] = meta["version"]
            if "replica" in meta:
                attrs["replica"] = meta["replica"]
            if self._observer is not None:
                try:
                    self._observer(attrs)
                except Exception:  # noqa: BLE001 - stats must not drop replies
                    logger.exception("serving request observer failed")
            req._set(row, attrs)
        if self._batch_observer is not None:
            try:
                self._batch_observer(self, meta)
            except Exception:  # noqa: BLE001
                logger.exception("serving batch observer failed")
        return True

    def fail(self, exc):
        if not self._claim():
            return False
        for req in self.requests:
            req._fail(exc)
        return True


def _signature(example, seq_axis=None, seq_cap=None):
    """Shape/dtype signature grouping co-batchable examples.

    With ``seq_axis`` set, that per-example axis's length is replaced by
    its power-of-two bucket (:func:`bucket_seq`), so examples of
    different sequence lengths that round to the same bucket co-batch —
    ``_flush`` pads each member up to the bucket (:func:`pad_seq`).
    """
    sig = []
    for k, v in sorted(example.items()):
        shape = tuple(np.shape(v))
        if seq_axis is not None and len(shape) > seq_axis:
            shape = (shape[:seq_axis]
                     + (bucket_seq(shape[seq_axis], seq_cap),)
                     + shape[seq_axis + 1:])
        sig.append((k, shape, str(np.asarray(v).dtype)))
    return tuple(sig)


_STOP = object()


class MicroBatcher:
    """Coalesce ``submit``-ed examples into bucket-padded batches and hand
    them to ``dispatch`` (a non-blocking callable, e.g.
    ``ReplicaPool.dispatch``) from a single batcher thread."""

    def __init__(self, dispatch, max_batch=None, max_delay_ms=None,
                 queue_max=None, observer=None, batch_observer=None,
                 on_shed=None, seq_axis=None, seq_cap=None):
        self._dispatch = dispatch
        self.max_batch = max_batch or max_batch_default()
        self.max_delay_s = (max_delay_ms_default() if max_delay_ms is None
                            else float(max_delay_ms)) / 1e3
        self.queue_max = queue_max or queue_max_default()
        # sequence bucketing (docs/serving.md): group by power-of-two
        # bucket of per-example axis ``seq_axis`` (axis seq_axis+1 of the
        # batched tensor), pad members up by edge-replication, and ship
        # the true lengths as a ``_seq_len`` int32 column so the model
        # can mask the padding (attention-mask-safe by contract).
        self.seq_axis = seq_axis
        self.seq_cap = seq_cap
        if seq_axis is not None and seq_cap is None:
            raise ValueError("seq_axis requires seq_cap (the max length)")
        self._observer = observer
        self._batch_observer = batch_observer
        self._on_shed = on_shed
        self._capacity = 1.0
        self._q = _queue.Queue()
        self._ids = itertools.count(1)
        self._closed = False
        self._thread = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="tfos-serve-batcher", daemon=True)
            self._thread.start()
        return self

    def set_capacity(self, frac):
        """Declared degraded-mode admission (docs/serving.md "Degrade by
        resize"): scale the effective queue bound by the pool's
        live/logical capacity fraction, so load past a shrunk pool sheds
        proportionally with Retry-After instead of queueing into
        timeouts.  ``frac=0`` (no live replicas) sheds everything —
        explicit 503s, never a silent stall."""
        frac = max(0.0, min(1.0, float(frac)))
        if frac != self._capacity:
            logger.info("batcher capacity -> %.0f%% (queue bound %d -> %d)",
                        frac * 100, self.effective_queue_max(),
                        self._bound_for(frac))
        self._capacity = frac

    @property
    def degraded(self):
        return self._capacity < 1.0

    def _bound_for(self, frac):
        return int(round(self.queue_max * frac)) if frac > 0 else 0

    def effective_queue_max(self):
        """Admission bound at the current capacity fraction (>=1 while
        any capacity remains — a degraded pool still serves)."""
        return max(1, self._bound_for(self._capacity)) \
            if self._capacity > 0 else 0

    def submit(self, example):
        """Queue one example ({tensor_name: array-like}, no batch axis);
        returns a :class:`PendingResult`.  Raises :class:`Overloaded`
        past ``queue_max`` pending requests (load shed)."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        if not isinstance(example, dict) or not example:
            raise TypeError(
                "example must be a non-empty {tensor_name: array} dict")
        depth = self._q.qsize()
        metrics_registry.set_gauge("tfos_serve_queue_depth", depth)
        limit = self.effective_queue_max()
        if depth >= limit:
            # shed BEFORE enqueueing: bounded queue depth is the whole
            # point — admitting then failing would still grow memory
            if self._on_shed is not None:
                try:
                    self._on_shed(depth, limit)
                except Exception:  # noqa: BLE001
                    logger.exception("serving shed observer failed")
            # degraded sheds hint a longer backoff: capacity returns on
            # pool-regrow timescales, not batch-flush timescales
            retry = max(self.max_delay_s, 0.05)
            if self.degraded:
                retry = max(retry, 0.25)
            raise Overloaded(depth, limit, retry_after=retry)
        req = PendingResult(
            {k: np.asarray(v) for k, v in example.items()})
        self._q.put(req)
        return req

    def _loop(self):
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except _queue.Empty:
                if self._closed:
                    return
                continue
            if first is _STOP:
                return
            reqs = [first]
            deadline = time.perf_counter() + self.max_delay_s
            stop = False
            while len(reqs) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                except _queue.Empty:
                    break
                if r is _STOP:
                    stop = True
                    break
                reqs.append(r)
            self._flush(reqs)
            if stop:
                return

    def _flush(self, reqs):
        """Stack one gathered wave into per-signature bucket batches."""
        groups = {}
        for req in reqs:
            groups.setdefault(
                _signature(req.example, self.seq_axis, self.seq_cap),
                []).append(req)
        for sig, members in groups.items():
            t0 = time.perf_counter()
            try:
                if self.seq_axis is None:
                    cols = {
                        k: np.stack([m.example[k] for m in members])
                        for k in members[0].example
                    }
                else:
                    buckets = {k: s for k, s, _ in sig}
                    cols = {}
                    for k in members[0].example:
                        tgt = buckets[k]
                        cols[k] = np.stack([
                            (pad_seq(m.example[k],
                                     tgt[self.seq_axis], axis=self.seq_axis)
                             if len(tgt) > self.seq_axis
                             else m.example[k])
                            for m in members])
                    lk = sorted(members[0].example)[0]
                    cols["_seq_len"] = np.asarray(
                        [np.shape(m.example[lk])[self.seq_axis]
                         if len(np.shape(m.example[lk])) > self.seq_axis
                         else 0 for m in members], np.int32)
                bucket = bucket_size(len(members), self.max_batch)
                cols = pad_columns(cols, bucket)
            except Exception as e:  # noqa: BLE001 - bad example payloads
                for m in members:
                    m._fail(e)
                continue
            batch = Batch(
                next(self._ids), members, cols, bucket,
                (time.perf_counter() - t0) * 1e3,
                observer=self._observer,
                batch_observer=self._batch_observer,
            )
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 - pool refused the batch
                batch.fail(e)

    def close(self, timeout=5.0):
        """Stop the batcher thread; queued-but-unflushed requests are
        failed so no client blocks into its full timeout on shutdown."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout)
        err = RuntimeError("server shut down before the request was batched")
        while True:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                break
            if req is not _STOP:
                req._fail(err)
