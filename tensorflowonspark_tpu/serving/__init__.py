"""Online inference serving (docs/serving.md).

No reference equivalent — the reference stack stops at offline batch
inference (Inference.scala:27-79); this subsystem turns an exported
model into a low-latency online service on the existing cluster runtime
(engine supervision + manager IPC + checkpoint restore + telemetry),
see PARITY.md §2.2.

Pieces:
  - :mod:`~tensorflowonspark_tpu.serving.batcher` — dynamic
    micro-batching into padded power-of-two shape buckets;
  - :mod:`~tensorflowonspark_tpu.serving.replicas` — supervised model
    replicas with least-loaded dispatch and checkpoint hot-reload;
  - :mod:`~tensorflowonspark_tpu.serving.elastic` — degrade-by-resize
    replica pool: logical capacity, live param resharding on loss,
    adopt-on-respawn, graceful drain (docs/serving.md "Degrade by
    resize");
  - :mod:`~tensorflowonspark_tpu.serving.server` — in-process Client,
    stdlib HTTP endpoint, SLO stats, ``tfos-serve`` CLI;
  - :mod:`~tensorflowonspark_tpu.serving.decode` — continuous-batching
    autoregressive decode (slot-paged KV cache, iteration-level
    scheduler, open-loop load generator).
"""

from tensorflowonspark_tpu.serving.batcher import (  # noqa: F401
    MicroBatcher,
    Overloaded,
    bucket_seq,
    bucket_size,
    pad_columns,
    pad_rows,
    pad_seq,
)
from tensorflowonspark_tpu.serving.decode import (  # noqa: F401
    DecodeEngine,
    DecodeSpec,
    PendingSession,
    run_open_loop,
)
from tensorflowonspark_tpu.serving.elastic import (  # noqa: F401
    ElasticReplicaPool,
)
from tensorflowonspark_tpu.serving.replicas import (  # noqa: F401
    ModelSpec,
    ReplicaPool,
)
from tensorflowonspark_tpu.serving.server import (  # noqa: F401
    Client,
    DecodeStats,
    Server,
    SLOStats,
    serve_http,
)
