"""Elastic serving tier: degrade-by-resize replica pool (docs/serving.md
"Degrade by resize").

No reference equivalent — the reference's only answer to a lost serving
node is cold respawn (Inference.scala:27-79 runs a fixed mapPartitions
job; TFSparkNode.py:480-482 has no serving tier at all).  The base
:class:`~tensorflowonspark_tpu.serving.replicas.ReplicaPool` inherits
that shape: SIGKILL -> engine respawn -> checkpoint reload.  This
subclass carries the training side's elastic contract
(``elastic/virtual.py`` + ``elastic/reshard.py``, VirtualFlow arXiv
2009.09523; the stable-replica-abstraction framing is TF-Replicator,
arXiv 1902.00465) over to serving:

- the pool declares a **logical capacity** (``logical_replicas`` slots
  on a logical mesh); each live replica covers its share, recomputed on
  every membership change;
- replica loss triggers a **resize**, not a reload: the pool generation
  bumps (epoch-fenced like rendezvous — stale acks and stale resize
  directives are dropped by generation compare), survivors reshard
  their *live* params under the new layout (``elastic/reshard.py``
  host-roundtrip ``device_put``), in-flight work re-dispatches through
  the resolve-once ledger, and orphaned decode sessions re-prefill on
  their new owner from the re-shipped prompt + sampling state;
- a **respawned** incarnation announces itself (``hello``) and is
  handed the survivors' params mirror to **adopt** — it re-joins from
  live state, never from a cold checkpoint/export read;
- while shrunk, admission control declares **degraded mode**
  (``MicroBatcher.set_capacity``): load past the shrunk capacity sheds
  proportionally with Retry-After, never silently;
- ``drain(replica)`` is the graceful inverse: stop admission to one
  replica, let its in-flight finish, retire it — the primitive both
  failover and future hot-resize need.

Chaos: ``serve.resize`` fires at the top of every resize attempt (a
failed resize is retried by the next supervisor tick), ``serve.dispatch``
and ``decode.step`` live in replicas.py / decode/scheduler.py — see
``utils/faults.SERVE_CHAOS_SITES``.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import sys
import threading
import time
import weakref

import cloudpickle

from tensorflowonspark_tpu.serving.replicas import (
    ReplicaPool,
    _import_qualname,
    _Predictor,
)
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

__all__ = ["ElasticReplicaPool", "assign_slots", "pool_table"]

BOOT_WAIT_ENV = "TFOS_SERVE_BOOT_WAIT"

#: tfos_serve_resize_seconds buckets — resizes are host-roundtrip
#: device_put + an IPC round, seconds-scale at worst, not the
#: DEFAULT_BUCKETS_MS milliseconds ladder.
RESIZE_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0)

#: Live elastic pools of this process, for the /statusz pool section.
_POOLS = weakref.WeakSet()


def pool_table():
    """[{generation, live, capacity, ...}] for every live elastic pool
    (obs/http.py renders this as the /statusz pool section)."""
    rows = []
    for pool in list(_POOLS):
        try:
            rows.append(pool.describe())
        except Exception:  # noqa: BLE001 - introspection must not raise
            logger.debug("pool describe failed", exc_info=True)
    return rows


def assign_slots(logical, live):
    """Distribute ``logical`` capacity slots over the ``live`` replica
    indices: evenly, remainder to the lowest indices — {idx: covered}.
    Deterministic, so the driver and a replaying postmortem agree."""
    live = sorted(live)
    if not live:
        return {}
    base, rem = divmod(int(logical), len(live))
    return {idx: base + (1 if pos < rem else 0)
            for pos, idx in enumerate(live)}


# -- replica-side helpers (imported lazily by replicas._replica_task) ---------

def boot_wait_default():
    return float(os.environ.get(BOOT_WAIT_ENV, "20"))


def await_boot(inq, timeout=None):
    """Replica-side boot gate: wait for the supervisor's directive after
    announcing ``hello``.  Returns ``("cold",)``, ``("adopt", version,
    params)`` or ``("stop",)``; times out to a cold boot so a pool whose
    supervisor died mid-handshake still comes up serveable.

    Non-boot messages already queued in this index's inherited inbox
    (a dead incarnation's batches/sessions) are discarded here: every
    in-flight entry is re-dispatched by the pool once this incarnation
    registers ``up``, and resolve-once dedups any overlap.
    """
    deadline = time.monotonic() + (boot_wait_default() if timeout is None
                                   else timeout)
    while time.monotonic() < deadline:
        try:
            msg = inq.get(timeout=0.25)
        except _queue.Empty:
            continue
        except Exception:  # noqa: BLE001 - manager gone: boot cold
            break
        if msg[0] == "boot":
            if msg[1] == "adopt":
                return ("adopt", msg[2], cloudpickle.loads(msg[3]))
            return ("cold",)
        if msg[0] == "stop":
            return ("stop",)
    logger.warning("no boot directive within the wait; booting cold")
    return ("cold",)


def adopt_predictor(payload, version, params):
    """Build a replica predictor from ADOPTED live params.

    Only the predict *symbol* is resolved from the spec/export metadata
    (``checkpoint.load_export_meta`` — no params read); the params come
    from the survivors' mirror.  This is the no-cold-reload path the
    acceptance gate checks: a re-grown replica serves the version the
    pool was serving, even if the checkpoint files are gone.
    """
    if params is None:
        raise ValueError("adopt directive carried no params")
    fn = payload.get("predict")
    if payload.get("export_dir") and not callable(fn):
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        meta = ckpt.load_export_meta(payload["export_dir"])
        spec = (fn if isinstance(fn, str) else None) or meta.get("predict")
        fn = _import_qualname(spec) if spec else None
    elif isinstance(fn, str):
        fn = _import_qualname(fn)
    return _Predictor(fn, params, version, payload.get("jit"))


def params_blob(params):
    """Cloudpickle-able host copy of live params (the supervisor's
    adoption mirror).  jax arrays are fetched to host numpy first —
    device buffers don't pickle across processes."""
    if "jax" in sys.modules:
        try:
            from tensorflowonspark_tpu.elastic.reshard import host_fetch

            params = host_fetch(params)
        except Exception:  # noqa: BLE001 - non-jax leaves pickle as-is
            logger.debug("host_fetch failed; pickling as-is", exc_info=True)
    return cloudpickle.dumps(params)


def apply_resize(pred, covered, logical):
    """Replica-side resize: re-place live params for this incarnation's
    share of the logical capacity; returns elapsed milliseconds.

    The serving mesh is logical ``data = covered * n_local_devices``:
    ``elastic/virtual.virtualize`` folds the surplus factor (``covered``)
    out of the data axis exactly like the training side, and the params
    re-place replicated under the folded mesh via ``elastic/reshard``'s
    host-roundtrip ``device_put``.  Pure-numpy (``jit=False``) predicts
    have host-resident params — placement is the identity there — but
    the mesh bookkeeping still applies: ``pred.mesh_shape`` keys the
    compile cache, so post-resize executables never reuse a stale
    sharding.
    """
    t0 = time.perf_counter()
    covered = max(1, int(covered))
    if "jax" in sys.modules:
        import jax

        from tensorflowonspark_tpu.elastic.reshard import reshard
        from tensorflowonspark_tpu.elastic.virtual import virtualize

        devs = jax.devices()
        layout = virtualize({"data": covered * len(devs)}, devs)
        pred.params = reshard(pred.params, layout.replicated())
        pred.mesh_shape = (("data", covered * len(devs)),
                           ("devices", len(devs)))
    else:
        pred.mesh_shape = (("data", covered),)
    return (time.perf_counter() - t0) * 1e3


# -- the pool supervisor ------------------------------------------------------

class ElasticReplicaPool(ReplicaPool):
    """A ReplicaPool that degrades by resize instead of blinking out.

    Rides the base pool's machinery end-to-end — engine respawn,
    manager IPC, liveness scan, InFlightTable re-dispatch — through the
    ``_payload``/``_handle_extra``/``_tick`` hooks; everything elastic
    is additive, so a non-elastic pool's behavior is byte-identical.
    """

    def __init__(self, spec, num_replicas=None, logical_replicas=None,
                 on_capacity=None, engine=None, env=None, max_retries=None,
                 request_timeout=None):
        super().__init__(spec, num_replicas=num_replicas, engine=engine,
                         env=env, max_retries=max_retries,
                         request_timeout=request_timeout)
        self.logical_replicas = int(logical_replicas or self.num_replicas)
        if self.logical_replicas < self.num_replicas:
            raise ValueError(
                f"logical_replicas={self.logical_replicas} < "
                f"num_replicas={self.num_replicas}: the logical capacity "
                "is the pool's full-strength shape")
        self._on_capacity = on_capacity
        self._el_lock = threading.RLock()
        self.generation = 0
        self.capacity_frac = 0.0     # no one is live until start()
        self.resizes = 0
        self.adoptions = 0
        self.last_resize_s = None
        self._assignments = {}       # idx -> covered logical slots
        self._draining = set()
        self._booting = {}           # idx -> dead incarnation's pid: a
        #                              hello arrived but the new pid isn't
        #                              registered yet, so the old one is
        #                              dead even if the monitor's death
        #                              scan never saw it (respawn raced it)
        self._resized_for = None     # (idx, pid) membership signature the
        #                              last resize covered — pid-aware, so
        #                              a respawned incarnation (same idx,
        #                              new pid) still triggers a resize
        self._resize_pending = {}    # gen -> {idx awaiting ack}
        self._resize_t0 = {}         # gen -> perf_counter at bump
        self._mirror_version = None  # newest replica-synced params
        self._mirror_blob = None

    # -- hooks into the base pool -------------------------------------------
    def _payload(self):
        payload = super()._payload()
        payload["elastic"] = {"logical": self.logical_replicas}
        return payload

    def _handle_extra(self, msg):
        kind = msg[0]
        if kind == "hello":
            _, idx, pid = msg
            # a hello from an index with a *recorded* prior incarnation
            # proves that incarnation is dead, even when the engine's
            # respawn beat the monitor's death scan (so the live set
            # never visibly shrank): shrink NOW so the degraded window
            # is declared, not skipped.  The exclusion is keyed to the
            # DEAD pid and dissolves by itself once the new incarnation
            # registers up (different pid) — no up-ordering race.
            # First-formation hellos (no pid on record yet) don't
            # resize; start() forms the pool once.
            dead_pid = self._table.pids().get(idx)
            if dead_pid is not None:
                with self._el_lock:
                    self._booting[idx] = dead_pid
                self._maybe_resize(f"replica {idx} rebooting")
            with self._el_lock:
                blob = self._mirror_blob
                version = self._mirror_version
            if blob is not None:
                self.adoptions += 1
                telemetry.event("serve/pool_adopt", replica=idx,
                                version=version)
                directive = ("boot", "adopt", version, blob)
            else:
                directive = ("boot", "cold")
            try:
                self._inqs[idx].put(directive)
            except Exception:  # noqa: BLE001 - it will boot cold on timeout
                logger.warning("boot directive to replica %s failed", idx)
            return True
        if kind == "params_sync":
            _, idx, version, blob = msg
            with self._el_lock:
                if self._accept_mirror(version):
                    self._mirror_version, self._mirror_blob = version, blob
            return True
        if kind == "resized":
            _, idx, gen, covered, replica_ms = msg
            with self._el_lock:
                if gen != self.generation:
                    return True  # stale ack: epoch-fenced, dropped
                pending = self._resize_pending.get(gen)
                if pending is None:
                    return True
                pending.discard(idx)
                if pending:
                    return True
                del self._resize_pending[gen]
                dur = time.perf_counter() - self._resize_t0.pop(gen)
                self.last_resize_s = dur
            metrics_registry.observe("tfos_serve_resize_seconds", dur,
                                     buckets=RESIZE_BUCKETS_S)
            telemetry.event("serve/pool_resized", generation=gen,
                            seconds=round(dur, 4))
            return True
        if kind == "resize_error":
            _, idx, gen, err = msg
            logger.warning("replica %s failed resize gen %s: %s",
                           idx, gen, err)
            telemetry.event("serve/pool_resize_error", replica=idx,
                            generation=gen, error=str(err)[:200])
            with self._el_lock:
                self._resized_for = None  # next tick re-resizes (new gen)
            return True
        return False

    def _accept_mirror(self, version):
        """Should a ``params_sync`` at ``version`` replace the adopt
        mirror?  Keyed to the pool's pinned version, never plain
        latest-wins (ROADMAP item 6 follow-on, closed with the fabric
        PR): mid-canary the canary arm syncs the unblessed candidate,
        and a replica regrown from the mirror must adopt the *blessed*
        version, not the candidate.  Without a promotion watermark the
        HOT-RELOAD watermark (the step the latest-wins watcher actually
        broadcast, replicas.reload_watermark) pins acceptance instead —
        so a respawn that cold-booted at a newer, never-broadcast
        checkpoint cannot smuggle it into the mirror ahead of the
        version the survivors serve.  With a watermark W: prefer the
        newest version <= W; a version > W is taken only when the
        mirror is empty (candidate params beat no params) or the mirror
        itself is already past W."""
        wm = self.watermark()
        if wm is None:
            wm = self.reload_watermark()
        cur = self._mirror_version
        if wm is None:
            return cur is None or version >= cur
        if version <= wm:
            return cur is None or cur > wm or version >= cur
        return cur is None or (cur > wm and version >= cur)

    def _tick(self):
        self._maybe_resize("membership changed")

    # -- lifecycle -----------------------------------------------------------
    def start(self, timeout=180.0):
        super().start(timeout=timeout)
        _POOLS.add(self)
        # deterministic initial formation: don't wait for the first
        # monitor tick to hand out assignments
        self._maybe_resize("formed")
        return self

    def stop(self):
        _POOLS.discard(self)
        super().stop()

    # -- resize choreography -------------------------------------------------
    def _maybe_resize(self, reason):
        with self._el_lock:
            pids = self._table.pids()
            # a booting exclusion holds only while the table still shows
            # the dead incarnation's pid; the new up dissolves it
            for i in [i for i, p in self._booting.items()
                      if pids.get(i) != p]:
                del self._booting[i]
            live = tuple(i for i in self._table.live()
                         if i not in self._draining
                         and i not in self._booting)
            # pid-aware signature: a respawned incarnation (same index,
            # new pid) is a membership change even though the index set
            # looks identical — it must be handed its assignment
            sig = tuple((i, pids.get(i)) for i in live)
            if sig == self._resized_for:
                return
            if not live:
                # nothing to resize onto: declare zero capacity (the
                # batcher sheds everything, explicitly) and wait for a
                # respawn to change the membership again
                self._resized_for = sig
                self._apply_capacity(0.0)
                return
            try:
                self._resize(live, reason)
                self._resized_for = sig
            except Exception as e:  # noqa: BLE001 - incl. injected faults
                logger.warning("pool resize (%s) failed; next tick "
                               "retries: %s", reason, e)

    def _resize(self, live, reason):
        """One generation bump: fence, assign, reshard, re-admit."""
        faults.check("serve.resize", reason=reason, live=len(live))
        t0 = time.perf_counter()
        self.generation += 1
        gen = self.generation
        self._assignments = assign_slots(self.logical_replicas, live)
        frac = min(1.0, len(live) / float(self.logical_replicas))
        self.resizes += 1
        metrics_registry.set_gauge("tfos_serve_pool_generation", gen)
        telemetry.event("serve/pool_resize", generation=gen, reason=reason,
                        live=list(live), capacity=round(frac, 4),
                        assignments={str(k): v for k, v
                                     in sorted(self._assignments.items())})
        try:  # black-box the degrade/regrow event for tfos-postmortem
            from tensorflowonspark_tpu.obs import flight as _flight

            _flight.snapshot("serve/pool_resize", node="serve-pool",
                             reason=f"{reason}: gen {gen} -> {list(live)}",
                             inflight=self._inflight_summary())
        except Exception:  # noqa: BLE001 - never block a resize
            logger.debug("flight snapshot failed", exc_info=True)
        self._resize_pending[gen] = set(live)
        self._resize_t0[gen] = t0
        # older generations can never complete now — drop their fences
        for old in [g for g in self._resize_pending if g < gen]:
            self._resize_pending.pop(old, None)
            self._resize_t0.pop(old, None)
        for idx in live:
            covered = self._assignments.get(idx, 0)
            try:
                self._inqs[idx].put(
                    ("resize", gen, covered, self.logical_replicas))
            except Exception:  # noqa: BLE001 - death races the directive;
                # the next membership change re-resizes
                logger.warning("resize directive to replica %s failed", idx)
        self._apply_capacity(frac)

    def _apply_capacity(self, frac):
        self.capacity_frac = frac
        degraded = frac < 1.0
        metrics_registry.set_gauge("tfos_serve_pool_degraded",
                                   1.0 if degraded else 0.0)
        if self._on_capacity is not None:
            try:
                self._on_capacity(frac, self.generation, degraded)
            except Exception:  # noqa: BLE001 - admission hook must not
                # wedge the supervisor
                logger.exception("on_capacity hook failed")

    @property
    def degraded(self):
        return self.capacity_frac < 1.0

    # -- graceful drain ------------------------------------------------------
    def drain(self, idx, timeout=30.0):
        """Gracefully retire replica ``idx``: stop admission to it
        (InFlightTable quiesce), resize its capacity share away, let its
        in-flight work finish (re-dispatching whatever remains at the
        deadline), then stop it.  Terminal: a drained replica's engine
        task returns cleanly and is not respawned.  True when the
        replica left the live set within ``timeout``."""
        idx = int(idx)
        live = self._table.live()
        if idx not in live:
            raise ValueError(f"replica {idx} is not live ({live})")
        if set(live) - self._draining <= {idx}:
            raise ValueError("cannot drain the last live replica")
        telemetry.event("serve/pool_drain", replica=idx)
        self._draining.add(idx)
        self._table.quiesce(idx)
        self._maybe_resize(f"drain {idx}")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._table.owned_count(idx):
                break
            time.sleep(0.05)
        else:
            # still holding work at the deadline: hand it to survivors
            # (resolve-once dedups any straggling double answer)
            self._redispatch({idx})
        try:
            self._inqs[idx].put(("stop",))
        except Exception:  # noqa: BLE001 - already gone
            pass
        while time.monotonic() < deadline and idx in self._table.live():
            time.sleep(0.05)
        return idx not in self._table.live()

    # -- introspection -------------------------------------------------------
    def describe(self):
        with self._el_lock:
            return {
                "generation": self.generation,
                "logical": self.logical_replicas,
                "live": self._table.live(),
                "draining": sorted(self._draining),
                "capacity": round(self.capacity_frac, 4),
                "degraded": self.degraded,
                "resizes": self.resizes,
                "adoptions": self.adoptions,
                "last_resize_ms": (round(self.last_resize_s * 1e3, 3)
                                   if self.last_resize_s is not None
                                   else None),
                "assignments": {str(k): v for k, v
                                in sorted(self._assignments.items())},
            }
