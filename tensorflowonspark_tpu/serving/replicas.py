"""Replica pool: N model replicas as supervised engine executors.

No reference equivalent (the reference stops at offline batch inference,
Inference.scala:27-79); the *machinery* is reused from this repo's
runtime instead of reinvented:

- replicas run as ``engine.foreach_partition(spread=True,
  retryable=True)`` tasks, so a SIGKILLed replica is respawned by the
  engine's supervision (engine.py `_respawn_executor`) and its task blob
  re-dispatched byte-identically;
- request/response transport is the executor IPC manager
  (manager.TFManager named queues — the DataFeed transport of
  reference TFSparkNode.py:480-482, batched);
- liveness is the keyed manager-KV heartbeat (``actors.liveness``) plus
  direct executor-process checks, the same two signals engine/node and
  actor supervision use.

Dispatch is least-loaded among live replicas (round-robin when idle —
ties broken by index), via the shared ``actors.dispatch.InFlightTable``
(one table, keys namespaced ``("batch", id)`` / ``("gen", sid)``).
In-flight batches of a dead replica are re-dispatched to survivors;
`batcher.Batch` resolves once, so a duplicate answer from a half-dead
replica is a no-op.

Checkpoint hot-reload: when the spec names a ``ckpt_dir``, a watcher
thread polls ``utils/checkpoint.latest`` every
``TFOS_SERVE_RELOAD_SECS`` and broadcasts an in-band ``reload`` message
to every replica.  In-band means ordered behind already-queued batches:
in-flight requests finish on the old params, later ones see the new —
no drop, no lock.

Canary routing (docs/deployment.md): ``set_canary`` pins a subset of
replicas at a candidate version (in-band ``("reload", step)`` — pinned
reloads may go DOWN-version, unlike the latest-wins watcher) and splits
dispatch deterministically by request id: ~pct% of traffic lands on the
canary arm, the rest on the baseline, least-loaded within the arm.  Arm
outcomes accumulate into ``tfos_deploy_*`` metrics and ``canary_stats``
for the promotion controller's burn-window verdict; ``promote_canary``
reloads the baseline at the candidate and advances the watermark,
``rollback_canary`` re-pins the canary arm at the blessed watermark.
While a watermark is set the latest-wins watcher stands down (the
controller owns version transitions) and a respawned replica that cold-
booted at the wrong version is steered back to its arm's pin.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time

import cloudpickle
import numpy as np

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import liveness
from tensorflowonspark_tpu.actors.dispatch import InFlightTable
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

REPLICAS_ENV = "TFOS_SERVE_REPLICAS"
RELOAD_SECS_ENV = "TFOS_SERVE_RELOAD_SECS"
RETRIES_ENV = "TFOS_SERVE_RETRIES"

HEARTBEAT_PREFIX = "serve_heartbeat:"
OUT_QUEUE = "serve_out"


def num_replicas_default():
    return int(os.environ.get(REPLICAS_ENV, "2"))


def reload_secs_default():
    return float(os.environ.get(RELOAD_SECS_ENV, "2"))


def max_retries_default():
    return int(os.environ.get(RETRIES_ENV, "8"))


def _in_queue(idx):
    return f"serve_in_{idx}"


class ModelSpec:
    """What a replica serves.  Two resolution paths:

    - ``export_dir``: a ``utils/checkpoint.export_model`` directory; the
      predict callable is resolved from the export metadata's
      ``predict`` ("module:qualname") entry, overridable via ``predict``
      here (the ``signature_def_key`` analogue, pipeline.py parity).
    - ``predict`` as a direct callable (+ optional ``params``): shipped
      to replicas by value via cloudpickle — the test/probe path; such
      replicas never import jax when ``jit=False``.

    ``ckpt_dir`` additionally arms checkpoint hot-reload: replicas start
    from the newest checkpoint in it (falling back to export params) and
    the pool's watcher broadcasts reloads as new steps appear.

    ``jit``: True forces AOT compilation (error if the predict is not
    jax-pure), False forces eager, None ("auto") tries AOT and falls
    back to eager.

    ``decode``: a ``serving.decode.scheduler.DecodeSpec`` mounts the
    continuous-batching autoregressive decode engine on every replica
    (docs/serving.md "Autoregressive decode"); the pool then accepts
    ``dispatch_session`` alongside batch ``dispatch``.  A decode-only
    spec needs no ``predict`` — params still resolve from
    ``export_dir``/``params``/``ckpt_dir``.
    """

    def __init__(self, export_dir=None, ckpt_dir=None, predict=None,
                 params=None, jit=None, decode=None):
        if export_dir is None and predict is None and decode is None:
            raise ValueError(
                "ModelSpec needs an export_dir, a predict "
                "callable/'module:qualname' string, or a decode spec")
        self.export_dir = export_dir
        self.ckpt_dir = ckpt_dir
        self.predict = predict
        self.params = params
        self.jit = jit
        self.decode = decode

    def to_payload(self):
        return {
            "export_dir": self.export_dir,
            "ckpt_dir": self.ckpt_dir,
            "predict": self.predict,
            "params": self.params,
            "jit": self.jit,
            "decode": self.decode,
        }


class _Predictor:
    """Replica-side model: params + per-signature compiled executables.

    The compile-count contract (the acceptance criterion's hook): one
    entry is added to ``compiles`` exactly when a new (shape, dtype)
    signature is first seen — via ``jax.jit(fn).lower(...).compile()``
    (AOT, one executable per bucket by construction) or, for non-jittable
    predicts, eager first-call instantiation.  Buckets repeat, signatures
    don't grow past ``log2(max_batch)+1`` per input layout.
    """

    def __init__(self, fn, params, version, jit_mode):
        self._fn = fn
        self.params = params
        self.version = version
        self._jit = jit_mode
        self._compiled = {}
        self.compiles = {}           # sig str -> compile count
        self.mesh_shape = None       # set by an elastic resize
        self.batches = 0
        self.rows = 0
        self.device_ms = 0.0

    def _sig(self, inputs):
        # keyed by (mesh shape, shapes/dtypes): after an elastic reshard
        # the same bucket must re-lower — reusing an executable against a
        # stale sharding would be a silent wrong-placement
        return (self.mesh_shape,) + tuple(
            (k, tuple(v.shape), str(v.dtype))
            for k, v in sorted(inputs.items()))

    def _lower(self, inputs):
        if self._jit is False:
            return None
        try:
            import jax

            return jax.jit(self._fn).lower(self.params, inputs).compile()
        except Exception as e:  # noqa: BLE001 - non-jax-pure predict
            if self._jit is True:
                raise
            logger.info("predict not AOT-compilable (%s); serving eagerly",
                        e)
            return None

    def __call__(self, inputs):
        if self._fn is None:
            raise RuntimeError(
                "this spec serves decode sessions only (no predict "
                "signature); use generate, not predict")
        sig = self._sig(inputs)
        if sig not in self._compiled:
            self._compiled[sig] = self._lower(inputs)
            key = str(sig)
            self.compiles[key] = self.compiles.get(key, 0) + 1
        exe = self._compiled[sig]
        t0 = time.perf_counter()
        if exe is None:
            out = self._fn(self.params, inputs)
        else:
            try:
                out = exe(self.params, inputs)
            except Exception:  # noqa: BLE001 - params changed layout
                # hot-reload swapped params whose avals no longer match
                # the executable (dtype/shape drift): re-lower once
                self._compiled[sig] = exe = self._lower(inputs)
                key = str(sig)
                self.compiles[key] = self.compiles.get(key, 0) + 1
                out = (exe(self.params, inputs) if exe is not None
                       else self._fn(self.params, inputs))
        out = {k: np.asarray(v) for k, v in out.items()}
        dur = (time.perf_counter() - t0) * 1e3
        self.batches += 1
        self.rows += next(iter(inputs.values())).shape[0]
        self.device_ms += dur
        return out, dur

    def stats(self):
        return {
            "version": self.version,
            "compiles": dict(self.compiles),
            "batches": self.batches,
            "rows": self.rows,
            "device_ms": round(self.device_ms, 3),
        }


def _import_qualname(spec):
    """Resolve a "module:qualname" predict spec (pipeline._load_predictor
    convention)."""
    import importlib

    mod_name, _, fn_name = spec.partition(":")
    fn = importlib.import_module(mod_name)
    for part in fn_name.split("."):
        fn = getattr(fn, part)
    return fn


def _resolve_predictor(payload):
    """Build the replica's :class:`_Predictor` from a ModelSpec payload."""
    fn = payload.get("predict")
    params = payload.get("params")
    version = 0
    if payload.get("export_dir"):
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        params, meta = ckpt.load_exported(payload["export_dir"])
        if not callable(fn):
            spec = (fn if isinstance(fn, str) else None) or meta.get("predict")
            if not spec and payload.get("decode") is None:
                raise ValueError(
                    f"export {payload['export_dir']} has no 'predict' "
                    "metadata and the spec names no callable")
            fn = _import_qualname(spec) if spec else None
    elif isinstance(fn, str):
        fn = _import_qualname(fn)
    pred = _Predictor(fn, params, version, payload.get("jit"))
    if payload.get("ckpt_dir"):
        _maybe_reload(pred, payload["ckpt_dir"])
    if pred.params is None:
        raise ValueError("no params: provide export_dir, params, or a "
                         "ckpt_dir containing a checkpoint")
    return pred


def _maybe_reload(pred, ckpt_dir, step=None):
    """Swap in new params; returns True when they changed.

    ``step=None``: the newest checkpoint, if newer than ``pred.version``
    (the latest-wins watcher path).  ``step=N``: that step EXACTLY —
    pinned reloads serve the canary candidate and the rollback target,
    and may go down-version by design."""
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    if step is not None:
        step = int(step)
        if step == pred.version:
            return False
        pred.params = ckpt.restore_step(ckpt_dir, step)
        pred.version = step
        logger.info("replica pinned params at step %d", step)
        return True
    step, _path = ckpt.latest(ckpt_dir)
    if step is None or step == pred.version:
        return False
    tree, step = ckpt.restore_any(ckpt_dir)
    if tree is None or step == pred.version:
        return False
    pred.params = tree
    pred.version = step
    logger.info("replica reloaded params at step %d", step)
    return True


def canary_arm(route_id, pct):
    """True when ``route_id`` hashes into the canary arm.  Deterministic
    (same id, same arm — across processes and retries) with 1% split
    granularity; zlib.crc32 so the split needs no seeding."""
    import zlib

    return (zlib.crc32(str(route_id).encode()) % 100) < float(pct)


def _make_replica_task(payload_blob, mgr_addr, mgr_authkey):
    """The engine task every replica runs.  A real module-level factory
    (not a heredoc/driver lambda): the closure is cloudpickled into the
    executor and must resolve this module by import there."""

    def _replica_task(it):
        items = list(it)
        idx = int(os.environ.get(
            "TFOS_PARTITION_INDEX", items[0] if items else 0))
        mgr = tfmanager.connect(mgr_addr, mgr_authkey)
        inq = mgr.get_queue(_in_queue(idx))
        outq = mgr.get_queue(OUT_QUEUE)
        telemetry.configure(node_id=f"replica-{idx}", role="serving")
        _elastic = None
        el_state = {"gen": 0, "covered": None, "resizes": 0, "boot": "cold"}
        try:
            payload = cloudpickle.loads(payload_blob)
            elastic_cfg = payload.get("elastic")
            if elastic_cfg:
                # elastic boot gate (serving/elastic.py): announce this
                # incarnation, then wait for the supervisor's directive —
                # "cold" (load from the spec) or "adopt" (live params
                # resharded from the survivors' mirror, never a
                # checkpoint reload)
                from tensorflowonspark_tpu.serving import elastic as _elastic

                outq.put(("hello", idx, os.getpid()))
                boot = _elastic.await_boot(inq)
                if boot[0] == "stop":
                    outq.put(("down", idx))
                    return
                if boot[0] == "adopt":
                    pred = _elastic.adopt_predictor(payload, boot[1], boot[2])
                    el_state["boot"] = "adopted"
                else:
                    pred = _resolve_predictor(payload)
            else:
                pred = _resolve_predictor(payload)
            engine = None
            if payload.get("decode") is not None:
                from tensorflowonspark_tpu.serving.decode.scheduler import (
                    DecodeEngine,
                )

                def _gen_emit(kind, sid, *rest):
                    outq.put(("gen_" + kind, idx, sid) + tuple(rest))

                engine = DecodeEngine(
                    pred.params, payload["decode"], _gen_emit,
                    replica=idx).start()
        except BaseException as e:  # noqa: BLE001 - report, then fail task
            outq.put(("init_error", idx, repr(e)))
            raise
        # keyed manager-KV heartbeat (actors.liveness): the pool reads
        # its age to tell a wedged replica from a slow one
        stop_beat = liveness.start_heartbeat(
            mgr, HEARTBEAT_PREFIX + str(idx))
        outq.put(("up", idx, os.getpid(), pred.version))
        if elastic_cfg and el_state["boot"] == "cold":
            # seed the supervisor's params mirror so the NEXT incarnation
            # can adopt instead of cold-loading
            outq.put(("params_sync", idx, pred.version,
                      _elastic.params_blob(pred.params)))
        try:
            while True:
                try:
                    msg = inq.get(timeout=1.0)
                except _queue.Empty:
                    continue
                kind = msg[0]
                if kind == "stop":
                    break
                if kind == "reload":
                    # bare ("reload",) = latest-wins; ("reload", step) =
                    # pinned (canary candidate / rollback target)
                    pin = msg[1] if len(msg) > 1 else None
                    try:
                        if payload.get("ckpt_dir") \
                                and _maybe_reload(pred, payload["ckpt_dir"],
                                                  step=pin):
                            if engine is not None:
                                engine.set_params(pred.params)
                            if elastic_cfg:
                                outq.put(("params_sync", idx, pred.version,
                                          _elastic.params_blob(pred.params)))
                        outq.put(("reloaded", idx, pred.version))
                    except Exception as e:  # noqa: BLE001 - keep serving
                        logger.exception("reload failed")
                        outq.put(("reload_error", idx, repr(e)))
                elif kind == "resize":
                    _, gen, covered, logical = msg
                    if gen <= el_state["gen"]:
                        continue  # stale generation: epoch-fenced
                    try:
                        ms = _elastic.apply_resize(pred, covered, logical)
                        el_state.update(gen=gen, covered=covered,
                                        resizes=el_state["resizes"] + 1)
                        if engine is not None:
                            engine.set_params(pred.params)
                        outq.put(("resized", idx, gen, covered, ms))
                    except Exception as e:  # noqa: BLE001 - keep serving
                        # on the previous layout; the supervisor retries
                        logger.exception("resize to covered=%s failed",
                                         covered)
                        outq.put(("resize_error", idx, gen, repr(e)))
                elif kind == "stats":
                    st = pred.stats()
                    if engine is not None:
                        st["decode"] = engine.stats()
                    if elastic_cfg:
                        st["elastic"] = dict(el_state)
                    outq.put(("stats", idx, st))
                elif kind == "gen":
                    _, sid, blob = msg
                    if engine is None:
                        outq.put(("gen_error", idx, sid,
                                  "spec has no decode engine"))
                        continue
                    try:
                        req = cloudpickle.loads(blob)
                        engine.submit(sid, req["prompt"],
                                      max_tokens=req.get("max_tokens"),
                                      eos_id=req.get("eos_id"),
                                      sampling=req.get("sampling"),
                                      trace=req.get("trace"))
                    except BaseException as e:  # noqa: BLE001 - one bad
                        # session must not take the replica down
                        outq.put(("gen_error", idx, sid, repr(e)))
                elif kind == "batch":
                    _, batch_id, blob = msg
                    try:
                        inputs, n_valid = cloudpickle.loads(blob)
                        with telemetry.span(telemetry.SERVE_BATCH,
                                            replica=idx, n=n_valid):
                            outputs, device_ms = pred(inputs)
                        meta = {"device_ms": device_ms,
                                "version": pred.version,
                                "replica": idx}
                        outq.put(("done", idx, batch_id,
                                  cloudpickle.dumps(outputs), meta))
                    except BaseException as e:  # noqa: BLE001 - one bad
                        # batch must not take the replica down
                        import traceback

                        outq.put(("batch_error", idx, batch_id,
                                  f"{e!r}\n{traceback.format_exc()}"))
        finally:
            stop_beat.set()
            if engine is not None:
                engine.stop()
            outq.put(("down", idx))
            telemetry.flush()

    return _replica_task


class ReplicaPool:
    """Owns the replicas' engine job, the IPC manager, dispatch, failover
    and hot-reload.  ``dispatch(batch)`` is the MicroBatcher sink."""

    def __init__(self, spec, num_replicas=None, engine=None, env=None,
                 max_retries=None, request_timeout=None):
        self.spec = spec
        self.num_replicas = int(num_replicas or num_replicas_default())
        self._engine = engine
        self._owns_engine = engine is None
        self._env = dict(env) if env else None
        self._max_retries = (max_retries_default() if max_retries is None
                             else int(max_retries))
        self._request_timeout = request_timeout
        self._mgr = None
        self._inqs = {}
        self._lock = threading.Lock()
        # membership, loads and the in-flight batch/session entries all
        # live in the shared dispatch table (actors.dispatch); keys are
        # namespaced ("batch", id) / ("gen", sid)
        self._table = InFlightTable(self.num_replicas)
        self._versions = {}          # idx -> last acked params version
        # staged-rollout state (all under self._lock): the open canary
        # split, the blessed watermark, and bounded per-arm outcome
        # accumulators for the controller's burn-window verdict
        self._canary = None          # {"replicas", "version", "pct"}
        self._watermark = None       # blessed step the pool is pinned to
        self._reload_watermark = None  # newest latest-wins broadcast step
        self._arm_stats = None       # arm -> {"n", "errors", "ms": [...]}
        self._stats_replies = {}
        self._stats_event = threading.Event()
        self._registered = threading.Event()
        self._job_error = None
        self._stop = threading.Event()
        self._threads = []
        self.respawns_observed = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self, timeout=180.0):
        if self._owns_engine:
            from tensorflowonspark_tpu.engine import LocalEngine

            self._engine = LocalEngine(self.num_replicas, env=self._env)
        authkey = os.urandom(16)
        self._mgr = tfmanager.start(
            authkey,
            [OUT_QUEUE] + [_in_queue(i) for i in range(self.num_replicas)])
        self._inqs = {i: self._mgr.get_queue(_in_queue(i))
                      for i in range(self.num_replicas)}
        self._outq = self._mgr.get_queue(OUT_QUEUE)
        task = _make_replica_task(
            cloudpickle.dumps(self._payload()),
            tuple(self._mgr.address), authkey)

        def _launch():
            try:
                ds = self._engine.parallelize(
                    list(range(self.num_replicas)), self.num_replicas)
                ds.foreach_partition(task, spread=True, retryable=True,
                                     max_retries=self._max_retries)
            except BaseException as e:  # noqa: BLE001 - surfaced by monitor
                self._job_error = e
                logger.error("serving replica job failed: %s", e)

        for name, target in (("tfos-serve-launch", _launch),
                             ("tfos-serve-collect", self._collect),
                             ("tfos-serve-monitor", self._monitor)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.spec.ckpt_dir:
            t = threading.Thread(target=self._watch_reload,
                                 name="tfos-serve-reload", daemon=True)
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._job_error is not None:
                raise RuntimeError(
                    f"replica pool failed to start: {self._job_error}")
            if len(self._table.live()) >= self.num_replicas:
                return self
            self._registered.wait(0.2)
            self._registered.clear()
        raise TimeoutError(
            f"replicas not up within {timeout}s "
            f"({len(self._table.live())}/{self.num_replicas})")

    def stop(self):
        if self._stop.is_set():
            return
        self._stop.set()
        err = RuntimeError("replica pool stopped")
        for key, entry in self._table.drain():
            if key[0] == "batch":
                entry["batch"].fail(err)
            else:
                entry["session"]._fail(err)
        for inq in self._inqs.values():
            try:
                inq.put(("stop",))
            except Exception:  # noqa: BLE001 - manager may be gone
                pass
        for t in self._threads:
            if t.name == "tfos-serve-launch":
                t.join(timeout=15)
        if self._owns_engine and self._engine is not None:
            self._engine.stop()
        if self._mgr is not None:
            try:
                self._mgr.shutdown()
            except Exception:  # noqa: BLE001
                pass

    def _payload(self):
        """Replica task payload hook (the elastic pool subclass rides it
        to ship its logical-capacity config alongside the ModelSpec)."""
        return self.spec.to_payload()

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, batch):
        """Send one batcher Batch to the least-loaded live replica.
        Called from the batcher thread; must not block on the device."""
        faults.check("serve.dispatch", what="batch", id=batch.id)
        if self._job_error is not None and not self._table.live():
            raise RuntimeError(
                f"no replicas left (job failed: {self._job_error})")
        blob = cloudpickle.dumps((batch.inputs, batch.n_valid))
        idx = self._route(("batch", batch.id),
                          {"batch": batch, "blob": blob}, batch.id)
        self._inqs[idx].put(("batch", batch.id, blob))

    def dispatch_session(self, session):
        """Send one decode :class:`~.decode.scheduler.PendingSession` to
        the least-loaded live replica.  Same failover contract as batch
        dispatch: a dead replica's sessions re-dispatch to survivors
        (full re-prefill there), and the session's index-keyed ledger
        plus resolve-once ``_set`` make the replay zero-drop/zero-dup.
        """
        faults.check("serve.dispatch", what="gen", id=session.id)
        if self.spec.decode is None:
            raise RuntimeError("spec has no decode engine; pass "
                               "ModelSpec(..., decode=DecodeSpec(...))")
        if self._job_error is not None and not self._table.live():
            raise RuntimeError(
                f"no replicas left (job failed: {self._job_error})")
        blob = cloudpickle.dumps({
            "prompt": session.prompt,
            "max_tokens": session.max_tokens,
            "eos_id": session.eos_id,
            # the resolved sampling dict (seed included) rides the blob,
            # so a failover re-dispatch replays the identical stream
            "sampling": getattr(session, "sampling", None),
            # traceparent header: replica-side admit/retire telemetry
            # joins the originating request's trace tree
            "trace": getattr(session, "trace", None),
        })
        idx = self._route(("gen", session.id),
                          {"session": session, "blob": blob}, session.id)
        self._inqs[idx].put(("gen", session.id, blob))

    def cancel_session(self, sid):
        """Forget a session (client gave up): its slot keeps generating
        replica-side, but late answers find no entry and are dropped."""
        return self._table.pop(("gen", sid)) is not None

    def outstanding_sessions(self):
        return sum(1 for k in self._table.keys() if k[0] == "gen")

    def _route(self, key, entry, route_id):
        """Owner pick: least-loaded overall, or — with a canary open —
        least-loaded within the arm ``route_id`` hashes into.  An arm
        with no live member degrades to any live replica (a routing
        split must never drop a request)."""
        with self._lock:
            canary = self._canary
        if canary is None:
            return self._table.add(key, entry)
        arm = "canary" if canary_arm(route_id, canary["pct"]) else "baseline"
        live = self._table.live()
        if arm == "canary":
            cands = [i for i in live if i in canary["replicas"]]
        else:
            cands = [i for i in live if i not in canary["replicas"]]
        entry["arm"] = arm
        if not cands:
            return self._table.add(key, entry)
        loads = self._table.loads()
        owner = min(cands, key=lambda i: (loads.get(i, 0), i))
        return self._table.add(key, entry, owner=owner)

    def _account(self, entry, ok):
        """Per-arm outcome accounting for a resolved entry dispatched
        under a canary split (no-op otherwise): feeds the
        ``tfos_deploy_*`` metrics and the bounded in-memory stats the
        promotion controller reads via :meth:`canary_stats`."""
        arm = entry.get("arm")
        if arm is None:
            return
        ms = (time.monotonic() - entry["t"]) * 1e3
        metrics_registry.inc("tfos_deploy_requests_total", arm=arm,
                             status="ok" if ok else "error")
        metrics_registry.observe("tfos_deploy_request_ms", ms, arm=arm)
        with self._lock:
            if self._arm_stats is None:
                return
            st = self._arm_stats.get(arm)
            if st is None:
                return
            st["n"] += 1
            if not ok:
                st["errors"] += 1
            st["ms"].append(ms)
            del st["ms"][:-512]  # bounded: enough for burn-window p95

    # -- canary / staged rollout ----------------------------------------------
    def set_watermark(self, step):
        """Pin the blessed version.  While set, the latest-wins reload
        watcher stands down (the promotion controller owns version
        transitions) and freshly-up replicas are steered to their arm's
        pin (:meth:`_enforce_version`).  ``None`` releases the pin."""
        with self._lock:
            self._watermark = None if step is None else int(step)

    def watermark(self):
        with self._lock:
            return self._watermark

    def reload_watermark(self):
        """Newest step the latest-wins reload watcher has broadcast
        (None before the first broadcast).  The fabric router and the
        elastic pool's mirror refresh key respawn convergence on it
        when no promotion watermark pins the pool: a respawn must adopt
        the version the survivors actually serve, not whatever
        checkpoint happens to be newest at its boot instant."""
        with self._lock:
            return self._reload_watermark

    def set_canary(self, replicas, version, pct):
        """Open a canary: pin ``replicas`` at candidate ``version`` (in-
        band pinned reload) and route ~``pct``% of traffic to them.
        The arm must leave at least one baseline replica."""
        arm = tuple(sorted(int(i) for i in replicas))
        live = self._table.live()
        if not arm or not set(arm) <= set(live):
            raise ValueError(f"canary replicas {arm} not all live ({live})")
        if len(arm) >= len(live):
            raise ValueError("canary arm must leave a baseline replica")
        version = int(version)
        with self._lock:
            self._canary = {"replicas": arm, "version": version,
                            "pct": float(pct)}
            self._arm_stats = {
                "canary": {"n": 0, "errors": 0, "ms": []},
                "baseline": {"n": 0, "errors": 0, "ms": []},
            }
        for idx in arm:
            self._inqs[idx].put(("reload", version))
        metrics_registry.set_gauge("tfos_deploy_canary_step", version)
        telemetry.event(telemetry.DEPLOY_CANARY, version=version,
                        replicas=list(arm), pct=float(pct))
        logger.info("canary open: replicas %s at step %d (%s%% traffic)",
                    arm, version, pct)
        return arm

    def promote_canary(self):
        """Candidate wins: reload the baseline at the candidate version,
        advance the watermark, clear the split.  Returns the promoted
        step."""
        with self._lock:
            canary = self._canary
        if canary is None:
            raise RuntimeError("promote_canary: no canary open")
        version = canary["version"]
        for idx in self._table.live():
            if idx not in canary["replicas"]:
                self._inqs[idx].put(("reload", version))
        with self._lock:
            self._watermark = version
            self._canary = None
        logger.info("canary promoted: pool pinned at step %d", version)
        return version

    def rollback_canary(self, step=None):
        """Candidate loses: re-pin the canary arm at the blessed
        watermark (or an explicit ``step``), clear the split.  Returns
        the step rolled back to."""
        with self._lock:
            canary = self._canary
            target = self._watermark if step is None else int(step)
        if canary is None:
            raise RuntimeError("rollback_canary: no canary open")
        if target is None:
            raise RuntimeError("rollback_canary: no watermark to re-pin")
        for idx in canary["replicas"]:
            self._inqs[idx].put(("reload", target))
        with self._lock:
            self._watermark = target
            self._canary = None
        logger.info("canary rolled back: arm %s re-pinned at step %d",
                    canary["replicas"], target)
        return target

    def pin_version(self, step):
        """Pin the WHOLE pool at blessed ``step``: targeted reloads on
        every live replica + the watermark.  The bootstrap promotion
        path (first blessed checkpoint, no baseline to canary against)
        and the recovery path (driver restart re-pins from the newest
        blessed manifest) both land here."""
        step = int(step)
        for idx in self._table.live():
            self._inqs[idx].put(("reload", step))
        self.set_watermark(step)
        return step

    def canary(self):
        """The open split ({"replicas", "version", "pct"}) or None."""
        with self._lock:
            return dict(self._canary) if self._canary else None

    def canary_stats(self):
        """Per-arm outcome snapshot since the split opened:
        ``{arm: {"n", "errors", "p50_ms", "p95_ms"}}`` — the burn-window
        evidence the promotion controller judges."""
        with self._lock:
            stats = self._arm_stats
            out = {}
            if stats is None:
                return out
            for arm, st in stats.items():
                ms = sorted(st["ms"])
                out[arm] = {
                    "n": st["n"],
                    "errors": st["errors"],
                    "p50_ms": ms[len(ms) // 2] if ms else None,
                    "p95_ms": ms[int(len(ms) * 0.95)] if ms else None,
                }
            return out

    def canary_snapshot(self):
        """The split's per-arm outcomes as a registry-shaped snapshot
        (``{metric: {"type", "series": [...]}}``) — the exact input
        ``obs/slo.evaluate`` consumes, so the promotion controller
        judges the burn window with the same SLO math as the live
        metrics plane.  The bounded ms samples are bucketed onto the
        default histogram bounds; empty without an open split."""
        bounds = list(metrics_registry.DEFAULT_BUCKETS_MS)
        counters, hists = [], []
        with self._lock:
            stats = self._arm_stats
            if stats is None:
                return {}
            for arm, st in sorted(stats.items()):
                counters.append({"labels": {"arm": arm, "status": "ok"},
                                 "value": float(st["n"] - st["errors"])})
                counters.append({"labels": {"arm": arm, "status": "error"},
                                 "value": float(st["errors"])})
                counts = [0] * (len(bounds) + 1)
                for v in st["ms"]:
                    for i, b in enumerate(bounds):
                        if v <= b:
                            counts[i] += 1
                            break
                    else:
                        counts[-1] += 1
                hists.append({"labels": {"arm": arm}, "bounds": bounds,
                              "counts": counts, "sum": float(sum(st["ms"])),
                              "count": len(st["ms"])})
        return {"tfos_deploy_requests_total": {"type": "counter",
                                               "series": counters},
                "tfos_deploy_request_ms": {"type": "histogram",
                                           "series": hists}}

    def _enforce_version(self, idx, version):
        """Respawn-mid-rollout convergence: a replica that just came up
        cold-booted at the NEWEST checkpoint, which mid-canary may be
        the unblessed candidate.  Steer it to its arm's pinned version
        with a targeted in-band reload."""
        with self._lock:
            canary, wm = self._canary, self._watermark
        if canary is not None and idx in canary["replicas"]:
            want = canary["version"]
        else:
            want = wm
        if want is None or version == want:
            return
        try:
            self._inqs[idx].put(("reload", want))
        except Exception:  # noqa: BLE001 - manager tearing down
            pass

    # -- background threads ----------------------------------------------------
    def _collect(self):
        """Drain serve_out: replica registrations, answers, acks."""
        while not self._stop.is_set():
            try:
                msg = self._outq.get(timeout=0.25)
            except _queue.Empty:
                continue
            except Exception:  # noqa: BLE001 - manager shut down
                return
            if self._handle_extra(msg):
                continue
            kind = msg[0]
            if kind == "up":
                _, idx, pid, version = msg
                respawned = self._table.up(idx, pid)
                if respawned:
                    self.respawns_observed += 1
                with self._lock:
                    self._versions[idx] = version
                self._registered.set()
                telemetry.event("serve/replica_up", replica=idx, pid=pid,
                                version=version)
                self._enforce_version(idx, version)
                if respawned:
                    # A respawn can beat the monitor's death-detection
                    # poll, so this is the authoritative failover trigger:
                    # batches the dead incarnation had popped are gone;
                    # ones still queued in the inherited inbox will at
                    # worst be answered twice (Batch resolves once, the
                    # duplicate is dropped).  Re-dispatch everything the
                    # old incarnation owned.
                    self._record_lost(idx, "respawned")
                    self._redispatch({idx})
            elif kind == "down":
                self._table.down(msg[1])
            elif kind == "done":
                _, idx, batch_id, payload, meta = msg
                entry = self._table.pop(("batch", batch_id))
                if entry is None:
                    continue  # duplicate answer after a re-dispatch
                try:
                    outputs = cloudpickle.loads(payload)
                    entry["batch"].complete(outputs, meta)
                    self._account(entry, ok=True)
                except Exception as e:  # noqa: BLE001
                    entry["batch"].fail(e)
                    self._account(entry, ok=False)
            elif kind == "batch_error":
                _, idx, batch_id, tb = msg
                entry = self._table.pop(("batch", batch_id))
                if entry is not None:
                    entry["batch"].fail(RuntimeError(
                        f"replica {idx} failed the batch:\n{tb}"))
                    self._account(entry, ok=False)
            elif kind == "gen_token":
                _, idx, sid, tindex, tok = msg
                # touch: a streamed token proves the stream is alive
                entry = self._table.touch(("gen", sid))
                if entry is not None:
                    entry["session"]._token(tindex, tok)
            elif kind == "gen_done":
                _, idx, sid, tokens, meta = msg
                entry = self._table.pop(("gen", sid))
                if entry is None:
                    continue  # duplicate answer after a re-dispatch
                entry["session"]._set(tokens, meta)
                self._account(entry, ok=True)
            elif kind == "gen_error":
                _, idx, sid, err = msg
                entry = self._table.pop(("gen", sid))
                if entry is not None:
                    entry["session"]._fail(RuntimeError(
                        f"replica {idx} failed the decode session: {err}"))
                    self._account(entry, ok=False)
            elif kind == "reloaded":
                with self._lock:
                    self._versions[msg[1]] = msg[2]
                telemetry.event("serve/replica_reloaded", replica=msg[1],
                                version=msg[2])
            elif kind == "stats":
                self._stats_replies[msg[1]] = msg[2]
                self._stats_event.set()
            elif kind in ("init_error", "reload_error"):
                logger.warning("replica %s reported %s: %s",
                               msg[1], kind, msg[2])

    def _handle_extra(self, msg):
        """Subclass hook, called before the base message chain: consume
        pool-specific out-queue traffic (the elastic pool's boot/mirror/
        resize-ack messages).  True when the message was handled."""
        return False

    def _tick(self):
        """Subclass hook, called once per monitor pass (the elastic pool
        rides it to reconcile membership against its assignments)."""

    def _monitor(self):
        """Failure detection: executor-process death (fast path) and
        stale manager-KV heartbeats (wedged-replica path).  Either way
        the replica's in-flight batches are re-dispatched to survivors
        (Batch resolves once, so duplicated answers are no-ops)."""
        while not self._stop.wait(0.2):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - next pass retries
                logger.exception("pool tick failed")
            now = time.monotonic()
            dead = liveness.scan(self._table.live(), self._proc_alive,
                                 self._beat_age, tfmanager.stale_after())
            for idx, why in dead:
                self._table.lost(idx)
                logger.warning("replica %d lost (%s); re-dispatching its "
                               "in-flight batches", idx, why)
                self._record_lost(idx, why)
            if dead:
                self._redispatch({idx for idx, _ in dead})
            # request timeout: fail requests stuck past the deadline so
            # clients see an error instead of their full wait.  A decode
            # session's ``t`` refreshes on every streamed token
            # (collect), so only a genuinely stalled stream times out —
            # not a long, healthy generation.
            for key, entry in self._table.stale(self._request_timeout, now):
                if key[0] == "batch":
                    entry["batch"].fail(TimeoutError(
                        "batch not answered within "
                        f"{self._request_timeout}s"))
                else:
                    entry["session"]._fail(TimeoutError(
                        "decode session streamed no token within "
                        f"{self._request_timeout}s"))
                self._account(entry, ok=False)

    def _redispatch(self, dead_idxs):
        """Re-send a dead replica's in-flight work to survivors.  Decode
        sessions re-prefill fully on their new replica; greedy decode is
        deterministic, so the survivor re-streams identical (index,
        token) pairs — the session ledger keeps first arrivals and _set
        resolves once.  With no survivor the entries stay assigned: the
        engine-respawned replica drains the inbox it inherited."""
        moved = {"batch": 0, "gen": 0}
        for key in self._table.owned_by(dead_idxs):
            idx = self._table.reassign(key)
            entry = self._table.get(key)
            if idx is None or entry is None:
                continue
            self._inqs[idx].put((key[0], key[1], entry["blob"]))
            moved[key[0]] += 1
        if moved["batch"] or moved["gen"]:
            telemetry.event("serve/redispatch", batches=moved["batch"],
                            sessions=moved["gen"], to=self._table.live())

    def _record_lost(self, idx, why):
        """Record one replica death: the telemetry event plus a
        black-box flight dump of the dispatch table (docs/telemetry.md
        "Flight recorder").  Called from whichever supervision path
        notices first — the monitor's death scan or the respawned
        incarnation's registration."""
        telemetry.event("serve/replica_lost", replica=idx, reason=why)
        try:  # never let a flight dump block failover
            from tensorflowonspark_tpu.obs import flight as _flight

            _flight.snapshot("serve/replica_lost",
                             node=f"replica-{idx}", reason=why,
                             inflight=self._inflight_summary())
        except Exception:  # noqa: BLE001
            logger.debug("flight snapshot failed", exc_info=True)

    def _inflight_summary(self, limit=32):
        """Small-scalar view of the dispatch table for flight dumps —
        ids, owners and trace headers only, never prompts or blobs
        (redaction contract, docs/telemetry.md "Flight recorder")."""
        out = []
        for key in list(self._table.keys())[:limit]:
            entry = self._table.get(key)
            if entry is None:
                continue
            item = {"kind": key[0], "id": key[1]}
            sess = entry.get("session") if isinstance(entry, dict) else None
            if sess is not None and getattr(sess, "trace", None):
                item["trace"] = sess.trace
            out.append(item)
        return out

    def _proc_alive(self, idx):
        procs = getattr(self._engine, "_procs", None)
        if procs is None or idx >= len(procs):
            return True  # foreign engine: no process visibility
        try:
            return procs[idx].is_alive()
        except Exception:  # noqa: BLE001
            return True

    def _beat_age(self, idx):
        return liveness.beat_age(self._mgr, HEARTBEAT_PREFIX + str(idx))

    def _watch_reload(self):
        """Poll utils/checkpoint.latest; broadcast in-band reloads."""
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        with self._lock:
            last = max(self._versions.values(), default=0)
        interval = reload_secs_default()
        while not self._stop.wait(interval):
            with self._lock:
                managed = (self._watermark is not None
                           or self._canary is not None)
            if managed:
                # a promotion controller owns version transitions:
                # latest-wins broadcasts would race the pinned arms
                continue
            try:
                step, _path = ckpt.latest(self.spec.ckpt_dir)
            except Exception:  # noqa: BLE001 - transient fs error
                continue
            if step is None or step == last:
                continue
            last = step
            with self._lock:
                self._reload_watermark = step
            metrics_registry.inc("tfos_serve_reloads_total")
            telemetry.event(telemetry.SERVE_RELOAD, step=step)
            logger.info("hot-reload: broadcasting checkpoint step %d", step)
            for idx in self._table.live():
                try:
                    self._inqs[idx].put(("reload",))
                except Exception:  # noqa: BLE001
                    pass

    # -- introspection ---------------------------------------------------------
    def live_replicas(self):
        return self._table.live()

    def replica_pids(self):
        return self._table.pids()

    def versions(self):
        with self._lock:
            return dict(self._versions)

    def stats(self, timeout=10.0):
        """Broadcast a stats request; gather per-replica predictor stats
        (compile counts per signature, batches, rows, version)."""
        targets = self._table.live()
        self._stats_replies = {}
        self._stats_event.clear()
        for idx in targets:
            self._inqs[idx].put(("stats",))
        deadline = time.monotonic() + timeout
        while (set(self._stats_replies) < set(targets)
               and time.monotonic() < deadline):
            self._stats_event.wait(0.1)
            self._stats_event.clear()
        return dict(self._stats_replies)
