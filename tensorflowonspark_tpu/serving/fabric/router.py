"""FabricRouter: driver-side cross-host dispatch for the serving fabric.

Parity note: the reference's TFCluster.py drives N hosts from one
driver over the manager wire for *training*; this is the serving-side
analogue (no reference equivalent for serving itself —
Inference.scala:27-79 stops at offline batch inference).  PARITY.md
§2.2 tracks the mapping.

The router implements the same pool protocol as
``serving.replicas.ReplicaPool`` (``start``/``stop``/``dispatch``/
``dispatch_session``/``cancel_session``/``stats``/...), so
``serving.server.Server`` mounts it unchanged — but its members are
fabric HOSTS (one engine executor each, N worker replicas inside, see
``fabric/host.py``) instead of single local replicas:

- **Cross-host addressing** — envelopes ride per-host manager queues;
  membership, per-host load and every in-flight batch/session live in
  the shared ``actors.dispatch.InFlightTable`` keyed by host index.
  A SIGKILLed host's in-flight entries re-dispatch to survivors;
  ``batcher.Batch``/``PendingSession`` resolve once, so duplicate
  answers from a half-dead host are no-ops (zero drop, zero dup).
- **Session affinity** — ``dispatch_session`` routes a session
  carrying a ``route_id`` to the ``(host, worker)`` whose
  ``PagedKVCache`` still holds its prefix blocks: a live binding wins
  (outcome ``"hit"``), an unknown route goes through the consistent-
  hash ring (``"miss"``), and a dead or saturated target falls back
  least-loaded (``"fallback"``).  The outcome rides the session's
  result meta so load generators can measure ``affinity_hit_rate``.
- **Autoscaling actuation** — the router publishes per-host
  ``{workers, depth}`` to the manager KV (``fabric:load``) and applies
  the ``ServeAutoscaler``'s plan (``fabric:plan``) as generation-fenced
  in-band ``("scale", gen, n)`` directives; acks update the worker map
  the ring is built from.
- **Version convergence** — a respawned host cold-boots at the newest
  checkpoint; ``_enforce_version`` steers it back to the promotion
  watermark when one is set, else to the hot-reload watermark the
  latest-wins watcher last broadcast (the pinned-version contract the
  elastic pool's mirror refresh shares, serving/elastic.py).

Chaos sites: ``serve.fabric_dispatch`` fires before an envelope is
routed, ``serve.fabric_route`` inside the affinity pick (utils/faults).
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time
import weakref

import cloudpickle

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import liveness
from tensorflowonspark_tpu.actors.dispatch import InFlightTable
from tensorflowonspark_tpu.serving.fabric import host as _host
from tensorflowonspark_tpu.serving.fabric.affinity import AffinityMap, Ring
from tensorflowonspark_tpu.serving.replicas import (
    max_retries_default,
    reload_secs_default,
)
from tensorflowonspark_tpu.utils import faults, metrics_registry, telemetry

logger = logging.getLogger(__name__)

HOSTS_ENV = "TFOS_FABRIC_HOSTS"

# Live routers, for the /statusz "pods" section (obs/http.py) — same
# weak-registry pattern as serving/elastic._POOLS / actors.actor_table.
_ROUTERS = weakref.WeakSet()


def num_hosts_default():
    return int(os.environ.get(HOSTS_ENV, "2"))


def fabric_table():
    """Per-host rows for every live router (the /statusz ``pods``
    section and the ``tfos-top --pods`` pane)."""
    rows = []
    for n, router in enumerate(list(_ROUTERS)):
        try:
            desc = router.describe()
        except Exception:  # noqa: BLE001 - router tearing down
            logger.debug("fabric_table: describe failed", exc_info=True)
            continue
        for hrow in desc.get("hosts", ()):
            rows.append(dict(hrow, router=n))
    return rows


class FabricRouter:
    """Owns the fabric hosts' engine job, the IPC manager, affinity
    routing, failover and the autoscaler loop.  Pool-protocol
    compatible: ``Server(..., fabric=True)`` mounts it as ``pool``."""

    def __init__(self, spec, num_hosts=None, replicas_per_host=1,
                 engine=None, env=None, max_retries=None,
                 request_timeout=None, autoscale=False,
                 affinity_max_load=None):
        self.spec = spec
        self.num_hosts = int(num_hosts or num_hosts_default())
        self.replicas_per_host = max(1, int(replicas_per_host))
        self._engine = engine
        self._owns_engine = engine is None
        self._env = dict(env) if env else None
        self._max_retries = (max_retries_default() if max_retries is None
                             else int(max_retries))
        self._request_timeout = request_timeout
        # autoscale: False | True | {kernel kwargs for ServeAutoscaler}
        self._autoscale = autoscale
        self._asys = None
        self._mgr = None
        self._inqs = {}
        self._lock = threading.Lock()
        self._table = InFlightTable(self.num_hosts)
        self._workers = {}           # host -> acked worker count
        self._versions = {}          # host -> last acked params version
        self._watermark = None       # promotion pin (set_watermark)
        self._reload_watermark = None  # newest latest-wins broadcast
        self._affinity = AffinityMap()
        self._ring = None
        self._ring_sig = None
        self._rr = 0
        decode = getattr(spec, "decode", None)
        self._sat_load = int(affinity_max_load
                             or (decode.slots if decode is not None else 8))
        self._aff = {"hit": 0, "miss": 0, "fallback": 0}
        self._aff_host = {}          # host -> outcome counts
        self._gen = 0                # scale-directive generation fence
        self._plan_applied = 0
        self._last_pub = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self.redispatched = 0
        self._stats_replies = {}
        self._stats_event = threading.Event()
        self._registered = threading.Event()
        self._job_error = None
        self._stop = threading.Event()
        self._threads = []
        self.respawns_observed = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self, timeout=180.0):
        if self._owns_engine:
            from tensorflowonspark_tpu.engine import LocalEngine

            self._engine = LocalEngine(self.num_hosts, env=self._env)
        authkey = os.urandom(16)
        self._mgr = tfmanager.start(
            authkey,
            [_host.OUT_QUEUE]
            + [_host._in_queue(h) for h in range(self.num_hosts)])
        self._inqs = {h: self._mgr.get_queue(_host._in_queue(h))
                      for h in range(self.num_hosts)}
        self._outq = self._mgr.get_queue(_host.OUT_QUEUE)
        payload = dict(self.spec.to_payload(),
                       fabric={"replicas_per_host": self.replicas_per_host})
        task = _host._make_host_task(
            cloudpickle.dumps(payload), tuple(self._mgr.address), authkey)

        def _launch():
            try:
                ds = self._engine.parallelize(
                    list(range(self.num_hosts)), self.num_hosts)
                ds.foreach_partition(task, spread=True, retryable=True,
                                     max_retries=self._max_retries)
            except BaseException as e:  # noqa: BLE001 - surfaced by monitor
                self._job_error = e
                logger.error("fabric host job failed: %s", e)

        for name, target in (("tfos-fabric-launch", _launch),
                             ("tfos-fabric-collect", self._collect),
                             ("tfos-fabric-monitor", self._monitor)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.spec.ckpt_dir:
            t = threading.Thread(target=self._watch_reload,
                                 name="tfos-fabric-reload", daemon=True)
            t.start()
            self._threads.append(t)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._job_error is not None:
                raise RuntimeError(
                    f"fabric failed to start: {self._job_error}")
            if len(self._table.live()) >= self.num_hosts:
                break
            self._registered.wait(0.2)
            self._registered.clear()
        else:
            raise TimeoutError(
                f"fabric hosts not up within {timeout}s "
                f"({len(self._table.live())}/{self.num_hosts})")
        if self._autoscale:
            self._start_autoscaler(authkey)
        _ROUTERS.add(self)
        return self

    def _start_autoscaler(self, authkey):
        """Spawn the supervised ServeAutoscaler actor against this
        router's manager KV (its own ActorSystem, its own process —
        SIGKILL-safe: a respawned incarnation reseeds its plan sequence
        from the KV)."""
        from tensorflowonspark_tpu.actors.policy import SupervisionPolicy
        from tensorflowonspark_tpu.actors.runtime import ActorSystem
        from tensorflowonspark_tpu.serving.fabric.autoscale import (
            ServeAutoscaler,
        )

        opts = dict(self._autoscale) if isinstance(self._autoscale, dict) \
            else {}
        tick = float(opts.pop("tick_secs", 0.5))
        actor = ServeAutoscaler(mgr_addr=tuple(self._mgr.address),
                                mgr_authkey=authkey, **opts)
        self._asys = ActorSystem(1, env=self._env)
        self._asys.spawn(actor, "serve-autoscaler",
                         policy=SupervisionPolicy(tick_secs=tick))

    def stop(self):
        if self._stop.is_set():
            return
        self._stop.set()
        _ROUTERS.discard(self)
        if self._asys is not None:
            try:
                self._asys.stop()
            except Exception:  # noqa: BLE001
                pass
        err = RuntimeError("fabric router stopped")
        for key, entry in self._table.drain():
            if key[0] == "batch":
                entry["batch"].fail(err)
            else:
                entry["session"]._fail(err)
        for inq in self._inqs.values():
            try:
                inq.put(("stop",))
            except Exception:  # noqa: BLE001 - manager may be gone
                pass
        for t in self._threads:
            if t.name == "tfos-fabric-launch":
                t.join(timeout=15)
        if self._owns_engine and self._engine is not None:
            self._engine.stop()
        if self._mgr is not None:
            try:
                self._mgr.shutdown()
            except Exception:  # noqa: BLE001
                pass

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, batch):
        """Send one batcher Batch to the least-loaded live host (predict
        batches coalesce unrelated requests, so session affinity does
        not apply — the host picks its least-busy worker)."""
        faults.check("serve.fabric_dispatch", what="batch", id=batch.id)
        if self._job_error is not None and not self._table.live():
            raise RuntimeError(
                f"no fabric hosts left (job failed: {self._job_error})")
        blob = cloudpickle.dumps((batch.inputs, batch.n_valid))
        h = self._table.add(("batch", batch.id),
                            {"batch": batch, "blob": blob})
        metrics_registry.inc("tfos_fabric_dispatches_total", kind="batch")
        self._inqs[h].put(("batch", batch.id, blob))

    def dispatch_session(self, session):
        """Route one decode session: affinity binding -> consistent-hash
        ring -> least-loaded fallback.  Same failover contract as the
        local pool — a dead host's sessions re-dispatch to survivors
        (full re-prefill there) and resolve exactly once."""
        faults.check("serve.fabric_dispatch", what="gen", id=session.id)
        if self.spec.decode is None:
            raise RuntimeError("spec has no decode engine; pass "
                               "ModelSpec(..., decode=DecodeSpec(...))")
        if self._job_error is not None and not self._table.live():
            raise RuntimeError(
                f"no fabric hosts left (job failed: {self._job_error})")
        blob = cloudpickle.dumps({
            "prompt": session.prompt,
            "max_tokens": session.max_tokens,
            "eos_id": session.eos_id,
            "sampling": getattr(session, "sampling", None),
            "trace": getattr(session, "trace", None),
        })
        route_id = getattr(session, "route_id", None)
        h, rid, outcome = self._route_session(route_id)
        entry = {"session": session, "blob": blob, "rid": rid,
                 "route_id": None if route_id is None else str(route_id),
                 "affinity": outcome}
        owner = self._table.add(("gen", session.id), entry, owner=h)
        metrics_registry.inc("tfos_fabric_dispatches_total", kind="gen")
        if outcome is not None:
            metrics_registry.inc("tfos_fabric_affinity_total",
                                 outcome=outcome)
            with self._lock:
                self._aff[outcome] += 1
                per = self._aff_host.setdefault(
                    owner, {"hit": 0, "miss": 0, "fallback": 0})
                per[outcome] += 1
        self._inqs[owner].put(("gen", session.id, rid, blob))

    def cancel_session(self, sid):
        return self._table.pop(("gen", sid)) is not None

    def outstanding_sessions(self):
        return sum(1 for k in self._table.keys() if k[0] == "gen")

    def _live_workers(self):
        """{live host: acked worker count} (>=1: a host that never
        acked a scale still runs its boot complement)."""
        live = self._table.live()
        with self._lock:
            return {h: max(1, int(self._workers.get(h, 1))) for h in live}

    def _ring_for(self, workers):
        """The consistent-hash ring over live (host, worker) endpoints,
        rebuilt only when membership or worker counts change."""
        sig = tuple(sorted(workers.items()))
        if sig != self._ring_sig:
            self._ring = Ring([(h, r) for h, n in sorted(workers.items())
                               for r in range(n)])
            self._ring_sig = sig
        return self._ring

    def _saturated(self, h, workers, loads):
        return loads.get(h, 0) >= workers.get(h, 1) * self._sat_load

    def _route_session(self, route_id):
        """(host, worker hint, outcome).  ``(None, None, None)`` lets
        the dispatch table pick least-loaded (no route id, or nothing
        live to route against)."""
        faults.check("serve.fabric_route", route=route_id)
        workers = self._live_workers()
        if route_id is None or not workers:
            return None, None, None
        key = str(route_id)
        loads = self._table.loads()
        bound = self._affinity.get(key)
        if bound is not None:
            bh, br = bound
            if (bh in workers and br < workers[bh]
                    and not self._saturated(bh, workers, loads)):
                return bh, br, "hit"
            outcome = "fallback"     # target dead, retired or saturated
        else:
            outcome = "miss"         # first sighting: place via the ring
        h, r = self._ring_for(workers).lookup(key)
        if self._saturated(h, workers, loads):
            cands = [x for x in workers
                     if not self._saturated(x, workers, loads)] or \
                list(workers)
            h = min(cands, key=lambda x: (loads.get(x, 0), x))
            self._rr += 1
            r = self._rr % workers[h]
            outcome = "fallback"
        self._affinity.bind(key, (h, r))
        return h, r, outcome

    # -- version pinning ------------------------------------------------------
    def set_watermark(self, step):
        """Pin the fabric at a blessed version: the latest-wins reload
        watcher stands down and respawned hosts are steered to it."""
        with self._lock:
            self._watermark = None if step is None else int(step)

    def watermark(self):
        with self._lock:
            return self._watermark

    def reload_watermark(self):
        with self._lock:
            return self._reload_watermark

    def _enforce_version(self, h, version):
        """A respawned host cold-boots at the NEWEST checkpoint; steer
        it to the pinned version — the promotion watermark when set,
        else the hot-reload watermark the watcher last broadcast."""
        with self._lock:
            want = (self._watermark if self._watermark is not None
                    else self._reload_watermark)
        if want is None or version == want:
            return
        try:
            self._inqs[h].put(("reload", want))
        except Exception:  # noqa: BLE001 - manager tearing down
            pass

    def _watch_reload(self):
        """Poll utils/checkpoint.latest; broadcast in-band reloads and
        record the step as the reload watermark respawns converge to."""
        from tensorflowonspark_tpu.utils import checkpoint as ckpt

        with self._lock:
            last = max(self._versions.values(), default=0)
        interval = reload_secs_default()
        while not self._stop.wait(interval):
            with self._lock:
                managed = self._watermark is not None
            if managed:
                continue
            try:
                step, _path = ckpt.latest(self.spec.ckpt_dir)
            except Exception:  # noqa: BLE001 - transient fs error
                continue
            if step is None or step == last:
                continue
            last = step
            with self._lock:
                self._reload_watermark = step
            metrics_registry.inc("tfos_serve_reloads_total")
            telemetry.event(telemetry.SERVE_RELOAD, step=step)
            for h in self._table.live():
                try:
                    self._inqs[h].put(("reload",))
                except Exception:  # noqa: BLE001
                    pass

    # -- background threads ----------------------------------------------------
    def _collect(self):
        """Drain fabric_out: host registrations, answers, acks."""
        while not self._stop.is_set():
            try:
                msg = self._outq.get(timeout=0.25)
            except _queue.Empty:
                continue
            except Exception:  # noqa: BLE001 - manager shut down
                return
            kind = msg[0]
            if kind == "up":
                _, h, pid, version, n_workers = msg
                respawned = self._table.up(h, pid)
                if respawned:
                    self.respawns_observed += 1
                with self._lock:
                    self._versions[h] = version
                    self._workers[h] = int(n_workers)
                self._registered.set()
                telemetry.event("serve/fabric_host_up", host=h, pid=pid,
                                version=version, workers=n_workers)
                self._enforce_version(h, version)
                if respawned:
                    # authoritative failover trigger (a respawn can beat
                    # the monitor's death scan) — same contract as
                    # ReplicaPool._collect
                    telemetry.event("serve/fabric_host_lost", host=h,
                                    reason="respawned")
                    self._redispatch({h})
            elif kind == "down":
                self._table.down(msg[1])
            elif kind == "done":
                _, h, batch_id, payload, meta = msg
                entry = self._table.pop(("batch", batch_id))
                if entry is None:
                    continue  # duplicate answer after a re-dispatch
                try:
                    outputs = cloudpickle.loads(payload)
                    entry["batch"].complete(outputs, meta)
                except Exception as e:  # noqa: BLE001
                    entry["batch"].fail(e)
            elif kind == "batch_error":
                _, h, batch_id, tb = msg
                entry = self._table.pop(("batch", batch_id))
                if entry is not None:
                    entry["batch"].fail(RuntimeError(
                        f"fabric host {h} failed the batch:\n{tb}"))
            elif kind == "gen_token":
                _, h, sid, tindex, tok = msg
                entry = self._table.touch(("gen", sid))
                if entry is not None:
                    entry["session"]._token(tindex, tok)
            elif kind == "gen_done":
                _, h, sid, tokens, meta = msg
                entry = self._table.pop(("gen", sid))
                if entry is None:
                    continue  # duplicate answer after a re-dispatch
                meta = dict(meta or {})
                meta["host"] = h
                if entry.get("affinity") is not None:
                    meta["affinity"] = entry["affinity"]
                entry["session"]._set(tokens, meta)
            elif kind == "gen_error":
                _, h, sid, err = msg
                entry = self._table.pop(("gen", sid))
                if entry is not None:
                    entry["session"]._fail(RuntimeError(
                        f"fabric host {h} failed the decode session: "
                        f"{err}"))
            elif kind == "reloaded":
                with self._lock:
                    self._versions[msg[1]] = msg[2]
            elif kind == "scaled":
                _, h, gen, n_workers = msg
                with self._lock:
                    self._workers[h] = int(n_workers)
            elif kind == "stats":
                self._stats_replies[msg[1]] = msg[2]
                self._stats_event.set()
            elif kind == "init_error":
                logger.warning("fabric host %s reported init_error: %s",
                               msg[1], msg[2])

    def _monitor(self):
        """Death/stale detection + plan actuation + load publishing."""
        while not self._stop.wait(0.2):
            now = time.monotonic()
            dead = liveness.scan(self._table.live(), self._proc_alive,
                                 self._beat_age, tfmanager.stale_after())
            for h, why in dead:
                self._table.lost(h)
                logger.warning("fabric host %d lost (%s); re-dispatching "
                               "its in-flight envelopes", h, why)
                telemetry.event("serve/fabric_host_lost", host=h,
                                reason=why)
            if dead:
                self._redispatch({h for h, _ in dead})
            for key, entry in self._table.stale(self._request_timeout, now):
                if key[0] == "batch":
                    entry["batch"].fail(TimeoutError(
                        "batch not answered within "
                        f"{self._request_timeout}s"))
                else:
                    entry["session"]._fail(TimeoutError(
                        "decode session streamed no token within "
                        f"{self._request_timeout}s"))
            try:
                self._apply_plan()
            except Exception:  # noqa: BLE001 - next pass retries
                logger.debug("plan application failed", exc_info=True)
            self._publish_load(now)

    def _apply_plan(self):
        """Actuate the autoscaler's newest plan (``fabric:plan``) as
        generation-fenced in-band scale directives."""
        if self._mgr is None:
            return
        try:
            plan = self._mgr.get(_host.PLAN_KEY)
        except Exception:  # noqa: BLE001 - manager tearing down
            return
        if not isinstance(plan, dict):
            return
        seq = int(plan.get("seq", 0))
        if seq <= self._plan_applied:
            return
        self._plan_applied = seq
        live = set(self._table.live())
        for hs, n in (plan.get("hosts") or {}).items():
            h, n = int(hs), int(n)
            if h not in live:
                continue
            with self._lock:
                cur = self._workers.get(h)
            if cur is None or n == cur:
                continue
            direction = "up" if n > cur else "down"
            if direction == "up":
                self.scale_ups += 1
            else:
                self.scale_downs += 1
            self._gen += 1
            metrics_registry.inc("tfos_fabric_scale_events_total",
                                 direction=direction)
            telemetry.event("serve/fabric_scale", host=h,
                            direction=direction, workers=n, seq=seq)
            logger.info("fabric scale %s: host %d %d -> %d workers",
                        direction, h, cur, n)
            try:
                self._inqs[h].put(("scale", self._gen, n))
            except Exception:  # noqa: BLE001
                pass

    def _publish_load(self, now):
        """Per-host {workers, depth} rollup to the manager KV — the
        autoscaler's input signal — plus the fabric gauges."""
        if now - self._last_pub < 0.5:
            return
        self._last_pub = now
        workers = self._live_workers()
        loads = self._table.loads()
        doc = {"ts": time.time(),
               "hosts": {str(h): {"workers": w,
                                  "depth": int(loads.get(h, 0))}
                         for h, w in workers.items()}}
        try:
            self._mgr.set(_host.LOAD_KEY, doc)
        except Exception:  # noqa: BLE001 - manager tearing down
            pass
        metrics_registry.set_gauge("tfos_fabric_hosts", len(workers))
        metrics_registry.set_gauge("tfos_fabric_replicas",
                                   sum(workers.values()))
        metrics_registry.set_gauge("tfos_fabric_queue_depth",
                                   len(self._table))

    def _redispatch(self, dead_hosts):
        """Re-send a dead host's in-flight envelopes to survivors.
        Re-dispatched sessions re-prefill on worker 0 of the survivor
        and the route is rebound there, so the session's NEXT request
        follows its blocks (deterministic decode keeps the replayed
        stream token-identical; the session ledger + resolve-once
        ``_set`` make it zero-drop/zero-dup)."""
        moved = {"batch": 0, "gen": 0}
        for key in self._table.owned_by(dead_hosts):
            h = self._table.reassign(key)
            entry = self._table.get(key)
            if h is None or entry is None:
                continue
            if key[0] == "batch":
                self._inqs[h].put(("batch", key[1], entry["blob"]))
            else:
                entry["rid"] = 0
                if entry.get("route_id") is not None:
                    self._affinity.bind(entry["route_id"], (h, 0))
                self._inqs[h].put(("gen", key[1], entry["rid"],
                                   entry["blob"]))
            metrics_registry.inc("tfos_fabric_redispatches_total",
                                 kind=key[0])
            moved[key[0]] += 1
            self.redispatched += 1
        if moved["batch"] or moved["gen"]:
            telemetry.event("serve/fabric_redispatch",
                            batches=moved["batch"], sessions=moved["gen"],
                            to=self._table.live())

    def _proc_alive(self, h):
        procs = getattr(self._engine, "_procs", None)
        if procs is None or h >= len(procs):
            return True  # foreign engine: no process visibility
        try:
            return procs[h].is_alive()
        except Exception:  # noqa: BLE001
            return True

    def _beat_age(self, h):
        return liveness.beat_age(self._mgr, _host.HEARTBEAT_PREFIX + str(h))

    # -- introspection ---------------------------------------------------------
    def live_replicas(self):
        return self._table.live()

    def replica_pids(self):
        return self._table.pids()

    def host_pids(self):
        return self._table.pids()

    def versions(self):
        with self._lock:
            return dict(self._versions)

    def affinity_binding(self, route_id):
        """The (host, worker) a route is bound to, or None."""
        return self._affinity.get(str(route_id))

    def affinity_counts(self):
        with self._lock:
            return dict(self._aff)

    def stats(self, timeout=10.0):
        """Broadcast a stats request; gather per-host rollups (worker
        predictor/decode stats keyed by worker id)."""
        targets = self._table.live()
        self._stats_replies = {}
        self._stats_event.clear()
        for h in targets:
            self._inqs[h].put(("stats",))
        deadline = time.monotonic() + timeout
        while (set(self._stats_replies) < set(targets)
               and time.monotonic() < deadline):
            self._stats_event.wait(0.1)
            self._stats_event.clear()
        return dict(self._stats_replies)

    def describe(self):
        """Summary + per-host rows (the /statusz pods section)."""
        live = set(self._table.live())
        loads = self._table.loads()
        pids = self._table.pids()
        with self._lock:
            workers = dict(self._workers)
            versions = dict(self._versions)
            aff = {h: dict(v) for h, v in self._aff_host.items()}
            aff_total = dict(self._aff)
        hosts = []
        for h in range(self.num_hosts):
            a = aff.get(h, {})
            total = sum(a.values())
            hosts.append({
                "host": h,
                "alive": h in live,
                "pid": pids.get(h),
                "replicas": int(workers.get(h, 0)) if h in live else 0,
                "queue_depth": int(loads.get(h, 0)),
                "version": versions.get(h),
                "affinity_hit_rate": (round(a.get("hit", 0) / total, 4)
                                      if total else None),
            })
        return {
            "fabric": True,
            "num_hosts": self.num_hosts,
            "live_hosts": len(live),
            "replicas": sum(int(workers.get(h, 0)) for h in live),
            "autoscale": bool(self._autoscale),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "redispatched": self.redispatched,
            "respawns": self.respawns_observed,
            "affinity": aff_total,
            "hosts": hosts,
        }
