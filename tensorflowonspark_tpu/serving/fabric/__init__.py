"""Pod-scale serving fabric (docs/serving.md "Pod-scale fabric").

Parity note: the reference federates many hosts under one driver for
*training* (TFCluster.py); its serving story stops at offline batch
inference (Inference.scala:27-79).  This subsystem is the serving-side
counterpart, PARITY.md §2.2: cross-host replica dispatch over the
manager wire, queue-driven replica autoscaling, and session/prefix-
affinity routing for the decode tier's paged KV caches.

Pieces:
  - :mod:`~tensorflowonspark_tpu.serving.fabric.affinity` —
    consistent-hash ring + bounded LRU route bindings (pure);
  - :mod:`~tensorflowonspark_tpu.serving.fabric.host` — the per-host
    engine task: N replica worker threads, each with its own predictor
    and decode engine;
  - :mod:`~tensorflowonspark_tpu.serving.fabric.router` — driver-side
    pool-protocol router (``Server(..., fabric=True)`` mounts it):
    InFlightTable-backed dispatch, SIGKILL failover, affinity routing,
    plan actuation;
  - :mod:`~tensorflowonspark_tpu.serving.fabric.autoscale` — the
    supervised ``ServeAutoscaler`` actor (hysteresis kernel shape from
    ``data/autoscale.py``) over the router's queue-vs-worker signal.
"""

from tensorflowonspark_tpu.serving.fabric.affinity import (  # noqa: F401
    AffinityMap,
    Ring,
)
from tensorflowonspark_tpu.serving.fabric.autoscale import (  # noqa: F401
    ServeAutoscaler,
)
from tensorflowonspark_tpu.serving.fabric.router import (  # noqa: F401
    FabricRouter,
    fabric_table,
    num_hosts_default,
)
