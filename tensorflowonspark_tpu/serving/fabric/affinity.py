"""Session/prefix-affinity routing primitives for the serving fabric.

No reference equivalent (the reference's TFCluster.py federates hosts
for training only; its serving story stops at offline batch inference,
Inference.scala:27-79).  The design follows the cache-aware routing
layer sketched in ROADMAP item 1: a returning ``/v1/generate`` session
should land on the replica whose ``PagedKVCache`` still holds its
prefix blocks, because a re-prefill elsewhere pays the full prompt
cost again.

Two pure, stdlib-only pieces:

- :class:`Ring` — a consistent-hash ring over ``(host, replica)``
  endpoints.  Hashing is md5-based, so placement is deterministic
  across processes (no ``PYTHONHASHSEED`` dependence) and adding or
  removing one endpoint only remaps the keys that pointed at it.
- :class:`AffinityMap` — a bounded LRU of ``route_id -> endpoint``
  bindings.  The binding, not the ring, is authoritative for a
  returning session: after a failover re-dispatch the router rebinds
  the route to the survivor that now holds the re-prefilled blocks,
  and later requests follow the binding even though the ring would
  point elsewhere.

Neither class knows about liveness or load — the router decides when a
binding or ring target is dead/saturated and falls back (outcome
``"fallback"``); these just answer "where would this key live?".
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from collections import OrderedDict

DEFAULT_VNODES = 64
DEFAULT_BINDINGS = 4096


def _hash64(data):
    """Deterministic 64-bit hash of a string (md5 prefix)."""
    return int.from_bytes(
        hashlib.md5(data.encode("utf-8", "replace")).digest()[:8], "big")


class Ring:
    """Consistent-hash ring over hashable endpoints.

    ``vnodes`` virtual points per endpoint smooth the key distribution;
    with one endpoint every key maps to it, with zero endpoints
    :meth:`lookup` raises.  Endpoints are placed by the md5 of their
    ``repr`` plus the vnode index, so two rings built from the same
    endpoint set agree everywhere.
    """

    def __init__(self, endpoints, vnodes=DEFAULT_VNODES):
        self.endpoints = tuple(endpoints)
        if not self.endpoints:
            raise ValueError("Ring needs at least one endpoint")
        points = []
        for ep in self.endpoints:
            for v in range(int(vnodes)):
                points.append((_hash64(f"{ep!r}#{v}"), ep))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    def lookup(self, key):
        """The endpoint owning ``key`` (first point clockwise)."""
        h = _hash64(str(key))
        i = bisect.bisect_right(self._keys, h)
        if i >= len(self._points):
            i = 0
        return self._points[i][1]


class AffinityMap:
    """Bounded LRU of ``route_id -> endpoint`` bindings (thread-safe).

    ``bind`` inserts or refreshes; ``get`` refreshes recency on hit, so
    an active session is never the one evicted.  Eviction only forgets
    the *hint* — a forgotten route re-routes through the ring and at
    worst re-prefills once.
    """

    def __init__(self, capacity=DEFAULT_BINDINGS):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("AffinityMap capacity must be >= 1")
        self._map = OrderedDict()
        self._lock = threading.Lock()

    def get(self, route_id):
        with self._lock:
            ep = self._map.get(route_id)
            if ep is not None:
                self._map.move_to_end(route_id)
            return ep

    def bind(self, route_id, endpoint):
        with self._lock:
            self._map[route_id] = endpoint
            self._map.move_to_end(route_id)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def pop(self, route_id):
        with self._lock:
            return self._map.pop(route_id, None)

    def __len__(self):
        with self._lock:
            return len(self._map)
