"""Fabric host: one engine executor running N replica worker threads.

Parity note: the reference runs one TF node per Spark executor and
multiplexes work over the manager wire (TFSparkNode.py:480-482, the
DataFeed transport); a fabric host generalizes that to one *serving*
process per host whose replica count changes at runtime under the
autoscaler's plan.  No reference equivalent for the serving side
itself (Inference.scala:27-79 stops at offline batch inference).

Shape mirrors ``serving/replicas._make_replica_task``: a module-level
task factory (cloudpickle-able under the spawn start method), manager
queues for transport, a keyed manager-KV heartbeat for liveness, and
an in-band message loop.  The difference is one level of fan-out: the
host's dispatcher loop routes envelopes onto per-worker thread inboxes,
and each :class:`_Worker` owns its own ``_Predictor`` and (when the
spec mounts decode) its own ``DecodeEngine`` — so a host with 3
replicas holds 3 independent KV caches, which is what makes
session-affinity routing (``router.py``) worth doing.

Wire (all host->driver messages lead with the HOST index — workers are
a host-local detail; the driver's dispatch table is keyed by host):

- driver->host (``fabric_in_<h>``): ``("batch", bid, blob)``,
  ``("gen", sid, rid, blob)`` (``rid`` = worker hint from affinity
  routing, ``None`` = host picks least-busy), ``("reload"[, step])``,
  ``("scale", gen, n)`` (generation-fenced; stale directives dropped),
  ``("stats",)``, ``("stop",)``.
- host->driver (``fabric_out``): ``("up", h, pid, version, workers)``,
  ``("down", h)``, ``("done", h, bid, blob, meta)``,
  ``("batch_error", h, bid, tb)``, ``("gen_token", h, sid, i, tok)``,
  ``("gen_done", h, sid, tokens, meta)``, ``("gen_error", h, sid,
  err)``, ``("reloaded", h, version)``, ``("scaled", h, gen, n)``,
  ``("stats", h, st)``, ``("init_error", h, err)``.

Scale-down retires the HIGHEST worker ids first (LIFO): a retiring
worker stops admitting, drains its inbox in order, waits out its live
decode sessions, then stops its engine — scale-down never drops an
in-flight request.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time

import cloudpickle

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import liveness
from tensorflowonspark_tpu.serving.replicas import (
    _maybe_reload,
    _resolve_predictor,
)
from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)

OUT_QUEUE = "fabric_out"
HEARTBEAT_PREFIX = "fabric_heartbeat:"
ENDPOINT_KEY = "fabric:ep:"     # + host index -> {"pid", "workers", ...}
LOAD_KEY = "fabric:load"        # router-published per-host load rollup
PLAN_KEY = "fabric:plan"        # autoscaler-published replica plan

RETIRE_GRACE_S = 30.0


def _in_queue(h):
    return f"fabric_in_{h}"


class _Worker:
    """One replica: a thread owning a predictor + optional decode engine.

    ``load()`` is the host's local routing signal: queued envelopes plus
    the one being handled plus live decode sessions.  The driver keeps
    its own per-host load in the dispatch table; this only breaks ties
    *within* a host.
    """

    def __init__(self, host, rid, payload, outq):
        self.host = host
        self.rid = rid
        self.payload = payload
        self.outq = outq
        self.inbox = _queue.Queue()
        self.accepting = True
        self.ready = threading.Event()
        self.error = None
        self.pred = None
        self.engine = None
        self._pending = 0
        self._sessions = 0
        self._lock = threading.Lock()
        self.thread = threading.Thread(
            target=self._run, name=f"fabric-worker-{host}-{rid}", daemon=True)

    def start(self):
        self.thread.start()
        return self

    def push(self, msg):
        with self._lock:
            self._pending += 1
        self.inbox.put(msg)

    def retire(self):
        """Stop admitting; the queued ``retire`` marker is handled after
        everything already in the inbox (in-band, ordered drain)."""
        self.accepting = False
        self.inbox.put(("retire",))

    def load(self):
        with self._lock:
            return self._pending + self._sessions

    def version(self):
        pred = self.pred
        return pred.version if pred is not None else None

    def stats(self):
        pred, engine = self.pred, self.engine
        st = pred.stats() if pred is not None else {}
        if engine is not None:
            st["decode"] = engine.stats()
        st["load"] = self.load()
        st["accepting"] = self.accepting
        return st

    def _emit(self, kind, sid, *rest):
        if kind in ("done", "error"):
            with self._lock:
                self._sessions = max(0, self._sessions - 1)
        self.outq.put(("gen_" + kind, self.host, sid) + tuple(rest))

    def _run(self):
        try:
            pred = _resolve_predictor(self.payload)
            engine = None
            if self.payload.get("decode") is not None:
                from tensorflowonspark_tpu.serving.decode.scheduler import (
                    DecodeEngine,
                )

                engine = DecodeEngine(
                    pred.params, self.payload["decode"], self._emit,
                    replica=self.rid).start()
        except BaseException as e:  # noqa: BLE001 - report, stay down
            self.error = e
            self.accepting = False
            self.ready.set()
            return
        self.pred = pred
        self.engine = engine
        self.ready.set()
        try:
            while True:
                msg = self.inbox.get()
                kind = msg[0]
                if kind == "retire":
                    break
                try:
                    if kind == "batch":
                        _, bid, blob = msg
                        inputs, n_valid = cloudpickle.loads(blob)
                        with telemetry.span(
                                telemetry.SERVE_BATCH,
                                replica=f"{self.host}/{self.rid}",
                                n=n_valid):
                            outputs, device_ms = pred(inputs)
                        meta = {"device_ms": device_ms,
                                "version": pred.version,
                                "replica": self.rid,
                                "host": self.host}
                        self.outq.put(("done", self.host, bid,
                                       cloudpickle.dumps(outputs), meta))
                    elif kind == "gen":
                        _, sid, blob = msg
                        if engine is None:
                            self.outq.put(("gen_error", self.host, sid,
                                           "spec has no decode engine"))
                        else:
                            req = cloudpickle.loads(blob)
                            with self._lock:
                                self._sessions += 1
                            engine.submit(sid, req["prompt"],
                                          max_tokens=req.get("max_tokens"),
                                          eos_id=req.get("eos_id"),
                                          sampling=req.get("sampling"),
                                          trace=req.get("trace"))
                    elif kind == "reload":
                        pin = msg[1]
                        if self.payload.get("ckpt_dir") \
                                and _maybe_reload(pred,
                                                  self.payload["ckpt_dir"],
                                                  step=pin):
                            if engine is not None:
                                engine.set_params(pred.params)
                        self.outq.put(("reloaded", self.host, pred.version))
                except BaseException as e:  # noqa: BLE001 - one bad
                    # envelope must not take the worker down
                    if kind == "batch":
                        import traceback

                        self.outq.put(("batch_error", self.host, msg[1],
                                       f"{e!r}\n{traceback.format_exc()}"))
                    elif kind == "gen":
                        with self._lock:
                            self._sessions = max(0, self._sessions - 1)
                        self.outq.put(("gen_error", self.host, msg[1],
                                       repr(e)))
                    else:
                        logger.exception("worker %d/%d failed a %s",
                                         self.host, self.rid, kind)
                finally:
                    with self._lock:
                        self._pending = max(0, self._pending - 1)
        finally:
            # retiring: wait out live decode sessions, then stop clean
            if engine is not None:
                deadline = time.monotonic() + RETIRE_GRACE_S
                while time.monotonic() < deadline:
                    with self._lock:
                        if self._sessions <= 0:
                            break
                    time.sleep(0.05)
                engine.stop()


class _Host:
    """Worker-thread supervisor inside one fabric host process."""

    def __init__(self, h, payload, outq):
        self.h = h
        self.payload = payload
        self.outq = outq
        self.gen = 0                 # last applied scale generation
        self._workers = []
        self._next_rid = 0
        self._lock = threading.Lock()

    def _active(self):
        return [w for w in self._workers
                if w.accepting and w.error is None]

    def scale_to(self, n, wait_first=False, timeout=120.0):
        """Grow/shrink to ``n`` accepting workers.  Growth is async
        (new workers admit once their predictor resolves); shrink
        retires the highest worker ids first (LIFO)."""
        n = max(1, int(n))
        with self._lock:
            active = self._active()
            while len(active) < n:
                w = _Worker(self.h, self._next_rid, self.payload, self.outq)
                self._next_rid += 1
                self._workers.append(w)
                w.start()
                active.append(w)
                if wait_first and len(active) == 1:
                    w.ready.wait(timeout)
                    if w.error is not None:
                        raise w.error
            excess = max(0, len(active) - n)
            for w in sorted(active, key=lambda x: -x.rid)[:excess]:
                w.retire()

    def route(self, msg):
        kind = msg[0]
        with self._lock:
            # a not-yet-ready worker is routable: its inbox queues until
            # the predictor resolves (admission gates live driver-side)
            cands = self._active()
        if not cands:
            mid = msg[1]
            err = "batch_error" if kind == "batch" else "gen_error"
            self.outq.put((err, self.h, mid, "host has no live workers"))
            return
        if kind == "gen":
            _, sid, rid, blob = msg
            w = next((x for x in cands if x.rid == rid), None)
            if w is None:
                w = min(cands, key=lambda x: (x.load(), x.rid))
            w.push(("gen", sid, blob))
        else:
            _, bid, blob = msg
            w = min(cands, key=lambda x: (x.load(), x.rid))
            w.push(("batch", bid, blob))

    def broadcast(self, msg):
        with self._lock:
            for w in self._active():
                w.push(msg)

    def reap(self):
        """Drop retired/broken workers whose threads have exited."""
        with self._lock:
            self._workers = [w for w in self._workers
                             if w.thread.is_alive() or
                             (w.accepting and w.error is None)]

    def n_workers(self):
        with self._lock:
            return len(self._active())

    def version(self):
        with self._lock:
            versions = [w.version() for w in self._active()]
        versions = [v for v in versions if v is not None]
        return max(versions, default=0)

    def load(self):
        with self._lock:
            return sum(w.load() for w in self._active())

    def stats(self):
        with self._lock:
            workers = list(self._workers)
        return {
            "pid": os.getpid(),
            "n_workers": self.n_workers(),
            "workers": {w.rid: w.stats() for w in workers
                        if w.error is None},
        }

    def endpoint_record(self):
        return {"pid": os.getpid(), "workers": self.n_workers(),
                "load": self.load(), "version": self.version(),
                "ts": time.time()}

    def stop(self):
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            w.retire()
        for w in workers:
            w.thread.join(timeout=5)


def _make_host_task(payload_blob, mgr_addr, mgr_authkey):
    """The engine task every fabric host runs.  A real module-level
    factory (spawn start method): the closure is cloudpickled into the
    executor and resolves this module by import there."""

    def _host_task(it):
        items = list(it)
        h = int(os.environ.get(
            "TFOS_PARTITION_INDEX", items[0] if items else 0))
        mgr = tfmanager.connect(mgr_addr, mgr_authkey)
        inq = mgr.get_queue(_in_queue(h))
        outq = mgr.get_queue(OUT_QUEUE)
        telemetry.configure(node_id=f"fabric-host-{h}", role="serving")
        try:
            payload = cloudpickle.loads(payload_blob)
            fabric_cfg = payload.get("fabric") or {}
            host = _Host(h, payload, outq)
            host.scale_to(int(fabric_cfg.get("replicas_per_host", 1)),
                          wait_first=True)
        except BaseException as e:  # noqa: BLE001 - report, then fail task
            outq.put(("init_error", h, repr(e)))
            raise
        stop_beat = liveness.start_heartbeat(mgr, HEARTBEAT_PREFIX + str(h))
        outq.put(("up", h, os.getpid(), host.version(), host.n_workers()))
        last_ep = 0.0
        try:
            while True:
                now = time.monotonic()
                if now - last_ep >= 1.0:
                    last_ep = now
                    try:
                        mgr.set(ENDPOINT_KEY + str(h),
                                host.endpoint_record())
                    except Exception:  # noqa: BLE001 - manager going away
                        pass
                try:
                    msg = inq.get(timeout=0.25)
                except _queue.Empty:
                    host.reap()
                    continue
                kind = msg[0]
                if kind == "stop":
                    break
                if kind == "scale":
                    _, gen, n = msg
                    if gen <= host.gen:
                        continue  # stale generation: epoch-fenced
                    host.gen = gen
                    try:
                        host.scale_to(int(n))
                    except Exception:  # noqa: BLE001 - keep serving
                        logger.exception("scale to %s failed", n)
                    outq.put(("scaled", h, gen, host.n_workers()))
                elif kind == "reload":
                    # bare ("reload",) = latest-wins; ("reload", step) =
                    # pinned (watermark convergence after a respawn)
                    pin = msg[1] if len(msg) > 1 else None
                    host.broadcast(("reload", pin))
                elif kind == "stats":
                    outq.put(("stats", h, host.stats()))
                elif kind in ("batch", "gen"):
                    host.route(msg)
        finally:
            stop_beat.set()
            host.stop()
            outq.put(("down", h))
            telemetry.flush()

    return _host_task
