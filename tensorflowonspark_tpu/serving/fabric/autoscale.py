"""ServeAutoscaler: queue-driven replica autoscaling for the fabric.

Parity note: no reference equivalent — the reference's executor count
is fixed at cluster start (TFCluster.py ``run(sc, ..., num_executors)``).
The hysteresis kernel reuses the shape proven in ``data/autoscale.py``
(stall-driven data-worker scaling): one actuation per cooldown window,
a high/low band so the signal must clearly cross before anything
moves, and hard min/max clamps.

The scaling signal is queueing collapse, not utilization: the router
publishes per-host ``{workers, depth}`` (``fabric:load``, where depth =
in-flight envelopes from the dispatch table) and the kernel acts on
``total depth / total workers`` — the queue-vs-device ratio ``/statusz``
already surfaces per request.  Above ``high`` it adds one replica to
the emptiest host (spreads before stacking); below ``low`` it retires
one from the fullest host, where the host process drops its
highest-numbered worker first — LIFO retirement, so long-lived workers
(and their warm KV caches) survive idle troughs.

Runs as a supervised actor (``actors.runtime.Actor``): the instance is
cloudpickled into an executor, reconnects to the *router's* manager in
``on_start`` (``ctx.mgr`` is the ActorSystem's own manager, not the
fabric's), and steps once per supervision tick.  SIGKILL-safe: a
respawned incarnation reseeds its plan sequence number from the KV, so
its next plan supersedes rather than regresses.  Plans are only ever
*published* (``fabric:plan``); the router actuates them with
generation-fenced in-band directives (router._apply_plan).

Knobs (env defaults): ``TFOS_SERVE_MIN_REPLICAS`` /
``TFOS_SERVE_MAX_REPLICAS`` clamp per-host workers;
``TFOS_SERVE_SCALE_HIGH`` / ``TFOS_SERVE_SCALE_LOW`` bound the
depth-per-worker band; ``TFOS_SERVE_SCALE_COOLDOWN`` spaces actions.
"""

from __future__ import annotations

import logging
import os
import time

from tensorflowonspark_tpu.actors.runtime import Actor
from tensorflowonspark_tpu.serving.fabric.host import LOAD_KEY, PLAN_KEY
from tensorflowonspark_tpu.utils import telemetry

logger = logging.getLogger(__name__)

MIN_ENV = "TFOS_SERVE_MIN_REPLICAS"
MAX_ENV = "TFOS_SERVE_MAX_REPLICAS"
HIGH_ENV = "TFOS_SERVE_SCALE_HIGH"
LOW_ENV = "TFOS_SERVE_SCALE_LOW"
COOLDOWN_ENV = "TFOS_SERVE_SCALE_COOLDOWN"

SIGNAL_STALE_S = 10.0


def min_replicas_default():
    return int(os.environ.get(MIN_ENV, "1"))


def max_replicas_default():
    return int(os.environ.get(MAX_ENV, "4"))


class ServeAutoscaler(Actor):
    """Hysteresis kernel + actor plumbing.

    Two wirings share ``step()``:

    - **KV mode** (production): ``mgr_addr``/``mgr_authkey`` name the
      fabric router's manager; the kernel reads ``fabric:load`` and
      publishes ``fabric:plan``.
    - **Injected mode** (tests): ``read_signal()`` returns
      ``{host: {"workers", "depth"}}`` and ``apply_plan(plan)`` takes
      ``{host: workers}`` — the kernel is exercised without processes.
    """

    def __init__(self, mgr_addr=None, mgr_authkey=None, read_signal=None,
                 apply_plan=None, min_replicas=None, max_replicas=None,
                 high=None, low=None, cooldown=None):
        self._mgr_addr = tuple(mgr_addr) if mgr_addr else None
        self._mgr_authkey = mgr_authkey
        self._read_signal = read_signal
        self._apply_plan = apply_plan
        self.min_replicas = (min_replicas_default() if min_replicas is None
                             else int(min_replicas))
        self.max_replicas = (max_replicas_default() if max_replicas is None
                             else int(max_replicas))
        self.high = float(os.environ.get(HIGH_ENV, "2.0")
                          if high is None else high)
        self.low = float(os.environ.get(LOW_ENV, "0.25")
                         if low is None else low)
        self.cooldown = float(os.environ.get(COOLDOWN_ENV, "5.0")
                              if cooldown is None else cooldown)
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min ({self.min_replicas}) <= max "
                f"({self.max_replicas})")
        if not (0 <= self.low < self.high):
            raise ValueError(
                f"need 0 <= low ({self.low}) < high ({self.high})")
        self._mgr = None
        self._plan_seq = 0
        self._last_action = float("-inf")
        self.scale_ups = 0
        self.scale_downs = 0

    # A live manager proxy is not picklable; the actor reconnects in
    # on_start (and lazily, so a driver-side instance works too).
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_mgr"] = None
        return state

    def _connect(self):
        if self._mgr is None and self._mgr_addr is not None:
            from tensorflowonspark_tpu import manager as tfmanager

            self._mgr = tfmanager.connect(self._mgr_addr, self._mgr_authkey)
            # reseed the sequence so a respawned incarnation's next plan
            # supersedes the one its predecessor published
            try:
                doc = self._mgr.get(PLAN_KEY)
                if isinstance(doc, dict):
                    self._plan_seq = int(doc.get("seq", 0))
            except Exception:  # noqa: BLE001 - empty KV on first boot
                pass
        return self._mgr

    def _read(self):
        """Normalized load signal: {int host: {"workers", "depth"}}."""
        if self._read_signal is not None:
            sig = self._read_signal()
        else:
            mgr = self._connect()
            if mgr is None:
                return None
            try:
                doc = mgr.get(LOAD_KEY)
            except Exception:  # noqa: BLE001 - router not publishing yet
                return None
            if not isinstance(doc, dict):
                return None
            if time.time() - float(doc.get("ts", 0)) > SIGNAL_STALE_S:
                return None  # stale rollup: the router stopped; sit still
            sig = doc.get("hosts")
        if not isinstance(sig, dict) or not sig:
            return None
        return {int(h): {"workers": int(v.get("workers", 0)),
                         "depth": int(v.get("depth", 0))}
                for h, v in sig.items()}

    def _apply(self, plan):
        if self._apply_plan is not None:
            self._apply_plan(dict(plan))
            return
        mgr = self._connect()
        if mgr is None:
            return
        self._plan_seq += 1
        mgr.set(PLAN_KEY, {"seq": self._plan_seq,
                           "hosts": {str(h): int(n)
                                     for h, n in plan.items()},
                           "ts": time.time()})

    def step(self, now=None):
        """One decision: "up", "down", or None (in cooldown, no signal,
        in band, or clamped)."""
        now = time.monotonic() if now is None else now
        if now - self._last_action < self.cooldown:
            return None
        sig = self._read()
        if not sig:
            return None
        workers = {h: max(0, v["workers"]) for h, v in sig.items()}
        total = sum(workers.values())
        if total <= 0:
            return None
        ratio = sum(v["depth"] for v in sig.values()) / total
        if ratio > self.high:
            cands = [h for h, n in workers.items() if n < self.max_replicas]
            if not cands:
                return None
            h = min(cands, key=lambda x: (workers[x], x))
            plan = dict(workers)
            plan[h] += 1
            self._apply(plan)
            self.scale_ups += 1
            self._last_action = now
            telemetry.event("serve/fabric_scale_up", host=h,
                            ratio=round(ratio, 3),
                            replicas=sum(plan.values()))
            logger.info("fabric scale-up: host %d -> %d workers "
                        "(depth/worker %.2f > %.2f)", h, plan[h], ratio,
                        self.high)
            return "up"
        if ratio < self.low:
            cands = [h for h, n in workers.items() if n > self.min_replicas]
            if not cands:
                return None
            h = max(cands, key=lambda x: (workers[x], x))
            plan = dict(workers)
            plan[h] -= 1
            self._apply(plan)
            self.scale_downs += 1
            self._last_action = now
            telemetry.event("serve/fabric_scale_down", host=h,
                            ratio=round(ratio, 3),
                            replicas=sum(plan.values()))
            logger.info("fabric scale-down: host %d -> %d workers "
                        "(depth/worker %.2f < %.2f)", h, plan[h], ratio,
                        self.low)
            return "down"
        return None

    # -- actor hooks -----------------------------------------------------------
    def on_start(self, ctx):
        self._connect()

    def on_tick(self, ctx):
        try:
            self.step()
        except Exception:  # noqa: BLE001 - next tick retries
            logger.exception("autoscaler step failed")

    def on_message(self, ctx, kind, payload):
        if kind == "step":
            return self.step()
        if kind == "status":
            return {"scale_ups": self.scale_ups,
                    "scale_downs": self.scale_downs,
                    "min": self.min_replicas, "max": self.max_replicas,
                    "high": self.high, "low": self.low,
                    "cooldown": self.cooldown}
        return None
