"""pyspark.ml interop: genuine Spark ML pipeline stages over the TPU
cluster runtime (parity: reference tensorflowonspark/pipeline.py:351-489,
where TFEstimator/TFModel subclass pyspark.ml.Estimator/Model and compose
in a ``pyspark.ml.Pipeline``).

Import requires pyspark.  The classes wrap this package's own
``pipeline.TFEstimator``/``pipeline.TFModel`` (which hold the Params
machinery, cluster launch, and cached-model inference) and add only the
Spark ML contract: ``Estimator._fit(DataFrame) -> Model`` and
``Model._transform(DataFrame) -> DataFrame``.

The reference user surface carries over verbatim::

    from tensorflowonspark_tpu.spark_ml import TFEstimator
    est = TFEstimator(main_fun, args).setClusterSize(2).setEpochs(1)
    model = Pipeline(stages=[est]).fit(df)
    preds = model.transform(df)
"""

from __future__ import annotations

import logging

from pyspark.ml import Estimator as _SparkEstimator, Model as _SparkModel

from tensorflowonspark_tpu import pipeline as _pipeline

logger = logging.getLogger(__name__)


class _DelegatesParams:
    """Routes the Has* setter/getter surface (setBatchSize, getEpochs, …)
    and Params introspection to the wrapped implementation object, while
    keeping ``self`` as the return value of setters so Spark ML style
    chaining stays on the Spark stage."""

    _impl = None

    def __getattr__(self, name):
        impl = object.__getattribute__(self, "_impl")
        if impl is None:
            raise AttributeError(name)
        attr = getattr(impl, name)
        if name.startswith("set") and callable(attr):
            def chaining_setter(*a, _attr=attr, **kw):
                _attr(*a, **kw)
                return self

            return chaining_setter
        return attr

    # Spark's Params surface, delegated so Pipeline/copy interop works
    @property
    def params(self):
        return self._impl.params

    def extractParamMap(self, extra=None):
        out = self._impl.extractParamMap()
        out.update(extra or {})
        return out

    def getOrDefault(self, param):
        return self._impl.getOrDefault(param)

    def isDefined(self, param):
        return self._impl.isDefined(param)

    def copy(self, extra=None):
        import copy as _copy

        dup = _copy.copy(self)
        dup._impl = self._impl.copy(
            {(k.name if hasattr(k, "name") else k): v
             for k, v in (extra or {}).items()}
        )
        return dup


class TFEstimator(_DelegatesParams, _SparkEstimator):
    """pyspark.ml.Estimator that trains via TFCluster on the DataFrame's
    SparkContext and returns a :class:`TFModel`."""

    def __init__(self, train_fn, tf_args=None, export_fn=None):
        super().__init__()
        self._impl = _pipeline.TFEstimator(train_fn, tf_args, export_fn)

    def _fit(self, dataset):
        model_impl = self._impl.fit(dataset)
        return TFModel._wrap(model_impl)


class TFModel(_DelegatesParams, _SparkModel):
    """pyspark.ml.Model running cached single-process batch inference per
    executor; ``transform`` returns a DataFrame of the output_mapping
    columns (parity: reference pipeline.TFModel + TFModel.scala:245-292)."""

    def __init__(self, tf_args=None):
        super().__init__()
        self._impl = _pipeline.TFModel(tf_args)

    @classmethod
    def _wrap(cls, impl):
        m = cls.__new__(cls)
        _SparkModel.__init__(m)
        m._impl = impl
        return m

    def _transform(self, dataset):
        from pyspark.sql import Row, SparkSession

        out_ds = self._impl.transform(dataset)  # SparkDataset of dict rows
        args = self._impl.merge_args_params()
        out_cols = (
            [c for _, c in sorted(args.output_mapping.items())]
            if getattr(args, "output_mapping", None) else None
        )

        def _to_rows(it, _cols=tuple(out_cols or ())):
            rows = []
            for d in it:
                cols = list(_cols) if _cols else sorted(d)
                rows.append(Row(**{c: d[c] for c in cols}))
            return rows

        row_rdd = out_ds.rdd.mapPartitions(_to_rows)
        session = getattr(dataset, "sparkSession", None) or (
            SparkSession.builder.getOrCreate()
        )
        return session.createDataFrame(row_rdd, schema=out_cols)
