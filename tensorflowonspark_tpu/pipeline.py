"""ML-pipeline layer: Estimator/Model with typed Params over the cluster
runtime (parity: reference tensorflowonspark/pipeline.py, 710 LoC).

The reference builds on ``pyspark.ml`` — ``TFEstimator.fit(df)`` spins a
TFCluster in InputMode.SPARK, feeds the DataFrame, and returns a
``TFModel`` whose ``transform`` runs cached single-node inference per
executor (pipeline.py:351-489,585-644).  This module keeps that exact
user surface — ``Has*`` mixins with ``setX/getX``, ``Namespace`` argument
unification, params-over-args merging (pipeline.py:339-348) — but is
self-contained: the Params machinery below has no pyspark dependency, and
when a real ``pyspark.ml`` Estimator is wanted the same classes accept
Spark DataFrames (``.rdd`` ducks into the engine Dataset contract).

TPU-native inference design: instead of a SavedModel signature looked up
by ``signature_def_key`` (pipeline.py:664-685), an export directory
(utils/checkpoint.export_model) carries the params pytree plus metadata
naming a ``predict`` function (``"module:qualname"``); the per-worker
cache jits it once and reuses it across partitions — the analogue of the
reference's per-python-worker model cache (pipeline.py:492-496).
"""

from __future__ import annotations

import argparse
import copy as _copy
import logging

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Lightweight Spark-ML-style Params machinery (no pyspark dependency)
# ---------------------------------------------------------------------------

class Param:
    """A typed, documented parameter owned by a Params class."""

    def __init__(self, name, doc, converter=None):
        self.name = name
        self.doc = doc
        self.converter = converter

    def __repr__(self):
        return f"Param({self.name})"


class TypeConverters:
    """Coercions for Param values (parity: pyspark TypeConverters +
    the reference's custom toDict, pipeline.py:39-46)."""

    @staticmethod
    def toInt(v):
        return int(v)

    @staticmethod
    def toFloat(v):
        return float(v)

    @staticmethod
    def toString(v):
        return str(v)

    @staticmethod
    def toBoolean(v):
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes")
        return bool(v)

    @staticmethod
    def toDict(v):
        if not isinstance(v, dict):
            raise TypeError(f"expected dict, got {type(v)}")
        return v


class Params:
    """Base class managing a param map with defaults.

    Mirrors the pyspark.ml.param.Params surface used by the reference
    (``_set``, ``_setDefault``, ``getOrDefault``, ``extractParamMap``,
    ``copy``) so Estimator/Model subclasses read identically.
    """

    def __init__(self):
        self._paramMap = {}
        self._defaultParamMap = {}
        self._init_mixin_defaults()

    @property
    def params(self):
        out = []
        for klass in type(self).__mro__:
            for name, val in vars(klass).items():
                if isinstance(val, Param):
                    out.append(val)
        return out

    def _param(self, name):
        for p in self.params:
            if p.name == name:
                return p
        raise KeyError(f"no param {name} on {type(self).__name__}")

    def _set(self, **kwargs):
        for name, value in kwargs.items():
            p = self._param(name)
            if value is not None and p.converter is not None:
                value = p.converter(value)
            self._paramMap[p] = value
        return self

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            self._defaultParamMap[self._param(name)] = value
        return self

    def isDefined(self, param):
        p = self._param(param) if isinstance(param, str) else param
        return p in self._paramMap or p in self._defaultParamMap

    def getOrDefault(self, param):
        p = self._param(param) if isinstance(param, str) else param
        if p in self._paramMap:
            return self._paramMap[p]
        return self._defaultParamMap[p]

    def extractParamMap(self):
        out = dict(self._defaultParamMap)
        out.update(self._paramMap)
        return out

    def copy(self, extra=None):
        dup = _copy.copy(self)
        dup._paramMap = dict(self._paramMap)
        dup._defaultParamMap = dict(self._defaultParamMap)
        for key, value in (extra or {}).items():
            # accept Param objects or plain names ({'epochs': 3})
            dup._set(**{key.name if isinstance(key, Param) else key: value})
        return dup

    def _init_mixin_defaults(self):
        """Install the default of every Has* mixin in this class's MRO.

        Called from Params.__init__ (the single init path — mixins define
        no __init__ of their own), so user subclasses with custom
        __init__ signatures are never re-invoked reflectively.
        """
        for klass in type(self).__mro__:
            pname = vars(klass).get("_mixin_param")
            if pname is not None:
                self._setDefault(**{pname: vars(klass)["_mixin_default"]})


def _mixin(name, doc, converter, default=None):
    """Build a Has<name> mixin with Param + setter/getter, mirroring the
    reference's ~20 hand-written mixins (pipeline.py:49-293).  Params
    declared without an explicit default get a default of None (unlike
    pyspark, getOrDefault on a fresh instance returns None, not raise)."""

    def snake_to_camel(s):
        return "".join(w.capitalize() for w in s.split("_"))

    param = Param(name, doc, converter)

    def _setter(self, value):
        return self._set(**{name: value})

    def _getter(self):
        return self.getOrDefault(name)

    cls = type(
        f"Has{snake_to_camel(name)}",
        (Params,),
        {
            name: param,
            "_mixin_param": name,
            "_mixin_default": default,
            f"set{snake_to_camel(name)}": _setter,
            f"get{snake_to_camel(name)}": _getter,
        },
    )
    return cls


HasBatchSize = _mixin("batch_size", "Number of records per batch", TypeConverters.toInt, 128)
HasClusterSize = _mixin("cluster_size", "Number of nodes in the cluster", TypeConverters.toInt, 1)
HasEpochs = _mixin("epochs", "Number of epochs to train", TypeConverters.toInt, 1)
HasGraceSecs = _mixin(
    "grace_secs",
    "Seconds to wait after feeding (for final tasks like model export)",
    TypeConverters.toInt, 30,
)
HasInputMapping = _mixin(
    "input_mapping", "Mapping of input column to input tensor", TypeConverters.toDict
)
HasInputMode = _mixin(
    "input_mode", "Input feeding mode (0=TENSORFLOW, 1=SPARK)", TypeConverters.toInt, 1
)
HasMasterNode = _mixin(
    "master_node", "Job name of the master/chief node", TypeConverters.toString, "chief"
)
HasModelDir = _mixin(
    "model_dir", "Path to save/load model checkpoints", TypeConverters.toString
)
HasExportDir = _mixin("export_dir", "Directory to export the model", TypeConverters.toString)
HasOutputMapping = _mixin(
    "output_mapping", "Mapping of output tensor to output column", TypeConverters.toDict
)
HasProtocol = _mixin(
    "protocol",
    "Network protocol (accepted for reference compat; data-plane is ICI/DCN)",
    TypeConverters.toString, "grpc",
)
HasReaders = _mixin("readers", "Number of reader/enqueue threads", TypeConverters.toInt, 1)
HasSteps = _mixin("steps", "Maximum number of steps to train", TypeConverters.toInt, 1000)
HasTensorboard = _mixin(
    "tensorboard", "Launch TensorBoard on the chief node", TypeConverters.toBoolean, False
)
HasTFRecordDir = _mixin(
    "tfrecord_dir",
    "Path to temporarily export a DataFrame as TFRecords (InputMode.TENSORFLOW apps)",
    TypeConverters.toString,
)
HasSignatureDefKey = _mixin(
    "signature_def_key",
    "Identifier of the exported predict function (overrides export metadata)",
    TypeConverters.toString,
)
HasTagSet = _mixin(
    "tag_set", "Comma-delimited tags identifying an export variant", TypeConverters.toString
)
HasNumPS = _mixin("num_ps", "Number of PS nodes in the cluster", TypeConverters.toInt, 0)
HasDriverPSNodes = _mixin(
    "driver_ps_nodes", "Run PS nodes on the driver", TypeConverters.toBoolean, False
)
HasNumChips = _mixin(
    "num_chips", "TPU chips claimed per executor (gpu-count analogue)",
    TypeConverters.toInt, 0,
)


class Namespace:
    """Dict / argv / argparse.Namespace unifier (pipeline.py:296-336).

    ``Namespace({'a': 1})``, ``Namespace(ns)``, ``Namespace(['--a','1'])``
    all expose attribute access plus ``argv`` round-tripping for user
    mains that re-parse ``sys.argv``.
    """

    def __init__(self, d=None, **kwargs):
        self.argv = None
        if isinstance(d, list):
            self.argv = list(d)
        elif isinstance(d, dict):
            self.__dict__.update(d)
        elif isinstance(d, Namespace):
            self.__dict__.update(vars(d))
            self.argv = d.argv
        elif isinstance(d, argparse.Namespace):
            self.__dict__.update(vars(d))
        elif d is not None:
            raise TypeError(f"unsupported args type: {type(d)}")
        self.__dict__.update(kwargs)

    def __contains__(self, key):
        return key in self.__dict__

    def __getitem__(self, key):
        return self.__dict__[key]

    def __iter__(self):
        return iter(self.__dict__)

    def items(self):
        return {k: v for k, v in self.__dict__.items() if k != "argv"}.items()

    def __repr__(self):
        return f"Namespace({self.__dict__})"


class TFParams(Params):
    """Shared behavior: fold current Param values over user args
    (pipeline.py:339-348; params win)."""

    args = None

    def merge_args_params(self):
        args = Namespace(self.args)
        for param, value in self.extractParamMap().items():
            setattr(args, param.name, value)
        return args


# ---------------------------------------------------------------------------
# Estimator
# ---------------------------------------------------------------------------

class TFEstimator(
    TFParams,
    HasBatchSize,
    HasClusterSize,
    HasEpochs,
    HasGraceSecs,
    HasInputMapping,
    HasInputMode,
    HasMasterNode,
    HasModelDir,
    HasExportDir,
    HasNumPS,
    HasDriverPSNodes,
    HasNumChips,
    HasProtocol,
    HasReaders,
    HasSteps,
    HasTensorboard,
    HasTFRecordDir,
):
    """Trains a model on a dataset and returns a TFModel
    (parity: pipeline.TFEstimator :351-432).

    ``train_fn(args, ctx)`` is the standard user main; ``export_fn`` is an
    optional driver-side post-export hook.
    """

    def __init__(self, train_fn, tf_args=None, export_fn=None):
        Params.__init__(self)
        self.train_fn = train_fn
        self.export_fn = export_fn
        self.args = Namespace(tf_args if tf_args is not None else {})

    def fit(self, dataset, params=None):
        if params:
            return self.copy(params).fit(dataset)
        return self._fit(dataset)

    def _fit(self, dataset):
        from tensorflowonspark_tpu import cluster as tfcluster

        args = self.merge_args_params()
        logger.info("fit: args=%s", args)

        engine, feed_ds = _dataset_and_engine(dataset)
        if args.input_mode == tfcluster.InputMode.TENSORFLOW:
            # export the dataset as TFRecords for direct-read mains
            # (parity: reference pipeline.py InputMode.TENSORFLOW branch)
            assert args.tfrecord_dir, (
                "InputMode.TENSORFLOW requires tfrecord_dir for temporary export"
            )
            from tensorflowonspark_tpu import dfutil

            logger.info("exporting dataset to %s", args.tfrecord_dir)
            dfutil.save_as_tfrecords(feed_ds, args.tfrecord_dir)
        elif getattr(args, "input_mapping", None):
            # order feed columns by *tensor name* so DataFeed's
            # sorted-by-tensor unpacking (feed.py) aligns by construction
            input_cols = [
                col for col, _t in sorted(args.input_mapping.items(),
                                          key=lambda kv: kv[1])
            ]
            feed_ds = _select_columns(feed_ds, input_cols)

        local_cluster = tfcluster.run(
            engine,
            self.train_fn,
            args,
            num_executors=args.cluster_size,
            num_ps=args.num_ps,
            driver_ps_nodes=args.driver_ps_nodes,
            tensorboard=args.tensorboard,
            input_mode=args.input_mode,
            master_node=args.master_node,
            num_chips=args.num_chips,
        )
        if args.input_mode == tfcluster.InputMode.SPARK:
            local_cluster.train(feed_ds, args.epochs)
        local_cluster.shutdown(grace_secs=args.grace_secs)

        if self.export_fn:
            assert args.export_dir, "export_fn requires export_dir"
            self.export_fn(args)

        # carry over shared params without clobbering TFModel-only params
        # (output_mapping / signature_def_key / tag_set keep their defaults)
        model = TFModel(self.args)
        model_params = {p.name for p in model.params}
        model._defaultParamMap.update(
            {p: v for p, v in self._defaultParamMap.items() if p.name in model_params}
        )
        model._paramMap.update(
            {p: v for p, v in self._paramMap.items() if p.name in model_params}
        )
        return model


# ---------------------------------------------------------------------------
# Model (cached single-node batch inference)
# ---------------------------------------------------------------------------

# per-python-worker model cache (parity: pipeline.py:492-496 globals)
_model_cache = {}


class TFModel(
    TFParams,
    HasBatchSize,
    HasInputMapping,
    HasOutputMapping,
    HasModelDir,
    HasExportDir,
    HasSignatureDefKey,
    HasTagSet,
):
    """Transforms a dataset with an exported model, one cached model per
    python worker (parity: pipeline.TFModel :435-489 + _run_model_tf2
    :585-644)."""

    def __init__(self, tf_args=None):
        Params.__init__(self)
        self.args = Namespace(tf_args if tf_args is not None else {})

    def transform(self, dataset, params=None):
        if params:
            return self.copy(params).transform(dataset)
        args = self.merge_args_params()
        assert getattr(args, "export_dir", None) or getattr(args, "model_dir", None), (
            "TFModel requires export_dir or model_dir"
        )
        logger.info("transform: args=%s", args)
        ds = _as_dataset(dataset)

        input_cols = sorted(args.input_mapping) if args.input_mapping else None
        if input_cols is not None:
            ds = _select_columns(ds, input_cols)
        return ds.map_partitions(_run_model(args))

    def as_service(self, num_replicas=None, watch_model_dir=True, **kw):
        """Turn this model into an online service (docs/serving.md).

        The online analogue of :meth:`transform`: the same export
        directory and predict resolution (``signature_def_key``
        override), served by ``num_replicas`` supervised replicas behind
        the micro-batcher.  ``watch_model_dir=True`` arms checkpoint
        hot-reload against ``model_dir`` when one is set.  Extra kwargs
        (``max_batch``, ``max_delay_ms``, ``queue_max``, ``engine``,
        ``env``) pass through to :class:`serving.Server`; the caller
        starts/stops the returned server.
        """
        from tensorflowonspark_tpu import serving

        args = self.merge_args_params()
        export_dir = getattr(args, "export_dir", None)
        model_dir = getattr(args, "model_dir", None)
        assert export_dir or model_dir, (
            "as_service requires export_dir or model_dir")
        spec = serving.ModelSpec(
            export_dir=export_dir,
            ckpt_dir=model_dir if watch_model_dir else None,
            predict=getattr(args, "signature_def_key", None),
        )
        return serving.Server(spec, num_replicas=num_replicas, **kw)


def _run_model(args):
    """Partition closure: cached model, batched predict
    (parity: _run_model_tf2, pipeline.py:585-644)."""

    def _predict_partition(iterator):
        import numpy as np

        # Resolve the cache through the imported module, NOT the closure:
        # cloudpickle ships this nested function by value with a *copied*
        # globals dict, so a closed-over _model_cache would be a fresh dict
        # in every deserialized task.  The worker's module singleton is the
        # only cache shared across partitions (parity: pipeline.py:492-496,
        # where _run_model is a top-level function pickled by reference).
        from tensorflowonspark_tpu import pipeline as _pipeline

        input_tensors = (
            [v for _, v in sorted(args.input_mapping.items())]
            if getattr(args, "input_mapping", None) else None
        )
        out_pairs = (
            sorted(args.output_mapping.items())
            if getattr(args, "output_mapping", None) else None
        )

        export_dir = getattr(args, "export_dir", None) or args.model_dir
        key = (export_dir, getattr(args, "signature_def_key", None))
        if key not in _pipeline._model_cache:
            _pipeline._model_cache[key] = _pipeline._load_predictor(export_dir, args)
            logger.info("loaded model %s into worker cache", key)
        predict, params = _pipeline._model_cache[key]

        from tensorflowonspark_tpu.recordio import marshal

        results = []
        for batch in yield_batch(iterator, args.batch_size):
            if input_tensors is None:
                inputs = {"inputs": np.asarray(batch)}
            else:
                # native row-batch -> dense-column marshalling (parity:
                # TFModel.scala:51-114 batch2tensors, compiled path)
                if batch and isinstance(batch[0], (tuple, list)):
                    cols = marshal.rows_to_columns(batch)
                else:
                    cols = (np.asarray(batch),)
                inputs = {t: cols[i] for i, t in enumerate(input_tensors)}
            n = len(batch)
            if n < args.batch_size and getattr(args, "pad_partial", True):
                # final partial batch: pad rows up to batch_size so the
                # jitted predict reuses the full-batch executable instead
                # of compiling a second shape (the serving bucket-pad
                # helper; padded rows are sliced back off below)
                from tensorflowonspark_tpu.serving import batcher as _b

                inputs = _b.pad_columns(inputs, args.batch_size)
            outputs = predict(params, inputs)
            if not isinstance(outputs, dict):
                name = out_pairs[0][0] if out_pairs else "outputs"
                outputs = {name: outputs}
            # mask padded rows: only the first n rows are real
            outputs = {k: np.asarray(v)[:n] for k, v in outputs.items()}
            for v in outputs.values():
                assert len(v) == n, f"output rows {len(v)} != input rows {n}"
            names = [t for t, _ in out_pairs] if out_pairs else sorted(outputs)
            out_names = [c for _, c in out_pairs] if out_pairs else names
            # dense columns -> rows (parity: TFModel.scala:121-239
            # tensors2batch, compiled path)
            row_tuples = marshal.columns_to_rows([outputs[t] for t in names])
            results.extend(
                dict(zip(out_names, row)) for row in row_tuples
            )
        return results

    return _predict_partition


def _load_predictor(export_dir, args):
    """Resolve (predict_fn, params) from an export directory.

    The export metadata's ``predict`` entry ("module:qualname", the
    SavedModel-signature analogue) is overridable by the
    ``signature_def_key`` param; the resolved callable receives
    ``(params, {tensor_name: ndarray})``.
    """
    import importlib

    from tensorflowonspark_tpu.utils.checkpoint import load_exported

    params, meta = load_exported(export_dir)
    spec = getattr(args, "signature_def_key", None) or meta.get("predict")
    if not spec:
        raise ValueError(
            f"export {export_dir} has no 'predict' metadata; set "
            "signature_def_key='module:function' on the TFModel"
        )
    mod_name, _, fn_name = spec.partition(":")
    fn = importlib.import_module(mod_name)
    for part in fn_name.split("."):
        fn = getattr(fn, part)
    return fn, params


def yield_batch(iterator, batch_size):
    """Group an iterator into lists of at most batch_size rows
    (parity: pipeline.yield_batch :688-710)."""
    batch = []
    for item in iterator:
        batch.append(item)
        if len(batch) >= batch_size:
            yield batch
            batch = []
    if batch:
        yield batch


# ---------------------------------------------------------------------------
# dataset plumbing
# ---------------------------------------------------------------------------

def _as_dataset(dataset):
    """Accept a framework Dataset, (engine, rows) pair, Spark DataFrame,
    or RDD; return just the Dataset (no engine construction)."""
    from tensorflowonspark_tpu.engine import as_dataset

    if isinstance(dataset, tuple) and len(dataset) == 2:
        engine, rows = dataset
        return engine.parallelize(rows) if isinstance(rows, list) else rows
    cls = type(dataset)
    if cls.__module__.startswith("pyspark.sql") and cls.__name__ == "DataFrame":
        dataset = dataset.rdd
    return as_dataset(dataset)


def _dataset_and_engine(dataset):
    """Like _as_dataset, but also build the engine that owns the dataset
    (fit needs it to launch the cluster)."""
    from tensorflowonspark_tpu.engine import LocalDataset, SparkEngine

    if isinstance(dataset, tuple) and len(dataset) == 2:
        engine, rows = dataset
        return engine, engine.parallelize(rows) if isinstance(rows, list) else rows
    ds = _as_dataset(dataset)
    if isinstance(ds, LocalDataset):
        return ds._engine, ds
    return SparkEngine(ds.rdd.context), ds


def _select_columns(ds, cols):
    """Project rows (dicts or Spark Rows) down to tuples of ``cols`` in
    order (parity: dataset.select(sorted(input_cols)).rdd,
    pipeline.py:411-413)."""

    def project(it):
        out = []
        for row in it:
            if isinstance(row, dict):
                out.append(tuple(row[c] for c in cols))
            elif hasattr(row, "asDict"):
                d = row.asDict()
                out.append(tuple(d[c] for c in cols))
            elif isinstance(row, (tuple, list)) and len(row) == len(cols):
                # already projected/ordered by the caller
                out.append(tuple(row))
            else:
                raise TypeError(
                    f"cannot project columns {cols} from row {row!r}; "
                    "rows must be dicts, Rows, or pre-ordered tuples of "
                    "matching arity"
                )
        return out

    return ds.map_partitions(project)
