"""Attention ops: flash attention (pallas, online softmax) + XLA reference.

Layout convention: ``[batch, seq, heads, head_dim]`` at the API boundary
(the natural layout for sequence-sharded meshes — the seq axis is axis 1
everywhere, so a NamedSharding P(None, 'sp', None, None) applies to q/k/v
alike).  The kernel internally flattens to ``[batch*heads, seq, head_dim]``
and tiles seq onto the MXU.

The pallas kernel computes softmax(q kᵀ·scale + mask) v blockwise with the
online-softmax recurrence (running max / running sum / rescaled
accumulator), so the [S, S] score matrix never materializes in HBM —
memory is O(block_q · seq) VMEM per program instead of O(seq²).  The
backward pass recomputes attention blockwise under ``jax.checkpoint``
semantics via a custom VJP over the reference implementation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


# -- rotary position embeddings ----------------------------------------------

def rope_angles(seq_len, head_dim, base=10000.0, dtype=jnp.float32):
    """(cos, sin) tables of shape [seq_len, head_dim//2]."""
    half = head_dim // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    ang = jnp.outer(pos, freqs)
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """Rotate [B, S, H, D] by the (cos, sin) tables.

    ``positions`` ([B, S] int) selects rows of the tables — used by
    sequence-parallel shards whose local positions are offset into the
    global sequence.
    """
    if positions is not None:
        cos = cos[positions]  # [B, S, half]
        sin = sin[positions]
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, : x.shape[1], None, :]
        sin = sin[None, : x.shape[1], None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# -- reference implementation (pure XLA) -------------------------------------

def mha_reference(q, k, v, *, causal=False, scale=None, q_offset=0, kv_offset=0):
    """Full-materialization attention; [B, S, H, D] in/out.

    ``q_offset``/``kv_offset`` shift the causal mask's global positions —
    the hook ring attention uses to attend a local q shard against a
    remote k/v shard (parallel/ring.py).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # [B, H, Sq, Skv]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = kv_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(q.dtype)


# -- pallas flash attention ---------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, seq_q, seq_kv,
                  block_q, block_kv, scale, causal):
    """One program of grid (B*H, num_q_blocks): one [block_q, D] q tile
    against the whole (masked) kv range."""
    import jax.experimental.pallas as pl

    q_blk = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    head_dim = q_blk.shape[-1]
    q_start = pl.program_id(1) * block_q

    num_kv = pl.cdiv(seq_kv, block_kv)
    if causal:
        # blocks strictly above the diagonal contribute nothing; the
        # dynamic fori bound trims them (the loop body stays static).
        num_kv = lax.min(
            num_kv, lax.div(q_start + block_q + block_kv - 1, block_kv)
        )

    def body(j, carry):
        acc, m, l = carry
        kv_start = j * block_kv
        k_blk = k_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        s = jnp.dot(q_blk, k_blk.T, preferred_element_type=jnp.float32)
        # tail masking (seq not a multiple of block) + causal masking, on
        # global positions
        qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        kpos = kv_start + lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        valid = (kpos < seq_kv) & (qpos < seq_q)
        if causal:
            valid &= qpos >= kpos
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = lax.fori_loop(0, num_kv, body, (acc0, m0, l0))
    # fully-masked rows (tail padding) have l == 0; avoid 0/0
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    # log-sum-exp per row, saved for the O(S*block) backward; trailing
    # singleton keeps the block TPU-tileable (block_q x 1 vs the (8,128)
    # divisibility rule)
    lse_ref[0, :, 0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _flash_forward(q, k, v, *, causal, scale, block_q, block_kv, interpret):
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, max(sq, 8))
    block_kv = min(block_kv, max(skv, 8))

    def flat(x):  # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = flat(q), flat(k), flat(v)
    pad_q = (-sq) % block_q
    pad_kv = (-skv) % block_kv
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_kv:
        kf = jnp.pad(kf, ((0, 0), (0, pad_kv), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_kv), (0, 0)))

    grid = (b * h, (sq + pad_q) // block_q)
    kernel = functools.partial(
        _flash_kernel,
        seq_q=sq,
        seq_kv=skv,
        block_q=block_q,
        block_kv=block_kv,
        scale=scale,
        causal=causal,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, skv + pad_kv, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, skv + pad_kv, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sq + pad_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq + pad_q, 1), jnp.float32),
        ),
        interpret=interpret,
    )(qf, kf, vf)
    out = out[:, :sq].reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :sq, 0].reshape(b, h, sq)  # [B, H, Sq]
    return out, lse


def _bwd_recompute(q_blk, k_blk, v_blk, g_blk, lse, delta, q_start,
                   kv_start, *, seq_q, seq_kv, scale, causal):
    """Shared backward recompute for one (q block, kv block) pair:
    probabilities from (q, k, lse) and the score gradient
        p  = exp(q kᵀ·scale − lse)        (masked)
        ds = p · (g vᵀ − delta) · scale
    Both kernels MUST use this — a masking/math fix applied to one of
    dq vs dk/dv only would silently desynchronize the gradients."""
    block_q, block_kv = q_blk.shape[0], k_blk.shape[0]
    s = jnp.dot(q_blk, k_blk.T, preferred_element_type=jnp.float32) * scale
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    kpos = kv_start + lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    valid = (kpos < seq_kv) & (qpos < seq_q)
    if causal:
        valid &= qpos >= kpos
    p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
    dp = jnp.dot(g_blk, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, *,
               seq_q, seq_kv, block_q, block_kv, scale, causal):
    """dq for one [block_q, D] q tile: loop kv blocks (causal-trimmed,
    like the forward), recomputing p from (q, k, lse):
        p = exp(q kᵀ·scale − lse);  ds = p·(g vᵀ − delta)·scale;
        dq = ds k
    """
    import jax.experimental.pallas as pl

    q_blk = q_ref[0].astype(jnp.float32)          # [bq, D]
    g_blk = g_ref[0].astype(jnp.float32)          # [bq, D]
    lse = lse_ref[0, :, 0]                        # [bq]
    delta = delta_ref[0, :, 0]                    # [bq]
    head_dim = q_blk.shape[-1]
    q_start = pl.program_id(1) * block_q

    num_kv = pl.cdiv(seq_kv, block_kv)
    if causal:
        num_kv = lax.min(
            num_kv, lax.div(q_start + block_q + block_kv - 1, block_kv)
        )

    def body(j, dq):
        kv_start = j * block_kv
        k_blk = k_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        _, ds = _bwd_recompute(
            q_blk, k_blk, v_blk, g_blk, lse, delta, q_start, kv_start,
            seq_q=seq_q, seq_kv=seq_kv, scale=scale, causal=causal)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(
        0, num_kv, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, *, seq_q, seq_kv, block_q, block_kv, scale, causal):
    """dk/dv for one [block_kv, D] kv tile: loop q blocks starting at the
    diagonal (causal lower bound — above-diagonal q blocks see none of
    this kv tile):
        dv += pᵀ g;  dk += dsᵀ q
    """
    import jax.experimental.pallas as pl

    k_blk = k_ref[0].astype(jnp.float32)          # [bkv, D]
    v_blk = v_ref[0].astype(jnp.float32)          # [bkv, D]
    head_dim = k_blk.shape[-1]
    kv_start = pl.program_id(1) * block_kv

    num_q = pl.cdiv(seq_q, block_q)
    i0 = lax.div(kv_start, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_start = i * block_q
        q_blk = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        g_blk = g_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(q_start, block_q), 0]
        delta = delta_ref[0, pl.ds(q_start, block_q), 0]
        p, ds = _bwd_recompute(
            q_blk, k_blk, v_blk, g_blk, lse, delta, q_start, kv_start,
            seq_q=seq_q, seq_kv=seq_kv, scale=scale, causal=causal)
        dv = dv + jnp.dot(p.T, g_blk, preferred_element_type=jnp.float32)
        dk = dk + jnp.dot(ds.T, q_blk, preferred_element_type=jnp.float32)
        return dk, dv

    zeros = jnp.zeros((block_kv, head_dim), jnp.float32)
    dk, dv = lax.fori_loop(i0, num_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward_pallas(q, k, v, out, lse, g, *, causal, scale, block_q,
                           block_kv, interpret):
    """Blockwise pallas backward: dq over q tiles (kv loop trimmed above
    the diagonal) + dk/dv over kv tiles (q loop started at the diagonal)
    — the causal triangle is never computed, unlike the XLA fallback
    which computes and masks it (~2x the attention-backward FLOPs at
    long seq)."""
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, max(sq, 8))
    block_kv = min(block_kv, max(skv, 8))

    def flat(x):  # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf, gf, of = flat(q), flat(k), flat(v), flat(g), flat(out)
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B*H, Sq, 1]
    lsef = lse.reshape(b * h, sq, 1)

    pad_q = (-sq) % block_q
    pad_kv = (-skv) % block_kv
    if pad_q:
        zq = ((0, 0), (0, pad_q), (0, 0))
        qf, gf = jnp.pad(qf, zq), jnp.pad(gf, zq)
        lsef, delta = jnp.pad(lsef, zq), jnp.pad(delta, zq)
    if pad_kv:
        zkv = ((0, 0), (0, pad_kv), (0, 0))
        kf, vf = jnp.pad(kf, zkv), jnp.pad(vf, zkv)

    sq_p, skv_p = sq + pad_q, skv + pad_kv
    common = dict(seq_q=sq, seq_kv=skv, block_q=block_q,
                  block_kv=block_kv, scale=scale, causal=causal)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(b * h, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(b * h, skv_p // block_kv),
        in_specs=[
            pl.BlockSpec((1, sq_p, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, sq_p, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, 1), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, 1), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_kv, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bh, j: (bh, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, skv_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, skv_p, d), v.dtype),
        ),
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, delta)

    def unflat(x, s):  # [B*H, S, D] -> [B, S, H, D]
        return x[:, :s].reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return unflat(dq, sq), unflat(dk, skv), unflat(dv, skv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_kv, interpret, bwd_impl):
    out, _lse = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_kv=block_kv, interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
               bwd_impl):
    out, lse = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_kv=block_kv, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_kv, interpret, bwd_impl, res, g):
    if bwd_impl == "pallas":
        q, k, v, out, lse = res
        return _flash_backward_pallas(
            q, k, v, out, lse, g, causal=causal, scale=scale,
            block_q=block_q, block_kv=block_kv, interpret=interpret,
        )
    return _flash_bwd_xla(causal, scale, block_q, block_kv, res, g)


def _flash_bwd_xla(causal, scale, block_q, block_kv, res, g):
    """Blockwise flash backward (pure XLA, lax.scan over q blocks).

    Memory is O(block_q * S_kv) per step instead of the O(S^2) score
    matrix a naive softmax backward materializes — per-block scores are
    recomputed from (q, k) and renormalized with the saved logsumexp:
        p   = exp(s - lse)
        dv += p^T g
        ds  = p * (g v^T - rowsum(g * out))
        dq  = scale * ds k ;  dk += scale * ds^T q
    """
    q, k, v, out, lse = res
    b, sq, h, d = q.shape
    skv = k.shape[1]
    block = min(block_q, max(sq, 8))
    pad_q = (-sq) % block
    nb = (sq + pad_q) // block

    def heads(x):  # [B, S, H, D] -> [B, H, S, D] f32
        return x.transpose(0, 2, 1, 3).astype(jnp.float32)

    qt, gt, ot = heads(q), heads(g), heads(out)
    kt, vt = heads(k), heads(v)
    delta = jnp.sum(gt * ot, axis=-1)  # [B, H, Sq]

    def padq(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, pad_q)) + ((0, 0),) * (x.ndim - 3))

    # stack q blocks on a leading scan axis: [nb, B, H, block, ...]
    qb = padq(qt).reshape(b, h, nb, block, d).transpose(2, 0, 1, 3, 4)
    gb = padq(gt).reshape(b, h, nb, block, d).transpose(2, 0, 1, 3, 4)
    lseb = padq(lse).reshape(b, h, nb, block).transpose(2, 0, 1, 3)
    deltab = padq(delta).reshape(b, h, nb, block).transpose(2, 0, 1, 3)
    qpos = jnp.pad(jnp.arange(sq), (0, pad_q), constant_values=-1).reshape(
        nb, block
    )
    kpos = jnp.arange(skv)

    def body(carry, xs):
        dk_acc, dv_acc = carry
        q_i, g_i, lse_i, delta_i, qpos_i = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", q_i, kt) * scale
        valid = (qpos_i[:, None] >= 0) & (kpos[None, :] < skv)
        if causal:
            valid &= qpos_i[:, None] >= kpos[None, :]
        p = jnp.where(valid[None, None], jnp.exp(s - lse_i[..., None]), 0.0)
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, g_i)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_i, vt)
        ds = p * (dp - delta_i[..., None]) * scale
        dq_i = jnp.einsum("bhqk,bhkd->bhqd", ds, kt)
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, q_i)
        return (dk_acc, dv_acc), dq_i

    zeros = jnp.zeros((b, h, skv, d), jnp.float32)
    (dk, dv), dq_blocks = lax.scan(
        body, (zeros, zeros), (qb, gb, lseb, deltab, qpos)
    )
    dq = dq_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, nb * block, d)
    dq = dq[:, :, :sq]

    def unheads(x, like):  # [B, H, S, D] -> [B, S, H, D] in input dtype
        return x.transpose(0, 2, 1, 3).astype(like.dtype)

    return unheads(dq, q), unheads(dk, k), unheads(dv, v)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal=False, scale=None, block_q=512,
                    block_kv=512, interpret=None, bwd_impl="xla"):
    """Flash attention on [B, S, H, D]; differentiable.

    ``interpret=None`` auto-selects: compiled pallas on TPU, interpreter
    mode elsewhere (CPU tests / virtual-device meshes).

    ``bwd_impl``: "xla" (default — blockwise scan, computes-then-masks
    the causal triangle) or "pallas" (dq/dkv kernels whose block loops
    are trimmed at the diagonal, skipping ~half the causal backward
    FLOPs at long seq; numerics identical, see tests).

    Defaults tuned on v5e (B=4, S=2048, H=8, D=128: 512/512 is ~4x the
    128/128 throughput).  The kernel keeps the full k/v sequence of one
    head in VMEM, so S*D*4 bytes must stay well under the ~16MB budget —
    beyond ~32k tokens at D=128, shard the sequence (parallel/ring.py)
    or shrink block_kv.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if bwd_impl not in ("xla", "pallas"):
        raise ValueError(f"bwd_impl must be 'xla' or 'pallas', "
                         f"got {bwd_impl!r}")
    return _flash(q, k, v, causal, scale, block_q, block_kv, interpret,
                  bwd_impl)
