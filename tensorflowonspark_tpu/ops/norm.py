"""Fused RMSNorm: pallas kernel + XLA reference.

RMSNorm is the transformer's bandwidth-bound elementwise hot op; the
fused kernel keeps the activation in VMEM for the reduce + scale instead
of two HBM round trips.  Differentiable via custom VJP that recomputes
through the reference formulation (cheap: O(N) recompute).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def rmsnorm_reference(x, scale, eps=1e-6):
    xf = x.astype(jnp.float32)
    rms = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms * scale.astype(jnp.float32)).astype(x.dtype)


def _rmsnorm_kernel(x_ref, s_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    rms = lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * rms * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_forward(x, scale, eps, block_rows, interpret):
    import jax.experimental.pallas as pl

    shape = x.shape
    dim = shape[-1]
    x2 = x.reshape(-1, dim)
    rows = x2.shape[0]
    block_rows = min(block_rows, rows)
    pad = (-rows) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=((rows + pad) // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
            pl.BlockSpec((dim,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, scale)
    return out[:rows].reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm(x, scale, eps, block_rows, interpret):
    return _rmsnorm_forward(x, scale, eps, block_rows, interpret)


def _rmsnorm_fwd(x, scale, eps, block_rows, interpret):
    return _rmsnorm(x, scale, eps, block_rows, interpret), (x, scale)


def _rmsnorm_bwd(eps, block_rows, interpret, res, g):
    x, scale = res
    _, vjp = jax.vjp(lambda x_, s_: rmsnorm_reference(x_, s_, eps), x, scale)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def fused_rmsnorm(x, scale, eps=1e-6, block_rows=256, interpret=None):
    """RMSNorm over the last axis; any leading shape; differentiable."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _rmsnorm(x, scale, eps, block_rows, interpret)
