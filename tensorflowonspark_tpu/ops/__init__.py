"""TPU kernel library (pallas) + XLA reference implementations.

The reference framework has no ops layer at all — TensorFlow is its
compute substrate (SURVEY.md §1 "TFoS has no kernel/ops layer").  In a
TPU-native framework the hot ops are first-party: flash attention for
the transformer/long-context path and fused normalization, written in
pallas against the MXU/VMEM model (/opt/skills/guides/pallas_guide.md),
with pure-XLA reference implementations used for verification and as
the CPU fallback.
"""

from tensorflowonspark_tpu.ops.attention import (  # noqa: F401
    apply_rope,
    flash_attention,
    mha_reference,
    rope_angles,
)
from tensorflowonspark_tpu.ops.norm import (  # noqa: F401
    fused_rmsnorm,
    rmsnorm_reference,
)
