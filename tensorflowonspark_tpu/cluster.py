"""Driver-side cluster orchestration (parity: reference TFCluster.py).

``run()`` computes the cluster template (which executor plays ps / chief /
evaluator / worker), starts the rendezvous server, launches one node per
executor on a background thread, waits for all registrations, and returns
a ``TFCluster`` handle with ``train`` / ``inference`` / ``shutdown``.

TPU-native notes:
- The rendezvous output is JAX-distributed bootstrap info (coordinator
  address + process ids), not a TF_CONFIG (node.py).
- ``num_chips`` is the per-executor TPU chip claim (the `num_gpus`
  analogue).
- ps/evaluator roles are preserved as API and lifecycle (background
  process + driver-controlled stop) even though parameter-server training
  is not idiomatic on TPU; SPMD jobs simply run with num_ps=0.
"""

from __future__ import annotations

import logging
import os
import random
import secrets
import sys
import threading
import time

from tensorflowonspark_tpu import engine as engine_mod
from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu import node, rendezvous
from tensorflowonspark_tpu.utils import metrics_registry, telemetry

logger = logging.getLogger(__name__)


class InputMode:
    """How the training job ingests data (parity: TFCluster.py:43-46)."""

    TENSORFLOW = 0  #: nodes read their own data (files, tfds, ...)
    SPARK = 1       #: engine partitions are fed through executor queues


# driver-side status shared with the launcher thread (TFCluster.py:40)
tf_status = {}


class TFCluster:
    sc = None
    engine = None
    meta = None
    nodes = None
    cluster_info = None
    cluster_meta = None
    input_mode = None
    queues = None
    server = None
    restarts = 0
    min_executors = 0
    _restarts_used = 0
    _node_fn = None
    _nodes_ds = None
    _node_ids = None
    _all_node_ids = None
    _template0 = None

    def train(self, dataset, num_epochs=1, feed_timeout=600, qname="input"):
        """Feed a dataset into the cluster (parity: TFCluster.train :63-94).

        Epochs are realized by unioning the dataset with itself — the exact
        reference mechanism (TFCluster.py:88-93).

        With ``run(..., restarts=N)`` this call supervises the whole job:
        a lost worker fails the feed job, which triggers recovery —
        quiesce survivors, bump the cluster epoch, relaunch nodes on a
        replenished executor pool, and re-feed only the partitions the
        rendezvous ledger has not recorded as fully consumed (trainers
        resume from their latest checkpoint via
        ``ctx.restore_latest``) — up to N times before the error
        propagates.
        """
        logger.info("feeding training data")
        assert self.input_mode == InputMode.SPARK, "train() requires InputMode.SPARK"
        if hasattr(dataset, "_blocks") and hasattr(dataset, "chunks"):
            # a data.Pipeline: serve it through the disaggregated data
            # service (docs/data.md) instead of per-partition feeders
            return self._train_data_service(dataset, num_epochs,
                                            feed_timeout, qname)
        if hasattr(dataset, "foreachRDD"):
            # Spark Streaming DStream (parity: TFCluster.py:83-85): every
            # micro-batch RDD's partitions are fed through the same
            # feeder closure; stop via DataFeed.terminate -> STOP ->
            # shutdown(ssc=...)'s awaitTerminationOrTimeout loop.
            feeder = node.train(
                self.cluster_info, self.cluster_meta, feed_timeout, qname
            )
            dataset.foreachRDD(lambda rdd: rdd.foreachPartition(feeder))
            return
        ds = engine_mod.as_dataset(dataset)
        assert num_epochs >= 0, "num_epochs cannot be negative"
        if num_epochs > 1:
            ds = ds.union(*[ds for _ in range(num_epochs - 1)])
        # this job's consumption ledger starts empty: partitions consumed
        # by a previous train() on this cluster must not be skipped here
        self.server.reset_feed(qname)
        while True:
            # partitions fully consumed before a mid-job failure are not
            # re-fed after recovery (exactly-once per partition)
            done = set(self.server.fed_partitions(qname))
            if done:
                logger.info("resuming feed: %d partitions already "
                            "consumed: %s", len(done), sorted(done))
            feeder = node.train(self.cluster_info, self.cluster_meta,
                                feed_timeout, qname, skip=done)
            # spread=True round-robins partitions across executors so SPMD
            # consumers see balanced feeds (uneven feeds would stall the
            # synchronous gradient all-reduce; cf. the reference's "90% of
            # steps" workaround, examples/mnist/keras/mnist_spark.py:58-66).
            try:
                ds.foreach_partition(feeder, spread=True,
                                     retryable=self.restarts > 0)
                return
            except (engine_mod.TaskError, RuntimeError, TimeoutError) as e:
                if self._restarts_used >= self.restarts:
                    raise
                self._recover(e)

    def _train_data_service(self, pipeline, num_epochs, feed_timeout,
                            qname):
        """Feed trainers from a ``data.Pipeline`` via the data service:
        ``data_workers`` engine tasks each run the pipeline and push its
        per-trainer shard over the feed wire (data/service.py).  The
        same supervision contract as feeder-mode ``train()``: a task or
        worker failure triggers recovery up to ``restarts`` times, and
        re-served streams resume at the per-trainer unit ledger instead
        of re-feeding consumed data.

        Dispatch is **dynamic** (FCFS split dispatch, data/splits.py) by
        default; ``TFOS_DATA_DISPATCH=static`` or
        ``run(..., data_dispatch="static")`` selects the rank-strided
        static sharding this method body implements."""
        from tensorflowonspark_tpu.data import service as data_service

        if data_service.dispatch_mode(self.meta) == "dynamic":
            return self._train_data_service_dynamic(
                pipeline, num_epochs, feed_timeout, qname)
        n_workers = int(self.meta.get("data_workers") or
                        data_service.default_workers())
        assert num_epochs >= 0, "num_epochs cannot be negative"
        if num_epochs > 1:
            pipeline = pipeline.repeat(num_epochs)
        # this job's unit ledgers start empty (cf. reset_feed in train())
        for rank, _m in data_service.trainer_ranks(self.cluster_info):
            self.server.reset_feed(data_service.ledger_feed(qname, rank))
        logger.info("data service: %d worker task(s) feeding %d trainers",
                    n_workers,
                    len(data_service.trainer_ranks(self.cluster_info)))
        while True:
            fn = data_service.serve_task(
                pipeline, self.cluster_info, self.cluster_meta,
                qname=qname, num_workers=n_workers,
                feed_timeout=feed_timeout)
            try:
                self.engine.parallelize(
                    list(range(n_workers)), n_workers
                ).foreach_partition(fn, spread=True,
                                    retryable=self.restarts > 0)
                return
            except (engine_mod.TaskError, RuntimeError, TimeoutError) as e:
                if self._restarts_used >= self.restarts:
                    raise
                self._recover(e)

    def _train_data_service_dynamic(self, pipeline, num_epochs,
                                    feed_timeout, qname):
        """Dynamic-dispatch data service (the FCFS redesign of
        ``_train_data_service``; docs/data.md "Dynamic sharding").

        Per attempt: a fresh driver-side ``ActorSystem`` hosts the split
        board (its manager KV/queues) and the supervised
        ``SplitProvider`` actor; ``data_workers`` dynamic worker tasks
        claim splits from it and push to whichever of their trainers is
        least backlogged.  Exactly-once is per split id on the durable
        ``split_feed`` rendezvous ledger — a recovery attempt spins up a
        new board, and the provider re-posts only what the ledger is
        missing.  When ``TFOS_DATA_MAX_WORKERS`` allows headroom, a
        stall-driven autoscaler (data/autoscale.py) adds/retires worker
        tasks by editing the board plan."""
        from tensorflowonspark_tpu.actors.runtime import ActorSystem
        from tensorflowonspark_tpu.data import autoscale as data_autoscale
        from tensorflowonspark_tpu.data import service as data_service
        from tensorflowonspark_tpu.data import splits as data_splits

        n_workers = int(self.meta.get("data_workers") or
                        data_service.default_workers())
        assert num_epochs >= 0, "num_epochs cannot be negative"
        num_epochs = max(1, int(num_epochs))
        n_trainers = len(data_service.trainer_ranks(self.cluster_info))
        # this job's split ledger starts empty (cf. reset_feed in train())
        self.server.reset_feed(data_splits.split_feed(qname))
        try:
            max_workers = int(
                os.environ.get(data_autoscale.MAX_WORKERS_ENV, "0"))
        except ValueError:
            max_workers = 0
        max_workers = max(n_workers, max_workers)
        try:
            window = int(os.environ.get(data_splits.WINDOW_ENV, "0"))
        except ValueError:
            window = 0
        window = window or max(16, 4 * max(1, n_trainers))
        logger.info("data service (dynamic): %d worker task(s) feeding "
                    "%d trainers, split window %d, max workers %d",
                    n_workers, n_trainers, window, max_workers)
        while True:
            system = ActorSystem(capacity=1)
            scaler = None
            try:
                board = data_splits.SplitBoard(system._mgr, qname)
                board.set_plan(range(n_workers))
                system.spawn(
                    data_splits.SplitProvider(
                        qname,
                        server_addr=self.cluster_meta["server_addr"],
                        num_epochs=num_epochs, window=window),
                    "split-provider")
                meta = dict(self.cluster_meta)
                meta[data_service.SPLIT_BOARD_META] = {
                    "address": tuple(system._mgr.address),
                    "authkey": system._authkey,
                }
                fn = data_service.dynamic_serve_task(
                    pipeline, self.cluster_info, meta, qname=qname,
                    feed_timeout=feed_timeout)
                if max_workers > n_workers:
                    scaler = self._start_data_autoscaler(
                        board, fn, n_workers, max_workers)
                self.engine.parallelize(
                    list(range(n_workers)), n_workers
                ).foreach_partition(fn, spread=True,
                                    retryable=self.restarts > 0)
                return
            except (engine_mod.TaskError, RuntimeError, TimeoutError) as e:
                if self._restarts_used >= self.restarts:
                    raise
                self._recover(e)
            finally:
                if scaler is not None:
                    scaler.stop()
                system.stop()

    def _start_data_autoscaler(self, board, serve_fn, n_workers,
                               max_workers):
        """Wire a ``StallAutoscaler`` to this cluster: the stall signal
        is the trainers' published feed-wait counters (read through
        their executor managers); scale-up launches one more dynamic
        worker task and grows the board plan, scale-down shrinks the
        plan (the worker drains and exits on its own)."""
        from tensorflowonspark_tpu.data import autoscale as data_autoscale
        from tensorflowonspark_tpu.data import service as data_service

        mgrs = {}

        def _snapshots():
            out = {}
            for rank, m in data_service.trainer_ranks(self.cluster_info):
                try:
                    mgr = mgrs.get(rank)
                    if mgr is None:
                        mgr = mgrs[rank] = node._get_manager(
                            self.cluster_info, m["host"],
                            m["executor_id"])
                    for k, v in mgr.obs_snapshots().items():
                        out[f"{rank}:{k}"] = v
                except Exception:  # noqa: BLE001 - trainer mid-restart
                    mgrs.pop(rank, None)
            return out

        def _scale_up(widx):
            board.set_plan(board.plan() + [widx])
            threading.Thread(
                target=lambda: self.engine.parallelize(
                    [widx], 1).foreach_partition(serve_fn),
                name=f"tfos-data-scale-{widx}", daemon=True).start()

        def _scale_down(widx):
            board.set_plan([w for w in board.plan() if w != widx])

        return data_autoscale.StallAutoscaler(
            data_autoscale.obs_stall_reader(_snapshots),
            _scale_up, _scale_down,
            min_workers=n_workers, max_workers=max_workers).start()

    def _spawn_launcher(self):
        """(Re)launch the node job on a background thread
        (TFCluster.py:317-334); also the relaunch half of recovery."""

        def _launch():
            try:
                self._nodes_ds.foreach_partition(
                    self._node_fn, placement=self._node_ids,
                    retryable=self.restarts > 0)
            except Exception as e:  # noqa: BLE001
                logger.exception("node launch failed")
                tf_status["error"] = str(e)

        t = threading.Thread(target=_launch, daemon=True,
                             name="tfos-launcher")
        t.start()
        return t

    def _recover(self, err):
        """One supervised restart: tear the failed incarnation down and
        bring up the next epoch (SURVEY.md §5 'restart job from
        checkpoint', made automatic).

        Order matters: (1) quiesce every surviving node — state ->
        terminating, poison its error queue so orphan feeders still
        blocked in await-consumption fail out and release their executor
        slots, kill the background trainer; (2) respawn dead executors so
        the relaunch sees a full pool — or, with ``min_executors=k``
        elastic supervision, re-form the cluster over the surviving pool
        when the heal falls short (the resize half of docs/elastic.md);
        (3) bump the epoch on the rendezvous server BEFORE joining the
        old launcher, so any stale in-flight re-registration REJECTS
        instead of contaminating the new reservation table; (4) relaunch
        and await the new incarnation."""
        self._restarts_used += 1
        epoch = int(self.meta.get("epoch", 0)) + 1
        telemetry.event("cluster/recover_begin", epoch=epoch,
                        restart=self._restarts_used,
                        restarts=self.restarts, error=str(err)[:400])
        logger.warning(
            "cluster failure (%s); recovery %d/%d -> epoch %d",
            str(err)[:200], self._restarts_used, self.restarts, epoch)
        with telemetry.span("cluster/recover", epoch=epoch,
                            restart=self._restarts_used):
            for m in self.cluster_info:
                _quiesce_node(m)
            heal_err = None
            if hasattr(self.engine, "ensure_executors"):
                try:
                    self.engine.ensure_executors()
                except Exception as e:  # noqa: BLE001 - budget exhausted
                    if not self.min_executors:
                        raise
                    heal_err = e
                    logger.warning(
                        "pool heal failed (%s); proceeding elastically "
                        "over the surviving executors", str(e)[:200])
            if self.min_executors:
                alive = self._alive_node_ids()
                if len(alive) < self.min_executors:
                    raise RuntimeError(
                        f"elastic recovery impossible: {len(alive)} "
                        f"executor(s) survive, min_executors="
                        f"{self.min_executors}") from (heal_err or err)
                if set(alive) != set(self._node_ids):
                    self._resize_cluster(alive)
            self.meta["epoch"] = epoch  # node closures read this dict
            self.server.reset(epoch)
            if self._launcher is not None:
                self._launcher.join(timeout=60)
                if self._launcher.is_alive():
                    logger.warning(
                        "old launcher still running after 60s; relaunching "
                        "anyway (stale registrations are epoch-fenced)")
            tf_status.pop("error", None)
            self._launcher = self._spawn_launcher()
            self.cluster_info = _await_cluster(
                self.server, tf_status,
                self.meta.get("reservation_timeout", 600))
        telemetry.event("cluster/recover_done", epoch=epoch,
                        nodes=len(self.cluster_info))
        logger.info("recovery complete: epoch %d with %d nodes",
                    epoch, len(self.cluster_info))

    def _alive_node_ids(self):
        """Engine-hosted node ids still backed by a live executor,
        computed against the ORIGINAL id set so a healed pool re-grows
        the cluster instead of staying shrunk.  Engines that cannot
        report liveness (sparkstub, pyspark) fall back to the current
        rigid id list — elastic resize then never triggers."""
        alive_fn = getattr(self.engine, "alive_executors", None)
        if alive_fn is None:
            return list(self._node_ids)
        alive = set(alive_fn())
        return sorted(i for i in self._all_node_ids if i in alive)

    def _resize_cluster(self, alive_ids):
        """Re-form the cluster template over ``alive_ids`` (shrink after
        an unhealable loss, or re-grow after the pool came back).  The
        node closures observe the change through ``cluster_meta`` — the
        same mutated dict they captured at launch — and the rendezvous
        server's reservation count moves BEFORE the epoch reset so the
        next incarnation awaits exactly the surviving nodes."""
        template = _elastic_template(self._template0, alive_ids)
        old_n = len(self._node_ids)
        self.meta["cluster_template"] = template
        self.meta["num_executors"] = len(alive_ids)
        self._node_ids = sorted(alive_ids)
        retire = getattr(self.engine, "retire_executors", None)
        if retire is not None:
            # dead slots leave the engine's dispatch pool too, so re-fed
            # spread jobs land only on the surviving executors (and a
            # re-grow to the full pool un-retires everything)
            retire(sorted(set(self._all_node_ids) - set(alive_ids)))
        self._nodes_ds = self.engine.parallelize(
            self._node_ids, len(self._node_ids))
        self.server.resize(len(self._node_ids))
        telemetry.event("cluster/resize", from_nodes=old_n,
                        to_nodes=len(self._node_ids),
                        template={k: list(v) for k, v in template.items()})
        metrics_registry.inc("tfos_elastic_resizes_total", scope="cluster")
        logger.warning("elastic resize: %d -> %d node(s), template %s",
                       old_n, len(self._node_ids), template)

    def train_stream(self, stream, feed_timeout=600, qname="input"):
        """Feed a streaming source: an iterable of datasets (micro-batches).

        Parity: DStream.foreachRDD feeding (TFCluster.py:83-85).  Stops
        gracefully when a consumer calls ``DataFeed.terminate()`` (which
        makes a feeder send STOP to the rendezvous server).
        """
        assert self.input_mode == InputMode.SPARK
        for micro in stream:
            if self.server.done.is_set():
                logger.info("train_stream: STOP received, ending stream feed")
                break
            ds = engine_mod.as_dataset(micro)
            ds.foreach_partition(
                node.train(self.cluster_info, self.cluster_meta, feed_timeout, qname)
            )

    def inference(self, dataset, feed_timeout=600, qname="input"):
        """Map a dataset through the cluster for predictions (lazy)
        (parity: TFCluster.inference :96-115)."""
        logger.info("feeding inference data")
        assert self.input_mode == InputMode.SPARK, "inference() requires InputMode.SPARK"
        ds = engine_mod.as_dataset(dataset)
        return ds.map_partitions(
            node.inference(self.cluster_info, self.cluster_meta, feed_timeout, qname)
        )

    def serve(self, export_dir=None, ckpt_dir=None, num_replicas=None, **kw):
        """Stand up an online inference service on this cluster's engine
        (no reference equivalent — TensorFlowOnSpark delegates online
        serving to TF Serving; see docs/serving.md and PARITY.md §2.2).

        Call after :meth:`shutdown`: serving replicas are ordinary engine
        jobs and need free executor slots.  Returns a started
        ``serving.Server`` — the caller owns ``stop()`` (or use it as a
        context manager).
        """
        from tensorflowonspark_tpu import serving

        spec = serving.ModelSpec(
            export_dir=export_dir,
            ckpt_dir=ckpt_dir,
            predict=kw.pop("predict", None),
        )
        n = num_replicas or self.meta["num_executors"]
        server = serving.Server(spec, num_replicas=n, engine=self.engine, **kw)
        server.start()
        return server

    def shutdown(self, ssc=None, grace_secs=0, timeout=259200):
        """Stop the cluster and propagate errors
        (parity: TFCluster.shutdown :117-205)."""
        logger.info("waiting for cluster to shut down")
        workers = [
            m for m in self.cluster_info if m["job_name"] not in ("ps", "evaluator")
        ]
        ps_eval = [
            m for m in self.cluster_info if m["job_name"] in ("ps", "evaluator")
        ]

        # watchdog (SIGALRM parity, TFCluster.py:136-144) — thread-based so
        # it also works off the main thread
        def _watchdog():
            logger.error("shutdown watchdog fired after %ss; cancelling jobs", timeout)
            self.engine.cancel_all_jobs()
            os._exit(1)

        watchdog = threading.Timer(timeout, _watchdog)
        watchdog.daemon = True
        watchdog.start()

        drained = []

        def _drain_once():
            # exactly one drain per shutdown, clean OR error path — the
            # error timeline is the one most worth collecting
            if drained:
                return
            drained.append(True)
            try:
                self._drain_telemetry()
            except Exception as e:  # noqa: BLE001 - drain is best-effort
                logger.warning("telemetry drain failed: %s", e)

        try:
            with telemetry.span("cluster/shutdown", grace_secs=grace_secs):
                try:
                    # Spark Streaming: wait for the StreamingContext to
                    # terminate, stopping it ourselves once a consumer's
                    # STOP reaches the rendezvous server
                    # (parity: TFCluster.py:146-153)
                    if ssc is not None:
                        while not ssc.awaitTerminationOrTimeout(1):
                            if self.server.done.is_set():
                                logger.info(
                                    "server done, stopping StreamingContext")
                                ssc.stop(stopSparkContext=False,
                                         stopGraceFully=True)
                                break
                    # signal end-of-feed on every worker's queues
                    worker_ids = sorted(m["executor_id"] for m in workers)
                    if worker_ids:
                        shutdown_ds = self.engine.parallelize(
                            worker_ids, len(worker_ids))
                        shutdown_ds.foreach_partition(
                            node.shutdown(
                                self.cluster_info, self.queues,
                                self.meta["id"], grace_secs
                            ),
                            placement=worker_ids,
                        )

                    # drive ps/evaluator to stop via their remote managers
                    # (TFCluster.py:186-194).  This MUST precede joining the
                    # launcher: ps/evaluator node tasks hold their engine
                    # slots until the control message arrives, so the
                    # launcher job cannot complete before they are told to
                    # stop.
                    for m in ps_eval:
                        _stop_remote_node(m)

                    # wait for the node-launcher thread (all nodes now run
                    # to completion)
                    if self._launcher is not None:
                        self._launcher.join(timeout=timeout)
                except BaseException:
                    _drain_once()  # a failed worker's timeline still drains
                    raise

                _drain_once()
                if tf_status.get("error"):
                    logger.error("cluster failed: %s", tf_status["error"])
                    telemetry.event(
                        "cluster/error", error=str(tf_status["error"])[:500])
                    self.engine.cancel_all_jobs()
                    sys.exit(1)
        finally:
            watchdog.cancel()
            if self.obs is not None:
                try:
                    self.obs.stop()
                except Exception:  # noqa: BLE001 - teardown
                    pass
                self.obs = None
            self.server.stop()
            telemetry.flush()
        logger.info("cluster shut down")

    def _drain_telemetry(self):
        """Collect every node's spooled telemetry JSONL into one run
        directory, ``$TFOS_TELEMETRY_DIR/run-<cluster id>/`` — the driver
        half of the drain (executor half: node.drain_telemetry; transport:
        the manager KV registry, manager.py).  No-op when telemetry is
        disabled."""
        rdir = telemetry.run_dir(self.meta["id"])
        if rdir is None:
            return None
        n = self.meta["num_executors"]
        with telemetry.span("cluster/telemetry_drain", executors=n) as sp:
            ds = self.engine.parallelize(list(range(n)), n)
            rows = ds.map_partitions(
                node.drain_telemetry(self.cluster_info)
            ).collect(spread=True)
            os.makedirs(rdir, exist_ok=True)
            files = 0
            for executor_id, name, text in rows:
                dest = os.path.join(rdir, f"exec{executor_id}-{name}")
                with open(dest, "a", encoding="utf-8") as f:
                    f.write(text)
                files += 1
            sp.add(files=files)
        logger.info("telemetry: drained %d node files into %s", files, rdir)
        return rdir

    def tensorboard_url(self):
        """URL of the dashboard node, if one was launched
        (parity: TFCluster.py:207-212)."""
        for m in self.cluster_info:
            if m.get("tb_port"):
                return f"http://{m['host']}:{m['tb_port']}"
        return None

    _launcher = None
    obs = None  # live ObsServer when TFOS_OBS_PORT is set (obs/http.py)


def _quiesce_node(m):
    """Drive one (possibly already dead) node incarnation to a terminal
    state during recovery.  Best-effort throughout — the manager may have
    died with its executor, and that is fine: the respawn path killed its
    pid-file children.  Loopback fallback as in ``_stop_remote_node``."""
    import socket as _socket

    addr = tuple(m["addr"])
    candidates = [addr]
    if addr[0] not in ("127.0.0.1", "localhost"):
        candidates.append(("127.0.0.1", addr[1]))
    old = _socket.getdefaulttimeout()
    _socket.setdefaulttimeout(5)
    try:
        for cand in candidates:
            try:
                mgr = tfmanager.connect(cand, bytes.fromhex(m["authkey"]))
            except Exception:  # noqa: BLE001 - dead with its executor
                continue
            try:
                mgr.set("state", "terminating")
                # orphan feeders of the failed job sit in await-consumption
                # polling this queue (with the state flag covering blocked
                # puts); the poison makes them raise and free their
                # executor slot for the relaunch
                mgr.get_queue("error").put(
                    "cluster recovery: node quiesced (epoch fence)")
                bg = mgr.get("bg_pid")
                if bg:
                    from tensorflowonspark_tpu.utils import kill_pid

                    kill_pid(int(str(bg)))
                    mgr.set("bg_pid", None)
            except Exception as e:  # noqa: BLE001
                logger.warning("quiesce executor %s: %s",
                               m["executor_id"], e)
            return
        logger.info("quiesce: no manager reachable for executor %s "
                    "(node already dead)", m["executor_id"])
    finally:
        _socket.setdefaulttimeout(old)


def _elastic_template(template, alive_ids):
    """Shrink (or re-grow) a cluster template to the executors in
    ``alive_ids``: every job keeps its surviving ids; a lost chief /
    master seat is re-assigned the lowest surviving worker id (some node
    must run task 0 of the coordinator job or rendezvous never
    completes); dead ps / evaluator seats are dropped — their state
    lives in checkpoints, not processes; jobs left empty disappear."""
    alive = set(alive_ids)
    out = {}
    for job, ids in template.items():
        out[job] = [i for i in ids if i in alive]
    for coord in ("chief", "master"):
        if coord in template and not out.get(coord):
            workers = out.get("worker") or []
            if workers:
                out[coord] = [workers.pop(0)]
    return {job: ids for job, ids in out.items() if ids}


def _await_cluster(server, status, timeout):
    """Wait for every node of the (re)launched incarnation to register,
    then run the duplicate-registration sanity check
    (TFCluster.py:338,355-370)."""
    cluster_info = server.await_reservations(status, timeout)
    seen = set()
    for m in cluster_info:
        key = (m["host"], m["executor_id"])
        if key in seen:
            raise RuntimeError(f"duplicate node registration for {key}")
        seen.add(key)
    logger.info("cluster_info: %s", [
        (m["job_name"], m["task_index"], m["host"], m["executor_id"])
        for m in cluster_info
    ])
    return cluster_info


def _stop_remote_node(m):
    """control.put(None) on a ps/evaluator's remote manager, with a
    connect timeout and a loopback fallback (the advertised host may be
    a non-routable discovery address in sandboxed single-host setups)."""
    import socket as _socket

    addr = tuple(m["addr"])
    candidates = [addr]
    if addr[0] not in ("127.0.0.1", "localhost"):
        candidates.append(("127.0.0.1", addr[1]))
    old = _socket.getdefaulttimeout()
    _socket.setdefaulttimeout(15)
    last = None
    try:
        for cand in candidates:
            try:
                mgr = tfmanager.connect(cand, bytes.fromhex(m["authkey"]))
                mgr.get_queue("control").put(None, block=True)
                return
            except Exception as e:  # noqa: BLE001 - try next candidate
                last = e
        logger.warning(
            "could not stop %s:%s at %s: %s",
            m["job_name"], m["task_index"], candidates, last,
        )
    finally:
        _socket.setdefaulttimeout(old)


def run(
    sc,
    map_fun,
    tf_args,
    num_executors,
    num_ps=0,
    tensorboard=False,
    input_mode=InputMode.TENSORFLOW,
    log_dir=None,
    driver_ps_nodes=False,
    master_node=None,
    reservation_timeout=600,
    queues=("input", "output", "error", "control"),
    eval_node=False,
    num_chips=0,
    background=None,
    restarts=0,
    data_workers=0,
    data_dispatch=None,
    min_executors=0,
):
    """Starts the distributed cluster (parity: TFCluster.run :215-383).

    Args mirror the reference; ``sc`` may be a pyspark SparkContext or a
    ``LocalEngine``.  ``num_chips`` replaces the implicit GPU count.

    ``restarts``: how many times a failed job may be recovered (teardown,
    epoch bump, node relaunch, checkpoint auto-resume) before the error
    propagates.  0 (default) keeps fail-fast semantics.  Supervision
    applies to ``InputMode.SPARK`` ``train()`` jobs — the feed job is the
    driver's observation point; TENSORFLOW-mode jobs (nodes read their
    own data) and streaming feeds are not auto-restarted (see
    docs/fault_tolerance.md).

    ``data_workers``: number of dedicated data-service tasks used when
    ``train()`` is given a ``data.Pipeline`` instead of a dataset
    (docs/data.md); 0 defers to ``TFOS_DATA_WORKERS`` (default 1) at
    ``train()`` time.

    ``data_dispatch``: ``"dynamic"`` (default — FCFS split dispatch,
    docs/data.md "Dynamic sharding") or ``"static"`` (rank-strided
    shards, the pre-split behaviour); ``TFOS_DATA_DISPATCH`` overrides.

    ``min_executors``: elastic recovery floor (docs/elastic.md).  0
    (default) keeps today's rigid semantics: recovery must heal the
    pool back to full strength or the error propagates.  ``k > 0``
    lets ``_recover`` re-form the cluster over however many executors
    survive (>= k) when the respawn budget is exhausted — and re-grow
    it on a later recovery if the pool comes back.  Nodes pick the new
    shape up from ``ctx`` and re-place their train state through
    ``elastic.ElasticRuntime.resize``/``restore``.
    """
    logger.info("Reserving TFSparkNodes-TPU")
    start_t0 = time.perf_counter()
    if os.environ.get(telemetry.DIR_ENV):
        # Pin the driver identity BEFORE any node closure can run in-process
        # (sparkstub / driver_ps_nodes): node_configure skips relabelling
        # when it sees role=driver.
        telemetry.configure(node_id="driver", role="driver")
        # Root the run's causal trace: exported on TFOS_TRACE_PARENT so
        # every later driver span, engine task and spawned node joins
        # one tree (docs/telemetry.md "Causal tracing").
        telemetry.trace_root(telemetry.CLUSTER_RUN,
                             executors=num_executors)
    eng = engine_mod.as_engine(sc)
    queues = list(queues)

    if driver_ps_nodes and input_mode != InputMode.TENSORFLOW:
        raise ValueError("driver_ps_nodes requires InputMode.TENSORFLOW")
    assert num_ps < num_executors or driver_ps_nodes, (
        "num_ps must be less than num_executors (or use driver_ps_nodes)"
    )

    # cluster template {job: [executor_ids]} (TFCluster.py:246-271)
    cluster_size = num_executors + (num_ps if driver_ps_nodes else 0)
    ids = list(range(cluster_size))
    template = {}
    if driver_ps_nodes:
        # ps ids live past the engine executors; they run as driver threads
        template["ps"] = ids[num_executors:]
        pool = ids[:num_executors]
    else:
        if num_ps > 0:
            template["ps"] = ids[:num_ps]
        pool = ids[num_ps:]
    if eval_node:
        template["evaluator"] = [pool.pop(0)]
    if master_node:
        assert master_node in ("chief", "master"), "master_node must be chief|master"
        template[master_node] = [pool.pop(0)]
    if pool:
        template["worker"] = pool
    logger.info("cluster template: %s", template)

    if background is None:
        background = input_mode == InputMode.SPARK

    server = rendezvous.Server(cluster_size)
    server_addr = server.start()

    cluster_meta = {
        "id": random.getrandbits(64),
        "epoch": 0,  # cluster incarnation; _recover bumps it in place
        "cluster_template": template,
        "num_executors": num_executors,
        "default_fs": eng.default_fs,
        "working_dir": os.getcwd(),
        "server_addr": list(server_addr),
        "authkey": secrets.token_hex(16),
        "reservation_timeout": reservation_timeout,
        "data_workers": int(data_workers),
        "data_dispatch": data_dispatch,
    }

    tf_status.clear()
    node_fn = node.run(
        map_fun,
        tf_args,
        cluster_meta,
        tensorboard=tensorboard,
        log_dir=log_dir,
        queues=queues,
        background=background,
        num_chips=num_chips,
    )

    # driver-hosted ps nodes run as local threads (TFCluster.py:296-314)
    if driver_ps_nodes:
        def _driver_ps(ps_id):
            try:
                node_fn([ps_id])
            except Exception as e:  # noqa: BLE001
                tf_status["error"] = str(e)

        for ps_id in template["ps"]:
            t = threading.Thread(target=_driver_ps, args=(ps_id,), daemon=True)
            t.start()

    # launch engine-hosted nodes on a background thread (TFCluster.py:317-334)
    node_ids = sorted(i for i in range(cluster_size)
                      if not (driver_ps_nodes and i >= num_executors))
    nodes_ds = eng.parallelize(node_ids, len(node_ids))

    c = TFCluster()
    c.sc = sc
    c.engine = eng
    c.meta = cluster_meta
    c.cluster_meta = cluster_meta
    c.nodes = nodes_ds
    c.input_mode = input_mode
    c.queues = queues
    c.server = server
    c.restarts = int(restarts)
    c._restarts_used = 0
    c._node_fn = node_fn
    c._nodes_ds = nodes_ds
    c._node_ids = node_ids
    c.min_executors = int(min_executors)
    c._all_node_ids = list(node_ids)
    c._template0 = {k: list(v) for k, v in template.items()}
    c._launcher = c._spawn_launcher()

    # wait for all nodes to register (TFCluster.py:338), then the
    # duplicate (host, executor_id) sanity check (TFCluster.py:355-370)
    c.cluster_info = _await_cluster(server, tf_status, reservation_timeout)
    telemetry.record_span(
        "cluster/start", time.perf_counter() - start_t0,
        cluster=f"{cluster_meta['id'] & 0xffffffff:x}",
        executors=num_executors, nodes=len(c.cluster_info))
    # live observability endpoint (/metrics /healthz /statusz): only
    # when TFOS_OBS_PORT is set; start_for_cluster returns None otherwise
    # (no server, no threads — docs/observability.md)
    from tensorflowonspark_tpu import obs as _obs

    c.obs = _obs.start_for_cluster(c)
    return c
