"""Benchmark: ResNet-50 training throughput on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
value is model FLOPs utilization (MFU) of the ResNet-50 train step and
vs_baseline is relative to the BASELINE.json north-star of 0.50 MFU.
Also reports images/sec/chip inside the same line's "extra" field.
"""

import json
import os
import time

import numpy as np

from tensorflowonspark_tpu.utils import telemetry

# known bf16 peak TFLOP/s per chip by device kind substring
_PEAKS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,  # trillium
}


def _peak_flops(device):
    env = os.environ.get("TFOS_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for k, v in _PEAKS.items():
        if k in kind:
            return v
    return 197e12  # default: v5e


# records per shm-ring chunk (node.FEED_CHUNK_RECORDS scale); bigger
# chunks amortize per-chunk python + copy overheads, smaller ones keep
# ring latency low — sweep with scripts/stress_fed.py
FED_CHUNK = int(os.environ.get("TFOS_FED_CHUNK", "64"))


def _feeder_main(ring_name, mgr_addr, authkey_hex, total_records, image,
                 pool=None, columnar=True):
    """Feeder child (no jax): generate (uint8 image, label) records and push
    chunks through the shm ring exactly like node.train's feeder closure —
    including its columnar chunk encoder (n-D image fields go over the
    wire as dense flattened columns; columnar=False reverts to pickled
    row lists for the A/B lane)."""
    import numpy as np

    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu import node as tfnode
    from tensorflowonspark_tpu.recordio import shm as shmq

    if telemetry.enabled():
        # same schema as cluster nodes, opt-in via TFOS_TELEMETRY_DIR
        # (inherited through the spawn env)
        telemetry.configure(node_id=f"feeder-{os.getpid()}", role="feeder")
    if columnar:
        encode = tfnode._make_chunk_encoder()
    else:
        def encode(chunk):
            return chunk
    mgr = tfmanager.connect(tuple(mgr_addr), bytes.fromhex(authkey_hex))
    ring = shmq.ShmQueue(ring_name, create=False, producer=True)
    rng = np.random.default_rng(0)
    # pool MUST exceed the chunk size: with repeats inside one chunk,
    # pickle memoizes the duplicate array references and the row-path
    # wire volume collapses to pool-size unique images — flattering the
    # row path by 4x in round-3 measurements
    pool = pool or 2 * FED_CHUNK
    images = [rng.integers(0, 256, (image, image, 3), dtype=np.uint8)
              for _ in range(pool)]
    sent = 0
    chunk = []
    with telemetry.span("feeder/push", records=total_records,
                        columnar=columnar):
        while sent < total_records:
            chunk.append((images[sent % pool], sent % 1000))
            sent += 1
            if len(chunk) >= FED_CHUNK:
                ring.put(encode(chunk))
                chunk = []
        if chunk:
            ring.put(encode(chunk))
        ring.put(None)  # end-of-feed marker
    ring.close()
    mgr.set("feeder_done", 1)
    telemetry.flush()


def _fed_setup(batch, image, steps, columnar=True, tag="", target=None,
               extra=(), rec_bytes=None):
    """Pre-jax setup of the fed pipeline: IPC manager + shm ring + a real
    feeder process.  Must run before jax/the TPU tunnel initializes in
    this process: the feeder child is spawned with PYTHONPATH cleared so
    the axon site hook never runs in it, and the manager server is forked
    before any accelerator state exists.

    ``target`` swaps the feeder entry point (default ``_feeder_main``);
    a custom target is called with ``(ring_name, mgr_addr, authkey_hex,
    total_records, *extra)`` and ``rec_bytes`` sizes the ring for its
    record width (stress_fed's pipeline A/B lanes use this)."""
    import multiprocessing as mp
    import secrets

    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.recordio import shm as shmq

    if not shmq.available():
        return None
    authkey = secrets.token_bytes(16)
    mgr = tfmanager.start(authkey, ["input", "output", "error", "control"])
    ring_name = f"/tfos-bench-{os.getpid():x}{tag}"
    # modest capacity on purpose: a huge ring would let the feeder run
    # steps ahead during compile and overstate steady-state throughput.
    # Must hold several chunks or producer/consumer serialize — scale
    # with TFOS_FED_CHUNK (env TFOS_FED_RING_MB overrides).
    ring_mb = int(os.environ.get(
        "TFOS_FED_RING_MB",
        str(max(64, 6 * FED_CHUNK * (rec_bytes or image * image * 3)
                // (1 << 20)))))
    ring = shmq.ShmQueue(ring_name, ring_mb << 20, create=True)
    mgr.set("shm_input", ring_name)
    total = (steps + 2) * batch  # +2 warmup batches
    ctx = mp.get_context("spawn")
    saved = os.environ.get("PYTHONPATH")
    os.environ["PYTHONPATH"] = ""
    try:
        if target is None:
            args = (ring_name, list(mgr.address), authkey.hex(), total,
                    image, None, columnar)
        else:
            args = (ring_name, list(mgr.address), authkey.hex(),
                    total) + tuple(extra)
        proc = ctx.Process(
            target=target or _feeder_main,
            args=args,
            daemon=True,
        )
        proc.start()
    finally:
        if saved is None:
            os.environ.pop("PYTHONPATH", None)
        else:
            os.environ["PYTHONPATH"] = saved
    return {"mgr": mgr, "ring": ring, "proc": proc, "steps": steps,
            "batch": batch, "image": image, "columnar": columnar}


def _fed_run(fed, step_fn, params, state, opt_state, loop_ips=None,
             xfer_ips=None):
    """Train from the fed pipeline on the device; report fed throughput,
    infeed stall, the device-resident per-dispatch comparator, and the
    raw host→device transfer ceiling.

    ``loop_ips``/``xfer_ips``: pass the comparator numbers from an
    earlier lane (same step_fn/shapes) to skip re-measuring them — the
    A/B counter-lane must not double the per-dispatch device time spent
    on fed benching."""
    import jax
    import numpy as np

    from tensorflowonspark_tpu.feed import DataFeed
    from tensorflowonspark_tpu.infeed import device_feed
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics

    batch, image, steps = fed["batch"], fed["image"], fed["steps"]
    fed_step = jax.jit(step_fn, donate_argnums=(0, 1, 2))
    p, s, o = params, state, opt_state

    if loop_ips is None:
        # comparator: same per-dispatch step loop, device-resident batch
        rng = np.random.default_rng(0)
        res_imgs = jax.device_put(
            rng.integers(0, 256, (batch, image, image, 3), dtype=np.uint8)
        )
        res_labels = jax.device_put(
            rng.integers(0, 1000, batch).astype(np.int32))
        p, s, o, loss, _ = fed_step(p, s, o, res_imgs, res_labels)  # compile
        float(loss)  # value fetch: block_until_ready is not a reliable
        t0 = time.perf_counter()  # barrier through the relay (PERF.md r4)
        for _ in range(steps):
            p, s, o, loss, _ = fed_step(p, s, o, res_imgs, res_labels)
        float(loss)
        loop_ips = batch * steps / (time.perf_counter() - t0)

    if xfer_ips is None:
        # raw host→device transfer ceiling: device_put of a full uint8
        # batch, no compute.  Through a tunneled/remote chip the LINK —
        # not the framework — is usually the fed wall (r4 measured
        # ~30 MB/s through the axon relay vs ~10s of GB/s PCIe DMA on a
        # real TPU VM); reporting it lets vs_transfer_ceiling separate
        # pipeline overhead from link physics.
        rng = np.random.default_rng(1)
        # 2 timed puts bound a serialized link fine; more would add
        # minutes of relay wall time to every unattended bench
        xfer_steps = int(os.environ.get("TFOS_BENCH_FED_XFER_STEPS",
                                        str(min(steps, 2))))
        bufs = [rng.integers(0, 256, (batch, image, image, 3),
                             dtype=np.uint8) for _ in range(2)]
        # a 1-element readback after each put is the completion proof
        # (block_until_ready is not a reliable barrier through the
        # relay - PERF.md r4); its cost is one tiny round trip
        int(jax.device_put(bufs[0])[0, 0, 0, 0])  # warm the path
        t0 = time.perf_counter()
        for i in range(xfer_steps):
            int(jax.device_put(bufs[i % 2])[0, 0, 0, 0])
        xfer_ips = batch * xfer_steps / (time.perf_counter() - t0)

    metrics = TrainMetrics()
    feed = DataFeed(fed["mgr"], train_mode=True,
                    input_mapping={"image": "image", "label": "label"},
                    metrics=metrics)

    # watchdog: a feeder that dies without pushing the end-of-feed None
    # would block the consumer forever — unblock it by closing the feed
    import threading

    stop_watch = threading.Event()

    def watchdog():
        fed["proc"].join()
        if fed["proc"].exitcode not in (0, None) and not stop_watch.is_set():
            import sys

            from tensorflowonspark_tpu.recordio import shm as shmq

            print(f"bench: feeder died rc={fed['proc'].exitcode}, "
                  "closing feed", file=sys.stderr, flush=True)
            try:
                shmq.ShmQueue(fed["ring"].name, create=False,
                              producer=True).put(None)
            except Exception:  # noqa: BLE001 - consumer may already be done
                pass

    threading.Thread(target=watchdog, daemon=True).start()

    # wedge watchdog: a feeder that stalls while alive (no records, no
    # exit) must not turn the whole unattended bench into a hang.  The
    # deadline is on PROGRESS, not wall clock — it resets every batch —
    # and firing is loud: logged and flagged in the lane's result.
    deadline_s = float(os.environ.get("TFOS_BENCH_FED_DEADLINE", "900"))
    progress = {"n": -1, "deadline_hit": False}

    def stall_watch():
        import sys

        last = (progress["n"], time.monotonic())
        while not stop_watch.wait(min(15.0, deadline_s / 4 or 1)):
            now = time.monotonic()
            if progress["n"] != last[0]:
                last = (progress["n"], now)
            elif now - last[1] > deadline_s:
                progress["deadline_hit"] = True
                print(f"bench: fed lane made no progress for "
                      f"{deadline_s:.0f}s; ending it early",
                      file=sys.stderr, flush=True)
                feed.poison()
                return

    threading.Thread(target=stall_watch, daemon=True).start()

    columnar = fed["columnar"]
    if columnar:
        # dense-array pull: aligned chunks pass through zero-copy, the
        # per-record python loop + np.stack (the 12k img/s wall, PERF.md)
        # is gone from the consumer hot path
        def collate(cols):
            return cols["image"], np.asarray(cols["label"], np.int32)
    else:
        def collate(cols):
            return np.stack(cols["image"]), np.asarray(cols["label"],
                                                       np.int32)

    nsteps = 0
    n_timed = 0
    t0 = None
    wait_base = 0.0
    last = None
    for imgs, labels in device_feed(feed, batch, collate=collate, depth=2,
                                    columnar=columnar):
        p, s, o, last, _ = fed_step(p, s, o, imgs, labels)
        nsteps += 1
        progress["n"] = nsteps
        if nsteps == 1:
            float(last)  # absorb warmup/compile skew (value fetch: a
            # reliable completion barrier through the relay, PERF.md r4)
            t0 = time.perf_counter()
            wait_base = metrics.infeed_time  # align stall window with dt
        else:
            n_timed += 1
    stop_watch.set()
    if last is None or n_timed == 0:  # feeder died before one full batch
        rc = fed["proc"].exitcode
        fed["mgr"].set("state", "stopped")
        fed["ring"].close()
        return {"error": f"no fed batches completed (feeder exitcode={rc})"}
    float(last)
    dt = time.perf_counter() - t0
    fed_ips = batch * n_timed / dt
    stall = max(metrics.infeed_time - wait_base, 0.0)

    fed["proc"].join(timeout=10)
    if fed["proc"].is_alive():
        fed["proc"].kill()
    fed["mgr"].set("state", "stopped")
    fed["ring"].close()

    # with depth-2 double buffering the best the fed path can do is the
    # slower of (pure transfer, pure compute); against a serialized link
    # it is the harmonic combination — report the optimistic one
    ceiling = min(xfer_ips, loop_ips) if xfer_ips and loop_ips else None
    out = {
        "images_per_sec_per_chip": round(fed_ips, 1),
        "loop_images_per_sec": round(loop_ips, 1),
        "transfer_images_per_sec": round(xfer_ips, 1) if xfer_ips else None,
        "vs_device_resident": round(fed_ips / loop_ips, 4) if loop_ips else None,
        "vs_transfer_ceiling": round(fed_ips / ceiling, 4) if ceiling else None,
        "infeed_wait_s": round(stall, 3),
        "infeed_stall_frac": round(stall / dt, 4) if dt else None,
        "steps": n_timed, "chunk_records": FED_CHUNK,
        "columnar": columnar,
    }
    if progress["deadline_hit"]:
        out["deadline_hit"] = True  # truncated lane: numbers are partial
    return out


def _on_tpu_guess():
    """Pre-jax platform guess (the fed pipeline must be set up before the
    TPU tunnel initializes in this process).  Chip discovery delegates to
    tpu_info (stdlib-only import, honors TFOS_TPU_CHIPS_PER_HOST)."""
    from tensorflowonspark_tpu import tpu_info

    plat = os.environ.get("JAX_PLATFORMS", "").lower()
    if plat in ("cpu",):
        return False
    return bool(plat) or tpu_info.count_chips() > 0


def bench_config_path():
    """THE bench_config.json location (TFOS_BENCH_CONFIG overrides the
    repo-root default).  Single source of truth — the sweep scripts'
    --promote writers and the session script's arg emitter all resolve
    through here so producer and consumer can never drift apart."""
    return os.environ.get("TFOS_BENCH_CONFIG") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_config.json")


_RUN_ID = None


def run_stamp():
    """Identity keys stamped onto THE one JSON line: a per-run id plus
    the telemetry sink it spooled to (null when telemetry was off), so a
    bench artifact can be joined to its trace spool after the fact.
    bench_check reads only the lane paths it names and ignores unknown
    top-level keys, so the stamp is compare-safe (tested in
    tests/test_obs.py)."""
    global _RUN_ID
    if _RUN_ID is None:
        _RUN_ID = time.strftime("%Y%m%dT%H%M%S") + "-" + os.urandom(3).hex()
    return {"run_id": _RUN_ID,
            "telemetry_dir": os.environ.get(telemetry.DIR_ENV)}


def _failsafe_line(error, **extra):
    """THE one JSON line, fail-safe form: value null + an error string.
    The driver parses the last stdout line of every round-end bench run;
    a dead tunnel must still produce a parseable artifact (rounds 3 AND 4
    both ended rc=124/parsed=null instead — VERDICT r4 weak #2)."""
    try:
        # the watchdog fire paths hard-exit (os._exit skips atexit):
        # persist any buffered telemetry alongside the artifact line
        telemetry.flush()
    except Exception:  # noqa: BLE001 - the artifact line must go out
        pass
    print(json.dumps({
        "metric": "resnet50_train_mfu",
        "value": None,
        "unit": "fraction_of_peak",
        "vs_baseline": None,
        "error": error,
        "extra": extra,
        **run_stamp(),
    }), flush=True)


def _tunnel_in_play():
    """True when this process would dial the axon TPU tunnel at jax
    import/init time (the site hook on PYTHONPATH dials the pool at
    interpreter startup; `import jax` HANGS — not errors — if the relay
    is dead)."""
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
        return False  # explicit CPU run: the tunnel is irrelevant
    return "axon" in os.environ.get("PYTHONPATH", "").lower() or \
        bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def _probe_relay(host, port):
    import socket

    try:
        with socket.create_connection((host, port), timeout=2):
            return True
    except OSError:
        return False


def _tunnel_note():
    """Pre-jax diagnosis of the axon relay.  When the loopback tunnel is
    dead, `import jax` HANGS, so an unattended bench run dies as an
    opaque rc=124 with no artifact (the round-3 AND round-4 failure
    mode).  Fail-safe is now the DEFAULT: after a short re-probe grace
    window (TFOS_BENCH_TUNNEL_WAIT, default 20s — it must beat
    with_tunnel_watchdog.sh's ~45s SIGKILL) the bench emits its one
    JSON line with value null + "error":"tunnel_dead" and exits 0 —
    well under 2 minutes, no env opt-in needed.  Set
    TFOS_BENCH_IGNORE_TUNNEL=1 to restore the old press-on behavior."""
    import sys

    if not _tunnel_in_play():
        return  # no tunnel in play (CI / explicit CPU)
    host = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
    port = int(os.environ.get("TFOS_TUNNEL_PORT", "8082"))
    if _probe_relay(host, port):
        return  # relay listening: proceed normally
    print(f"bench: WARNING axon relay {host}:{port} is not listening - "
          "the TPU tunnel looks DEAD; jax backend init would hang "
          "(the round-3/round-4 rc=124 failure mode)",
          file=sys.stderr, flush=True)
    if os.environ.get("TFOS_BENCH_IGNORE_TUNNEL") == "1":
        print("bench: TFOS_BENCH_IGNORE_TUNNEL=1 - pressing on anyway",
              file=sys.stderr, flush=True)
        return
    # default grace must finish BEFORE scripts/with_tunnel_watchdog.sh's
    # SIGKILL (4 failed probes at 15s intervals, ~45-60s): a session-run
    # bench must get its fail-safe line out ahead of the outer kill
    grace = float(os.environ.get("TFOS_BENCH_TUNNEL_WAIT", "20"))
    deadline = time.monotonic() + grace
    while True:
        # probe-first, then sleep only the REMAINING window: the old
        # sleep(5)-then-probe loop overshot sub-5s / non-multiple-of-5
        # TFOS_BENCH_TUNNEL_WAIT values by up to a full 5s tick
        if _probe_relay(host, port):
            print("bench: relay came back during the grace window",
                  file=sys.stderr, flush=True)
            return
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(5.0, remaining))
    print(f"bench: relay still dead after {grace:.0f}s grace - emitting "
          "the fail-safe line and exiting", file=sys.stderr, flush=True)
    _failsafe_line("tunnel_dead", relay=f"{host}:{port}")
    raise SystemExit(0)


def _arm_init_watchdog(cleanup=None):
    """A relay that dies BETWEEN the probe and backend init still wedges
    `import jax` / `jax.devices()` for the driver's whole timeout (r4
    lost 26 min to exactly this, tail 09:22->09:48).  Arm a daemon timer
    before the jax import: if backend init hasn't completed within
    TFOS_BENCH_INIT_TIMEOUT (default 900s — cold tunnel init is minutes,
    never 15), print the fail-safe JSON line and hard-exit.  A wedged
    jax ignores SIGTERM (memory: round-4), so os._exit is the only
    reliable escape from inside the process — which skips
    multiprocessing's atexit teardown, so ``cleanup`` must reap anything
    spawned earlier (the fed feeder/manager children + shm rings)."""
    import threading

    if not _tunnel_in_play():
        return lambda: None, lambda: None
    cap = float(os.environ.get("TFOS_BENCH_INIT_TIMEOUT", "900"))
    host = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
    port = int(os.environ.get("TFOS_TUNNEL_PORT", "8082"))
    done = threading.Event()
    deadline = [time.monotonic() + cap]

    def extend(horizon=0.0):
        # re-arm per init attempt: the UNAVAILABLE retry schedule sleeps
        # 60+120+180s by design, so one fixed cap spanning all attempts
        # would kill the exact runs the retries were built to save.
        # ``horizon`` covers a planned sleep longer than the cap itself.
        deadline[0] = time.monotonic() + max(cap, horizon)

    def fire(error, **extra):
        import sys

        print(f"bench: init watchdog firing ({error}); emitting the "
              "fail-safe line", file=sys.stderr, flush=True)
        _failsafe_line(error, **extra)
        if cleanup is not None:
            try:
                cleanup()
            except Exception:  # noqa: BLE001 - exiting anyway
                pass
        os._exit(0)

    # TFOS_BENCH_IGNORE_TUNNEL=1 means "press on even though the relay
    # looks dead" (_tunnel_note let init proceed) — the port trigger
    # would fire ~15s in and defeat that opt-in.  Keep the time cap: a
    # wedge is a wedge regardless of why the operator pressed on.
    port_trigger = os.environ.get("TFOS_BENCH_IGNORE_TUNNEL") != "1"

    def watchdog():
        # two triggers: the per-attempt time cap (a wedge against a SICK
        # tunnel whose port still listens), and the relay port closing
        # mid-init (the r4 post-probe death mode).  The port trigger must
        # fire FAST: under the session harness with_tunnel_watchdog.sh
        # SIGKILLs the whole group ~45-60s after the ports close, and the
        # fail-safe line has to be out before that.
        port_down = 0
        while not done.wait(min(5.0, cap)):
            if port_trigger:
                port_down = 0 if _probe_relay(host, port) else port_down + 1
                if port_down >= 3:  # ~15-21s of consecutive closed probes
                    fire("tunnel_died_during_init", relay=f"{host}:{port}")
            if time.monotonic() >= deadline[0]:
                fire("backend_init_timeout", timeout_s=cap)

    threading.Thread(target=watchdog, daemon=True).start()
    return done.set, extend


def _arm_run_watchdog(extra):
    """The init watchdog disarms at _init_done(), but the relay can die
    DURING the lanes too — a mid-lane death leaves value fetches wedged
    and the run ends rc=124 with no artifact, exactly the failure mode
    the fail-safe contract exists for.  Arm a port-probe daemon for the
    whole measured phase: three consecutive closed probes emit the
    fail-safe line (carrying whatever lane results ``extra`` has
    accumulated so far — partial numbers beat none) and hard-exit.
    ``extra`` must be the live dict main() keeps ``.update()``-ing.
    No time cap here: lanes have their own deadlines, and a healthy
    first TPU compile can legitimately run many minutes (CLAUDE.md).
    Returns a disarm callable; a no-op without a tunnel in play or under
    TFOS_BENCH_IGNORE_TUNNEL=1 (same opt-out as the init watchdog)."""
    import threading

    if not _tunnel_in_play() or \
            os.environ.get("TFOS_BENCH_IGNORE_TUNNEL") == "1":
        return lambda: None
    host = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
    port = int(os.environ.get("TFOS_TUNNEL_PORT", "8082"))
    done = threading.Event()

    def watchdog():
        import sys

        port_down = 0
        while not done.wait(5.0):
            port_down = 0 if _probe_relay(host, port) else port_down + 1
            if port_down >= 3:
                print("bench: relay died mid-run; emitting the fail-safe "
                      "line with partial lane results",
                      file=sys.stderr, flush=True)
                snapshot = {"partial": True}
                snapshot.update(extra)
                _failsafe_line("tunnel_died_mid_run",
                               relay=f"{host}:{port}", **snapshot)
                os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()
    return done.set


def _init_failsafe(e):
    """One place for every backend-init failure: with a tunnel in play,
    emit the parseable fail-safe line (the unattended-round contract)
    and exit 0; without one (CPU/CI), re-raise so a genuine code failure
    keeps its traceback and nonzero rc.  The traceback is printed to
    stderr either way — a null artifact must still be debuggable."""
    import sys
    import traceback

    traceback.print_exc(file=sys.stderr)
    sys.stderr.flush()
    if not _tunnel_in_play():
        raise e
    _failsafe_line("backend_init_failed", detail=str(e)[:200])
    raise SystemExit(0)


def _fed_teardown(*ctxs):
    """Reap a fed lane's children + shm ring without relying on atexit
    (the watchdog's os._exit path skips it): kill the feeder, close the
    ring (creator close unlinks the segment), shut the manager server
    down."""
    for fed in ctxs:
        if not isinstance(fed, dict) or "proc" not in fed:
            continue
        try:
            fed["proc"].kill()
        except Exception:  # noqa: BLE001
            pass
        try:
            fed["ring"].close()
        except Exception:  # noqa: BLE001
            pass
        try:
            fed["mgr"].shutdown()
        except Exception:  # noqa: BLE001
            pass


def _promoted_config():
    """Optional bench_config.json at the repo root: sweep winners
    applied to the TPU bench without code edits.  Top-level keys are the
    ResNet config (scripts/sweep_resnet.py --promote); the "transformer"
    sub-dict is the transformer sweep's winner
    (scripts/sweep_transformer.py --promote).  TFOS_BENCH_* env vars
    still win over promoted values."""
    path = bench_config_path()
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            cfg = json.load(f)
        return cfg if isinstance(cfg, dict) else {}
    except (OSError, ValueError) as e:
        import sys

        print(f"bench: ignoring unreadable bench_config.json: {e}",
              file=sys.stderr, flush=True)
        return {}


def main():
    if os.environ.get(telemetry.DIR_ENV):
        # opt-in: the bench emits the same span schema as cluster nodes
        # so trace_merge.py can lay a bench run on the same timeline
        telemetry.configure(node_id="bench", role="bench")
    _tunnel_note()
    on_tpu = _on_tpu_guess()
    promoted = _promoted_config() if on_tpu else {}
    batch = int(os.environ.get(
        "TFOS_BENCH_BATCH",
        promoted.get("batch", 256) if on_tpu else 16))
    image = int(os.environ.get(
        "TFOS_BENCH_IMAGE",
        promoted.get("image", 224) if on_tpu else 64))
    steps = int(os.environ.get("TFOS_BENCH_STEPS", "20" if on_tpu else "3"))
    stem_s2d = os.environ.get(
        "TFOS_BENCH_STEM_S2D",
        "1" if promoted.get("stem_s2d", True) else "0") != "0"
    remat = os.environ.get(
        "TFOS_BENCH_REMAT",
        "1" if promoted.get("remat", False) else "0") != "0"
    # resolved AFTER backend init (actual platform, not the guess):
    # default FALSE on TPU unless a sweep promoted it — the fused-BN graph
    # must never make its TPU debut inside the unattended round-end bench
    bn_fused_env = os.environ.get("TFOS_BENCH_BN_FUSED")

    fed_ctx = fed_ctx_rows = None
    if os.environ.get("TFOS_BENCH_FED", "1") != "0":
        columnar = os.environ.get("TFOS_BENCH_FED_COLUMNAR", "1") != "0"
        try:
            fed_ctx = _fed_setup(batch, image, steps, columnar=columnar)
        except Exception as e:  # noqa: BLE001 - fed lane is best-effort
            fed_ctx = {"setup_error": str(e)[:200]}
        # the A/B counter-lane (row-list wire + np.stack consumer): its
        # feeder must ALSO spawn pre-jax — forking the manager server
        # after the accelerator runtime is live is fork-after-threads
        # territory.  The extra feeder just blocks on its full ring
        # until its lane runs.
        if columnar and os.environ.get("TFOS_BENCH_FED_AB", "1") != "0":
            try:
                fed_ctx_rows = _fed_setup(batch, image, steps,
                                          columnar=False, tag="-rows")
            except Exception as e:  # noqa: BLE001
                fed_ctx_rows = {"setup_error": str(e)[:200]}

    # the watchdog covers the import AND every init attempt below: any
    # wedge against a dying tunnel ends in a parseable fail-safe line
    # (and reaps the already-spawned fed children before the hard exit)
    _init_done, _init_extend = _arm_init_watchdog(
        cleanup=lambda: _fed_teardown(fed_ctx, fed_ctx_rows))

    init_t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp
        import optax

        from tensorflowonspark_tpu.models import resnet
    except Exception as e:  # noqa: BLE001 - e.g. ConnectionRefusedError
        # from the site hook once the relay ports close (r4's ending)
        _init_failsafe(e)

    # backend init retry: a TPU pool can answer UNAVAILABLE transiently
    # (observed: tunnel claim errors that clear after minutes) — one
    # retry cycle is cheap insurance for an unattended bench run
    dev = None
    for attempt in range(int(os.environ.get("TFOS_BENCH_INIT_RETRIES", "3"))):
        _init_extend()  # fresh watchdog budget per attempt
        try:
            dev = jax.devices()[0]
            break
        except RuntimeError as e:
            import sys

            if "UNAVAILABLE" not in str(e):
                _init_failsafe(e)  # permanent misconfiguration
            print(f"bench: backend init failed (try {attempt + 1}): "
                  f"{str(e)[:120]}", file=sys.stderr, flush=True)
            try:  # drop the cached failure so the next call re-dials
                from jax._src import xla_bridge as _xb

                _xb._clear_backends()
            except Exception:  # noqa: BLE001 - internal API may move
                pass
            backoff = 60 * (attempt + 1)
            _init_extend(backoff + 60)  # keep the watchdog clear of the
            time.sleep(backoff)         # deliberate backoff sleep
        except Exception as e:  # noqa: BLE001 - non-Runtime init failure
            _init_failsafe(e)
    if dev is None:
        try:
            dev = jax.devices()[0]  # final attempt
        except Exception as e:  # noqa: BLE001
            _init_failsafe(e)
    _init_done()
    telemetry.record_span("bench/backend_init",
                          time.perf_counter() - init_t0,
                          platform=dev.platform)
    # mid-run fail-safe: ``extra`` is created HERE and only .update()d
    # below so the watchdog's snapshot sees every lane result landed so
    # far; disarmed right before the final JSON line
    extra = {}
    _run_done = _arm_run_watchdog(extra)
    guessed_tpu = on_tpu
    on_tpu = dev.platform != "cpu"
    if on_tpu != guessed_tpu:
        import sys

        print(f"bench: platform guess ({guessed_tpu}) != actual "
              f"({dev.platform}); workload sized from the guess",
              file=sys.stderr, flush=True)

    from jax import lax

    # init under one jit program: eager init is hundreds of tiny
    # dispatches — minutes of wall time over a remote-compile TPU tunnel
    opt = optax.sgd(0.1, momentum=0.9)

    @jax.jit
    def init_all(key):
        params, state = resnet.init(key, depth=50, num_classes=1000)
        return params, state, opt.init(params)

    params, state, opt_state = init_all(jax.random.PRNGKey(0))
    bn_fused = (bn_fused_env != "0") if bn_fused_env is not None \
        else bool(promoted.get("bn_fused", not on_tpu))
    step_fn = resnet.make_train_step(opt, depth=50, stem_s2d=stem_s2d,
                                     remat=remat, bn_fused=bn_fused)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((batch, image, image, 3), dtype=np.float32),
                         dtype=jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, batch), dtype=jnp.int32)

    # Chain `steps` train steps inside one jit (lax.scan): one dispatch,
    # one result fetch — honest device time, no per-step host round-trips
    # (and immune to async-dispatch timing artifacts).
    @jax.jit
    def run_steps(params, state, opt_state, images, labels):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss, _acc = step_fn(p, s, o, images, labels)
            return (p, s, o), loss

        (p, s, o), losses = lax.scan(body, (params, state, opt_state),
                                     None, length=steps)
        return losses[-1]

    with telemetry.span("bench/resnet_scan", batch=batch, image=image,
                        steps=steps):
        dt, loss = _time_scanned(run_steps, params, state, opt_state,
                                 images, labels)
    imgs_per_sec = batch * steps / dt
    # fwd+bwd ≈ 3x forward FLOPs
    flops_per_img = 3.0 * resnet.flops_per_image(50, image)
    achieved = imgs_per_sec * flops_per_img
    mfu = achieved / _peak_flops(dev)

    extra.update({
        "images_per_sec_per_chip": round(imgs_per_sec, 1),
        "batch": batch, "image": image, "steps": steps,
        "stem_s2d": stem_s2d, "remat": remat, "bn_fused": bn_fused,
        "device": str(dev), "platform": dev.platform,
        "loss": loss,
    })
    if on_tpu != guessed_tpu:
        extra["platform_guess_mismatch"] = True
    if fed_ctx is not None:
        # the north-star metric is *fed* (InputMode.SPARK-ingestion)
        # throughput: feeder process -> shm ring -> DataFeed -> device
        if "setup_error" in fed_ctx:
            extra["fed"] = fed_ctx
        else:
            try:
                with telemetry.span("bench/fed", batch=batch, image=image):
                    extra["fed"] = _fed_run(fed_ctx, step_fn, params, state,
                                            opt_state)
            except Exception as e:  # noqa: BLE001 - report, don't mask resnet
                extra["fed"] = {"error": str(e)[:200]}
    if fed_ctx_rows is not None:
        # row-wire counter-lane: same train step, pickled row lists +
        # np.stack consumer — the A/B lands in ONE bench line
        if "setup_error" in fed_ctx_rows:
            extra["fed_rows"] = fed_ctx_rows
        else:
            try:
                # the first fed lane DONATED the train state; re-init
                # (compile-cached, so this is one cheap dispatch)
                p2, s2, o2 = init_all(jax.random.PRNGKey(0))
                with telemetry.span("bench/fed_rows", batch=batch,
                                    image=image):
                    extra["fed_rows"] = _fed_run(
                        fed_ctx_rows, step_fn, p2, s2, o2,
                        loop_ips=extra.get("fed", {}).get(
                            "loop_images_per_sec"),
                        xfer_ips=extra.get("fed", {}).get(
                            "transfer_images_per_sec"))
            except Exception as e:  # noqa: BLE001
                extra["fed_rows"] = {"error": str(e)[:200]}
        a = extra.get("fed", {}).get("images_per_sec_per_chip")
        b = extra.get("fed_rows", {}).get("images_per_sec_per_chip")
        if a and b:
            extra["fed_rows"]["columnar_speedup"] = round(a / b, 3)

    if os.environ.get("TFOS_BENCH_TRANSFORMER", "1") != "0":
        try:
            with telemetry.span("bench/transformer"):
                extra["transformer"] = _transformer_bench(dev, on_tpu)
        except Exception as e:  # noqa: BLE001 - secondary metric only
            extra["transformer"] = {"error": str(e)[:200]}

    # BASELINE.json configs 2/4/5: TFRecord direct read, segmentation,
    # batch inference — small first-number runs, each independent
    for name, fn in (("tfrecord_read", _tfrecord_bench),
                     ("segmentation", _segmentation_bench),
                     ("batch_inference", _inference_bench),
                     ("serve", _serve_bench),
                     ("elastic_serve", _elastic_serve_bench),
                     ("deploy", _deploy_bench),
                     ("decode", _decode_bench),
                     ("serve_fabric", _fabric_bench),
                     ("data", _data_bench),
                     ("elastic", _elastic_bench),
                     ("actors", _actors_bench)):
        if os.environ.get(f"TFOS_BENCH_{name.upper()}", "1") != "0":
            try:
                with telemetry.span(f"bench/{name}"):
                    extra[name] = fn(dev, on_tpu)
            except Exception as e:  # noqa: BLE001 - secondary metric only
                extra[name] = {"error": str(e)[:200]}

    _run_done()
    telemetry.flush()
    try:
        # watchtower roll-up (anomalies seen across every lane's monitor,
        # max straggler skew) — a non-lane key like run_stamp, proven
        # ignored by bench_check in tests/test_health.py
        from tensorflowonspark_tpu.obs import health as _health

        health_block = _health.process_summary()
    except Exception as e:  # noqa: BLE001 - the artifact line must go out
        health_block = {"error": str(e)[:200]}
    print(json.dumps({
        "metric": "resnet50_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": extra,
        "health": health_block,
        **run_stamp(),
    }))


def _time_scanned(run, *args):
    """Compile+warm one jitted scanned-steps fn, then time a second call.
    Returns (seconds, last_loss) — the shared harness for every model
    section in this file."""
    loss = float(run(*args))  # compile + warmup
    t0 = time.perf_counter()
    loss = float(run(*args))
    return time.perf_counter() - t0, loss


def _transformer_bench(dev, on_tpu):
    """Secondary metric: decoder-only transformer train-step throughput
    with the pallas flash-attention kernel (tokens/sec/chip + MFU)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.utils import metrics as M

    promoted = (_promoted_config().get("transformer", {})
                if on_tpu else {})
    if on_tpu:
        # base config fits one v5e with f32 adam state; the sweep's
        # winner (scripts/sweep_transformer.py --promote) can raise
        # batch / change flash blocks / enable remat via
        # bench_config.json's "transformer" section.  attn="reference"
        # is the sweep's recorded fallback when the compiled pallas
        # forward failed on this backend.
        cfg = transformer.Config(
            vocab_size=16384, dim=1024, n_layers=8, n_heads=8,
            max_seq=int(promoted.get("seq", 2048)), dtype="bfloat16",
            attn_impl=promoted.get("attn", "flash"),
        )
        batch, steps = int(promoted.get("batch", 8)), 10
    else:
        cfg = transformer.Config(
            vocab_size=512, dim=128, n_layers=2, n_heads=4, max_seq=128,
            dtype="float32", attn_impl="reference",
        )
        batch, steps = 2, 3
    # bool or the selective policy name "dots" — pass through (int 1
    # must coerce: `1 in (True,)` is True but `1 is True` is not)
    remat = promoted.get("remat", False)
    if remat != "dots":
        remat = bool(remat)
    ce_impl = ("blockwise" if promoted.get("ce") == "block" else "dense")
    attn_fn = None
    if (promoted.get("block_q") or promoted.get("block_kv")) \
            and promoted.get("attn", "flash") == "flash":
        import functools

        from tensorflowonspark_tpu import ops

        attn_fn = functools.partial(
            ops.flash_attention, causal=True,
            block_q=int(promoted.get("block_q", 512)),
            block_kv=int(promoted.get("block_kv", 512)),
            bwd_impl=promoted.get("bwd", "xla"))

    opt = optax.adam(1e-3)

    @jax.jit
    def init_all(key):
        params = transformer.init(key, cfg)
        return params, opt.init(params)

    params, opt_state = init_all(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (batch, cfg.max_seq)),
        jnp.int32,
    )

    @jax.jit
    def run(params, opt_state, tokens):
        def body(carry, _):
            p, o = carry
            loss, grads = jax.value_and_grad(transformer.loss_fn)(
                p, tokens, cfg, attn_fn=attn_fn, remat=remat,
                ce_impl=ce_impl, ce_block=min(2048, cfg.vocab_size),
            )
            updates, o = opt.update(grads, o)
            return (optax.apply_updates(p, updates), o), loss
        (p, o), losses = lax.scan(body, (params, opt_state), None,
                                  length=steps)
        return losses[-1]

    dt, loss = _time_scanned(run, params, opt_state, tokens)
    toks_per_sec = batch * cfg.max_seq * steps / dt
    flops_per_tok = M.transformer_flops_per_token(cfg)
    out = {
        "tokens_per_sec_per_chip": round(toks_per_sec, 1),
        "mfu": round(toks_per_sec * flops_per_tok / _peak_flops(dev), 4),
        # honest denominator for causal-skipping kernels: attention
        # counted at the algorithmically required (causal) half
        "mfu_causal_flops": round(
            toks_per_sec * M.transformer_flops_per_token(cfg, causal=True)
            / _peak_flops(dev), 4),
        "dim": cfg.dim, "layers": cfg.n_layers, "seq": cfg.max_seq,
        "batch": batch, "loss": loss,
    }
    if remat:
        out["remat"] = remat
    if ce_impl != "dense":
        out["ce"] = "block"  # same spelling as the promoted config
    if promoted:
        out["promoted"] = {k: promoted[k] for k in sorted(promoted)}
    return out


def _tfrecord_bench(dev, on_tpu):
    """BASELINE config #2: InputMode.TENSORFLOW equivalent — TFRecord
    direct read -> host decode/batch -> device train (MNIST shape)."""
    import shutil
    import tempfile

    import jax
    import optax

    from tensorflowonspark_tpu import dfutil, recordio
    from tensorflowonspark_tpu.models import mnist

    n_rec = 8192 if on_tpu else 1024
    batch = 256 if on_tpu else 64
    tmp = tempfile.mkdtemp(prefix="tfos_bench_tfr_")
    try:
        rng = np.random.default_rng(0)
        feats = rng.random((n_rec, 784)).astype(np.float32)
        labels = rng.integers(0, 10, n_rec).astype(np.int64)
        path = os.path.join(tmp, "part-r-00000")
        with recordio.TFRecordWriter(path) as w:
            for i in range(n_rec):
                w.write(recordio.encode_example({
                    "image": ("float", feats[i].tolist()),
                    "label": ("int64", [int(labels[i])]),
                }))

        # read+decode rate (records/s) through the production reader
        # (schema inferred once, then per-record decode — dfutil.py:140-163)
        t0 = time.perf_counter()
        rows, _schema = dfutil.load_tfrecords(None, tmp)
        read_dt = time.perf_counter() - t0
        assert len(rows) == n_rec

        # bulk columnar load (the TPU-first direct-read fast path):
        # one C pass -> dense arrays, np-sliced into device batches below
        t0 = time.perf_counter()
        cols = dfutil.load_tfrecords_columnar(tmp)
        col_dt = time.perf_counter() - t0
        imgs_all = cols["image"].reshape(-1, 28, 28, 1)
        labels_all = cols["label"].astype(np.int32)
        assert imgs_all.shape[0] == n_rec

        params = mnist.init_params(jax.random.PRNGKey(0))
        opt = optax.sgd(0.1, momentum=0.9)
        opt_state = opt.init(params)
        step = jax.jit(mnist.make_train_step(opt), donate_argnums=(0, 1))

        def batches():
            for i in range(0, n_rec - batch + 1, batch):
                yield imgs_all[i:i + batch], labels_all[i:i + batch]

        # warmup/compile on the first batch
        it = batches()
        x, y = next(it)
        params, opt_state, loss, _ = step(params, opt_state, x, y)
        float(loss)  # value-fetch barriers (PERF.md r4)
        t0 = time.perf_counter()
        n_img = 0
        for x, y in it:
            params, opt_state, loss, _ = step(params, opt_state, x, y)
            n_img += len(y)
        float(loss)
        dt = time.perf_counter() - t0
        return {
            "decode_records_per_sec": round(n_rec / read_dt, 1),
            "columnar_records_per_sec": round(n_rec / col_dt, 1),
            "train_images_per_sec": round(n_img / dt, 1) if n_img else None,
            "records": n_rec, "batch": batch,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _segmentation_bench(dev, on_tpu):
    """BASELINE config #4: MobileNetV2-UNet segmentation train step."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu.models import segmentation

    batch, size, steps = (16, 256, 10) if on_tpu else (2, 64, 2)

    opt = optax.adam(1e-3)

    @jax.jit
    def init_all(key):
        params, state = segmentation.init(key, num_classes=21)
        return params, state, opt.init(params)

    params, state, opt_state = init_all(jax.random.PRNGKey(0))
    step_fn = segmentation.make_train_step(opt)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((batch, size, size, 3), np.float32),
                         jnp.float32)
    masks = jnp.asarray(rng.integers(0, 21, (batch, size, size)), jnp.int32)

    @jax.jit
    def run(params, state, opt_state, images, masks):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss = step_fn(p, s, o, images, masks)
            return (p, s, o), loss
        (_, _, _), losses = lax.scan(
            body, (params, state, opt_state), None, length=steps)
        return losses[-1]

    dt, loss = _time_scanned(run, params, state, opt_state, images, masks)
    from tensorflowonspark_tpu.utils import metrics as M

    ips = batch * steps / dt
    # MFU counts fwd+bwd ≈ 3x forward (resnet-lane convention); the
    # reported flops field stays forward-only to match the
    # metrics.segmentation_flops_per_image helper and flops_per_row
    fwd_flops = M.segmentation_flops_per_image(size, num_classes=21)
    return {
        "images_per_sec_per_chip": round(ips, 1),
        "mfu": round(ips * 3.0 * fwd_flops / _peak_flops(dev), 4),
        "fwd_flops_per_image": fwd_flops,
        "batch": batch, "image": size, "steps": steps, "loss": loss,
    }


def _inference_bench(dev, on_tpu):
    """BASELINE config #5: Spark-ML-style cached-model batch inference
    through pipeline._run_model (marshalling + device forward)."""
    import shutil
    import tempfile

    import jax

    from tensorflowonspark_tpu import pipeline as P
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    n_rows = 16384 if on_tpu else 1024
    tmp = tempfile.mkdtemp(prefix="tfos_bench_inf_")
    try:
        params = mnist.init_params(jax.random.PRNGKey(0))
        export = os.path.join(tmp, "export")
        ckpt.export_model(export, params, metadata={
            "predict": "tensorflowonspark_tpu.models.mnist:predict",
        })
        rng = np.random.default_rng(0)
        rows = [(list(map(float, r)),)
                for r in rng.random((n_rows, 784), np.float32)]
        args = P.Namespace({
            "export_dir": export, "batch_size": 1024,
            "input_mapping": {"features": "image"},
            "output_mapping": {"prediction": "pred"},
        })
        run = P._run_model(args)
        warm = run(iter(rows[:1024]))  # load + compile
        assert len(warm) == 1024
        t0 = time.perf_counter()
        out = run(iter(rows))
        dt = time.perf_counter() - t0
        assert len(out) == n_rows and "pred" in out[0]
        from tensorflowonspark_tpu.utils import metrics as M

        rps = n_rows / dt
        flops = M.mnist_inference_flops_per_row()  # forward only
        return {"rows_per_sec": round(rps, 1),
                "mfu": round(rps * flops / _peak_flops(dev), 6),
                "fwd_flops_per_row": flops,
                "rows": n_rows, "batch": 1024}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _serve_bench(dev, on_tpu):
    """Online-serving lane (TFOS_BENCH_SERVE=0 to skip): a 2-replica
    CPU service under OPEN-LOOP Poisson load — latency p50/p99, req/s,
    shed rate, micro-batch coalescing and the per-bucket compile counts
    (docs/serving.md).  Open loop (serving/decode/loadgen.py) replaced
    the old closed-loop client burst: a closed loop self-throttles when
    the server slows, hiding queueing collapse; arrivals now fire on a
    seeded schedule regardless of outstanding requests, so the p99 is
    the one the SLO is written against.  TFOS_BENCH_SERVE_RPS sets the
    offered rate, TFOS_BENCH_SERVE_N the request count; the legacy
    TFOS_BENCH_SERVE_CLIENTS x TFOS_BENCH_SERVE_REQUESTS pair survives
    as a deprecated alias for the total when TFOS_BENCH_SERVE_N is
    unset.

    Replicas are FORCED onto CPU regardless of the bench device: the
    tunnel serializes TPU claims, and the main bench process holds the
    claim — a second jax-on-axon process would wedge both.
    """
    import shutil
    import tempfile

    import jax

    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.serving.decode import run_open_loop
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    replicas = int(os.environ.get("TFOS_BENCH_SERVE_REPLICAS", "2"))
    # deprecated alias: CLIENTS x REQUESTS was the closed-loop total
    clients = int(os.environ.get("TFOS_BENCH_SERVE_CLIENTS", "64"))
    per_client = int(os.environ.get("TFOS_BENCH_SERVE_REQUESTS", "6"))
    n_requests = int(os.environ.get("TFOS_BENCH_SERVE_N",
                                    str(clients * per_client)))
    rate_rps = float(os.environ.get("TFOS_BENCH_SERVE_RPS", "120"))
    tmp = tempfile.mkdtemp(prefix="tfos_bench_serve_")
    try:
        params = mnist.init_params(jax.random.PRNGKey(0))
        export = os.path.join(tmp, "export")
        ckpt.export_model(export, params, metadata={
            "predict": "tensorflowonspark_tpu.models.mnist:serve_predict",
        })
        spec = serving.ModelSpec(export_dir=export)
        rng = np.random.default_rng(0)
        images = rng.random((64, 28, 28, 1), np.float32)

        with serving.Server(
            spec, num_replicas=replicas, max_batch=32, max_delay_ms=5,
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
        ) as srv:
            client = srv.client()
            # warmup: first predicts pay jax import + bucket-1 compile
            for _ in range(2):
                client.predict({"image": images[0]}, timeout=120)

            def request(i):
                # one loadgen arrival = one trace root; the in-process
                # client shares the arrival thread so the replica-bound
                # serve/predict span joins this tree via the TLS stack
                with telemetry.trace_span(telemetry.BENCH_REQUEST,
                                          lane="serve", req=i):
                    return client.predict(
                        {"image": images[i % len(images)]}, timeout=120)

            stats = run_open_loop(
                request,
                rate_rps=rate_rps, n_requests=n_requests, seed=0,
                shed_exc=serving.Overloaded)
            summ = srv.summary(include_replicas=True)

        out = {
            "requests": stats["requests"],
            "req_per_sec": stats["completed_rps"],
            "offered_rps": stats["offered_rps"],
            "p50_ms": stats["latency_p50_ms"],
            "p99_ms": stats["latency_p99_ms"],
            "shed": stats["shed"],
            "shed_rate": summ.get("shed_rate"),
            "mean_device_batch": summ.get("mean_device_batch"),
            "buckets": summ.get("buckets"),
            "replicas": replicas,
            "client_errors": stats["errors"],
        }
        compiles = {}
        for st in (summ.get("replica_stats") or {}).values():
            for sig, n in (st.get("compiles") or {}).items():
                compiles[sig] = compiles.get(sig, 0) + n
        if compiles:
            out["compiles"] = compiles
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _elastic_serve_bench(dev, on_tpu):
    """Elastic-serving lane (TFOS_BENCH_ELASTIC_SERVE=0 to skip): the
    serve lane's open-loop Poisson load against a 2-replica
    degrade-by-resize pool, with one replica SIGKILLed a third of the
    way through the arrival schedule (docs/serving.md "Degrade by
    resize").  Reports the degraded-window p99, the pool resize time
    and ``dropped`` — client-visible request errors, which the
    zero-drop contract pins at 0 (sheds are counted separately; they
    are explicit 503s, not drops).  Replicas are CPU-forced like the
    serve lane: this measures failover choreography, not the chip.
    """
    import shutil
    import signal
    import tempfile
    import threading

    import jax

    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.serving.decode import run_open_loop
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    n_requests = int(os.environ.get("TFOS_BENCH_ELASTIC_SERVE_N", "240"))
    rate_rps = float(os.environ.get("TFOS_BENCH_ELASTIC_SERVE_RPS", "80"))
    tmp = tempfile.mkdtemp(prefix="tfos_bench_eserve_")
    try:
        params = mnist.init_params(jax.random.PRNGKey(0))
        export = os.path.join(tmp, "export")
        ckpt.export_model(export, params, metadata={
            "predict": "tensorflowonspark_tpu.models.mnist:serve_predict",
        })
        spec = serving.ModelSpec(export_dir=export)
        rng = np.random.default_rng(0)
        images = rng.random((64, 28, 28, 1), np.float32)

        with serving.Server(
            spec, num_replicas=2, max_batch=32, max_delay_ms=5,
            elastic=True,
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
        ) as srv:
            client = srv.client()
            for _ in range(2):
                client.predict({"image": images[0]}, timeout=120)

            kill_at = max(1, n_requests // 3)
            killed = {"pid": None}
            deg_lock = threading.Lock()
            deg_ms = []

            def request(i):
                if i == kill_at and killed["pid"] is None:
                    live = srv.pool.live_replicas()
                    victim = srv.pool.replica_pids()[live[0]]
                    killed["pid"] = victim
                    os.kill(victim, signal.SIGKILL)
                with telemetry.trace_span(telemetry.BENCH_REQUEST,
                                          lane="elastic_serve", req=i):
                    t0 = time.perf_counter()
                    row = client.predict(
                        {"image": images[i % len(images)]}, timeout=120)
                    if srv.pool.degraded:
                        with deg_lock:
                            deg_ms.append((time.perf_counter() - t0) * 1e3)
                    return row and None

            stats = run_open_loop(
                request,
                rate_rps=rate_rps, n_requests=n_requests, seed=0,
                shed_exc=serving.Overloaded)
            # regrow: the engine respawn adopts live params, the pool
            # reshards back to full capacity — wait for it so the lane
            # reports the restored state, not a race
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (len(srv.pool.live_replicas()) == 2
                        and not srv.pool.degraded):
                    break
                time.sleep(0.2)
            pool = srv.pool.describe()

        deg_sorted = sorted(deg_ms)
        deg_p99 = (deg_sorted[min(len(deg_sorted) - 1,
                                  round(0.99 * (len(deg_sorted) - 1)))]
                   if deg_sorted else None)
        return {
            "requests": stats["requests"],
            "req_per_sec": stats["completed_rps"],
            "offered_rps": stats["offered_rps"],
            "p50_ms": stats["latency_p50_ms"],
            "p99_ms": stats["latency_p99_ms"],
            # degraded-window latency; falls back to overall p99 when
            # the resize outran every in-window arrival (samples says so)
            "degraded_p99_ms": (round(deg_p99, 3) if deg_p99 is not None
                                else stats["latency_p99_ms"]),
            "degraded_samples": len(deg_sorted),
            "resize_ms": pool["last_resize_ms"],
            "resizes": pool["resizes"],
            "generation": pool["generation"],
            "adoptions": pool["adoptions"],
            "regrown": pool["live"],
            "shed": stats["shed"],
            "dropped": stats["errors"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _deploy_probe_predict(params, inputs):
    """Module-level probe model for the deploy lane (cloudpickled into
    the CPU replicas): answers with the params version that served it."""
    x = np.asarray(inputs["x"])
    return {"version": np.full(x.shape[0],
                               float(np.asarray(params["version"])))}


def _deploy_bench(dev, on_tpu):
    """Blessed-deployment lane (TFOS_BENCH_DEPLOY=0 to skip): the serve
    lane's open-loop Poisson load against a 3-replica CPU pool while the
    deployment loop (workloads/deploy_loop.py) walks one full staged
    promotion and one full auto-rollback (docs/deployment.md).  Reports
    the end-to-end commit latency of each transition (candidate blessed
    -> pool converged), the under-rollout p99, and ``dropped`` —
    client-visible request errors across both transitions, which the
    zero-drop contract pins at 0 (bench_check gates it).  Replicas are
    CPU-forced like the serve lanes: this measures rollout
    choreography, not the chip."""
    import shutil
    import tempfile
    import threading

    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.serving.decode import run_open_loop
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.workloads.deploy_loop import DeployLoop

    n_requests = int(os.environ.get("TFOS_BENCH_DEPLOY_N", "240"))
    rate_rps = float(os.environ.get("TFOS_BENCH_DEPLOY_RPS", "60"))
    burn_secs = float(os.environ.get("TFOS_BENCH_DEPLOY_BURN", "1.0"))
    tmp = tempfile.mkdtemp(prefix="tfos_bench_deploy_")
    try:
        d = os.path.join(tmp, "ckpt")

        def publish(step, score):
            # trainer + promotion-gate surrogate: checkpoint arrives
            # already blessed (the gate itself is timed in the e2e test
            # lane, not here — this lane times the rollout)
            ckpt.save_checkpoint(
                d, {"version": np.array(float(step))}, step=step)
            ckpt.bless_checkpoint(d, step, score=score)

        publish(1, 0.5)
        spec = serving.ModelSpec(predict=_deploy_probe_predict,
                                 ckpt_dir=d, jit=False)
        x = np.zeros(8, np.float32)
        with serving.Server(
            spec, num_replicas=3, max_batch=32, max_delay_ms=5,
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
        ) as srv:
            client = srv.client()
            for _ in range(2):
                client.predict({"x": x}, timeout=120)

            loop = DeployLoop(srv.pool, d, pct=50, canary_count=1,
                              burn_secs=burn_secs, min_samples=1,
                              lat_tol=20.0)
            loop.pump()  # bootstrap: pin the pool at step 1
            stop = threading.Event()

            def pumper():
                while not stop.is_set():
                    try:
                        loop.pump()
                    except Exception:  # noqa: BLE001 - lane must finish
                        pass
                    stop.wait(0.05)

            pump_thread = threading.Thread(target=pumper, daemon=True)
            pump_thread.start()

            def request(i):
                with telemetry.trace_span(telemetry.BENCH_REQUEST,
                                          lane="deploy", req=i):
                    return client.predict({"x": x}, timeout=120)

            def wait_for(cond, what, timeout=60):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return
                    time.sleep(0.05)
                raise RuntimeError(f"deploy lane: {what} never landed "
                                   f"({loop.status()})")

            # phase 1: a clean candidate canaries and promotes under load
            t1 = time.perf_counter()
            publish(2, 0.45)
            stats1 = run_open_loop(
                request, rate_rps=rate_rps, n_requests=n_requests,
                seed=0, shed_exc=serving.Overloaded)
            wait_for(lambda: loop.promotions >= 2, "promotion")
            promote_s = time.perf_counter() - t1

            # phase 2: a regressed candidate auto-rolls back under load
            t2 = time.perf_counter()
            publish(3, 50.0)  # 100x the blessed score: eval regression
            stats2 = run_open_loop(
                request, rate_rps=rate_rps, n_requests=n_requests,
                seed=1, shed_exc=serving.Overloaded)
            wait_for(lambda: loop.rollbacks >= 1, "rollback")
            rollback_s = time.perf_counter() - t2
            stop.set()
            pump_thread.join(timeout=10)
            watermark = srv.pool.watermark()

        return {
            "requests": stats1["requests"] + stats2["requests"],
            "req_per_sec": round((stats1["completed_rps"]
                                  + stats2["completed_rps"]) / 2, 3),
            "p99_ms": max(stats1["latency_p99_ms"],
                          stats2["latency_p99_ms"]),
            "promote_s": round(promote_s, 3),
            "rollback_s": round(rollback_s, 3),
            "promotions": loop.promotions,
            "rollbacks": loop.rollbacks,
            "watermark": watermark,
            "shed": stats1["shed"] + stats2["shed"],
            "dropped": stats1["errors"] + stats2["errors"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _decode_bench(dev, on_tpu):
    """Autoregressive-decode lane (TFOS_BENCH_DECODE=0 to skip): a
    2-replica continuous-batching decode service under open-loop
    Poisson session arrivals — TTFT p50/p99, per-token-gap p50/p99 and
    aggregate tokens/s, the three SLO numbers docs/serving.md defines
    for the decode tier.

    TFOS_BENCH_DECODE_PREFIX (default 0.6) is the fraction of sessions
    that share one of a small pool of long system prompts; the lane runs
    a second arm with prefix sharing disabled on the same trace and
    reports its TTFT p50 as ``nosharing_ttft_p50_ms`` (the paged-cache
    win is TTFT p50 strictly below that arm plus a nonzero
    ``prefix_hit_rate`` / ``prefill_tokens_saved``).

    Like the serve lane, replicas are FORCED onto CPU: the main bench
    process may hold the (serialized) TPU claim.
    """
    import shutil
    import tempfile

    import jax

    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.models import transformer as _tfm
    from tensorflowonspark_tpu.serving.decode import (run_open_loop,
                                                      shared_prefix_prompts)
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    replicas = int(os.environ.get("TFOS_BENCH_DECODE_REPLICAS", "2"))
    slots = int(os.environ.get("TFOS_BENCH_DECODE_SLOTS", "8"))
    n_sessions = int(os.environ.get("TFOS_BENCH_DECODE_N", "24"))
    rate_rps = float(os.environ.get("TFOS_BENCH_DECODE_RPS", "4"))
    max_tokens = int(os.environ.get("TFOS_BENCH_DECODE_TOKENS", "16"))
    prefix_frac = float(os.environ.get("TFOS_BENCH_DECODE_PREFIX", "0.6"))
    cfg = _tfm.Config(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                      max_seq=128, dtype="float32", attn_impl="reference")
    tmp = tempfile.mkdtemp(prefix="tfos_bench_decode_")
    try:
        params = _tfm.init(jax.random.PRNGKey(0), cfg)
        export = os.path.join(tmp, "export")
        ckpt.export_model(export, params, metadata={})
        prompts, pool = shared_prefix_prompts(
            n_sessions, vocab_size=cfg.vocab_size,
            prefix_frac=prefix_frac, seed=0)
        warm = pool[0] + prompts[0][-4:]
        # same pool prefix, full-width tail: compiles the trie-matched
        # extend bucket (tail bucket 16, 4 shared blocks) the measured
        # shared sessions land in
        warm_tail = pool[0] + pool[1][:16]

        def _prefix_stats(srv):
            out = {"prefix_hits": 0, "prefix_tokens_saved": 0}
            for rep in srv.summary(
                    include_replicas=True)["replica_stats"].values():
                d = (rep or {}).get("decode") or {}
                for k in out:
                    out[k] += int(d.get(k) or 0)
            return out

        def _arm(sharing):
            spec = serving.ModelSpec(
                export_dir=export,
                decode=serving.DecodeSpec(cfg, slots=slots,
                                          max_tokens=max_tokens,
                                          prefix_sharing=sharing))
            with serving.Server(
                spec, num_replicas=replicas, request_timeout=300,
                env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
            ) as srv:
                # warmup: pay jax import + prefill/decode_step compiles
                # on every replica before the clock starts; two
                # same-prefix generations per replica also seed the trie
                # and warm the matched extend path when sharing is on
                for _ in range(2 * replicas):
                    srv.generate(warm, max_tokens=2, timeout=300)
                for _ in range(replicas):
                    srv.generate(warm_tail, max_tokens=2, timeout=300)
                base = _prefix_stats(srv)

                def session(i):
                    with telemetry.trace_span(telemetry.BENCH_REQUEST,
                                              lane="decode", req=i):
                        out = srv.generate(prompts[i % len(prompts)],
                                           max_tokens=max_tokens,
                                           timeout=300)
                    return {"ttft_ms": out.get("ttft_ms"),
                            "token_ms": out.get("token_ms"),
                            "tokens": len(out.get("tokens") or ())}

                stats = run_open_loop(session, rate_rps=rate_rps,
                                      n_requests=n_sessions, seed=0,
                                      shed_exc=serving.Overloaded)
                after = _prefix_stats(srv)
            return stats, {k: after[k] - base[k] for k in after}

        stats, pstats = _arm(True)
        nosharing, _ = _arm(False)

        completed = max(1, stats["completed"])
        return {
            "sessions": stats["requests"],
            "completed": stats["completed"],
            "shed": stats["shed"],
            "errors": stats["errors"],
            "offered_rps": stats["offered_rps"],
            "tokens": stats.get("tokens", 0),
            "tokens_per_sec": stats.get("tokens_per_sec", 0.0),
            "ttft_p50_ms": stats.get("ttft_p50_ms"),
            "ttft_p99_ms": stats.get("ttft_p99_ms"),
            "tok_p50_ms": stats.get("tok_p50_ms"),
            "tok_p99_ms": stats.get("tok_p99_ms"),
            "prefix_frac": prefix_frac,
            "prefix_hits": pstats["prefix_hits"],
            "prefix_hit_rate": round(
                pstats["prefix_hits"] / completed, 4),
            "prefill_tokens_saved": pstats["prefix_tokens_saved"],
            "nosharing_ttft_p50_ms": nosharing.get("ttft_p50_ms"),
            "replicas": replicas,
            "slots": slots,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _fabric_bench(dev, on_tpu):
    """Pod-scale fabric lane (TFOS_BENCH_SERVE_FABRIC=0 to skip): the
    decode lane's open-loop Poisson sessions against a multi-host
    fabric (``Server(fabric=True)``, >=2 host processes) with stable
    per-session route ids, while (a) the autoscaler grows replicas
    1 -> N under the induced queueing and (b) the host an affinity-bound
    session targets is SIGKILLed a third of the way through the arrival
    schedule (docs/serving.md "Pod-scale fabric").  Reports p99 across
    the whole run (bench_check gates no-regression as replicas scale),
    ``dropped`` — client-visible errors, pinned at 0 by the zero-drop
    contract — plus ``affinity_hit_rate`` and the actuated
    ``scale_ups``.  Hosts are CPU-forced like every serving lane: this
    measures fabric choreography, not the chip."""
    import shutil
    import signal
    import tempfile
    import threading

    import jax

    from tensorflowonspark_tpu import serving
    from tensorflowonspark_tpu.models import transformer as _tfm
    from tensorflowonspark_tpu.serving.decode import (run_open_loop,
                                                      session_route_ids)
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    hosts = int(os.environ.get("TFOS_BENCH_FABRIC_HOSTS", "2"))
    n_sessions = int(os.environ.get("TFOS_BENCH_FABRIC_N", "48"))
    rate_rps = float(os.environ.get("TFOS_BENCH_FABRIC_RPS", "16"))
    max_tokens = int(os.environ.get("TFOS_BENCH_FABRIC_TOKENS", "12"))
    route_sessions = int(os.environ.get("TFOS_BENCH_FABRIC_SESSIONS", "8"))
    cfg = _tfm.Config(vocab_size=61, dim=32, n_layers=2, n_heads=2,
                      max_seq=64, dtype="float32", attn_impl="reference")
    tmp = tempfile.mkdtemp(prefix="tfos_bench_fabric_")
    try:
        params = _tfm.init(jax.random.PRNGKey(0), cfg)
        export = os.path.join(tmp, "export")
        ckpt.export_model(export, params, metadata={})
        spec = serving.ModelSpec(
            export_dir=export,
            decode=serving.DecodeSpec(cfg, slots=4, max_tokens=max_tokens))
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, cfg.vocab_size,
                                size=5 + i % 8).tolist()
                   for i in range(n_sessions)]
        ids = session_route_ids(n_sessions, sessions=route_sessions,
                                seed=1)
        # low=0.0 suppresses mid-run scale-DOWN so the lane measures a
        # clean 1 -> N growth; the router's LIFO retire is the slow
        # lane's business (tests/test_fabric.py)
        with serving.Server(
            spec, fabric=True, fabric_hosts=hosts, replicas_per_host=1,
            request_timeout=300, decode_queue_max=4 * n_sessions,
            autoscale={"min_replicas": 1, "max_replicas": 3,
                       "high": 1.5, "low": 0.0, "cooldown": 1.0,
                       "tick_secs": 0.2},
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
        ) as srv:
            # warmup: pay jax import + prefill/decode compiles on every
            # host before the clock starts, and bind the kill victim
            for _ in range(2 * hosts):
                srv.generate(prompts[0], max_tokens=2, timeout=300)
            srv.generate(prompts[0], max_tokens=2, timeout=300,
                         route_id=ids[0])
            victim = srv.pool.affinity_binding(ids[0])[0]
            kill_at = max(1, n_sessions // 3)
            killed = {"pid": None}

            def session(i, route_id):
                if i == kill_at and killed["pid"] is None:
                    pid = srv.pool.host_pids().get(victim)
                    if pid:
                        killed["pid"] = pid
                        os.kill(pid, signal.SIGKILL)
                with telemetry.trace_span(telemetry.BENCH_REQUEST,
                                          lane="serve_fabric", req=i):
                    out = srv.generate(prompts[i], max_tokens=max_tokens,
                                       timeout=300, route_id=route_id)
                return {"ttft_ms": out.get("ttft_ms"),
                        "tokens": len(out.get("tokens") or ()),
                        "affinity": out.get("affinity")}

            stats = run_open_loop(session, rate_rps=rate_rps,
                                  n_requests=n_sessions, seed=0,
                                  shed_exc=serving.Overloaded,
                                  route_fn=ids.__getitem__)
            # regrow: wait for the killed host's respawn so the lane
            # reports the restored fabric, not a race
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(srv.pool.live_replicas()) == hosts:
                    break
                time.sleep(0.2)
            desc = srv.pool.describe()

        return {
            "sessions": stats["requests"],
            "completed": stats["completed"],
            "req_per_sec": stats["completed_rps"],
            "offered_rps": stats["offered_rps"],
            "p50_ms": stats["latency_p50_ms"],
            "p99_ms": stats["latency_p99_ms"],
            "ttft_p50_ms": stats.get("ttft_p50_ms"),
            "ttft_p99_ms": stats.get("ttft_p99_ms"),
            "tokens_per_sec": stats.get("tokens_per_sec", 0.0),
            "shed": stats["shed"],
            "dropped": stats["errors"],
            "affinity_hit_rate": stats.get("affinity_hit_rate", 0.0),
            "affinity_hits": stats.get("affinity_hits", 0),
            "affinity_fallbacks": stats.get("affinity_fallbacks", 0),
            "hosts": hosts,
            "replicas_final": desc["replicas"],
            "scale_ups": desc["scale_ups"],
            "redispatched": desc["redispatched"],
            "respawns": desc["respawns"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _data_bench(dev, on_tpu):
    """Input-pipeline lane (TFOS_BENCH_DATA=0 to skip): host-side rec/s
    for the three feeding tiers over the same 784-float TFRecord shards —
    (a) raw ``dfutil.iter_tfrecords_columnar``, (b) the composed data/
    pipeline graph (interleave + map + batch + prefetch), (c) a mini
    in-process data service serving one consumer over the manager wire
    (queue transport, ledger-less).  Host-side only: never touches jax
    or the device, so it is safe alongside a TPU claim (docs/data.md)."""
    import secrets
    import shutil
    import tempfile
    import threading

    from tensorflowonspark_tpu import data, dfutil, recordio
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.data import service as dsvc
    from tensorflowonspark_tpu.feed import DataFeed

    n = int(os.environ.get("TFOS_BENCH_DATA_RECORDS", "8192"))
    width = 784
    batch = 256
    per = max(1, n // 4)
    tmp = tempfile.mkdtemp(prefix="tfos_bench_data_")
    try:
        rng = np.random.default_rng(0)
        for s in range(4):
            base = rng.random((per, width), dtype=np.float32)
            with recordio.TFRecordWriter(
                    os.path.join(tmp, f"part-{s:05d}")) as w:
                for i in range(per):
                    w.write(recordio.encode_example(
                        {"x": ("float", base[i].tolist()),
                         "y": ("int64", [s * per + i])}))
        total = 4 * per
        out = {"records": total, "width": width, "batch": batch}

        t0 = time.perf_counter()
        seen = 0
        for cols in dfutil.iter_tfrecords_columnar(tmp, batch):
            seen += len(cols["y"])
        out["raw_records_per_sec"] = round(seen / (time.perf_counter() - t0),
                                           1)

        pipe = (data.from_tfrecords(tmp, block_size=batch)
                .interleave(cycle_length=2)
                .map(lambda b: {"x": b["x"] * (1.0 / 255.0), "y": b["y"]})
                .batch(batch)
                .prefetch(4))
        t0 = time.perf_counter()
        seen = 0
        for blk in pipe.blocks():
            seen += len(blk["y"])
        out["pipeline_records_per_sec"] = round(
            seen / (time.perf_counter() - t0), 1)

        # mini data service: one trainer stream over the manager queue,
        # drained by an in-process DataFeed consumer thread
        authkey = secrets.token_bytes(16)
        mgr = tfmanager.start(authkey, ["input", "output", "error"])
        meta = {"executor_id": 0, "host": "localhost", "job_name": "worker",
                "addr": list(mgr.address), "authkey": authkey.hex()}
        svc = dsvc.DataService(
            pipe, cluster_info=[meta],
            cluster_meta={"server_addr": ("127.0.0.1", 1)},
            qname="input", num_workers=1, worker_index=0)
        feed = DataFeed(mgr, train_mode=True,
                        input_mapping={"x": "x", "y": "y"})
        got = [0]

        def drain():
            while got[0] < total:
                cols = feed.next_batch_columns(batch)
                got[0] += len(cols.get("y", ()))

        t0 = time.perf_counter()
        consumer = threading.Thread(target=drain, daemon=True)
        consumer.start()
        svc.run()
        consumer.join(timeout=120)
        dt = time.perf_counter() - t0
        mgr.set("state", "stopped")
        out["service_records_per_sec"] = round(got[0] / dt, 1)
        out["service_records"] = got[0]

        # dynamic-split dispatch over the same wire: board + provider +
        # one DynamicDataService worker, FCFS split claims (ISSUE 19)
        from tensorflowonspark_tpu.data import splits as dsplits

        bkey = secrets.token_bytes(16)
        bmgr = tfmanager.start(bkey, [])
        akey = secrets.token_bytes(16)
        amgr = tfmanager.start(akey, ["input", "output", "error"])
        ameta = {"executor_id": 0, "host": "localhost",
                 "job_name": "worker", "addr": list(amgr.address),
                 "authkey": akey.hex()}
        board = dsplits.SplitBoard(bmgr, "input")
        board.set_plan([0])

        class _Ctx:
            def __init__(self, m):
                self.mgr = m
                self._kv = {}

            def kv_get(self, k):
                return self._kv.get(k)

            def kv_set(self, k, v):
                self._kv[k] = v

        ictx = _Ctx(bmgr)
        provider = dsplits.SplitProvider("input", server_addr=None,
                                         num_epochs=1, window=16)
        provider.on_start(ictx)
        dyn = dsvc.DynamicDataService(
            pipe, cluster_info=[ameta],
            cluster_meta={dsvc.SPLIT_BOARD_META: {
                "address": tuple(bmgr.address), "authkey": bkey}},
            qname="input", worker_index=0, use_cache=False)
        # ledger-less board: completion needs the provider to see done
        # splits, which NullLedgerClient never reports — drain by count
        dfeed = DataFeed(amgr, train_mode=True,
                         input_mapping={"x": "x", "y": "y"})
        dgot = [0]

        def ddrain():
            while dgot[0] < total:
                cols = dfeed.next_batch_columns(batch)
                dgot[0] += len(cols.get("y", ()))

        stop_tick = threading.Event()

        def dtick():
            while not stop_tick.is_set() and not board.complete():
                provider.on_tick(ictx)
                time.sleep(0.02)

        t0 = time.perf_counter()
        dconsumer = threading.Thread(target=ddrain, daemon=True)
        dworker = threading.Thread(target=dyn.run, daemon=True)
        ticker = threading.Thread(target=dtick, daemon=True)
        dconsumer.start()
        dworker.start()
        ticker.start()
        dconsumer.join(timeout=120)
        dt = time.perf_counter() - t0
        # ledger-less lane: completion is declared here, not by the
        # provider — lets the worker exit instead of idling on claims
        board.set_complete()
        stop_tick.set()
        dworker.join(timeout=30)
        amgr.set("state", "stopped")
        out["dynamic_records_per_sec"] = round(dgot[0] / dt, 1)
        out["dynamic_records"] = dgot[0]

        # shared epoch cache: decode once, replay from memory/spill
        from tensorflowonspark_tpu.data import cache as dcache

        epoch_cache = dcache.EpochCache(pipe)
        t0 = time.perf_counter()
        seen = sum(len(b["y"]) for b in epoch_cache.blocks_range())
        out["cache_cold_records_per_sec"] = round(
            seen / (time.perf_counter() - t0), 1)
        t0 = time.perf_counter()
        seen = sum(len(b["y"]) for b in epoch_cache.blocks_range())
        hit_rps = seen / (time.perf_counter() - t0)
        epoch_cache.close()
        out["cache_hit_records_per_sec"] = round(hit_rps, 1)
        if out["pipeline_records_per_sec"]:
            # the ISSUE 19 shared-cache gate: second consumer reads at
            # >= 5x the cold pipeline rec/s
            out["cache_hit_speedup"] = round(
                hit_rps / out["pipeline_records_per_sec"], 2)

        # straggler A/B (TFOS_BENCH_DATA_STRAGGLER=0 to skip): the
        # stress_fed service-dynamic lane in a scrubbed-CPU subprocess
        # (host-only: spawns consumer processes, never touches jax)
        if os.environ.get("TFOS_BENCH_DATA_STRAGGLER", "1") != "0":
            import subprocess
            import sys

            env = dict(os.environ)
            env.update({"PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
            root = os.path.dirname(os.path.abspath(__file__))
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(root, "scripts", "stress_fed.py"),
                 "--mode", "service-dynamic"],
                capture_output=True, text=True, timeout=300, cwd=root,
                env=env)
            line = None
            for ln in reversed(proc.stdout.splitlines()):
                ln = ln.strip()
                if ln.startswith("{"):
                    line = json.loads(ln)
                    break
            if proc.returncode or line is None:
                out["straggler_error"] = (proc.stderr or proc.stdout)[-200:]
            else:
                out["straggler_ratio"] = line["straggler_ratio"]
                out["straggler_speedup"] = line["straggler_speedup"]
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _elastic_bench(dev, on_tpu):
    """Elastic-runtime lane (TFOS_BENCH_ELASTIC=0 to skip): mesh build /
    resize / reshard / cross-mesh restore latencies on 8 fake CPU
    devices (docs/elastic.md).  Runs scripts/bench_elastic.py in a
    SUBPROCESS with a scrubbed CPU env so it never contends for the TPU
    claim the main bench process may hold."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    root = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "bench_elastic.py")],
        capture_output=True, text=True, timeout=600, cwd=root, env=env)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode or not lines:
        raise RuntimeError(
            f"bench_elastic rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-300:]}")
    return json.loads(lines[-1])


def _actors_bench(dev, on_tpu):
    """Actor-substrate micro-lane (TFOS_BENCH_ACTORS=0 to skip): ask
    round-trip latency through the mailbox wire and SIGKILL->respawn
    resume time on a 2-member EchoActor group (docs/actors.md).
    Members run with a scrubbed CPU env and never import jax, so the
    lane is safe alongside a TPU claim the main process holds."""
    from tensorflowonspark_tpu.actors import (
        ActorSystem, EchoActor, SupervisionPolicy,
    )

    n = int(os.environ.get("TFOS_BENCH_ACTORS_N", "200"))
    pol = SupervisionPolicy(heartbeat_secs=0.2, stale_secs=5.0,
                            tick_secs=0.1)
    with ActorSystem(2, env={"JAX_PLATFORMS": "cpu",
                             "PYTHONPATH": ""}) as system:
        g = system.spawn(EchoActor(), "bench", count=2, policy=pol)
        for i in range(10):  # warm the wire (queue proxies, pickler)
            g.ask("echo", i).result(60)
        lat = []
        for i in range(n):
            t0 = time.perf_counter()
            g.ask("echo", i).result(60)
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        # failover clock: SIGKILL member 0, time until the supervisor
        # has observed the respawn AND the slot answers again
        pid0 = g.ask("pid", index=0).result(60)
        t0 = time.perf_counter()
        g.tell("crash", index=0)
        resumed = None
        while time.perf_counter() - t0 < 120:
            try:
                changed = g.ask("pid", index=0).result(10) != pid0
            except Exception:  # noqa: BLE001 - mid-failover ask may fail
                changed = False
            if changed and g.respawns_observed >= 1:
                resumed = (time.perf_counter() - t0) * 1e3
                break
        if resumed is None:
            raise RuntimeError("member never respawned within 120s")
        return {
            "asks": n,
            "ask_p50_ms": round(lat[n // 2], 3),
            "ask_p99_ms": round(lat[min(n - 1, int(n * 0.99))], 3),
            "respawn_resume_ms": round(resumed, 1),
        }


if __name__ == "__main__":
    main()
