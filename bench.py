"""Benchmark: ResNet-50 training throughput on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
value is model FLOPs utilization (MFU) of the ResNet-50 train step and
vs_baseline is relative to the BASELINE.json north-star of 0.50 MFU.
Also reports images/sec/chip inside the same line's "extra" field.
"""

import json
import os
import time

import numpy as np

# known bf16 peak TFLOP/s per chip by device kind substring
_PEAKS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,  # trillium
}


def _peak_flops(device):
    env = os.environ.get("TFOS_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for k, v in _PEAKS.items():
        if k in kind:
            return v
    return 197e12  # default: v5e


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import resnet

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch = int(os.environ.get("TFOS_BENCH_BATCH", "256" if on_tpu else "16"))
    image = int(os.environ.get("TFOS_BENCH_IMAGE", "224" if on_tpu else "64"))
    steps = int(os.environ.get("TFOS_BENCH_STEPS", "20" if on_tpu else "3"))

    from jax import lax

    params, state = resnet.init(jax.random.PRNGKey(0), depth=50, num_classes=1000)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    step_fn = resnet.make_train_step(opt, depth=50)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((batch, image, image, 3), dtype=np.float32),
                         dtype=jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, batch), dtype=jnp.int32)

    # Chain `steps` train steps inside one jit (lax.scan): one dispatch,
    # one result fetch — honest device time, no per-step host round-trips
    # (and immune to async-dispatch timing artifacts).
    @jax.jit
    def run_steps(params, state, opt_state, images, labels):
        def body(carry, _):
            p, s, o = carry
            p, s, o, loss, _acc = step_fn(p, s, o, images, labels)
            return (p, s, o), loss

        (p, s, o), losses = lax.scan(body, (params, state, opt_state),
                                     None, length=steps)
        return losses[-1]

    # warmup / compile
    float(run_steps(params, state, opt_state, images, labels))

    t0 = time.perf_counter()
    loss = float(run_steps(params, state, opt_state, images, labels))
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * steps / dt
    # fwd+bwd ≈ 3x forward FLOPs
    flops_per_img = 3.0 * resnet.flops_per_image(50, image)
    achieved = imgs_per_sec * flops_per_img
    mfu = achieved / _peak_flops(dev)

    extra = {
        "images_per_sec_per_chip": round(imgs_per_sec, 1),
        "batch": batch, "image": image, "steps": steps,
        "device": str(dev), "platform": dev.platform,
        "loss": loss,
    }
    if os.environ.get("TFOS_BENCH_TRANSFORMER", "1") != "0":
        try:
            extra["transformer"] = _transformer_bench(dev, on_tpu)
        except Exception as e:  # noqa: BLE001 - secondary metric only
            extra["transformer"] = {"error": str(e)[:200]}

    print(json.dumps({
        "metric": "resnet50_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": extra,
    }))


def _transformer_bench(dev, on_tpu):
    """Secondary metric: decoder-only transformer train-step throughput
    with the pallas flash-attention kernel (tokens/sec/chip + MFU)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.utils import metrics as M

    if on_tpu:
        # largest config that fits one v5e with f32 adam state + the
        # f32 logits/CE path at seq 2048 (dim 2048 needs ~19GB)
        cfg = transformer.Config(
            vocab_size=16384, dim=1024, n_layers=8, n_heads=8,
            max_seq=2048, dtype="bfloat16", attn_impl="flash",
        )
        batch, steps = 8, 10
    else:
        cfg = transformer.Config(
            vocab_size=512, dim=128, n_layers=2, n_heads=4, max_seq=128,
            dtype="float32", attn_impl="reference",
        )
        batch, steps = 2, 3

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (batch, cfg.max_seq)),
        jnp.int32,
    )

    @jax.jit
    def run(params, opt_state, tokens):
        def body(carry, _):
            p, o = carry
            loss, grads = jax.value_and_grad(transformer.loss_fn)(
                p, tokens, cfg
            )
            updates, o = opt.update(grads, o)
            return (optax.apply_updates(p, updates), o), loss
        (p, o), losses = lax.scan(body, (params, opt_state), None,
                                  length=steps)
        return losses[-1]

    float(run(params, opt_state, tokens))  # compile
    t0 = time.perf_counter()
    loss = float(run(params, opt_state, tokens))
    dt = time.perf_counter() - t0

    toks_per_sec = batch * cfg.max_seq * steps / dt
    flops_per_tok = M.transformer_flops_per_token(cfg)
    return {
        "tokens_per_sec_per_chip": round(toks_per_sec, 1),
        "mfu": round(toks_per_sec * flops_per_tok / _peak_flops(dev), 4),
        "dim": cfg.dim, "layers": cfg.n_layers, "seq": cfg.max_seq,
        "batch": batch, "loss": loss,
    }


if __name__ == "__main__":
    main()
