"""Live metrics plane: registry semantics, node publish -> driver KV
sweep, the /metrics + /healthz + /statusz endpoint, tfos-top, and the
catalog/docs lint.

Parity framing: the reference's only runtime surface is driver log
lines (reference ``TFCluster.py:343-344``, SURVEY.md §5); these tests
pin the in-flight replacement — one env gate, no threads when off,
per-process registries that never alias across fork/spawn, and a
driver endpoint that reflects node liveness within one publish
interval.
"""

import io
import json
import multiprocessing as mp
import os
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine
from tensorflowonspark_tpu.obs import http as obs_http
from tensorflowonspark_tpu.obs import publish as obs_publish
from tensorflowonspark_tpu.obs import slo as obs_slo
from tensorflowonspark_tpu.obs import top as obs_top
from tensorflowonspark_tpu.utils import metrics_registry as reg

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tensorflowonspark_tpu")

_ENV_KEYS = (reg.PORT_ENV, reg.INTERVAL_ENV, obs_http.HOST_ENV,
             obs_slo.SPEC_ENV)


@pytest.fixture(autouse=True)
def _obs_env():
    """Every test starts gate-off with a clean registry and leaves no
    obs env behind (the gate is ambient by design: children inherit)."""
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    reg.reset()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    reg.reset()


def _enable(port="0", interval="0.2"):
    os.environ[reg.PORT_ENV] = port
    os.environ[reg.INTERVAL_ENV] = interval
    reg.reset()


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


# --- registry core ----------------------------------------------------------

def test_disabled_is_total_noop():
    assert not reg.enabled()
    reg.inc("tfos_engine_jobs_total", status="ok")
    reg.set_gauge("tfos_feed_ring_bytes", 42)
    reg.observe("tfos_train_step_ms", 12.5)
    assert reg.snapshot() is None
    # no publisher thread, no server either
    assert obs_publish.start_publisher(object(), "n-0") is None
    assert obs_http.start_for_cluster(None) is None
    names = {t.name for t in threading.enumerate()}
    assert not any(n.startswith("tfos-obs") for n in names)


def test_counter_gauge_histogram_semantics():
    _enable()
    assert reg.enabled()
    reg.inc("tfos_engine_tasks_total", status="ok")
    reg.inc("tfos_engine_tasks_total", 2, status="ok")
    reg.inc("tfos_engine_tasks_total", status="error")
    reg.set_gauge("tfos_serve_queue_depth", 7)
    reg.set_gauge("tfos_serve_queue_depth", 3)  # last write wins
    for v in (1.0, 8.0, 40.0, 900.0):
        reg.observe("tfos_train_step_ms", v)
    snap = reg.snapshot()

    tasks = {tuple(sorted(s["labels"].items())): s["value"]
             for s in snap["tfos_engine_tasks_total"]["series"]}
    assert tasks[(("status", "ok"),)] == 3.0
    assert tasks[(("status", "error"),)] == 1.0
    (q,) = snap["tfos_serve_queue_depth"]["series"]
    assert q["value"] == 3.0
    (h,) = snap["tfos_train_step_ms"]["series"]
    assert h["count"] == 4 and h["sum"] == pytest.approx(949.0)
    assert sum(h["counts"]) == 4
    assert len(h["counts"]) == len(h["bounds"]) + 1  # +Inf bin

    text = reg.render_text([({"node": "w-0"}, snap)])
    assert "# TYPE tfos_engine_tasks_total counter" in text
    assert "# HELP tfos_train_step_ms" in text
    assert 'tfos_engine_tasks_total{node="w-0",status="ok"} 3' in text
    # histogram buckets are cumulative and end at +Inf = count
    assert 'tfos_train_step_ms_bucket{le="+Inf",node="w-0"} 4' in text
    assert 'tfos_train_step_ms_count{node="w-0"} 4' in text
    m = re.findall(r'le="1000",node="w-0"} (\d+)', text)
    assert m == ["4"]  # 900ms lands at or below the 1000ms bound


def test_quantile_interpolation_and_inf_clamp():
    _enable()
    for v in (1.0, 8.0, 40.0, 900.0, 10**9):  # last -> +Inf bucket
        reg.observe("tfos_train_step_ms", v)
    (h,) = reg.snapshot()["tfos_train_step_ms"]["series"]
    p50 = reg.quantile(h, 0.5)
    assert 25.0 <= p50 <= 50.0  # interpolated inside the 25-50ms bucket
    # the +Inf bucket clamps to the last finite bound, never inf
    assert reg.quantile(h, 0.999) == h["bounds"][-1]
    assert reg.quantile({"count": 0}, 0.5) is None


def test_gate_change_rekeys_registry():
    _enable(port="0")
    reg.inc("tfos_engine_jobs_total")
    assert reg.snapshot()
    os.environ[reg.PORT_ENV] = "9090"  # different gate value
    assert reg.snapshot() == {}  # fresh registry, counts gone
    del os.environ[reg.PORT_ENV]
    assert not reg.enabled()


def _child_probe(q):
    from tensorflowonspark_tpu.utils import metrics_registry as r

    q.put({"enabled": r.enabled(), "snap": r.snapshot(),
           "pid": os.getpid()})
    r.inc("tfos_feed_chunks_total")
    q.put({"snap2": r.snapshot()})


def test_spawn_child_gets_fresh_registry():
    """A spawned child inherits the gate through the env but NOT the
    parent's counts — registries are keyed by pid."""
    _enable()
    reg.inc("tfos_engine_jobs_total", status="ok")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_probe, args=(q,))
    p.start()
    first, second = q.get(timeout=60), q.get(timeout=60)
    p.join(60)
    assert p.exitcode == 0
    assert first["enabled"] and first["pid"] != os.getpid()
    assert first["snap"] == {}  # empty, not the parent's series
    assert set(second["snap2"]) == {"tfos_feed_chunks_total"}
    # and the parent never saw the child's series
    assert "tfos_feed_chunks_total" not in reg.snapshot()


# --- instrumented subsystems (in-process) -----------------------------------

def test_checkpoint_metrics(tmp_path):
    np = pytest.importorskip("numpy")
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    _enable()
    ckpt.save_checkpoint(str(tmp_path), {"w": np.ones(4)}, step=1)
    _step, path = ckpt.latest(str(tmp_path))
    ckpt.load_checkpoint(path)
    snap = reg.snapshot()
    assert obs_http._metric_total(snap, "tfos_checkpoint_saves_total") == 1
    assert obs_http._metric_total(snap, "tfos_checkpoint_restores_total") == 1
    assert obs_http._metric_hist(snap, "tfos_checkpoint_save_ms")["count"] == 1
    assert obs_http._metric_hist(
        snap, "tfos_checkpoint_restore_ms")["count"] == 1


def test_serving_metrics():
    np = pytest.importorskip("numpy")
    from tensorflowonspark_tpu.serving import replicas as R
    from tensorflowonspark_tpu.serving import server as S

    _enable()
    spec = R.ModelSpec(predict=_double_predict, params=2.0, jit=False)
    with S.Server(spec, num_replicas=1, max_batch=8, max_delay_ms=5) as srv:
        c = srv.client()
        for i in range(4):
            c.predict({"x": np.full((2,), float(i), np.float32)}, timeout=60)
    snap = reg.snapshot()
    assert obs_http._metric_total(snap, "tfos_serve_requests_total") == 4
    assert obs_http._metric_hist(snap, "tfos_serve_request_ms")["count"] == 4
    assert obs_http._metric_total(snap, "tfos_serve_batches_total") >= 1
    # one row per request (the (2,) vector is the feature dim)
    assert obs_http._metric_total(snap, "tfos_serve_batch_rows_total") == 4
    assert obs_http._metric_gauge(snap, "tfos_serve_queue_depth") is not None


def _double_predict(params, inputs):
    return {"y": inputs["x"] * params}


def test_train_metrics_bridge():
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics

    _enable()
    os.environ["TFOS_PEAK_FLOPS"] = "1e12"
    try:
        tm = TrainMetrics(flops_per_item=1e9, device=object())
        tm.step()  # arms the timer
        for _ in range(3):
            tm.infeed_wait(0.001)
            tm.step(items=32)
    finally:
        del os.environ["TFOS_PEAK_FLOPS"]
    snap = reg.snapshot()
    assert obs_http._metric_total(snap, "tfos_train_steps_total") == 3
    assert obs_http._metric_hist(snap, "tfos_train_step_ms")["count"] == 3
    assert obs_http._metric_gauge(snap, "tfos_train_items_per_sec") > 0
    # sub-ms fake steps make the absolute MFU meaningless; just wired
    assert obs_http._metric_gauge(snap, "tfos_train_mfu") > 0
    assert obs_http._metric_gauge(
        snap, "tfos_train_infeed_stall_frac") <= 1.0
    summary = obs_http.node_summary(snap)
    assert summary["steps"] == 3 and summary["items_per_sec"] > 0
    assert summary["step_ms_p50"] <= summary["step_ms_p99"]


# --- e2e: cluster run with the endpoint up ----------------------------------

def _obs_trainer_fn(args, ctx):
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics

    tm = TrainMetrics()
    feed = ctx.get_data_feed(train_mode=True, metrics=tm)
    tm.step()
    while not feed.should_stop():
        batch = feed.next_batch(8)
        tm.step(items=len(batch))


def test_cluster_endpoints_e2e():
    """The acceptance scenario: TFOS_OBS_PORT set, a small SPARK-mode
    run, and curl-style scrapes see engine + feed + train series, a
    live /statusz, and a 200 /healthz — then everything tears down."""
    _enable(port="0", interval="0.1")
    engine = LocalEngine(2)
    cluster = None
    try:
        cluster = TFCluster.run(
            engine, _obs_trainer_fn, [], num_executors=2,
            input_mode=InputMode.SPARK)
        assert cluster.obs is not None and cluster.obs.port > 0
        base = cluster.obs.url
        ds = engine.parallelize(range(64), 2)
        cluster.train(ds)

        want = ("tfos_engine_jobs_total", "tfos_feed_chunks_total",
                "tfos_train_steps_total")
        deadline = time.monotonic() + 60
        text = ""
        while time.monotonic() < deadline:
            _, text = _get(base + "/metrics")
            if all(w in text for w in want):
                break
            time.sleep(0.2)
        assert all(w in text for w in want), text[-2000:]
        # engine counters come from the driver process ...
        assert 'node="driver"' in text
        # ... feed/train series from the published worker snapshots
        assert re.search(r'tfos_train_steps_total\{node="worker-\d"\}', text)

        # a serving roundtrip in the driver process shows up on the
        # same scrape (acceptance: engine+feed+train+serving covered)
        np = pytest.importorskip("numpy")
        from tensorflowonspark_tpu.serving import replicas as R
        from tensorflowonspark_tpu.serving import server as S
        spec = R.ModelSpec(predict=_double_predict, params=2.0, jit=False)
        with S.Server(spec, num_replicas=1, max_batch=4,
                      max_delay_ms=5) as srv:
            srv.client().predict({"x": np.ones(2, np.float32)}, timeout=60)
        _, text = _get(base + "/metrics")
        assert ('tfos_serve_requests_total'
                '{node="driver",status="ok"} 1') in text

        code, body = _get(base + "/healthz")
        health = json.loads(body)
        assert code == 200 and health["status"] == "ok"
        assert any(nid.startswith("worker-") for nid in health["nodes"])
        assert all(n["alive"] for n in health["nodes"].values())

        _, body = _get(base + "/statusz")
        status = json.loads(body)
        assert status["cluster"]["num_executors"] == 2
        workers = {nid: e for nid, e in status["nodes"].items()
                   if nid.startswith("worker-")}
        assert len(workers) == 2
        assert all(e["alive"] and e["role"] == "worker"
                   for e in workers.values())
        assert any(e["summary"].get("steps", 0) > 0
                   for e in workers.values())
        # freshness: published within a few publish intervals
        assert all(e["last_seen_age_s"] < 10 for e in workers.values()
                   if e.get("last_seen_age_s") is not None)

        # tfos-top renders the real statusz
        out = io.StringIO()
        assert obs_top.main(["--url", base, "--once"], out=out) == 0
        table = out.getvalue()
        assert "NODE" in table and "worker-0" in table and "yes" in table

        cluster.shutdown()
        assert cluster.obs is None  # server stopped with the cluster
        names = {t.name for t in threading.enumerate()}
        assert not any(n.startswith("tfos-obs") for n in names)
    finally:
        if cluster is not None and cluster.obs is not None:
            cluster.obs.stop()
        engine.stop()


def test_cluster_without_gate_has_no_obs():
    engine = LocalEngine(1)
    try:
        cluster = TFCluster.run(
            engine, _noop_fn, [], num_executors=1,
            input_mode=InputMode.TENSORFLOW)
        assert cluster.obs is None
        names = {t.name for t in threading.enumerate()}
        assert not any(n.startswith("tfos-obs") for n in names)
        cluster.shutdown()
    finally:
        engine.stop()


def _noop_fn(args, ctx):
    pass


# --- tfos-top against a canned statusz --------------------------------------

_CANNED = {
    "cluster": {"id": "abcd1234", "epoch": 0, "num_executors": 2,
                "restarts": 2, "restarts_used": 1},
    "feeds": {"default": 4},
    "nodes": {
        "worker-0": {"role": "worker", "alive": True,
                     "last_seen_age_s": 0.4,
                     "summary": {"steps": 120, "step_ms_p50": 12.5,
                                 "items_per_sec": 25562.0, "mfu": 0.41,
                                 "stall_frac": 0.02, "queue_depth": 3,
                                 "serve_p50_ms": 4.0, "serve_p99_ms": 21.0}},
        "worker-1": {"role": "worker", "alive": False,
                     "heartbeat_age_s": 99.0, "summary": {}},
    },
    # an obs/slo.py report, rendered only under --slo
    "slo": [
        {"name": "decode_ttft", "kind": "latency",
         "metric": "tfos_decode_ttft_ms", "target_pct": 99.0,
         "threshold_ms": 500.0, "current": 128.5, "burn": 0.4,
         "breaching": False, "samples": 900},
        {"name": "serve_availability", "kind": "availability",
         "metric": "tfos_serve_requests_total", "target_pct": 99.0,
         "current": 0.985, "burn": 1.5, "breaching": True,
         "samples": 4000},
        {"name": "quiet", "kind": "latency", "metric": "m",
         "target_pct": 99.0, "threshold_ms": 10.0, "current": None,
         "burn": None, "breaching": False, "samples": 0},
    ],
    # a serving/fabric fabric_table() rollup, rendered only under --pods
    "pods": [
        {"router": 0, "host": 0, "alive": True, "pid": 4242,
         "replicas": 3, "queue_depth": 2, "version": 7,
         "affinity_hit_rate": 0.75},
        {"router": 0, "host": 1, "alive": False, "pid": 4243,
         "replicas": 0, "queue_depth": 0, "version": 7,
         "affinity_hit_rate": 0.0},
    ],
}


class _StatuszStub(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):  # noqa: N802 - http.server API
        body = json.dumps(_CANNED).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_tfos_top_once_renders_table():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StatuszStub)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        out = io.StringIO()
        assert obs_top.main(["--url", url, "--once"], out=out) == 0
        text = out.getvalue()
        assert "cluster abcd1234" in text and "restarts=1/2" in text
        assert "feed ledger: default:4" in text
        lines = text.splitlines()
        (w0,) = [ln for ln in lines if ln.startswith("worker-0")]
        assert "yes" in w0 and "25.6k" in w0     # items/s compacted
        assert "41.0" in w0 and "4/21" in w0     # mfu%, p50/p99
        (w1,) = [ln for ln in lines if ln.startswith("worker-1")]
        assert "DOWN" in w1
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_tfos_top_errors_without_target():
    assert obs_top.main(["--once"], out=io.StringIO()) == 2  # no url, no env
    # unreachable target with --once: exit 2, not a hang
    assert obs_top.main(["--url", "http://127.0.0.1:1", "--once"],
                        out=io.StringIO()) == 2


def test_tfos_top_slo_pane():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StatuszStub)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        out = io.StringIO()
        assert obs_top.main(["--url", url, "--once", "--slo"],
                            out=out) == 0
        text = out.getvalue()
        assert "slo burn (obs/slo.py):" in text
        lines = text.splitlines()
        (ttft,) = [ln for ln in lines if "decode_ttft" in ln]
        assert "<500ms" in ttft and "128.5ms" in ttft and "ok" in ttft
        (avail,) = [ln for ln in lines if "serve_availability" in ln]
        assert "BREACH" in avail and "98.5" in avail and "1.5" in avail
        (quiet,) = [ln for ln in lines if ln.startswith("quiet")]
        assert "no-data" in quiet
        # without --slo the pane stays hidden
        out2 = io.StringIO()
        assert obs_top.main(["--url", url, "--once"], out=out2) == 0
        assert "slo burn" not in out2.getvalue()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert "(no objectives reported)" in obs_top.render_slo({})


def test_tfos_top_pods_pane():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StatuszStub)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        out = io.StringIO()
        assert obs_top.main(["--url", url, "--once", "--pods"],
                            out=out) == 0
        text = out.getvalue()
        assert "pods (serving/fabric/):" in text
        lines = text.splitlines()
        (h0,) = [ln for ln in lines if ln.startswith("0/0")]
        assert "yes" in h0 and "4242" in h0 and "75.0" in h0
        (h1,) = [ln for ln in lines if ln.startswith("0/1")]
        assert "DOWN" in h1
        # without --pods the pane stays hidden
        out2 = io.StringIO()
        assert obs_top.main(["--url", url, "--once"], out=out2) == 0
        assert "pods (serving/fabric/)" not in out2.getvalue()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert "(no fabric routers)" in obs_top.render_pods({})


# --- slo engine (obs/slo.py) -------------------------------------------------

def test_slo_spec_parse_errors_disable_engine():
    bad = (
        "nope",                      # no fields at all
        "n:weird:m@9",               # unknown kind
        "n:latency:m@9",             # latency without threshold
        "n:latency:m<x@9",           # non-numeric threshold
        "n:availability:m@0",        # target must be in (0, 100)
        "n:availability:m@100",
        "n:availability:m",          # missing @good_pct
        ":latency:m<1@9",            # empty name
    )
    for spec in bad:
        with pytest.raises(ValueError):
            obs_slo.parse_spec(spec)
    assert obs_slo.parse_spec("") == []
    # an invalid env/ctor spec disables the engine instead of raising
    assert obs_slo.Engine("garbage").objectives == []
    # the default spec parses and round-trips through repr
    objs = obs_slo.parse_spec(obs_slo.DEFAULT_SPEC)
    assert [o.name for o in objs] == ["decode_ttft", "serve_availability"]
    again = obs_slo.parse_spec(";".join(repr(o) for o in objs))
    assert [repr(o) for o in again] == [repr(o) for o in objs]


def test_slo_engine_burn_math_and_edge_trigger():
    _enable()
    eng = obs_slo.Engine(
        "av:availability:tfos_serve_requests_total@99;"
        "lat:latency:tfos_decode_ttft_ms<500@99")
    rep = eng.step([])
    assert [r["burn"] for r in rep["objectives"]] == [None, None]
    assert not any(r["breaching"] for r in rep["objectives"])

    snap = {
        "tfos_serve_requests_total": {"series": [
            {"labels": {"status": "ok"}, "value": 90.0},
            {"labels": {"status": "error"}, "value": 10.0},
        ]},
        "tfos_decode_ttft_ms": {"series": [
            {"labels": {}, "bounds": [100.0, 500.0],
             "counts": [8.0, 1.0, 1.0], "sum": 1000.0, "count": 10},
        ]},
    }
    rep = eng.step([snap, snap])
    by = {r["name"]: r for r in rep["objectives"]}
    av, lat = by["av"], by["lat"]
    # availability: 10% bad against a 1% error budget -> burn 10x
    assert av["samples"] == 200 and av["current"] == pytest.approx(0.9)
    assert av["burn"] == pytest.approx(10.0) and av["breaching"]
    # latency: 10% of samples in the +Inf bucket (> 500ms) @ p99 target
    assert lat["samples"] == 20
    assert lat["burn"] == pytest.approx(10.0) and lat["breaching"]
    assert lat["current"] == pytest.approx(500.0)  # clamps to last bound
    # breach counter is edge-triggered: a second breaching step no-ops
    eng.step([snap, snap])
    series = reg.snapshot()["tfos_slo_breaches_total"]["series"]
    counts = {s["labels"]["objective"]: s["value"] for s in series}
    assert counts == {"av": 1.0, "lat": 1.0}


def test_slo_endpoint_and_statusz_section():
    _enable()
    reg.inc("tfos_serve_requests_total", 99, status="ok")
    reg.inc("tfos_serve_requests_total", 1, status="shed")
    for _ in range(10):
        reg.observe("tfos_decode_ttft_ms", 5.0)
    srv = obs_http.ObsServer(cluster=None, port=0, interval=999).start()
    try:
        status, text = _get(srv.url + "/slo")
        assert status == 200
        doc = json.loads(text)
        assert set(doc) == {"ts", "objectives"}
        by = {r["name"]: r for r in doc["objectives"]}
        av = by["serve_availability"]
        assert av["burn"] == pytest.approx(1.0) and not av["breaching"]
        ttft = by["decode_ttft"]
        assert ttft["burn"] == 0.0 and ttft["samples"] == 10
        # statusz grows an slo section once the poller has stepped
        srv.poll_once()
        status, text = _get(srv.url + "/statusz")
        assert status == 200
        names = {r["name"] for r in json.loads(text)["slo"]}
        assert names == {"decode_ttft", "serve_availability"}
        status, text = _get(srv.url + "/metrics")
        assert "tfos_slo_burn_rate" in text
        assert 'objective="serve_availability"' in text
    finally:
        srv.stop()


# --- catalog / docs lint ----------------------------------------------------

_CALL_RE = re.compile(
    r'(?:inc|set_gauge|observe)\(\s*"(tfos_[a-z0-9_]+)"')


def _source_metric_names():
    """Metric names at actual instrumentation call sites (inc /
    set_gauge / observe), so unrelated ``tfos_*`` string literals
    (env keys, KV keys) don't trip the lint."""
    names = set()
    for dirpath, _dirs, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname), encoding="utf-8") as f:
                names.update(_CALL_RE.findall(f.read()))
    return names


def test_every_metric_in_catalog_and_docs():
    """The CATALOG is the contract: every ``tfos_*`` literal the package
    uses must be declared there, and every declared metric must be
    documented in docs/observability.md (same lint discipline as the
    telemetry span table)."""
    in_code = _source_metric_names()
    in_catalog = set(reg.CATALOG)
    assert in_code <= in_catalog, (
        f"undeclared metric names: {sorted(in_code - in_catalog)}")
    assert in_catalog <= in_code, (
        f"catalog entries never emitted: {sorted(in_catalog - in_code)}")
    with open(os.path.join(REPO, "docs", "observability.md"),
              encoding="utf-8") as f:
        docs = f.read()
    missing = [n for n in sorted(in_catalog) if n not in docs]
    assert not missing, f"metrics undocumented in docs/observability.md: {missing}"
