"""Row⇄column marshalling over the full dtype matrix (parity: reference
TFModelTest.scala:18-128 — marshalling tested exhaustively with no
cluster and no model — and TestData.scala's rows-covering-every-type)."""

import numpy as np
import pytest

from tensorflowonspark_tpu.recordio import marshal

# 2 rows x every supported column kind (TestData.scala:11-46 analogue)
ROWS = [
    (True, 1, 2**40, 1.5, 2.5, [True, False], [1, 2], [2**40, 3], [0.5, 1.5], [2.5, 3.5]),
    (False, 4, 2**41, 4.5, 5.5, [False, True], [3, 4], [2**41, 6], [2.5, 3.5], [4.5, 5.5]),
]
SPEC = [("?", 0), ("i", 0), ("l", 0), ("f", 0), ("d", 0),
        ("?", 2), ("i", 2), ("l", 2), ("f", 2), ("d", 2)]
DTYPES = [np.bool_, np.int32, np.int64, np.float32, np.float64] * 2


@pytest.fixture(params=["native", "numpy"])
def impl(request, monkeypatch):
    if request.param == "native":
        if not marshal.native_available():
            pytest.skip("native marshal not built")
    else:
        monkeypatch.setattr(marshal, "_ext", None)
        monkeypatch.setattr(marshal, "_ext_tried", True)
    return request.param


def test_rows_to_columns_dtype_matrix(impl):
    cols = marshal.rows_to_columns(ROWS, SPEC)
    assert len(cols) == len(SPEC)
    for arr, dt, (code, w) in zip(cols, DTYPES, SPEC):
        assert arr.dtype == np.dtype(dt), (arr.dtype, dt)
        assert arr.shape == ((2,) if w == 0 else (2, w))
    assert cols[0].tolist() == [True, False]
    assert cols[2].tolist() == [2**40, 2**41]
    assert cols[4].tolist() == [2.5, 5.5]
    assert cols[7].tolist() == [[2**40, 3], [2**41, 6]]
    np.testing.assert_allclose(cols[8], [[0.5, 1.5], [2.5, 3.5]])


def test_columns_to_rows_dtype_matrix(impl):
    cols = [np.asarray(list(col), dtype=dt)
            for col, dt in zip(zip(*ROWS), DTYPES)]
    rows = marshal.columns_to_rows(cols)
    assert len(rows) == 2
    for got, want in zip(rows, ROWS):
        assert len(got) == len(want)
        # scalar columns come back as python scalars, array columns as lists
        assert isinstance(got[0], bool) and got[0] == want[0]
        assert isinstance(got[2], int) and got[2] == want[2]
        assert isinstance(got[5], list)
        assert got[6] == want[6]
        np.testing.assert_allclose(got[9], want[9])


def test_roundtrip(impl):
    cols = marshal.rows_to_columns(ROWS, SPEC)
    back = marshal.columns_to_rows(cols)
    for got, want in zip(back, ROWS):
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w)


def test_infer_spec():
    spec = marshal.infer_spec(ROWS[0])
    # python scalars widen to int64/float64 (numpy default semantics)
    assert spec == [("?", 0), ("l", 0), ("l", 0), ("d", 0), ("d", 0),
                    ("?", 2), ("l", 2), ("l", 2), ("d", 2), ("d", 2)]


def test_infer_spec_strings():
    assert marshal.infer_spec(("a", b"b", ["x", "y"])) == [
        ("O", 0), ("O", 0), ("O", 2)]
    cols = marshal.rows_to_columns([("a", b"b"), ("c", b"d")],
                                   [("O", 0), ("O", 0)])
    assert cols[0].dtype == object and list(cols[0]) == ["a", "c"]


def test_ragged_array_column_rejected(impl):
    with pytest.raises(ValueError):
        marshal.rows_to_columns([([1.0],), ([1.0, 2.0],)], [("d", 1)])


def test_row_arity_mismatch_rejected(impl):
    with pytest.raises(ValueError):
        marshal.rows_to_columns([(1.0, 2.0), (3.0,)], [("d", 0), ("d", 0)])


def test_lossy_casts_refused(impl):
    """A spec inferred from an int/bool first row must not silently
    truncate floats (2.9 -> 2) or coerce ints (2 -> True) that appear in
    later rows — both paths must raise so the feed encoder falls back to
    the exact row representation."""
    with pytest.raises((TypeError, ValueError)):
        marshal.rows_to_columns([(1,), (2.9,)], [("l", 0)])
    with pytest.raises((TypeError, ValueError)):
        marshal.rows_to_columns([(True,), (2,)], [("?", 0)])


def test_numpy_bool_scalars_accepted(impl):
    """np.bool_ fields (numpy/pandas-sourced rows) must marshal like
    python bools on both paths."""
    cols = marshal.rows_to_columns(
        [(np.bool_(True),), (np.bool_(False),)], [("?", 0)]
    )
    assert cols[0].dtype == np.bool_
    assert cols[0].tolist() == [True, False]


def test_int32_spec_overflow_refused(impl):
    with pytest.raises((OverflowError, ValueError)):
        marshal.rows_to_columns([(1,), (2 ** 35,)], [("i", 0)])


def test_infer_spec_int8_is_not_bool():
    """numpy's int8 char 'b' must not collide with the bool code '?'
    ([5,0,2] silently became [True,False,True] before round 3); since
    round 4 narrow ints keep their exact width on the wire ('b')."""
    spec = marshal.infer_spec((np.array([5, 0, 2], np.int8),))
    assert spec == [("b", 3)]
    cols = marshal.rows_to_columns(
        [(np.array([5, 0, 2], np.int8),)], spec
    )
    assert cols[0].dtype == np.int8
    assert cols[0].tolist() == [[5, 0, 2]]


def test_narrow_uint8_column_roundtrip():
    """Image bytes must not upcast on the wire: uint8 rows -> 'B' spec ->
    uint8 dense column -> exact scalars back (values 0..255)."""
    rows = [(np.array([0, 127, 255], np.uint8), i) for i in range(4)]
    spec = marshal.infer_spec(rows[0])
    assert spec[0] == ("B", 3)
    cols = marshal.rows_to_columns(rows, spec)
    assert cols[0].dtype == np.uint8 and cols[0].shape == (4, 3)
    back = marshal.columns_to_rows(cols)
    assert back[0][0] == [0, 127, 255]
    # overflow into a narrow spec is refused by value, like int32
    with pytest.raises(ValueError, match="overflow"):
        marshal.rows_to_columns(
            [(np.array([5], np.int64),)] + [(np.array([300], np.int64),)],
            [("B", 1)])


def test_infer_spec_rejects_uint64_and_multidim():
    with pytest.raises(ValueError):
        marshal.infer_spec((np.array([1], np.uint64),))
    with pytest.raises(ValueError):
        marshal.infer_spec((np.zeros((2, 2), np.float32),))


def test_schema_to_spec():
    fields = [("flag", "boolean"), ("n", "bigint"), ("x", "float"),
              ("emb", "array<double>"), ("name", "string")]
    assert marshal.schema_to_spec(fields, widths={"emb": 4}) == [
        ("?", 0), ("l", 0), ("f", 0), ("d", 4), ("O", 0)]


def test_multidim_output_keeps_nesting():
    rows = marshal.columns_to_rows([np.arange(8, dtype=np.float32).reshape(2, 2, 2)])
    assert rows[0][0] == [[0.0, 1.0], [2.0, 3.0]]


@pytest.mark.skipif(not marshal.native_available(), reason="no native ext")
def test_native_beats_numpy_path():
    """The compiled path must actually be faster than the numpy fallback
    on a realistic inference batch (VERDICT item 6's 'measured speedup')."""
    import time

    rows = [(float(i), [float(i)] * 16, i, True) for i in range(4096)]
    spec = [("d", 0), ("f", 16), ("l", 0), ("?", 0)]

    def timed(fn, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_native = timed(lambda: marshal._ext.rows_to_columns(rows, spec))

    def numpy_path():
        cols = list(zip(*rows))
        return [np.asarray(cols[i], dtype=d)
                for i, d in enumerate([np.float64, np.float32, np.int64, np.bool_])]

    t_numpy = timed(numpy_path)
    speedup = t_numpy / t_native
    print(f"rows_to_columns native speedup: {speedup:.2f}x "
          f"({t_numpy*1e3:.2f}ms -> {t_native*1e3:.2f}ms)")
    assert speedup > 1.0, f"native path slower than numpy ({speedup:.2f}x)"


def test_marshal_ext_fuzz_no_crash():
    """Seeded hostile inputs (wrong arity/types/dtypes, ragged rows,
    non-contiguous and >2-D arrays) must raise cleanly, never corrupt
    memory.  (A longer 7000-case run was clean.)"""
    import numpy as np

    from tensorflowonspark_tpu.recordio import marshal

    ext = marshal._load_ext()
    if ext is None:
        return
    rng = np.random.default_rng(1)
    vals = [1, -1, 2 ** 40, 1.5, True, None, "x", b"y", [1, 2], [1.0],
            (), {"a": 1}, float("nan"), 2 ** 70]
    codes = ["?", "i", "l", "f", "d", "z"]
    for _ in range(400):
        ncols = rng.integers(1, 4)
        spec = [(codes[rng.integers(0, len(codes))], int(rng.integers(0, 4)))
                for _ in range(ncols)]
        rows = []
        for _ in range(rng.integers(0, 4)):
            arity = ncols if rng.integers(0, 4) else rng.integers(0, 5)
            rows.append(tuple(vals[rng.integers(0, len(vals))]
                              for _ in range(arity)))
        try:
            ext.rows_to_columns(rows, spec)
        except (TypeError, ValueError, OverflowError):
            pass
    arrs = [np.zeros((3,), np.float32), np.zeros((2, 2), np.int64),
            np.zeros((3,), np.complex64), np.zeros((0,), np.float64),
            np.zeros((2, 2, 2), np.int32), np.array(["a", "b"]),
            np.zeros((4,), np.int64)[::2]]
    for _ in range(300):
        cols = [arrs[rng.integers(0, len(arrs))]
                for _ in range(rng.integers(1, 4))]
        try:
            ext.columns_to_rows(cols)
        except (TypeError, ValueError, BufferError):
            pass
