"""Minimum end-to-end slice (SURVEY.md §7 step 5):

MNIST CNN, InputMode.SPARK, 2 executor processes, TRUE multi-controller
data-parallel training — each executor joins one JAX SPMD job over CPU
(gloo collectives), the batch is mesh-sharded, XLA all-reduces the
gradients (the MultiWorkerMirroredStrategy parity path), and the chief
exports the model.

Parity: reference test_pipeline.py:89-172 + examples/mnist/keras/
mnist_spark.py (DataFeed generator → strategy.fit).
"""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine

BATCH = 64
STEPS = 30


def mnist_main(args, ctx):
    # runs inside the background training process on each executor
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics

    env = ctx.jax_initialize()
    assert env["num_processes"] == 2, env
    assert jax.process_count() == 2

    mesh = make_mesh({"data": -1})
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.05, momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    # metrics feed both report() and (when TFOS_TELEMETRY_DIR is set)
    # the train/step + feed/wait spans that trace_merge aggregates
    metrics = TrainMetrics()
    feed = ctx.get_data_feed(train_mode=True, metrics=metrics)
    losses = []
    per_proc = BATCH // env["num_processes"]
    while not feed.should_stop():
        batch = feed.next_batch(per_proc)
        if len(batch) < per_proc:
            continue  # drop ragged tail (global-stop handled by None marker)
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = np.asarray([b[1] for b in batch], dtype=np.int32)
        gimages, glabels = local_to_global(mesh, (images, labels))
        params, opt_state, loss, acc = step_fn(params, opt_state, gimages, glabels)
        losses.append(float(loss))
        metrics.step(per_proc)

    assert len(losses) >= 5, f"too few steps ran: {len(losses)}"
    first, last = np.mean(losses[:3]), np.mean(losses[-3:])
    with open("losses.txt", "w") as f:
        f.write(f"{first} {last} {len(losses)}")
    assert last < first, f"loss did not decrease: {first} -> {last}"
    ckpt.export_model(os.path.join(args["model_dir"], "export"), params, ctx)


@pytest.mark.slow
def test_mnist_spark_mode_e2e(tmp_path, monkeypatch):
    from tensorflowonspark_tpu.utils import telemetry

    # opt-in telemetry for the whole run (driver + executors + trainers):
    # the acceptance path is this e2e followed by scripts/trace_merge.py
    telemetry_dir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(telemetry_dir))
    engine = LocalEngine(
        2,
        env={
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": "",          # drop the TPU-tunnel site hook
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        },
    )
    try:
        cluster = TFCluster.run(
            engine,
            mnist_main,
            {"model_dir": str(tmp_path)},
            num_executors=2,
            input_mode=InputMode.SPARK,
            master_node="chief",
        )
        # synthetic, learnable dataset (see models.mnist.synthetic_batch)
        rng = np.random.default_rng(0)
        n = BATCH * STEPS
        images = rng.random((n, 28, 28, 1), dtype=np.float32)
        q = np.stack(
            [
                images[:, :14, :14, 0].mean((1, 2)),
                images[:, :14, 14:, 0].mean((1, 2)),
                images[:, 14:, :14, 0].mean((1, 2)),
                images[:, 14:, 14:, 0].mean((1, 2)),
            ],
            axis=-1,
        )
        labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
        records = list(zip(list(images), list(labels)))
        ds = engine.parallelize(records, 4)
        cluster.train(ds, num_epochs=1, feed_timeout=240)
        cluster.shutdown(grace_secs=5)
        export = os.path.join(tmp_path, "export")
        assert os.path.exists(os.path.join(export, "params.npz")), (
            "chief did not export the model"
        )
        from tensorflowonspark_tpu.utils.checkpoint import load_exported

        params, meta = load_exported(export)
        assert meta["format"] == "tfos-tpu-export-v1"
        assert params["conv1"]["w"].shape == (3, 3, 1, 32)

        # --- telemetry: drained run dir -> Chrome trace + summary -------
        runs = [d for d in os.listdir(telemetry_dir)
                if d.startswith("run-")]
        assert len(runs) == 1, f"expected one drained run dir: {runs}"
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))), "scripts", "trace_merge.py"),
             str(telemetry_dir)],
            capture_output=True, text=True,
            env=dict(os.environ, PYTHONPATH=""), timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        import json

        trace = json.loads(
            (telemetry_dir / "trace.json").read_text())
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"cluster/start", "node/boot", "train/step",
                "feed/wait", "checkpoint/export"} <= names
        # per-node step percentiles + infeed-stall fraction made it into
        # the summary for both training nodes (master_node="chief")
        assert "chief-0" in proc.stdout and "worker-0" in proc.stdout
        assert "p50_ms" in proc.stdout and "stall" in proc.stdout
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)  # cluster.run pinned driver identity
