"""Test fixtures: force an 8-device virtual CPU platform before jax imports.

Mirrors the reference test strategy (reference test/run_tests.sh boots a
2-worker local Spark Standalone cluster): we test multi-chip sharding with
multiple *virtual* devices on one host, and multi-node behavior with
multiple executor *processes* on one host.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Strip any TPU-tunnel site hook (e.g. an axon sitecustomize on
# PYTHONPATH) from the env that child processes inherit: the hook dials
# the accelerator pool at *interpreter startup*, so LocalEngine executor
# children would block whenever another process holds the tunnel — tests
# must be runnable while a bench/profile owns the TPU.  Module imports in
# spawn children are unaffected (sys.path travels via multiprocessing's
# preparation data, not PYTHONPATH).
_pp = os.environ.get("PYTHONPATH", "")
if _pp:
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in _pp.split(os.pathsep) if "axon" not in p.lower()
    )

# A TPU-tunnel site hook may have forced jax_platforms at interpreter
# start; pin the test session back to the virtual CPU platform before any
# backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
