"""Test fixtures: force an 8-device virtual CPU platform before jax imports.

Mirrors the reference test strategy (reference test/run_tests.sh boots a
2-worker local Spark Standalone cluster): we test multi-chip sharding with
multiple *virtual* devices on one host, and multi-node behavior with
multiple executor *processes* on one host.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A TPU-tunnel site hook may have forced jax_platforms at interpreter
# start; pin the test session back to the virtual CPU platform before any
# backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
