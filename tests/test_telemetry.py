"""utils.telemetry + scripts/trace_merge: span recording, spawn/fork
safety, driver-side drain, and the Chrome-trace merge.

Parity framing: the reference's observability is log lines only
(reference ``__init__.py:1-5``, SURVEY.md §5); these tests pin the
structured replacement — one schema everywhere, no files when disabled,
every node's records collected into one run directory at shutdown.
"""

import importlib.util
import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine, TaskError
from tensorflowonspark_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_MERGE = os.path.join(REPO, "scripts", "trace_merge.py")

_ENV_KEYS = (telemetry.DIR_ENV, telemetry.SPOOL_ENV, telemetry.NODE_ENV,
             telemetry.ROLE_ENV, telemetry.BUFFER_ENV, telemetry.FLUSH_ENV,
             telemetry.TRACE_ENV, telemetry.RING_ENV)


def _load_trace_merge():
    spec = importlib.util.spec_from_file_location("trace_merge", TRACE_MERGE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _telemetry_env():
    """Isolate every test from ambient telemetry env AND restore it:
    cluster.run/configure write identity into os.environ by design."""
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    yield
    telemetry.flush()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _records(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _all_records(root):
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            if name.endswith(".jsonl"):
                out.extend(_records(os.path.join(dirpath, name)))
    return out


# --- recorder core ----------------------------------------------------------

def test_disabled_is_noop(tmp_path):
    assert not telemetry.enabled()
    assert telemetry.sink_path() is None
    assert telemetry.span("x") is telemetry._NULL
    with telemetry.span("x", a=1) as sp:
        sp.add(b=2)
    telemetry.event("y")
    telemetry.record_span("z", 0.1)
    telemetry.flush()
    assert list(tmp_path.iterdir()) == []  # nothing anywhere


def test_span_schema_nesting_and_monotonic_clocks(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    assert telemetry.enabled()
    with telemetry.span("outer", phase="a"):
        time.sleep(0.02)
        with telemetry.span("inner") as sp:
            time.sleep(0.01)
            sp.add(marker=1)
    telemetry.event("tick", n=3)
    telemetry.flush()

    path = telemetry.sink_path()
    assert os.path.basename(path) == f"t-0-{os.getpid()}.jsonl"
    recs = _records(path)
    assert [set(r) for r in recs] == [set(telemetry.SCHEMA_KEYS)] * 3
    by_name = {r["name"]: r for r in recs}
    inner, outer, tick = by_name["inner"], by_name["outer"], by_name["tick"]
    assert outer["kind"] == "span" and tick["kind"] == "event"
    assert tick["dur_ms"] is None
    assert inner["attrs"] == {"marker": 1}
    # monotonic-clock durations, wall-clock anchors: the inner span
    # starts after and ends before the outer one
    assert outer["dur_ms"] >= inner["dur_ms"] >= 9.0
    assert outer["ts"] <= inner["ts"] <= tick["ts"]
    assert inner["ts"] + inner["dur_ms"] / 1e3 <= \
        outer["ts"] + outer["dur_ms"] / 1e3 + 0.01
    assert all(r["node_id"] == "t-0" and r["role"] == "test" for r in recs)


def test_record_span_backdates_start(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    before = time.time()
    telemetry.record_span("train/step", 1.5, items=8)
    telemetry.flush()
    (rec,) = _records(telemetry.sink_path())
    assert rec["dur_ms"] == pytest.approx(1500.0)
    # self-timed spans anchor at START so the trace lays them out right
    assert rec["ts"] == pytest.approx(before - 1.5, abs=0.25)


def test_span_error_annotates_and_propagates(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    with pytest.raises(ValueError, match="boom"):
        with telemetry.span("will/fail"):
            raise ValueError("boom")
    telemetry.flush()
    (rec,) = _records(telemetry.sink_path())
    assert "boom" in rec["attrs"]["error"]


def test_ring_buffer_counts_drops(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    os.environ[telemetry.BUFFER_ENV] = "4"
    os.environ[telemetry.FLUSH_ENV] = "1000"  # no threshold flush
    telemetry.configure(node_id="t-0", role="test")
    for i in range(10):
        telemetry.event("e", i=i)
    telemetry.flush()
    recs = _records(telemetry.sink_path())
    dropped = [r for r in recs if r["name"] == "telemetry/dropped"]
    assert dropped and dropped[0]["attrs"]["count"] >= 1
    assert len([r for r in recs if r["name"] == "e"]) <= 4


def _spawn_child_emit():
    # relies on the exit-time Finalize/atexit flush: NO explicit flush
    from tensorflowonspark_tpu.utils import telemetry as t

    with t.span("spawn/child", pid=os.getpid()):
        pass


def test_spawn_child_roundtrip(tmp_path):
    """A spawned child inherits the env channel, writes its own
    <node>-<pid>.jsonl, and its exit hook flushes without help."""
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="parent", role="test")
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_spawn_child_emit)
    p.start()
    p.join(60)
    assert p.exitcode == 0
    child = [r for r in _all_records(tmp_path) if r["name"] == "spawn/child"]
    assert len(child) == 1
    assert child[0]["attrs"]["pid"] == p.pid
    assert child[0]["node_id"] == "parent"  # identity inherited via env
    files = sorted(f.name for f in tmp_path.iterdir())
    assert f"parent-{p.pid}.jsonl" in files


# --- causal tracing ---------------------------------------------------------

def test_trace_context_mint_child_header_roundtrip():
    ctx = telemetry.TraceContext()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert ctx.parent_id is None
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id
    assert kid.parent_id == ctx.span_id and kid.span_id != ctx.span_id
    hdr = kid.to_header()
    assert hdr == f"00-{kid.trace_id}-{kid.span_id}-01"
    back = telemetry.TraceContext.from_header(hdr)
    assert back.trace_id == kid.trace_id and back.span_id == kid.span_id
    # malformed headers parse to None, never raise
    for bad in ("", "garbage", "00-zz-xx-01", None, "00-abc-def-01"):
        assert telemetry.TraceContext.from_header(bad) is None


def test_trace_span_links_parents_and_rides_attrs(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    with telemetry.trace_span("serve/request") as root:
        rctx = root.ctx
        with telemetry.span("engine/task"):
            telemetry.event("tick")
    telemetry.flush()
    recs = {r["name"]: r for r in _records(telemetry.sink_path())}
    outer, inner, tick = (recs["serve/request"], recs["engine/task"],
                          recs["tick"])
    assert outer["attrs"]["trace_id"] == rctx.trace_id
    assert outer["attrs"]["parent_id"] is None
    assert inner["attrs"]["trace_id"] == rctx.trace_id
    assert inner["attrs"]["parent_id"] == outer["attrs"]["span_id"]
    # events carry the enclosing span as parent
    assert tick["attrs"]["parent_id"] == inner["attrs"]["span_id"]
    # outside any trace, current() is empty again
    assert telemetry.current() is None


def test_trace_span_exception_path_pops_context(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    with pytest.raises(RuntimeError, match="kaboom"):
        with telemetry.trace_span("serve/request"):
            raise RuntimeError("kaboom")
    # the thread-local stack MUST unwind on the error path, or every
    # later span in this thread would silently join the failed trace
    assert telemetry.current() is None
    telemetry.flush()
    (rec,) = _records(telemetry.sink_path())
    assert "kaboom" in rec["attrs"]["error"]
    assert rec["attrs"]["trace_id"]


def _spawn_traced_child():
    from tensorflowonspark_tpu.utils import telemetry as t

    # the child sees the parent's exported context via TFOS_TRACE_PARENT
    with t.span("spawn/traced_child"):
        pass


def test_trace_inherited_across_spawn(tmp_path):
    """trace_root exports TFOS_TRACE_PARENT; a spawned child's spans
    join the same trace with a valid parent link."""
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="parent", role="test")
    ctx = telemetry.trace_root("cluster/run")
    assert os.environ[telemetry.TRACE_ENV] == ctx.to_header()
    p = mp.get_context("spawn").Process(target=_spawn_traced_child)
    p.start()
    p.join(60)
    assert p.exitcode == 0
    telemetry.flush()
    recs = _all_records(tmp_path)
    child = next(r for r in recs if r["name"] == "spawn/traced_child")
    anchor = next(r for r in recs if r["name"] == "cluster/run")
    assert child["attrs"]["trace_id"] == ctx.trace_id
    assert child["attrs"]["parent_id"] == ctx.span_id
    assert anchor["attrs"]["span_id"] == ctx.span_id


def test_trace_disabled_is_noop(tmp_path):
    assert not telemetry.enabled()
    assert telemetry.trace_root("cluster/run") is None
    assert telemetry.trace_span("serve/request") is telemetry._NULL
    assert telemetry.current() is None
    with telemetry.activate("00-" + "a" * 32 + "-" + "b" * 16 + "-01"):
        assert telemetry.current() is None
    assert list(tmp_path.iterdir()) == []


# --- cluster drain ----------------------------------------------------------

def _telemetry_node_fn(args, ctx):
    from tensorflowonspark_tpu.utils import telemetry as t

    with t.span("user/work", task=ctx.task_index):
        time.sleep(0.01)


def _fail_after_feed_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        feed.next_batch(100)
    raise RuntimeError("deliberate failure after feeding")


def _run_dirs(root):
    return sorted(d for d in os.listdir(root) if d.startswith("run-"))


def test_drain_on_clean_shutdown(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    engine = LocalEngine(2)
    try:
        cluster = TFCluster.run(
            engine, _telemetry_node_fn, [], num_executors=2,
            input_mode=InputMode.TENSORFLOW,
        )
        cluster.shutdown()
    finally:
        engine.stop()
    (run,) = _run_dirs(tmp_path)
    drained = _all_records(tmp_path / run)
    names = {r["name"] for r in drained}
    # node lifecycle + user spans all collected into the one run dir
    assert {"node/boot", "node/main", "user/work",
            "rendezvous/register"} <= names
    assert {r["node_id"] for r in drained if r["name"] == "user/work"} == \
        {"worker-0", "worker-1"}
    # the driver's own spans land in the root (cluster/start before the
    # run id exists; the drain span itself covers the collection)
    driver = [r for r in _all_records(tmp_path)
              if r["role"] == "driver"]
    dnames = {r["name"] for r in driver}
    assert {"cluster/start", "cluster/shutdown",
            "cluster/telemetry_drain"} <= dnames


def test_drain_on_error_shutdown(tmp_path):
    """A failing node program must still get its telemetry drained —
    the error path is exactly when the timeline matters most."""
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    engine = LocalEngine(2)
    try:
        cluster = TFCluster.run(
            engine, _fail_after_feed_fn, [], num_executors=2,
            input_mode=InputMode.SPARK,
        )
        ds = engine.parallelize(range(100), 2)
        cluster.train(ds)
        with pytest.raises((TaskError, SystemExit)) as ei:
            cluster.shutdown(grace_secs=3)
    finally:
        engine.stop()
    (run,) = _run_dirs(tmp_path)
    names = {r["name"] for r in _all_records(tmp_path / run)}
    assert "node/boot" in names
    driver = {r["name"] for r in _all_records(tmp_path)
              if r["role"] == "driver"}
    assert "cluster/shutdown" in driver
    if isinstance(ei.value, SystemExit):
        # the tf_status error path emits the cluster/error event before
        # cancelling jobs (a TaskError from the stop-job raises earlier)
        assert "cluster/error" in driver


def test_telemetry_disabled_cluster_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # catch stray spool dirs too
    engine = LocalEngine(2)
    try:
        cluster = TFCluster.run(
            engine, _telemetry_node_fn, [], num_executors=2,
            input_mode=InputMode.TENSORFLOW,
        )
        cluster.shutdown()
    finally:
        engine.stop()
    assert not list(tmp_path.glob("**/*.jsonl"))
    assert not (tmp_path / ".tfos_telemetry").exists()


def _flight_dump_node_fn(args, ctx):
    from tensorflowonspark_tpu.obs import flight
    from tensorflowonspark_tpu.utils import telemetry as t

    with t.span("user/work", task=ctx.task_index):
        time.sleep(0.01)
    assert flight.snapshot("test/manual", reason="spool survival probe")


def test_flight_dump_survives_engine_stop(tmp_path):
    """Regression (deploy-loop satellite): flight dumps used to spool
    into a dotdir inside the engine scratch cwd, which engine.stop()
    deletes — the black box died with the plane.  They must spool under
    $TFOS_TELEMETRY_DIR and outlive full engine teardown."""
    import glob as _glob

    telemetry_dir = tmp_path / "telemetry"
    os.environ[telemetry.DIR_ENV] = str(telemetry_dir)
    engine = LocalEngine(2)
    try:
        cluster = TFCluster.run(
            engine, _flight_dump_node_fn, [], num_executors=2,
            input_mode=InputMode.TENSORFLOW,
        )
        cluster.shutdown()
    finally:
        engine.stop()
    # engine scratch is gone; the dumps are not
    dumps = _glob.glob(os.path.join(str(telemetry_dir), "spool-*",
                                    "flight-*.json"))
    assert dumps, "flight dump did not survive engine stop"
    doc = json.loads(open(dumps[0], encoding="utf-8").read())
    assert doc["trigger"] == "test/manual"
    # and postmortem's recursive walk can see them (non-dot spool dirs)
    from tensorflowonspark_tpu.obs import postmortem

    found = postmortem.load_dumps(str(telemetry_dir))
    assert found, "postmortem walk missed the surviving dump"


# --- trace merge ------------------------------------------------------------

def _synthesize(tmp_path):
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    for node, role in (("worker-0", "worker"), ("worker-1", "worker")):
        telemetry.configure(node_id=node, role=role)
        for i in range(10):
            telemetry.record_span(
                "train/step", 0.010 + 0.001 * i, items=32,
                flops_per_item=2.0e9, peak_flops=197e12)
            telemetry.record_span("feed/wait", 0.002, eof=False)
        telemetry.event("node/tb_spawn", port=6006)
        telemetry.flush()


def test_trace_merge_golden(tmp_path):
    _synthesize(tmp_path)
    tm = _load_trace_merge()
    pairs, skipped = tm.load_records(str(tmp_path))
    assert skipped == 0 and len(pairs) == 42
    assert [p[0]["ts"] for p in pairs] == \
        sorted(p[0]["ts"] for p in pairs)

    trace = tm.to_chrome_trace(pairs)
    evs = trace["traceEvents"]
    phases = {e["ph"] for e in evs}
    assert phases == {"M", "X", "i"}
    procs = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"worker-0 (worker)", "worker-1 (worker)"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 40
    assert all(e["ts"] >= 0 and e["dur"] > 0 for e in xs)

    text, stats = tm.summarize(pairs, skipped)
    assert stats["phases"]["train/step"]["count"] == 20
    for node in ("worker-0", "worker-1"):
        n = stats["nodes"][node]
        assert n["steps"] == 10
        assert n["p50_ms"] == pytest.approx(14.0, abs=0.1)
        assert n["p99_ms"] >= n["p90_ms"] >= n["p50_ms"]
        # 10 x 2ms waits in a ~165ms loop (145ms steps + 20ms waits)
        assert n["infeed_stall_frac"] == pytest.approx(20 / 165, abs=0.02)
        # mfu = items*flops / (time * peak)
        assert n["mfu"] == pytest.approx(
            (10 * 32 * 2.0e9) / (n["step_total_s"] * 197e12), rel=1e-6)
    assert "train/step" in text and "worker-1" in text


def test_trace_merge_skips_malformed_lines(tmp_path):
    _synthesize(tmp_path)
    bad = tmp_path / "torn-123.jsonl"
    bad.write_text('{"ts": 1.0, "half a record...\nnot json\n')
    tm = _load_trace_merge()
    pairs, skipped = tm.load_records(str(tmp_path))
    assert len(pairs) == 42 and skipped == 2


def test_read_spool_skips_truncated_trailing_record(tmp_path):
    """A writer SIGKILLed mid-write leaves a torn trailing line; the
    drain must keep the valid prefix, drop the torn tail, and never
    raise (it runs on live executors)."""
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    telemetry.configure(node_id="t-0", role="test")
    telemetry.event("good", n=1)
    telemetry.event("good", n=2)
    telemetry.flush()
    path = telemetry.sink_path()
    with open(path, "r+", encoding="utf-8") as f:
        whole = f.read()
        head, last = whole.rstrip("\n").rsplit("\n", 1)
        f.seek(0)
        f.truncate()
        # valid record, then a record cut mid-JSON with no newline
        f.write(head + "\n" + last[: len(last) // 2])
    # a sibling file that is ALL garbage is dropped entirely
    (tmp_path / "junk-1.jsonl").write_text("\x00\x01 not json")

    out = telemetry.read_spool(str(tmp_path))
    by_name = dict(out)
    assert os.path.basename(path) in by_name
    assert "junk-1.jsonl" not in by_name
    recs = [json.loads(ln) for ln in
            by_name[os.path.basename(path)].splitlines()]
    assert [r["attrs"]["n"] for r in recs if r["name"] == "good"] == [1]
    # sanitized output ends with a newline (merge-safe concatenation)
    assert by_name[os.path.basename(path)].endswith("\n")


def test_read_spool_missing_dir_is_empty(tmp_path):
    assert telemetry.read_spool(str(tmp_path / "nope")) == []


def test_trace_merge_summary_json(tmp_path):
    """--summary-json writes the machine-readable stats next to the
    human summary, numbers identical to summarize()'s dict."""
    _synthesize(tmp_path)
    env = dict(os.environ, PYTHONPATH="")
    out_json = tmp_path / "stats.json"
    proc = subprocess.run(
        [sys.executable, TRACE_MERGE, str(tmp_path),
         "--summary-json", str(out_json)],
        capture_output=True, text=True, env=env, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    stats = json.loads(out_json.read_text())
    assert stats["records"] == 42 and stats["skipped"] == 0
    tm = _load_trace_merge()
    pairs, skipped = tm.load_records(str(tmp_path))
    _text, want = tm.summarize(pairs, skipped)
    for node in ("worker-0", "worker-1"):
        assert stats["nodes"][node]["steps"] == 10
        assert stats["nodes"][node]["p50_ms"] == \
            pytest.approx(want["nodes"][node]["p50_ms"])
        assert stats["nodes"][node]["mfu"] == \
            pytest.approx(want["nodes"][node]["mfu"])
    assert stats["phases"]["train/step"]["count"] == 20


def test_trace_merge_cli(tmp_path):
    _synthesize(tmp_path)
    env = dict(os.environ, PYTHONPATH="")
    proc = subprocess.run(
        [sys.executable, TRACE_MERGE, str(tmp_path),
         "--summary-out", str(tmp_path / "summary.txt")],
        capture_output=True, text=True, env=env, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "per-node train steps" in proc.stdout
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["traceEvents"]
    assert "worker-0" in (tmp_path / "summary.txt").read_text()

    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, TRACE_MERGE, str(empty)],
        capture_output=True, text=True, env=env, timeout=60)
    assert proc.returncode == 1
    assert "no telemetry records" in proc.stderr
