"""LocalEngine tests: the substrate must behave like Spark executors do."""

import os

import pytest

from tensorflowonspark_tpu.engine import LocalEngine, TaskError


@pytest.fixture()
def engine():
    e = LocalEngine(2)
    yield e
    e.stop()


def _square_sum(it):
    return [sum(x * x for x in it)]


def test_parallelize_collect(engine):
    ds = engine.parallelize(range(10), 2)
    assert ds.num_partitions == 2
    assert sorted(ds.collect()) == sorted(range(10))


def test_map_partitions(engine):
    ds = engine.parallelize(range(1000), 4)
    out = ds.map_partitions(_square_sum).collect()
    assert sum(out) == sum(x * x for x in range(1000))


def test_map_partitions_chained(engine):
    ds = engine.parallelize(range(8), 2)
    out = (
        ds.map_partitions(lambda it: [x + 1 for x in it])
        .map_partitions(lambda it: [x * 10 for x in it])
        .collect()
    )
    assert sorted(out) == [10 * (x + 1) for x in range(8)]


def test_union(engine):
    a = engine.parallelize(range(4), 2)
    b = engine.parallelize(range(4, 8), 2)
    u = a.union(b)
    assert u.num_partitions == 4
    assert sorted(u.collect()) == list(range(8))


def test_executors_are_processes(engine):
    ds = engine.parallelize(range(2), 2)
    pids = ds.map_partitions(lambda it: [os.getpid()]).collect()
    assert all(p != os.getpid() for p in pids)


def test_spread_puts_one_task_per_executor(engine):
    ds = engine.parallelize(range(2), 2)
    seen = []

    def record(it):
        list(it)
        with open("touched", "w") as f:
            f.write(os.environ["TFOS_EXECUTOR_INDEX"])

    ds.foreach_partition(record, spread=True)
    for d in engine.executor_dirs:
        with open(os.path.join(d, "touched")) as f:
            seen.append(f.read())
    assert sorted(seen) == ["0", "1"]


def test_executor_cwd_is_stable(engine):
    """Feeder tasks must find files written by earlier node tasks."""
    ds = engine.parallelize(range(2), 2)

    def write(it):
        list(it)
        with open("state", "w") as f:
            f.write("x")

    ds.foreach_partition(write, spread=True)
    found = (
        engine.parallelize(range(2), 2)
        .map_partitions(lambda it: [os.path.exists("state")])
        .collect()
    )
    assert found == [True, True]


def test_task_error_propagates(engine):
    ds = engine.parallelize(range(4), 2)

    def boom(it):
        raise ValueError("deliberate failure")

    with pytest.raises(TaskError, match="deliberate failure"):
        ds.foreach_partition(boom)


def test_closure_capture(engine):
    factor = 7
    ds = engine.parallelize(range(5), 2)
    out = ds.map_partitions(lambda it: [x * factor for x in it]).collect()
    assert sorted(out) == [x * 7 for x in range(5)]


def test_repartition_balances_and_preserves_rows():
    """RDD repartition parity: one shard feeding many workers must be
    splittable (a starved worker would global-stop training at step 0)."""
    from tensorflowonspark_tpu.engine import LocalEngine

    engine = LocalEngine(2)
    try:
        ds = engine.parallelize(list(range(20)), 1)
        ds = ds.map_partitions(lambda it: [x * 2 for x in it])
        assert ds.num_partitions == 1
        re = ds.repartition(4)
        assert re.num_partitions == 4
        sizes = [len(p) for p in re._partitions]
        assert max(sizes) - min(sizes) <= 1  # round-robin balance
        assert sorted(re.collect()) == [x * 2 for x in range(20)]
        # more partitions than rows: no empty-partition explosion
        tiny = engine.parallelize([1, 2], 1).repartition(8)
        assert sorted(tiny.collect()) == [1, 2]
    finally:
        engine.stop()


def test_repartition_logs_materialized_volume(caplog):
    """The local-engine repartition materializes through the driver;
    it must SAY so with the measured volume (VERDICT r3 weak #6)."""
    import logging

    import numpy as np

    from tensorflowonspark_tpu.engine import LocalEngine, _approx_bytes

    engine = LocalEngine(2)
    try:
        rows = [(np.zeros((8, 8), np.uint8), i) for i in range(10)]
        with caplog.at_level(logging.INFO,
                             logger="tensorflowonspark_tpu.engine"):
            engine.parallelize(rows, 1).repartition(4)
        assert any("materialized 10 rows" in r.message for r in caplog.records)
    finally:
        engine.stop()
    # the estimator sees ndarray payloads, not container overhead only
    est = _approx_bytes(rows)
    assert est >= 10 * 64  # 10 rows x 64-byte arrays


def test_spark_dataset_repartition_via_stub():
    import os
    import sys

    stub = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "sparkstub")
    sys.path.insert(0, stub)
    try:
        import pyspark

        from tensorflowonspark_tpu.engine import SparkDataset

        sc = pyspark.SparkContext(master="local-stub[2]")
        try:
            rdd = sc.parallelize(list(range(10)), 1)
            ds = SparkDataset(rdd)
            re = ds.repartition(4)
            assert re.num_partitions == 4
            assert sorted(re.collect()) == list(range(10))
        finally:
            sc.stop()
    finally:
        sys.path.remove(stub)
