"""Supervised-actor substrate tests (tensorflowonspark_tpu/actors).

Covers the four substrate pillars — mailboxes/backpressure, liveness,
supervision policy, resolve-once ledgers — plus the two pure-actor
workloads (eval sidecar, successive-halving sweep) and the ISSUE 10
lint: no bespoke supervision/respawn/ledger code outside ``actors/``.
"""

import io
import os
import queue
import signal
import time
import tokenize

import pytest

from tensorflowonspark_tpu.actors import (
    Actor,
    ActorSystem,
    EchoActor,
    MailboxFull,
    SupervisionPolicy,
    dispatch,
    ledger,
    liveness,
    mailbox,
    supervise,
)

pytestmark = pytest.mark.actors

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tensorflowonspark_tpu")

# Fast failure detection for multiprocess tests.
FAST = dict(heartbeat_secs=0.2, stale_secs=5.0, tick_secs=0.1)


class _FakeMgr:
    """Dict-backed stand-in for the manager KV (set/get/kv)."""

    def __init__(self):
        self._kv = {}

    def set(self, key, value):
        self._kv[key] = value

    def get(self, key):
        return self._kv.get(key)

    def kv(self):
        return dict(self._kv)


# --- supervise: budgets and retry schedules ---------------------------------

def test_respawn_budget_counts_then_exhausts():
    b = supervise.RespawnBudget(2, what="worker", env_name="TFOS_X")
    assert b.consume(0) == 1
    assert b.consume(1) == 2
    assert b.used == 2
    with pytest.raises(supervise.BudgetExhausted) as ei:
        b.consume(5)
    # canonical message names the member and the env knob
    assert "worker 5 died and the respawn budget (TFOS_X=2)" in str(ei.value)


def test_respawn_budget_custom_error_class():
    b = supervise.RespawnBudget(0, error_cls=ValueError)
    with pytest.raises(ValueError):
        b.consume(0)


def test_retry_schedule_backoff_and_exhaustion():
    s = supervise.RetrySchedule(max_retries=2, backoff=0.1, cap=5.0)
    assert not s.exhausted("t")
    s.record_failure("t", "first boom")
    d1 = s.next_delay("t")
    assert 0.05 <= d1 <= 0.15  # 0.1 * jitter in [0.5, 1.5)
    assert s.attempt("t") == 1
    s.record_failure("t", "second boom")
    d2 = s.next_delay("t")
    assert 0.1 <= d2 <= 0.3   # doubled, jittered
    assert s.exhausted("t")


def test_retry_schedule_zero_retries_fails_fast():
    s = supervise.RetrySchedule(max_retries=0, backoff=0.1)
    s.record_failure("t", "boom")
    assert s.exhausted("t")  # exhausted before any retry is granted
    assert s.permanent_error("t", "task t failed") == "task t failed:\nboom"


def test_retry_schedule_permanent_error_chains_attempts():
    s = supervise.RetrySchedule(max_retries=1, backoff=0.1)
    s.record_failure("t", "first")
    s.next_delay("t")
    s.record_failure("t", "second")
    msg = s.permanent_error("t", "task t failed")
    assert msg.startswith("task t failed:\nsecond")  # latest first
    assert "--- earlier attempt ---" not in msg.split("first")[0] or True
    assert "first" in msg and "2 attempts" in msg


# --- dispatch: the in-flight table ------------------------------------------

def test_inflight_up_detects_respawn_and_resets_load():
    t = dispatch.InFlightTable(2)
    assert t.up(0, 100) is False      # first incarnation
    t.add(("batch", 1), {}, owner=0)
    assert t.loads()[0] == 1
    assert t.up(0, 100) is False      # same pid: not a respawn
    assert t.up(0, 200) is True       # new pid: respawn, load reset
    assert t.loads()[0] == 0


def test_inflight_pop_is_resolve_once():
    t = dispatch.InFlightTable(1)
    t.up(0, 1)
    t.add(("batch", 7), {"x": 1})
    entry = t.pop(("batch", 7))
    assert entry["x"] == 1 and entry["owner"] == 0
    assert t.pop(("batch", 7)) is None  # duplicate answer: no-op
    assert t.loads()[0] == 0


def test_inflight_picks_least_loaded():
    t = dispatch.InFlightTable(3)
    for i in range(3):
        t.up(i, 10 + i)
    assert t.add("a", {}) == 0
    assert t.add("b", {}) == 1
    assert t.add("c", {}) == 2
    t.pop("b")
    assert t.add("d", {}) == 1        # freed slot is least loaded again


def test_inflight_reassign_and_owned_by():
    t = dispatch.InFlightTable(2)
    t.up(0, 1)
    t.up(1, 2)
    t.add("k", {}, owner=0)
    t.lost(0)
    assert t.owned_by({0}) == ["k"]
    assert t.reassign("k") == 1       # moved to the survivor
    assert t.get("k")["owner"] == 1
    t.lost(1)
    t.add("k2", {}, owner=1)
    assert t.reassign("k2") is None   # nobody live: entry stays put
    assert t.get("k2") is not None


def test_inflight_stale_sweep_and_drain():
    t = dispatch.InFlightTable(1)
    t.up(0, 1)
    t.add("old", {})
    t.add("new", {})
    now = time.monotonic()
    t.get("old")["t"] = now - 100
    popped = t.stale(30, now)
    assert [k for k, _ in popped] == ["old"]
    assert t.stale(None) == []        # no timeout configured: no sweep
    assert [k for k, _ in t.drain()] == ["new"]
    assert len(t) == 0 and t.keys() == []


# --- ledger: resolve-once primitives ----------------------------------------

def test_once_gate_first_claim_wins():
    g = ledger.OnceGate()
    assert g.claim() is True
    assert g.claim() is False
    assert g.claimed() is True


def test_resolve_once_first_resolution_wins():
    f = ledger.ResolveOnce()
    assert f.resolve(41) is True
    assert f.resolve(42) is False     # duplicate answer after re-dispatch
    assert f.reject(RuntimeError("late")) is False
    assert f.wait(1) == 41


def test_resolve_once_reject_raises_stored_error():
    f = ledger.ResolveOnce()
    f.reject(ValueError("boom"))
    with pytest.raises(ValueError, match="boom"):
        f.wait(1)


def test_resolve_once_timeout_message():
    f = ledger.ResolveOnce()
    with pytest.raises(TimeoutError, match="request not served within 0.01s"):
        f.wait(0.01, "request not served")


def test_index_ledger_first_arrival_wins():
    led = ledger.IndexLedger()
    assert led.record(1, "b") is True
    assert led.record(0, "a") is True
    assert led.record(0, "A-replay") is False  # failover re-delivery
    assert led.values() == ["a", "b"]          # index order, originals kept
    assert set(led.times()) == {0, 1}
    assert len(led) == 2


def test_delivery_ledger_contract():
    led = ledger.DeliveryLedger()
    assert not led
    assert led.record("input", 3) is True
    assert led.record("input", 3) is False
    assert led.record("input", 1) is True
    assert led.record("eval", 7) is True
    assert led.done("input", 3) and not led.done("input", 2)
    assert led.done_units("input") == [1, 3]
    assert led.items() == [("eval", frozenset({7})),
                           ("input", frozenset({1, 3}))]
    assert len(led) == 2 and bool(led)
    led.reset("input")
    assert led.done_units("input") == []
    assert led.done_units("eval") == [7]


def test_kv_ledger_survives_recorder_identity():
    mgr = _FakeMgr()
    a = ledger.KVLedger(mgr, "grp")
    assert a.record("eval", 100) is True
    assert a.record("eval", 100) is False
    # a "respawned incarnation" (fresh object, same KV) sees the record
    b = ledger.KVLedger(mgr, "grp")
    assert b.done("eval", 100)
    assert b.record("eval", 200) is True
    assert b.done_units("eval") == [100, 200]
    # a different namespace is blind to it
    assert ledger.KVLedger(mgr, "other").done_units("eval") == []


def test_resume_cursor_skips_done_units():
    assert ledger.resume_cursor([], start=0) == 0
    assert ledger.resume_cursor([0, 1, 2], start=0) == 3
    assert ledger.resume_cursor([0, 2], start=0) == 1
    assert ledger.resume_cursor([5, 6], start=5) == 7


def test_null_ledger_client_api():
    c = ledger.NullLedgerClient()
    assert c.fed_partitions("input") == []
    c.partition_done("input", 0)
    c.close()


# --- liveness ---------------------------------------------------------------

def test_beat_and_beat_age_roundtrip():
    mgr = _FakeMgr()
    assert liveness.beat_age(mgr, "k") is None   # never beat: unknown
    liveness.beat(mgr, "k")
    age = liveness.beat_age(mgr, "k")
    assert age is not None and age < 5
    mgr.set("k", "garbage")
    assert liveness.beat_age(mgr, "k") is None   # unreadable: unknown


def test_scan_flags_dead_process_and_stale_beat():
    ages = {0: 0.1, 1: 99.0, 2: None}
    lost = liveness.scan(
        [0, 1, 2, 3],
        proc_alive=lambda i: i != 3,
        age_of=ages.get,
        stale_secs=10.0)
    assert lost == [(1, "heartbeat stale (99.0s)"), (3, "process death")]
    # None age is "unknown", never "dead": member 2 survives the sweep


# --- mailbox ----------------------------------------------------------------

def test_checked_put_backpressure():
    q = queue.Queue()
    name = mailbox.in_queue("g", 0)
    assert mailbox.checked_put(q, name, ("tell",), 2) == 1
    assert mailbox.checked_put(q, name, ("tell",), 2) == 2
    with pytest.raises(MailboxFull) as ei:
        mailbox.checked_put(q, name, ("tell",), 2)
    assert ei.value.limit == 2 and ei.value.depth == 2
    assert name in str(ei.value)
    # unbounded (0/None) never rejects
    assert mailbox.checked_put(q, name, ("tell",), 0) == 3


def test_queue_and_key_names_are_namespaced():
    assert mailbox.in_queue("g", 3) != mailbox.in_queue("g", 4)
    assert mailbox.in_queue("a", 0) != mailbox.in_queue("b", 0)
    assert mailbox.out_queue("a") != mailbox.out_queue("b")
    assert mailbox.beat_key("g", 0) != mailbox.epoch_key("g", 0)


# --- policy: TFOS_ACTOR_* env family with legacy aliases --------------------

def test_policy_env_family_and_legacy_aliases(monkeypatch):
    for name in ("TFOS_ACTOR_HEARTBEAT_SECS", "TFOS_HEARTBEAT_SECS",
                 "TFOS_ACTOR_RESPAWNS", "TFOS_EXECUTOR_RESPAWNS",
                 "TFOS_ACTOR_RETRIES", "TFOS_TASK_RETRIES",
                 "TFOS_ACTOR_MAILBOX_DEPTH"):
        monkeypatch.delenv(name, raising=False)
    p = SupervisionPolicy()
    assert (p.respawns, p.retries, p.heartbeat_secs) == (8, 2, 2.0)
    # legacy alias honored...
    monkeypatch.setenv("TFOS_EXECUTOR_RESPAWNS", "3")
    monkeypatch.setenv("TFOS_HEARTBEAT_SECS", "7")
    p = SupervisionPolicy()
    assert (p.respawns, p.heartbeat_secs) == (3, 7.0)
    # ...and the canonical TFOS_ACTOR_* name wins over it
    monkeypatch.setenv("TFOS_ACTOR_RESPAWNS", "5")
    monkeypatch.setenv("TFOS_ACTOR_HEARTBEAT_SECS", "1.5")
    p = SupervisionPolicy()
    assert (p.respawns, p.heartbeat_secs) == (5, 1.5)
    # the manager chokepoint reads the same pair (retunes every tier)
    from tensorflowonspark_tpu import manager as tfmanager

    assert tfmanager.heartbeat_interval() == 1.5
    # explicit constructor args beat the environment
    assert SupervisionPolicy(respawns=1).respawns == 1


# --- lint: no bespoke supervision/ledger code outside actors/ ---------------

def _code_tokens(path):
    """Source tokens with comments and string literals stripped, joined
    by single spaces (docstring mentions must not trip the lint)."""
    with open(path, "rb") as f:
        toks = tokenize.tokenize(f.readline)
        return " ".join(
            t.string for t in toks
            if t.type not in (tokenize.COMMENT, tokenize.STRING,
                              tokenize.ENCODING, tokenize.NEWLINE,
                              tokenize.NL, tokenize.INDENT,
                              tokenize.DEDENT))


def _package_files(exclude_dirs=("actors",)):
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs
                   if d not in exclude_dirs and d != "__pycache__"]
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def test_no_bespoke_supervision_outside_actors():
    """The substrate is THE copy: respawn counters, heartbeat loops,
    setdefault-set ledgers and resume-cursor loops may exist only in
    ``actors/`` (thin policy shims call into them instead)."""
    import re

    rules = [
        ("respawn counter", re.compile(r"self \. _respawns \+=")),
        ("heartbeat loop", re.compile(r"def _beat \(")),
        ("setdefault-set ledger",
         re.compile(r"\. setdefault \([^()]*\) \. add \(")),
        ("resume-cursor loop",
         re.compile(r"while \S+ in (done|consumed)\b")),
    ]
    respawn_def = re.compile(r"def _respawn\w* \(")
    offenders = []
    for path in _package_files():
        rel = os.path.relpath(path, REPO)
        code = _code_tokens(path)
        for what, rx in rules:
            if rx.search(code):
                offenders.append(f"{rel}: {what}")
        # the engine is the one sanctioned respawn *shim* (it consumes
        # the shared RespawnBudget); everyone else must go through it
        if respawn_def.search(code) and not rel.endswith("engine.py"):
            offenders.append(f"{rel}: bespoke respawn method")
    assert not offenders, (
        "bespoke supervision code outside actors/ (migrate onto "
        "tensorflowonspark_tpu.actors):\n  " + "\n  ".join(offenders))


def test_workloads_carry_zero_supervision_code():
    """ISSUE 10 acceptance: the eval sidecar and the sweep scheduler are
    pure behavior — no threads, signals, kills, respawns or hand-rolled
    ledgers of their own (the substrate provides all of it)."""
    import re

    forbidden = re.compile(
        r"\b(threading|signal|kill|SIGKILL|setdefault|respawn\w*|"
        r"heartbeat\w*|_beat\w*|Lock)\b")
    wdir = os.path.join(PKG, "workloads")
    offenders = []
    for name in sorted(os.listdir(wdir)):
        if not name.endswith(".py"):
            continue
        code = _code_tokens(os.path.join(wdir, name))
        hits = sorted(set(forbidden.findall(code)))
        if hits:
            offenders.append(f"workloads/{name}: {hits}")
    assert not offenders, (
        "workloads must contain zero supervision code:\n  "
        + "\n  ".join(offenders))


# --- multiprocess: the substrate end-to-end ---------------------------------

class _LedgerActor(Actor):
    """Records units exactly-once in the KV ledger."""

    def on_message(self, ctx, kind, payload):
        if kind == "record":
            return ctx.ledger.record("units", payload)
        if kind == "done":
            return ctx.ledger.done_units("units")
        raise NotImplementedError(kind)


class _FailActor(Actor):
    def on_message(self, ctx, kind, payload):
        raise ValueError(f"boom on {kind}")


def test_actor_system_ask_tell_and_errors():
    pol = SupervisionPolicy(**FAST)
    with ActorSystem(4) as sys_:
        g = sys_.spawn(EchoActor(), "echo", count=2, policy=pol)
        assert g.live() == [0, 1]
        assert g.ask("echo", {"x": 1}).result(30) == {"x": 1}
        # index-pinned asks land on distinct member processes
        pids = {g.ask("pid", index=i).result(30) for i in (0, 1)}
        assert len(pids) == 2
        assert sorted(g.pids().values()) == sorted(pids)
        # a failing handler surfaces at the future (never a hang),
        # and the member keeps serving afterwards
        fg = sys_.spawn(_FailActor(), "failer", policy=pol)
        with pytest.raises(RuntimeError, match="boom on anything"):
            fg.ask("anything").result(30)
        with pytest.raises(RuntimeError, match="boom on again"):
            fg.ask("again").result(30)
        # exactly-once KV ledger across duplicate records
        lg = sys_.spawn(_LedgerActor(), "ledger", policy=pol)
        assert lg.ask("record", 0).result(30) is True
        assert lg.ask("record", 0).result(30) is False
        assert lg.ask("done").result(30) == [0]
        assert lg.outstanding() == 0
        rows = g.rows()
        assert [r["actor"] for r in rows] == [0, 1]
        assert all(r["live"] for r in rows)


def test_actor_mailbox_backpressure_e2e():
    tiny = SupervisionPolicy(mailbox_depth=2, heartbeat_secs=0.2,
                             stale_secs=30.0, tick_secs=0.1)
    with ActorSystem(1) as sys_:
        g = sys_.spawn(EchoActor(), "echo", policy=tiny)
        g.tell("sleep", 2.0)          # wedge the consumer
        hits = 0
        for _ in range(50):
            try:
                g.tell("note", "x")
            except MailboxFull as e:
                assert e.limit == 2
                hits += 1
        assert hits > 0, "backpressure never fired"


def test_spawn_rejects_overcommit_and_duplicate_names():
    with ActorSystem(1) as sys_:
        sys_.spawn(EchoActor(), "a", policy=SupervisionPolicy(**FAST))
        with pytest.raises(ValueError, match="slots free"):
            sys_.spawn(EchoActor(), "b")
        with pytest.raises(ValueError, match="already exists"):
            sys_.spawn(EchoActor(), "a")


def _trial_score(config, budget):
    # deterministic, picklable: higher config and budget score higher
    return config * 10 + budget


def test_successive_halving_sweep():
    from tensorflowonspark_tpu.workloads.sweep import successive_halving

    out = successive_halving(_trial_score, [1, 2, 3, 4], budget=1, eta=2,
                             workers=2, policy=SupervisionPolicy(**FAST),
                             timeout=120.0)
    assert out["best"]["config"] == 4
    # rungs: 4 trials @ b1 -> 2 @ b2 -> 1 @ b4 (then single-survivor stop)
    assert [len(r["scores"]) for r in out["history"]] == [4, 2, 1]
    assert [r["budget"] for r in out["history"]] == [1, 2, 4]
    assert out["best"]["budget"] == 4


def test_successive_halving_target_early_stop():
    from tensorflowonspark_tpu.workloads.sweep import successive_halving

    out = successive_halving(_trial_score, [1, 2, 3, 4], budget=1, eta=2,
                             workers=2, policy=SupervisionPolicy(**FAST),
                             target=41.0, timeout=120.0)
    # config 4 scores 41 at rung 0: the sweep stops there
    assert out["best"]["config"] == 4
    assert len(out["history"]) == 1


def test_actor_spans_through_trace_merge(tmp_path, monkeypatch):
    import json
    import subprocess
    import sys as _sys

    from tensorflowonspark_tpu.utils import telemetry

    tdir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(tdir))
    monkeypatch.setenv(telemetry.NODE_ENV, "test-driver")
    monkeypatch.delenv(telemetry.SPOOL_ENV, raising=False)
    monkeypatch.delenv(telemetry.ROLE_ENV, raising=False)
    try:
        assert telemetry.enabled()
        with ActorSystem(1) as sys_:
            g = sys_.spawn(EchoActor(), "echo",
                           policy=SupervisionPolicy(**FAST))
            for i in range(3):
                assert g.ask("echo", i).result(30) == i
        telemetry.flush()
    finally:
        telemetry.flush()

    proc = subprocess.run(
        [_sys.executable, os.path.join(REPO, "scripts", "trace_merge.py"),
         str(tdir)],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=""), timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # the actor health section (ISSUE satellite: `-- actors --` section)
    assert "-- actors (actor/message spans) --" in proc.stdout
    assert "echo" in proc.stdout
    stats = json.loads(
        (tdir / "summary.json").read_text()) if (
            tdir / "summary.json").exists() else None
    if stats is not None and "actors" in stats:
        assert stats["actors"]["messages"]["echo:echo"]["count"] == 3


# --- slow lane: SIGKILL failover e2e ----------------------------------------

@pytest.mark.slow
def test_sigkill_failover_respawn_and_survivor():
    pol = SupervisionPolicy(**FAST)
    with ActorSystem(2) as sys_:
        g = sys_.spawn(EchoActor(), "ha", count=2, policy=pol)
        pid0 = g.ask("pid", index=0).result(30)
        epoch0 = g.epochs()[0]
        g.tell("crash", index=0)
        deadline = time.monotonic() + 90
        pid_changed = False
        while time.monotonic() < deadline:
            try:
                # a redispatched ask may be served by the survivor, so
                # the pid change alone does not prove the respawn —
                # wait for the supervisor to observe the new "up" too
                pid_changed = g.ask("pid", index=0).result(10) != pid0
            except Exception:
                pass
            if pid_changed and g.respawns_observed >= 1:
                break
        else:
            pytest.fail("member 0 never respawned")
        assert g.respawns_observed >= 1
        assert g.epochs()[0] > epoch0        # inherited mail is fenced
        # the survivor served throughout
        assert g.ask("echo", "alive", index=1).result(30) == "alive"


@pytest.mark.slow
def test_eval_sidecar_exactly_once_across_sigkill(tmp_path):
    import numpy as np

    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.workloads.eval_sidecar import EvalSidecar

    ckpt_dir = str(tmp_path / "ckpts")
    os.makedirs(ckpt_dir)

    def eval_fn(tree, step):
        return {"wsum": float(np.sum(tree["w"])), "step": step}

    pol = SupervisionPolicy(**FAST)
    with ActorSystem(1) as sys_:
        g = sys_.spawn(EvalSidecar(ckpt_dir, eval_fn), "eval", policy=pol)

        def wait_evaluated(steps, timeout=60):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    if g.ask("evaluated").result(10) == steps:
                        return
                except Exception:
                    pass
                time.sleep(0.2)
            pytest.fail(f"steps {steps} not evaluated in time")

        ckpt.save_checkpoint(ckpt_dir, {"w": np.ones(4)}, step=1)
        wait_evaluated([1])
        latest = g.ask("latest").result(30)
        assert latest["step"] == 1 and latest["metrics"]["wsum"] == 4.0

        # SIGKILL the sidecar; the substrate respawns it and the
        # driver-held KV ledger makes step 1 skip on re-poll
        os.kill(g.pids()[0], signal.SIGKILL)
        ckpt.save_checkpoint(ckpt_dir, {"w": 2 * np.ones(4)}, step=2)
        wait_evaluated([1, 2])
        assert g.respawns_observed >= 1
        # exactly one eval/result event per step across both incarnations
        events = [p for _i, kind, p in g.events if kind == "eval/result"]
        steps = [e["step"] for e in events]
        assert steps.count(1) == 1 and steps.count(2) == 1
