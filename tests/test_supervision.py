"""Supervision-layer tests: engine retry/backoff/respawn, failure
detection latency, manager heartbeat liveness, and rendezvous epoch
fencing + feed ledger (the building blocks of cluster.run(restarts=N))."""

import os
import threading
import time

import pytest

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu import rendezvous
from tensorflowonspark_tpu.engine import LocalEngine, ResultPumpError, TaskError

pytestmark = pytest.mark.faults


@pytest.fixture()
def engine(tmp_path):
    eng = LocalEngine(2, workdir=str(tmp_path / "eng"))
    yield eng
    eng.stop()


# --- task closures (module-level: shipped to executor processes) ------------

def _flaky_fn(marker_dir):
    """Fails the first attempt of each task, succeeds on retry (attempt
    counted via a marker file — survives the executor process)."""

    def _fn(it):
        items = list(it)
        mark = os.path.join(marker_dir, f"attempt-{items[0]}")
        if not os.path.exists(mark):
            with open(mark, "w") as f:
                f.write("1")
            raise RuntimeError(f"flaky failure on items {items}")
        return items

    return _fn


def _poison_fn(it):
    raise RuntimeError("permanently poisoned task")


def _touch_then_block_fn(marker_dir):
    def _fn(it):
        items = list(it)
        with open(os.path.join(marker_dir, f"started-{items[0]}"), "w") as f:
            f.write("1")
        time.sleep(60)
        return items

    return _fn


def _touch_then_sleep_briefly_fn(marker_dir):
    def _fn(it):
        items = list(it)
        path = os.path.join(marker_dir, f"started-{items[0]}")
        first = not os.path.exists(path)
        with open(path, "a") as f:
            f.write("x")
        if first and items[0] == 2:
            time.sleep(30)  # first attempt of task 1: wait to be killed
        return items

    return _fn


def _unpicklable_fn(it):
    return [(x for x in range(3))]  # generators cannot be pickled


# --- engine retry / poison --------------------------------------------------

def test_flaky_task_retries_to_success(engine, tmp_path):
    d = str(tmp_path)
    out = (engine.parallelize(range(4), 2)
           .map_partitions(_flaky_fn(d)).collect(spread=True, retryable=True))
    assert sorted(out) == [0, 1, 2, 3]
    # both tasks failed once then succeeded
    assert sorted(os.listdir(d)) == ["attempt-0", "attempt-2", "eng"]


def test_poison_task_fails_permanently_with_chain(engine):
    t0 = time.monotonic()
    with pytest.raises(TaskError) as ei:
        engine.parallelize(range(2), 1).foreach_partition(
            _poison_fn, retryable=True)
    msg = str(ei.value)
    assert "permanently poisoned task" in msg
    assert "permanent after 3 attempts" in msg
    assert "earlier attempt" in msg
    assert time.monotonic() - t0 < 30


def test_non_retryable_fails_fast_unchanged(engine):
    with pytest.raises(TaskError) as ei:
        engine.parallelize(range(2), 1).foreach_partition(_poison_fn)
    assert "task 0 failed on executor:" in str(ei.value)
    assert "permanent after" not in str(ei.value)


def test_retry_env_budget(tmp_path, monkeypatch):
    monkeypatch.setenv("TFOS_TASK_RETRIES", "0")
    eng = LocalEngine(1, workdir=str(tmp_path / "eng"))
    try:
        with pytest.raises(TaskError):
            eng.parallelize(range(2), 1).foreach_partition(
                _flaky_fn(str(tmp_path)), retryable=True)
    finally:
        eng.stop()


# --- executor loss ----------------------------------------------------------

def test_sigkill_detected_fast_when_not_retryable(engine, tmp_path):
    d = str(tmp_path)
    errors = []

    def _job():
        try:
            engine.parallelize(range(2), 2).foreach_partition(
                _touch_then_block_fn(d), spread=True)
        except TaskError as e:
            errors.append(e)

    t = threading.Thread(target=_job)
    t.start()
    deadline = time.monotonic() + 20
    while not os.path.exists(os.path.join(d, "started-1")):
        assert time.monotonic() < deadline, "task 1 never started"
        time.sleep(0.05)
    t0 = time.monotonic()
    os.kill(engine._procs[1].pid, 9)
    t.join(timeout=15)
    latency = time.monotonic() - t0
    assert errors, "executor death was not detected"
    assert "died with tasks in flight" in str(errors[0])
    assert latency < 10, f"death detection took {latency:.1f}s"


def test_sigkill_respawn_completes_job(engine, tmp_path):
    d = str(tmp_path)
    results = []
    errors = []

    def _job():
        try:
            results.extend(
                engine.parallelize(range(4), 2)
                .map_partitions(_touch_then_sleep_briefly_fn(d))
                .collect(spread=True, retryable=True))
        except TaskError as e:  # pragma: no cover - failure detail
            errors.append(e)

    t = threading.Thread(target=_job)
    t.start()
    deadline = time.monotonic() + 20
    while not os.path.exists(os.path.join(d, "started-2")):
        assert time.monotonic() < deadline, "task 1 never started"
        time.sleep(0.05)
    os.kill(engine._procs[1].pid, 9)
    t.join(timeout=60)
    assert not t.is_alive(), "job hung after executor kill"
    assert not errors, f"job failed: {errors}"
    assert sorted(results) == [0, 1, 2, 3]
    assert engine._respawns >= 1


def test_respawn_budget_exhaustion(tmp_path, monkeypatch):
    monkeypatch.setenv("TFOS_EXECUTOR_RESPAWNS", "0")
    eng = LocalEngine(1, workdir=str(tmp_path / "eng"))
    try:
        d = str(tmp_path)
        errors = []

        def _job():
            try:
                eng.parallelize(range(1), 1).foreach_partition(
                    _touch_then_block_fn(d), spread=True, retryable=True)
            except TaskError as e:
                errors.append(e)

        t = threading.Thread(target=_job)
        t.start()
        deadline = time.monotonic() + 20
        while not os.path.exists(os.path.join(d, "started-0")):
            assert time.monotonic() < deadline
            time.sleep(0.05)
        os.kill(eng._procs[0].pid, 9)
        t.join(timeout=30)
        assert errors and "respawn budget" in str(errors[0])
    finally:
        eng.stop()


# --- result transport -------------------------------------------------------

def test_unpicklable_result_fails_only_its_job(engine):
    with pytest.raises(TaskError):
        engine.parallelize(range(2), 1).map_partitions(
            _unpicklable_fn).collect()
    # engine still works for the next job
    out = engine.parallelize(range(4), 2).map_partitions(
        lambda it: [sum(it)]).collect()
    assert sorted(out) == [1, 5]


def test_result_pump_error_is_typed():
    assert issubclass(ResultPumpError, TaskError)


# --- heartbeat liveness -----------------------------------------------------

class _FakeMgr:
    def __init__(self):
        self.kv = {}

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k):
        return self.kv.get(k)


def test_heartbeat_age_unknown_without_beat():
    assert tfmanager.heartbeat_age(_FakeMgr()) is None


def test_heartbeat_age_tracks_beats():
    mgr = _FakeMgr()
    tfmanager.beat(mgr)
    assert tfmanager.heartbeat_age(mgr) < 1.0
    mgr.set(tfmanager.HEARTBEAT_KEY, time.time() - 120)
    assert tfmanager.heartbeat_age(mgr) > 100


def test_heartbeat_thread_beats_and_stops():
    mgr = _FakeMgr()
    stop = tfmanager.start_heartbeat(mgr, interval=0.05)
    deadline = time.monotonic() + 5
    while tfmanager.heartbeat_age(mgr) is None:
        assert time.monotonic() < deadline
        time.sleep(0.02)
    stop.set()


def test_stale_tunable(monkeypatch):
    monkeypatch.setenv("TFOS_HEARTBEAT_STALE", "3.5")
    assert tfmanager.stale_after() == 3.5


# --- rendezvous epoch fencing + feed ledger ---------------------------------

def _meta(executor_id, **kw):
    m = {"executor_id": executor_id, "host": "h", "job_name": "worker",
         "task_index": executor_id, "port": 1, "addr": ["h", 1],
         "authkey": ""}
    m.update(kw)
    return m


def test_epoch_mismatch_rejected():
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        server.reset(epoch=2)
        client = rendezvous.Client(addr)
        with pytest.raises(RuntimeError, match="epoch 0 != cluster epoch 2"):
            client.register(_meta(0), epoch=0)
        client.register(_meta(0), epoch=2)
        assert len(client.await_reservations(timeout=5)) == 1
        client.close()
    finally:
        server.stop()


def test_reregistration_replaces_same_executor():
    server = rendezvous.Server(2)
    addr = server.start()
    try:
        client = rendezvous.Client(addr)
        client.register(_meta(0, port=10))
        client.register(_meta(0, port=20))  # respawned node, same executor
        client.register(_meta(1))
        info = client.await_reservations(timeout=5)
        assert len(info) == 2
        assert {m["port"] for m in info if m["executor_id"] == 0} == {20}
        client.close()
    finally:
        server.stop()


def test_reset_clears_reservations_keeps_feed_ledger():
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        client = rendezvous.Client(addr)
        client.register(_meta(0))
        client.partition_done("input", 0)
        client.partition_done("input", 2)
        client.partition_done("eval", 7)
        server.reset(epoch=1)
        assert server.reservations.remaining() == 1  # table wiped
        assert client.fed_partitions("input") == [0, 2]
        assert client.fed_partitions("eval") == [7]
        server.reset_feed("input")
        assert client.fed_partitions("input") == []
        assert client.fed_partitions("eval") == [7]
        client.close()
    finally:
        server.stop()


def test_idempotent_call_reconnects_transparently():
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        client = rendezvous.Client(addr)
        client.register(_meta(0))
        client._sock.close()  # simulate a dropped connection
        assert len(client.await_reservations(timeout=5)) == 1  # QUERY replays
        client._sock.close()
        with pytest.raises(ConnectionError):
            client.request_stop()  # STOP is not idempotent: no replay
        client.close()
    finally:
        server.stop()
