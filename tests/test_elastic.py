"""Elastic SPMD runtime (fast lane): virtual-device algebra, runtime
resize, reshard placement/value fidelity, cross-mesh checkpoint
round-trips, and the cluster/rendezvous resize plumbing (docs/elastic.md).
The kill-one-executor recovery e2e is test_elastic_e2e.py (slow lane)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu import elastic
from tensorflowonspark_tpu.cluster import _elastic_template
from tensorflowonspark_tpu.elastic.virtual import virtualize
from tensorflowonspark_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------- virtualize

def test_virtualize_identity_fold(eight_devices):
    layout = virtualize({"data": 4, "fsdp": 2}, eight_devices)
    assert layout.accum_steps == 1
    assert layout.physical == layout.logical == {"data": 4, "fsdp": 2}
    assert dict(layout.mesh.shape) == {"data": 4, "fsdp": 2}


def test_virtualize_folds_deficit_into_accum_axis(eight_devices):
    layout = virtualize({"data": 8, "fsdp": 2}, eight_devices)
    assert layout.accum_steps == 2
    assert layout.physical == {"data": 4, "fsdp": 2}
    assert layout.logical == {"data": 8, "fsdp": 2}
    assert layout.n_virtual == 16 and layout.n_physical == 8
    # non-accum axes never shrink: fsdp stays at its logical size
    assert layout.physical["fsdp"] == layout.logical["fsdp"]


def test_virtualize_canonicalizes_aliases(eight_devices):
    layout = virtualize({"pipe": 2, "expert": 4}, eight_devices,
                        accum_axis="expert")
    assert layout.logical == {"pp": 2, "ep": 4}
    assert layout.accum_axis == "ep"


def test_virtualize_rejects_non_divisor_topology(eight_devices):
    with pytest.raises(ValueError, match="divisor"):
        virtualize({"data": 8}, eight_devices[:3])


def test_virtualize_rejects_minus_one(eight_devices):
    with pytest.raises(ValueError, match="fully specified"):
        virtualize({"data": -1}, eight_devices)


def test_virtualize_rejects_missing_accum_axis(eight_devices):
    with pytest.raises(ValueError, match="no 'data' axis"):
        virtualize({"fsdp": 8}, eight_devices[:4])


def test_virtualize_rejects_indivisible_accum_axis(eight_devices):
    # factor 4 cannot fold into data=2
    with pytest.raises(ValueError, match="cannot fold"):
        virtualize({"data": 2, "model": 4}, eight_devices[:2])


def test_virtualize_microbatch_schedule(eight_devices):
    layout = virtualize({"data": 8}, eight_devices[:4])
    assert layout.accum_steps == 2
    assert layout.microbatch(256) == 128
    with pytest.raises(ValueError, match="not divisible"):
        layout.microbatch(255)


def test_virtualize_accumulated_grad_matches_flat(eight_devices):
    """The fold is numerically invisible: accumulated value_and_grad over
    the layout's microbatches equals the flat gradient on the full batch."""
    layout = virtualize({"data": 8}, eight_devices[:4])
    w = jnp.ones((4,))
    batch = jnp.arange(32.0).reshape(8, 4)

    def loss_fn(w, b):
        return jnp.mean((b @ w) ** 2)

    flat_l, flat_g = jax.value_and_grad(loss_fn)(w, batch)
    acc_l, acc_g = layout.value_and_grad(loss_fn)(w, batch)
    np.testing.assert_allclose(acc_l, flat_l, rtol=1e-5)
    np.testing.assert_allclose(acc_g, flat_g, rtol=1e-5)


# ------------------------------------------------------------ ElasticRuntime

def _toy_state(key=0):
    params = {"w": jnp.asarray(
        np.random.default_rng(key).random((128, 64), np.float32))}
    state = {"step": jnp.zeros((), jnp.int32)}
    opt_state = optax.sgd(0.1).init(params)
    return params, state, opt_state


def test_runtime_resize_refolds_same_logical_shape(eight_devices):
    rt = elastic.ElasticRuntime(
        elastic.TrainSpec({"data": 8}, global_batch=64), devices=eight_devices)
    assert rt.generation == 0
    assert rt.layout.accum_steps == 1
    assert rt.batch_schedule() == {
        "global": 64, "microbatch": 64, "per_device": 8, "accum_steps": 1}

    rt.resize(devices=eight_devices[:4])  # shrink: 8 virtual on 4 devices
    assert rt.generation == 1
    assert rt.layout.accum_steps == 2
    assert dict(rt.mesh.shape) == {"data": 4}
    assert rt.batch_schedule() == {
        "global": 64, "microbatch": 32, "per_device": 8, "accum_steps": 2}

    rt.resize(devices=eight_devices)  # re-grow back to the full pool
    assert rt.generation == 2
    assert rt.layout.accum_steps == 1


def test_runtime_reshard_moves_state_and_keeps_values(eight_devices):
    rt = elastic.ElasticRuntime(
        elastic.TrainSpec({"data": 4, "fsdp": 2}), devices=eight_devices)
    params, state, opt_state = _toy_state()
    (params, state, opt_state), _ = rt.shard_train_state(
        params, state, opt_state)
    before = np.asarray(params["w"])

    rt.resize(devices=eight_devices[:4])
    (params, state, opt_state), (p_sh, _s, _o) = rt.reshard_train_state(
        params, state, opt_state)
    np.testing.assert_array_equal(np.asarray(params["w"]), before)
    new_devs = set(rt.mesh.devices.flat)
    assert set(params["w"].sharding.device_set) <= new_devs
    assert p_sh["w"].mesh is rt.mesh


def test_runtime_reshard_default_shardings(eight_devices):
    rt = elastic.ElasticRuntime(
        elastic.TrainSpec({"data": 2, "fsdp": 2}), devices=eight_devices[:4])
    tree = {"w": jnp.ones((128, 64))}
    placed = rt.reshard(tree)
    np.testing.assert_array_equal(np.asarray(placed["w"]), 1.0)
    assert set(placed["w"].sharding.device_set) <= set(rt.mesh.devices.flat)


def test_runtime_trainspec_coercion_and_metrics(eight_devices, monkeypatch):
    from tensorflowonspark_tpu.utils import metrics_registry

    monkeypatch.setenv(metrics_registry.PORT_ENV, "0")
    metrics_registry.reset()
    try:
        rt = elastic.ElasticRuntime({"data": 8}, devices=eight_devices)
        assert rt.spec.mesh_axes == {"data": 8}
        rt.resize(devices=eight_devices[:4])
        snap = metrics_registry.snapshot()
    finally:
        metrics_registry.reset()

    def value(name):
        return snap[name]["series"][0]["value"]

    assert value("tfos_elastic_mesh_devices") == 4
    assert value("tfos_elastic_virtual_devices") == 8
    assert value("tfos_elastic_accum_steps") == 2
    resizes = snap["tfos_elastic_resizes_total"]["series"][0]
    assert resizes["labels"] == {"scope": "runtime"}
    assert resizes["value"] == 1


# -------------------------------------------------- cross-mesh checkpointing

def test_checkpoint_cross_mesh_round_trip(tmp_path, eight_devices):
    """Save under an 8-device fold, restore under a 4-device fold: values
    identical, placement on the new mesh (the resize-resume path)."""
    rt8 = elastic.ElasticRuntime(
        elastic.TrainSpec({"data": 4, "fsdp": 2}), devices=eight_devices)
    params, state, opt_state = _toy_state()
    (params, _state, _opt), _ = rt8.shard_train_state(
        params, state, opt_state)
    ckpt.save_checkpoint(str(tmp_path), params, step=3)

    rt4 = elastic.ElasticRuntime(
        elastic.TrainSpec({"data": 4, "fsdp": 2}), devices=eight_devices[:4])
    restored, step = rt4.restore(str(tmp_path))
    assert step == 3
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.asarray(params["w"]))
    assert set(restored["w"].sharding.device_set) <= set(
        rt4.mesh.devices.flat)


def test_restore_any_explicit_target_shardings(tmp_path, eight_devices):
    from tensorflowonspark_tpu.parallel import fsdp_sharding, make_mesh

    params = {"w": jnp.asarray(
        np.random.default_rng(1).random((128, 64), np.float32))}
    ckpt.save_checkpoint(str(tmp_path), params, step=11)

    mesh4 = make_mesh({"data": 2, "fsdp": 2}, devices=eight_devices[:4])
    tree, step = ckpt.restore_any(
        str(tmp_path),
        target_shardings=lambda t: fsdp_sharding(mesh4, t))
    assert step == 11
    np.testing.assert_array_equal(
        np.asarray(tree["w"]), np.asarray(params["w"]))
    assert set(tree["w"].sharding.device_set) <= set(mesh4.devices.flat)
    # without target_shardings the old host-numpy behavior is unchanged
    plain, _ = ckpt.restore_any(str(tmp_path))
    assert isinstance(plain["w"], np.ndarray)


# ------------------------------------------- cluster/rendezvous resize bits

def test_elastic_template_promotes_coordinator():
    t0 = {"chief": [0], "worker": [1, 2, 3], "ps": [4]}
    assert _elastic_template(t0, [1, 2, 3, 4]) == {
        "chief": [1], "worker": [2, 3], "ps": [4]}


def test_elastic_template_drops_empty_jobs():
    t0 = {"chief": [0], "worker": [1, 2], "ps": [3], "evaluator": [4]}
    assert _elastic_template(t0, [0, 2]) == {"chief": [0], "worker": [2]}


def test_elastic_template_regrow_is_identity():
    t0 = {"chief": [0], "worker": [1, 2, 3]}
    assert _elastic_template(t0, [0, 1, 2, 3]) == t0


def test_rendezvous_resize_changes_required():
    from tensorflowonspark_tpu import rendezvous

    server = rendezvous.Server(3)
    try:
        server.start()
        assert server.reservations.required == 3
        server.resize(2)
        assert server.reservations.required == 2
        assert server.reservations.remaining() == 2
    finally:
        server.stop()
