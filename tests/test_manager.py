"""Executor IPC manager tests (parity: DataFeed/TFManager usage patterns)."""

from tensorflowonspark_tpu import manager as tfmanager


def test_queue_roundtrip_and_kv():
    m = tfmanager.start(b"secret", ["input", "output"])
    try:
        assert m.get("state") == "running"
        m.set("state", "terminating")
        assert m.get("state") == "terminating"
        assert m.get("missing") is None

        q = m.get_queue("input")
        q.put([1, 2, 3])  # a batch
        assert q.get() == [1, 2, 3]
        q.task_done()

        # second connection (the feeder-reattach path)
        c = tfmanager.connect(m.address, b"secret")
        assert c.get("state") == "terminating"
        c.get_queue("output").put(["r"])
        assert m.get_queue("output").get() == ["r"]
    finally:
        m.shutdown()


def test_queue_join_semantics():
    m = tfmanager.start(b"secret", ["input"])
    try:
        q = m.get_queue("input")
        q.put(["batch"])
        import threading

        done = threading.Event()

        def consume():
            q.get()
            q.task_done()
            done.set()

        t = threading.Thread(target=consume)
        t.start()
        q.join()  # returns only after task_done (server-side)
        # the consumer thread may still be between its task_done RPC
        # returning and setting the event — allow a grace window
        assert done.wait(5)
        t.join()
    finally:
        m.shutdown()
