"""ImageNet example ladder: JPEG TFRecords -> imagenet_data_setup
(engine-parallel decode-once prep) -> resnet_imagenet_spark training
from raw records via shard striping + the columnar feed."""

import io
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = {k: v for k, v in os.environ.items() if not k.startswith("TFOS_")}
    env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return env


def test_decode_record_rules():
    """The shared decode helper: JPEG magic beats the size heuristic
    (a compressed payload of exactly H*W*3 bytes must decode, not pass
    through as 'raw'), missing fields raise, 1-based labels shift."""
    sys.path.insert(0, os.path.join(REPO, "examples", "resnet"))
    try:
        import imagenet_records as IR
    finally:
        sys.path.pop(0)
    from PIL import Image

    rng = np.random.default_rng(0)
    hw = 24  # big enough that a q20 JPEG fits under hw*hw*3 bytes
    raw = rng.integers(0, 256, (hw, hw, 3), np.uint8)

    arr, label = IR.decode_record(
        {"image": raw.tobytes(), "label": 3}, hw)
    np.testing.assert_array_equal(arr, raw)
    assert label == 3

    # a JPEG padded to exactly hw*hw*3 bytes must still DECODE
    buf = io.BytesIO()
    Image.fromarray(raw, "RGB").save(buf, "JPEG", quality=20)
    payload = buf.getvalue()
    assert len(payload) < hw * hw * 3
    payload = payload + b"\0" * (hw * hw * 3 - len(payload))
    arr, label = IR.decode_record(
        {"image/encoded": [payload], "image/class/label": [4]}, hw)
    assert arr.shape == (hw, hw, 3)
    assert label == 3  # 1-based input

    with pytest.raises(KeyError, match="label"):
        IR.decode_record({"image": raw.tobytes()}, hw)
    with pytest.raises(KeyError, match="image"):
        IR.decode_record({"label": 1}, hw)
    with pytest.raises(ValueError, match="neither"):
        IR.decode_record({"image": b"junkbytes", "label": 1}, hw)


def test_prep_then_train(tmp_path):
    from PIL import Image

    from tensorflowonspark_tpu import recordio

    jpeg_dir = tmp_path / "jpeg"
    jpeg_dir.mkdir()
    rng = np.random.default_rng(0)
    with recordio.TFRecordWriter(str(jpeg_dir / "part-r-00000")) as w:
        for i in range(48):
            arr = rng.integers(0, 256, (40, 48, 3), np.uint8)  # non-square
            buf = io.BytesIO()
            Image.fromarray(arr, "RGB").save(buf, "JPEG", quality=92)
            w.write(recordio.encode_example({
                "image/encoded": ("bytes", [buf.getvalue()]),
                "image/class/label": ("int64", [1 + i % 8]),  # 1-based
            }))

    raw_dir = tmp_path / "raw"
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples/resnet/imagenet_data_setup.py"),
         "--input_dir", str(jpeg_dir), "--output_dir", str(raw_dir),
         "--image_size", "32", "--num_executors", "2"],
        cwd=str(tmp_path), env=_env(), capture_output=True, text=True,
        timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "wrote 48 raw 32px records" in out.stdout

    # prepped records round-trip at the right shape/labels
    rec = next(iter(recordio.TFRecordReader(
        str(next(raw_dir.glob("part-r-*"))))))
    feats = {k: v for k, (_kind, v) in recordio.decode_example(rec).items()}
    assert len(feats["image"][0]) == 32 * 32 * 3
    assert 0 <= feats["label"][0] < 8  # 1-based input became 0-based

    train = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples/resnet/resnet_imagenet_spark.py"),
         "--cluster_size", "2", "--batch_size", "8", "--image_size", "32",
         "--steps", "2", "--num_classes", "8",
         "--data_dir", str(raw_dir),
         "--model_dir", str(tmp_path / "ckpt")],
        cwd=str(tmp_path), env=_env(), capture_output=True, text=True,
        timeout=420)
    assert train.returncode == 0, train.stdout[-3000:] + train.stderr[-2000:]
    assert "final: step=" in train.stdout