"""End-to-end fault-tolerance acceptance (slow lane).

1. The MNIST e2e survives ONE injected executor SIGKILL mid-training with
   ``restarts=1``: the driver recovers (quiesce, respawn, epoch bump,
   relaunch), trainers resume from their checkpoints, the unconsumed
   partition is re-fed, and the restart + resume are visible as telemetry
   events in the merged trace.
2. ``restarts=0`` with the same injection fails fast with the remote
   traceback (today's behavior).
3. A chaos smoke: a randomized-but-reproducible fault plan (seed logged,
   printed on failure) over the feed pipeline with restarts=1 — any
   outcome is acceptable except a hang or an unclean exit.
"""

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine, TaskError
from tensorflowonspark_tpu.utils import faults, telemetry

pytestmark = [pytest.mark.slow, pytest.mark.faults]

N_PART = 4
PER_PART = 320
CHUNK = 64  # 5 puts/partition; executor 1's 6th put = its 2nd partition


def mnist_ft_main(args, ctx):
    """Single-process-per-worker MNIST CNN with checkpoint auto-resume
    (the SPMD variant of this loop is test_mnist_e2e; recovery semantics
    are identical and this one keeps the chaos deterministic)."""
    import jax
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    ckpt_dir = os.path.join(args["model_dir"], f"worker-{ctx.task_index}")
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.05, momentum=0.9)
    opt_state = opt.init(params)
    saved, start = ctx.restore_latest(ckpt_dir)
    if saved is not None:
        params = saved  # fresh opt state after restart is acceptable
    step_fn = jax.jit(mnist.make_train_step(opt))

    feed = ctx.get_data_feed(train_mode=True)
    step = start
    while not feed.should_stop():
        batch = feed.next_batch(32)
        if not batch:
            continue
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = np.asarray([b[1] for b in batch], dtype=np.int32)
        params, opt_state, loss, acc = step_fn(
            params, opt_state, images, labels)
        step += 1
        ckpt.save_checkpoint(ckpt_dir, params, step)


def _synthetic_records(n):
    rng = np.random.default_rng(0)
    images = rng.random((n, 28, 28, 1), dtype=np.float32)
    q = np.stack(
        [
            images[:, :14, :14, 0].mean((1, 2)),
            images[:, :14, 14:, 0].mean((1, 2)),
            images[:, 14:, :14, 0].mean((1, 2)),
            images[:, 14:, 14:, 0].mean((1, 2)),
        ],
        axis=-1,
    )
    labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
    return list(zip(list(images), list(labels)))


def _engine(extra_env=None):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",  # drop the TPU-tunnel site hook
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TFOS_FEED_CHUNK": str(CHUNK),
    }
    env.update(extra_env or {})
    return LocalEngine(2, env=env)


def _read_all(root):
    text = ""
    for path in glob.glob(os.path.join(str(root), "**", "*"), recursive=True):
        if os.path.isfile(path):
            with open(path, errors="replace") as f:
                text += f.read()
    return text


def test_mnist_survives_executor_kill(tmp_path, monkeypatch):
    telemetry_dir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(telemetry_dir))
    monkeypatch.chdir(tmp_path)
    engine = _engine({
        faults.PLAN_ENV: "feed.put:kill@6",
        faults.EXECUTOR_ENV: "1",
    })
    try:
        cluster = TFCluster.run(
            engine, mnist_ft_main, {"model_dir": str(tmp_path / "model")},
            num_executors=2, input_mode=InputMode.SPARK, restarts=1,
        )
        ds = engine.parallelize(_synthetic_records(N_PART * PER_PART), N_PART)
        cluster.train(ds, num_epochs=1, feed_timeout=240)
        assert cluster._restarts_used == 1, (
            f"expected exactly one recovery, got {cluster._restarts_used}")
        cluster.shutdown(grace_secs=2)
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)

    # both workers trained past the kill: newest checkpoints exist and the
    # epoch-1 incarnation resumed from a step > 0
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    steps = [ckpt.latest_step(str(tmp_path / "model" / f"worker-{i}"))
             for i in range(2)]
    assert all(s and s > 0 for s in steps), f"missing checkpoints: {steps}"

    # recovery + resume are telemetry events in the drained run dir, and
    # trace_merge accepts the whole timeline
    raw = _read_all(telemetry_dir)
    for ev in ("cluster/recover_begin", "cluster/recover_done",
               "engine/executor_respawn", "node/resume"):
        assert ev in raw, f"telemetry event {ev} missing from drained run"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "scripts", "trace_merge.py"),
         str(telemetry_dir)],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=""), timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    trace = json.loads((telemetry_dir / "trace.json").read_text())
    names = {e["name"] for e in trace["traceEvents"]}
    assert "cluster/recover" in names or "cluster/recover_begin" in names


def test_restarts_zero_fails_fast(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    engine = _engine({
        faults.PLAN_ENV: "feed.put:kill@6",
        faults.EXECUTOR_ENV: "1",
    })
    try:
        cluster = TFCluster.run(
            engine, mnist_ft_main, {"model_dir": str(tmp_path / "model")},
            num_executors=2, input_mode=InputMode.SPARK, restarts=0,
        )
        ds = engine.parallelize(_synthetic_records(N_PART * PER_PART), N_PART)
        t0 = time.monotonic()
        with pytest.raises(TaskError, match="died with tasks in flight"):
            cluster.train(ds, num_epochs=1, feed_timeout=240)
        assert time.monotonic() - t0 < 120
        assert cluster._restarts_used == 0
        # shutdown cannot reach the dead executor; any exit but a hang is
        # today's behavior
        try:
            cluster.shutdown(grace_secs=1, timeout=120)
        except (TaskError, SystemExit):
            pass
    finally:
        engine.stop()


def _chaos_consumer(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        feed.next_batch(64)


def test_chaos_smoke(tmp_path, monkeypatch):
    """Randomized fault plan over the feed pipeline.  The ONLY hard
    requirement is a clean bounded exit; reproduce failures with
    TFOS_CHAOS_SEED=<printed seed>."""
    seed = int(os.environ.get("TFOS_CHAOS_SEED", "0") or 0)
    if not seed:
        seed = int(time.time()) % 100000
    plan = faults.random_plan(seed)
    print(f"chaos seed={seed} plan={plan!r} "
          f"(replay: TFOS_CHAOS_SEED={seed})")
    monkeypatch.chdir(tmp_path)
    engine = _engine({faults.PLAN_ENV: plan})
    try:
        outcome = "clean"
        try:
            cluster = TFCluster.run(
                engine, _chaos_consumer, {}, num_executors=2,
                input_mode=InputMode.SPARK, restarts=1,
                reservation_timeout=120,
            )
            ds = engine.parallelize(range(N_PART * PER_PART), N_PART)
            cluster.train(ds, num_epochs=1, feed_timeout=60)
            cluster.shutdown(grace_secs=1, timeout=180)
        except (TaskError, RuntimeError, TimeoutError, SystemExit) as e:
            outcome = f"failed cleanly: {type(e).__name__}: {str(e)[:200]}"
        print(f"chaos seed={seed}: {outcome}")
    except BaseException:
        print(f"CHAOS FAILURE: replay with TFOS_CHAOS_SEED={seed} "
              f"(plan {plan!r})")
        raise
    finally:
        engine.stop()
