"""Causal request tracing, the span-catalog docs lint, the flight
recorder, and ``tfos-postmortem``.

Parity framing: the reference has neither request tracing nor a crash
recorder — its failure story is free-text executor stdout
(reference ``TFSparkNode.py:356``, SURVEY.md §5).  These tests pin the
ISSUE 12 acceptance gates: one HTTP generate through a ReplicaPool
yields ONE trace_id spanning at least two OS processes with every
parent link resolving; ``trace_merge --trace`` renders that request's
waterfall + critical path; flight dumps are bounded and
redaction-safe; ``tfos-postmortem`` names the SIGKILLed node and the
in-flight work at the moment of death (slow lane).
"""

import glob
import importlib.util
import io
import json
import os
import re
import threading
import time
import urllib.request

import pytest

from tensorflowonspark_tpu.obs import flight
from tensorflowonspark_tpu.obs import postmortem
from tensorflowonspark_tpu.utils import telemetry

pytestmark = pytest.mark.tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tensorflowonspark_tpu")
TRACE_MERGE = os.path.join(REPO, "scripts", "trace_merge.py")

_ENV_KEYS = (telemetry.DIR_ENV, telemetry.SPOOL_ENV, telemetry.NODE_ENV,
             telemetry.ROLE_ENV, telemetry.TRACE_ENV, telemetry.RING_ENV,
             flight.CAP_ENV, flight.WINDOW_ENV, flight.KEEP_ENV)


@pytest.fixture(autouse=True)
def _trace_env():
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    yield
    telemetry.flush()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _load_trace_merge():
    spec = importlib.util.spec_from_file_location("trace_merge", TRACE_MERGE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _all_records(root):
    out = []
    for dirpath, _dirs, files in os.walk(str(root)):
        for name in sorted(files):
            if name.endswith(".jsonl"):
                with open(os.path.join(dirpath, name),
                          encoding="utf-8") as f:
                    for ln in f:
                        if ln.strip():
                            out.append(json.loads(ln))
    return out


# --- span-catalog docs lint (satellite: docs lint) --------------------------

# Literal first-arg span/event names at instrumentation call sites.
# \s* spans continuation lines (cluster/start, node/boot, data/serve are
# multi-line calls); f-strings never match (the quote isn't adjacent).
_SPAN_CALL_RE = re.compile(
    r'\.(?:span|event|record_span|trace_span|trace_root)\(\s*"([^"\n]+)"')
# telemetry.py's ALL-CAPS name constants (the sites that emit through
# them won't match the literal regex above)
_CONST_RE = re.compile(r'^([A-Z][A-Z0-9_]*) = "([^"]*/[^"]*)"', re.M)


def _code_span_names():
    files = []
    for dirpath, _dirs, names in os.walk(PKG):
        for n in names:
            # telemetry.py is excluded from the call-site scan (its
            # docstrings show "phase/name" examples); its constants are
            # folded in below instead
            if n.endswith(".py") and not (
                    dirpath.endswith("utils") and n == "telemetry.py"):
                files.append(os.path.join(dirpath, n))
    files.append(os.path.join(REPO, "bench.py"))
    files.extend(glob.glob(os.path.join(REPO, "scripts", "*.py")))
    names = set()
    for path in files:
        with open(path, encoding="utf-8") as f:
            names.update(_SPAN_CALL_RE.findall(f.read()))
    with open(os.path.join(PKG, "utils", "telemetry.py"),
              encoding="utf-8") as f:
        names.update(v for _k, v in _CONST_RE.findall(f.read()))
    return names


def _docs_span_names():
    with open(os.path.join(REPO, "docs", "telemetry.md"),
              encoding="utf-8") as f:
        text = f.read()
    section = text.split("## Span catalog", 1)[1].split("\n## ", 1)[0]
    rows = re.findall(r"^\| `([^`]+)` \|", section, re.M)
    assert rows, "docs/telemetry.md span-catalog table not found"
    # rows containing < are f-string families, exempt from the
    # code-side match by design (bench/<lane>, stress_fed/<mode>)
    return {r for r in rows if "<" not in r}


def test_span_catalog_matches_code_both_ways():
    """Every literal span/event name the package, bench.py and scripts/
    emit appears in docs/telemetry.md's span catalog, and every catalog
    row is emitted somewhere (same discipline as the metric lint)."""
    in_code = _code_span_names()
    in_docs = _docs_span_names()
    assert in_code <= in_docs, (
        f"spans missing from docs/telemetry.md: {sorted(in_code - in_docs)}")
    assert in_docs <= in_code, (
        f"catalog rows never emitted: {sorted(in_docs - in_code)}")


# --- CPU e2e gate: one request, one trace, >=2 processes --------------------

def _decode_server(tmp_path):
    import jax

    from tensorflowonspark_tpu.models import transformer as T
    from tensorflowonspark_tpu.serving import decode as D
    from tensorflowonspark_tpu.serving import replicas as R
    from tensorflowonspark_tpu.serving import server as S
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = T.Config(vocab_size=61, dim=32, n_layers=2, n_heads=2,
                   max_seq=32, dtype="float32", attn_impl="reference")
    params = T.init(jax.random.PRNGKey(0), cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=8))
    return S, spec


def test_http_generate_is_one_trace_across_processes(tmp_path, monkeypatch):
    """THE tentpole gate: a single ``POST /v1/generate`` through a
    1-replica pool produces one trace_id whose spans come from at least
    two OS processes (driver + replica), every parent_id resolves
    inside the trace, and a client traceparent header is continued, not
    replaced.  Then ``trace_merge --trace`` renders the waterfall and
    the queue/prefill/decode critical-path decomposition from the same
    spools."""
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    monkeypatch.setenv(telemetry.DIR_ENV, str(tdir))
    telemetry.configure(node_id="driver", role="driver")
    S, spec = _decode_server(tmp_path)
    prompt = [2, 3, 5, 7]
    client = telemetry.TraceContext()  # the "remote caller"'s context
    with S.Server(spec, num_replicas=1, request_timeout=300) as srv:
        httpd = S.serve_http(srv, port=0, block=False)
        try:
            host, port = httpd.server_address
            for hdrs in ({}, {"traceparent": client.to_header()}):
                req = urllib.request.Request(
                    f"http://{host}:{port}/v1/generate",
                    data=json.dumps({"prompt": prompt,
                                     "max_tokens": 6}).encode(),
                    headers={"Content-Type": "application/json", **hdrs})
                with urllib.request.urlopen(req, timeout=300) as resp:
                    assert resp.status == 200
        finally:
            httpd.shutdown()
    telemetry.flush()

    recs = _all_records(tdir)
    gens = [r for r in recs if r["name"] == "serve/generate"]
    assert len(gens) == 2
    # (a) the header request CONTINUES the client's trace: same
    # trace_id, parented at the client's span
    cont = [r for r in gens
            if r["attrs"]["trace_id"] == client.trace_id]
    assert len(cont) == 1
    assert cont[0]["attrs"]["parent_id"] == client.span_id
    # (b) the headerless request minted its own root; use it for the
    # structural no-orphan check (its whole tree lives in the spools)
    (root,) = [r for r in gens if r is not cont[0]]
    tid = root["attrs"]["trace_id"]
    assert tid != client.trace_id and root["attrs"]["parent_id"] is None
    trace = [r for r in recs
             if (r.get("attrs") or {}).get("trace_id") == tid]
    names = {r["name"] for r in trace}
    assert {"serve/generate", "decode/session",
            "decode/admit", "decode/retire"} <= names
    # one request, >=2 OS processes on one causal tree
    assert len({r["node_id"] for r in trace}) >= 2
    span_ids = {r["attrs"]["span_id"] for r in trace
                if r["kind"] == "span" and "span_id" in r["attrs"]}
    for r in trace:
        parent = r["attrs"].get("parent_id")
        assert parent is None or parent in span_ids, (r["name"], parent)
    # admission queue time rides the replica-side admit event
    (admit,) = [r for r in trace if r["name"] == "decode/admit"]
    assert admit["attrs"]["queue_ms"] >= 0

    # (c) the merge tool renders the request end to end
    tm = _load_trace_merge()
    full, t_recs = tm.find_trace([(r, "x") for r in recs], tid[:16])
    assert full == tid
    text, stats = tm.render_waterfall(full, t_recs)
    assert stats["orphans"] == 0 and len(stats["nodes"]) >= 2
    assert stats["critical_path"][0] == "serve/generate"
    assert stats["decomposition"]["total"] > 0
    assert stats["decomposition"]["decode"] is not None
    assert "-- critical path" in text and "decode/admit" in text
    # the CLI entry: exit 0 and the waterfall on stdout
    out = os.path.join(str(tmp_path), "trace_stats.json")
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tm.main([str(tdir), "--trace", tid, "--summary-json", out])
    assert rc == 0 and f"trace {tid}" in buf.getvalue()
    with open(out, encoding="utf-8") as f:
        assert json.load(f)["trace_id"] == tid
    # an empty prefix matches both traces -> loud ambiguity, never a
    # silently-merged waterfall
    with pytest.raises(ValueError, match="ambiguous"):
        tm.find_trace([(r, "x") for r in recs], "")


# --- flight recorder (satellite: bounded + redaction-safe) ------------------

def test_flight_snapshot_disabled_is_noop(tmp_path):
    assert not telemetry.enabled()
    assert flight.snapshot("test/trigger") is None
    assert list(tmp_path.iterdir()) == []


def test_flight_dump_redacts_and_bounds(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.DIR_ENV, str(tmp_path))
    monkeypatch.setenv(flight.CAP_ENV, "4096")
    telemetry.configure(node_id="t-0", role="test")
    for i in range(200):
        telemetry.event("spin", i=i, note="n" * 120)
    telemetry.event("secret", prompt="p" * 500, blob=[1, 2, 3],
                    arr={"nested": 1})
    path = flight.snapshot(
        "serve/replica_lost", node="replica-1", reason="proc-exit",
        inflight=[{"kind": "gen", "id": 7, "prompt": "q" * 500,
                   "tensor": object()}])
    assert path and os.path.exists(path)
    # bounded: the dump obeys the byte cap by dropping oldest records,
    # and says how many it dropped
    assert os.path.getsize(path) <= 4096 + 16
    with open(path, encoding="utf-8") as f:
        dump = json.load(f)
    assert dump["trigger"] == "serve/replica_lost"
    assert dump["node"] == "replica-1"
    assert dump["truncated"] > 0
    # redaction: strings clipped at 200 chars, non-scalars typed out
    (entry,) = dump["inflight"]
    assert entry["kind"] == "gen" and entry["id"] == 7
    assert len(entry["prompt"]) == 201 and entry["prompt"].endswith("…")
    assert entry["tensor"] == "<redacted object>"
    kept = {r["name"]: r for r in dump["records"]}
    if "secret" in kept:  # newest records survive the cap
        a = kept["secret"]["attrs"]
        assert len(a["prompt"]) == 201
        assert a["blob"] == "<redacted list>"
        assert a["arr"] == "<redacted dict>"


def test_flight_rotation_keeps_newest(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.DIR_ENV, str(tmp_path))
    monkeypatch.setenv(flight.KEEP_ENV, "2")
    telemetry.configure(node_id="t-0", role="test")
    telemetry.event("tick")
    paths = [flight.snapshot("test/trigger") for _ in range(4)]
    assert all(paths)
    left = sorted(glob.glob(str(tmp_path / "flight-*.json")))
    assert left == sorted(paths[-2:])


# --- tfos-postmortem --------------------------------------------------------

def test_postmortem_skips_corrupt_and_reports_victim(tmp_path, monkeypatch):
    buf = io.StringIO()
    assert postmortem.main(["--dir", str(tmp_path)], out=buf) == 2
    assert "no usable flight dumps" in buf.getvalue()

    monkeypatch.setenv(telemetry.DIR_ENV, str(tmp_path))
    telemetry.configure(node_id="driver", role="driver")
    telemetry.event("serve/replica_lost", replica=1, reason="proc-exit")
    telemetry.flush()
    assert flight.snapshot("serve/replica_lost", node="replica-1",
                           reason="proc-exit",
                           inflight=[{"kind": "gen", "id": 3}])
    # a SIGKILL can land mid-write: torn and wrong-shaped dumps are
    # skipped WITH a count, never fatal
    (tmp_path / "flight-torn-1-0001.json").write_text('{"trigger": "x"')
    (tmp_path / "flight-shape-1-0001.json").write_text('{"nope": 1}')
    dumps, corrupt = postmortem.load_dumps(str(tmp_path))
    assert len(dumps) == 1 and corrupt == 2

    buf = io.StringIO()
    assert postmortem.main(["--dir", str(tmp_path)], out=buf) == 0
    text = buf.getvalue()
    assert "skipped 2 corrupt/truncated" in text
    assert "victim=replica-1" in text and "reason=proc-exit" in text
    assert "kind=gen id=3" in text
    assert "serve/replica_lost" in text  # the spool window table


# --- slow lane: the postmortem gate -----------------------------------------

@pytest.mark.slow
def test_postmortem_after_sigkill_mid_decode(tmp_path, monkeypatch):
    """ISSUE 12 postmortem gate: SIGKILL a replica mid-decode, then
    ``tfos-postmortem`` over the telemetry tree names the killed node
    and shows the sessions that were in flight when it died."""
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    monkeypatch.setenv(telemetry.DIR_ENV, str(tdir))
    telemetry.configure(node_id="driver", role="driver")
    S, spec = _decode_server(tmp_path)
    with S.Server(spec, num_replicas=2, request_timeout=300) as srv:
        srv.generate([1, 2, 3], max_tokens=2, timeout=300)  # warm compiles
        errs = []

        def one(i):
            try:
                srv.generate([2 + i, 3, 5], max_tokens=20, timeout=300)
            except Exception as e:  # noqa: BLE001 - asserted below
                errs.append(e)

        ts = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        deadline = time.time() + 120
        while srv.pool.outstanding_sessions() < 3 and \
                time.time() < deadline:
            time.sleep(0.01)
        pids = srv.pool.replica_pids()
        victim = sorted(pids)[0]
        os.kill(pids[victim], 9)
        for t in ts:
            t.join()
        assert not errs, errs
        # the monitor snapshotted the flight ring when it noticed
        deadline = time.time() + 30
        while not glob.glob(str(tdir / "flight-*.json")) and \
                time.time() < deadline:
            time.sleep(0.05)
    telemetry.flush()

    dumps = glob.glob(str(tdir / "flight-*.json"))
    assert dumps, "no flight dump written on replica loss"
    buf = io.StringIO()
    assert postmortem.main(["--dir", str(tdir), "--all"], out=buf) == 0
    text = buf.getvalue()
    assert "trigger=serve/replica_lost" in text
    assert f"victim=replica-{victim}" in text
    # the in-flight sessions at the moment of death are named
    assert "kind=gen" in text
    # and the spool window attributes activity to the nodes
    assert "records   last:" in text
