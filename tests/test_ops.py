"""ops/: flash attention + fused rmsnorm vs reference implementations.

Pallas kernels run in interpret mode on the CPU test platform; the same
code path compiles on TPU.  Mirrors the reference's exhaustive
marshalling-matrix style (SURVEY.md §4 takeaway d) over shapes/flags.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import ops


def _qkv(key, b, s, h, d, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, s, h, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [64, 96])  # 96: tail-masking path (not % 64)
def test_flash_matches_reference(causal, seq):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, seq, 2, 16)
    ref = ops.mha_reference(q, k, v, causal=causal)
    out = ops.flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grad_matches_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 32, 2, 8)

    def loss_flash(q, k, v):
        return jnp.sum(ops.flash_attention(q, k, v, causal=True,
                                           block_q=16, block_kv=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ops.mha_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_flash_under_jit_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 64, 4, 16, dtype=jnp.bfloat16)
    out = jax.jit(
        lambda q, k, v: ops.flash_attention(q, k, v, causal=True)
    )(q, k, v)
    ref = ops.mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_rope_roundtrip_and_offset():
    cos, sin = ops.rope_angles(128, 16)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 2, 16))
    # positions arg with explicit offsets == slicing the table
    pos = jnp.broadcast_to(jnp.arange(8) + 32, (2, 8))
    a = ops.apply_rope(x, cos, sin, positions=pos)
    b = ops.apply_rope(
        jnp.asarray(x), cos[32:40], sin[32:40]
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # norm preservation (rotations)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(a), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        atol=1e-4,
    )


def test_fused_rmsnorm_matches_reference_and_grads():
    x = jax.random.normal(jax.random.PRNGKey(4), (3, 17, 64))
    scale = jax.random.normal(jax.random.PRNGKey(5), (64,)) + 1.0
    np.testing.assert_allclose(
        np.asarray(ops.fused_rmsnorm(x, scale, block_rows=8)),
        np.asarray(ops.rmsnorm_reference(x, scale)),
        atol=1e-5,
    )
    g1 = jax.grad(lambda x, s: jnp.sum(ops.fused_rmsnorm(x, s) ** 2),
                  argnums=(0, 1))(x, scale)
    g2 = jax.grad(lambda x, s: jnp.sum(ops.rmsnorm_reference(x, s) ** 2),
                  argnums=(0, 1))(x, scale)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("bq,bkv", [(512, 512), (1024, 512), (512, 1024),
                                    (1024, 1024)])
def test_flash_sweep_blocks_at_seq2048(bq, bkv):
    """The exact block combos scripts/sweep_transformer.py runs at seq
    2048: validates the block-dependent masking/online-softmax logic in
    interpret mode.  (TPU-only failure modes — Mosaic tiling limits,
    VMEM overflow at the sweep's real d=128 bf16 shapes — can only
    surface on the chip.)"""
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 2048, 1, 8)
    ref = ops.mha_reference(q, k, v, causal=True)
    out = ops.flash_attention(q, k, v, causal=True, block_q=bq, block_kv=bkv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [64, 96])  # 96: tail-masking blocks
def test_flash_pallas_backward_matches_reference(causal, seq):
    """The diagonal-trimmed pallas backward must produce the same
    gradients as autodiff through the reference implementation."""
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, seq, 2, 16)

    def loss_ref(q, k, v):
        return jnp.sum(ops.mha_reference(q, k, v, causal=causal) ** 2)

    def loss_pal(q, k, v):
        return jnp.sum(ops.flash_attention(
            q, k, v, causal=causal, block_q=64, block_kv=64,
            bwd_impl="pallas") ** 2)

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    pal = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for r, p, name in zip(ref, pal, "qkv"):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), atol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal}, seq={seq})")


def _assert_pallas_bwd_matches_xla(key, seq, block_q, block_kv, atol):
    """Shared pallas-vs-xla backward parity check: grads of a sum-of-
    squares loss through flash_attention under both bwd_impls."""
    q, k, v = _qkv(key, 1, seq, 1, 8)

    def grads(impl):
        def f(q, k, v):
            return jnp.sum(ops.flash_attention(
                q, k, v, causal=True, block_q=block_q, block_kv=block_kv,
                bwd_impl=impl) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for r, p, name in zip(grads("xla"), grads("pallas"), "qkv"):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), atol=atol,
            err_msg=f"d{name} mismatch (seq={seq}, bq={block_q}, "
                    f"bkv={block_kv})")


def test_flash_pallas_backward_uneven_blocks():
    """block_q != block_kv exercises the diagonal bounds in both kernels
    (dq trims kv at ceil boundaries, dkv starts q at floor boundaries)."""
    _assert_pallas_bwd_matches_xla(jax.random.PRNGKey(3), 128, 64, 32,
                                   atol=5e-4)


def test_flash_pallas_backward_seq2048_sweep_blocks():
    _assert_pallas_bwd_matches_xla(jax.random.PRNGKey(4), 2048, 512, 512,
                                   atol=2e-3)


@pytest.mark.parametrize("bq,bkv", [(512, 512), (1024, 1024)])
def test_flash_pallas_backward_seq4096(bq, bkv):
    """The r5 long-seq sweep configs' regime (s4096 configs in
    scripts/sweep_transformer.py): fwd + pallas backward parity at
    seq 4096 with both queued block sizes, interpret mode."""
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 4096, 1, 8)
    ref = ops.mha_reference(q, k, v, causal=True)
    out = ops.flash_attention(q, k, v, causal=True, block_q=bq,
                              block_kv=bkv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)
    _assert_pallas_bwd_matches_xla(jax.random.PRNGKey(5), 4096, bq, bkv,
                                   atol=4e-3)
