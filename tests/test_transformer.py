"""Transformer family: forward/grad correctness, attn_impl equivalence,
and a fully sharded dp x fsdp x seq x model train step on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu.models import transformer
from tensorflowonspark_tpu.parallel import sequence_parallel_attention

CFG = transformer.Config(
    vocab_size=96, dim=32, n_layers=2, n_heads=4, max_seq=64,
    dtype="float32", attn_impl="reference",
)


def _tokens(key, b=2, s=32):
    return jax.random.randint(key, (b, s), 0, CFG.vocab_size)


def test_forward_shapes_and_loss():
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    toks = _tokens(jax.random.PRNGKey(1))
    logits = transformer.apply(params, toks, CFG)
    assert logits.shape == (2, 32, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    loss = transformer.loss_fn(params, toks, CFG)
    assert np.isfinite(float(loss))
    # untrained loss should be near ln(vocab)
    assert abs(float(loss) - np.log(CFG.vocab_size)) < 2.0


def test_flash_and_reference_impls_agree():
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    toks = _tokens(jax.random.PRNGKey(1))
    ref = transformer.apply(params, toks, CFG)
    flash_cfg = transformer.Config(**{
        **CFG.__dict__, "attn_impl": "flash"
    })
    out = transformer.apply(params, toks, flash_cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_blockwise_ce_matches_dense():
    """ce_impl='blockwise' (streamed vocab, online logsumexp, no [N,V]
    tensor) must match the dense CE in value AND gradients — including
    with an ignore-mask label layout and a vocab block smaller than,
    equal to, and dividing the vocab unevenly (error)."""
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    toks = _tokens(jax.random.PRNGKey(1))

    for block in (32, 48, 96):
        dense = transformer.loss_fn(params, toks, CFG, ce_impl="dense")
        blk = transformer.loss_fn(params, toks, CFG, ce_impl="blockwise",
                                  ce_block=block)
        np.testing.assert_allclose(float(blk), float(dense), rtol=2e-5)

    gd = jax.grad(transformer.loss_fn)(params, toks, CFG, ce_impl="dense")
    gb = jax.grad(transformer.loss_fn)(params, toks, CFG,
                                       ce_impl="blockwise", ce_block=32)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(gd),
            jax.tree_util.tree_leaves_with_path(gb)):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4,
                                   err_msg=str(ka))

    # masked-label layout (zigzag path): -1 positions ignored identically
    labels = jnp.where(jnp.arange(32)[None, :] % 5 == 0, -1,
                       jnp.roll(toks, -1, axis=1))
    dense = transformer.loss_fn(params, toks, CFG, labels=labels)
    blk = transformer.loss_fn(params, toks, CFG, labels=labels,
                              ce_impl="blockwise", ce_block=48)
    np.testing.assert_allclose(float(blk), float(dense), rtol=2e-5)

    with pytest.raises(ValueError, match="not divisible"):
        transformer.loss_fn(params, toks, CFG, ce_impl="blockwise",
                            ce_block=40)
    with pytest.raises(ValueError, match="unknown ce_impl"):
        transformer.loss_fn(params, toks, CFG, ce_impl="nope")


def test_loss_decreases_single_device():
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    toks = _tokens(jax.random.PRNGKey(1))
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, toks, CFG
        )
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert float(loss) < first - 0.1, (first, float(loss))


@pytest.mark.parametrize("attn", ["gspmd", "ring"])
def test_sharded_train_step_4axis_mesh(eight_devices, attn):
    """2x1x2x2 (data, fsdp, seq, model) mesh; one jitted train step; the
    ring variant exchanges k/v shards over the seq axis explicitly."""
    mesh = Mesh(
        np.array(eight_devices).reshape(2, 1, 2, 2),
        ("data", "fsdp", "seq", "model"),
    )
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    specs = transformer.param_specs(CFG)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.device_put(params, shardings)
    toks = jax.device_put(
        _tokens(jax.random.PRNGKey(1), b=4, s=32),
        NamedSharding(mesh, P(("data", "fsdp"), "seq")),
    )
    attn_fn = (
        sequence_parallel_attention(mesh, "ring", causal=True)
        if attn == "ring" else None
    )

    @jax.jit
    def step(params, toks):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, toks, CFG, attn_fn=attn_fn
        )
        return loss, grads

    loss, grads = step(params, toks)
    assert np.isfinite(float(loss))
    # gradient shardings should match param shardings (GSPMD round-trip)
    assert jax.tree.structure(grads) == jax.tree.structure(params)

    # sharded loss == single-device loss (numerical parity of the mesh)
    ref_loss = transformer.loss_fn(
        jax.device_get(params), jax.device_get(toks), CFG
    )
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=3e-4)


def test_blockwise_ce_compiles_sharded(eight_devices):
    """Blockwise CE under a data x fsdp mesh: the vocab-block scan must
    compile and grad against sharded params/batch (documented as the
    single-chip/data-parallel option — this pins that envelope)."""
    mesh = Mesh(np.array(eight_devices).reshape(4, 2), ("data", "fsdp"))
    params = transformer.init(jax.random.PRNGKey(0), CFG)
    toks = _tokens(jax.random.PRNGKey(1), b=4)
    specs = transformer.param_specs(CFG, mesh=mesh)
    with mesh:
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs))
        toks = jax.device_put(toks, NamedSharding(mesh, P("data", None)))
        loss, grads = jax.jit(
            jax.value_and_grad(lambda p, t: transformer.loss_fn(
                p, t, CFG, ce_impl="blockwise", ce_block=32)))(params, toks)
        dense = transformer.loss_fn(params, toks, CFG)
    np.testing.assert_allclose(float(loss), float(dense), rtol=2e-5)
    assert np.isfinite(float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.sum(jnp.abs(b)), grads, 0.0)))


def test_remat_matches_no_remat():
    """jax.checkpoint over the scanned layer must not change loss or
    gradients (it only changes what the backward pass keeps resident)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.models import transformer

    cfg = transformer.Config(vocab_size=128, dim=64, n_layers=2, n_heads=2,
                             max_seq=32, dtype="float32",
                             attn_impl="reference")
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 32)), jnp.int32)

    base_loss, base_grads = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, cfg)
    # full remat plus both selective policies: loss and grads must be
    # bit-compatible (policies only change residency, not math)
    for mode in (True, "dots"):
        r_loss, r_grads = jax.value_and_grad(
            lambda p, t, m=mode: transformer.loss_fn(p, t, cfg, remat=m))(
            params, tokens)
        np.testing.assert_allclose(float(base_loss), float(r_loss),
                                   rtol=1e-6, err_msg=f"remat={mode}")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            base_grads, r_grads)

    with pytest.raises(ValueError, match="remat"):
        transformer.loss_fn(params, tokens, cfg, remat="bogus")
