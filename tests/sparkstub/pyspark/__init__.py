"""Contract test double for the pyspark API surface this framework uses.

NOT a Spark reimplementation: a faithful stand-in backed by the package's
own ``LocalEngine`` (separate executor *processes*, one task slot each —
the same fixture philosophy as the reference's 2-worker local Standalone
cluster, reference test/run_tests.sh:15-22).  Tests insert this package's
parent dir on ``sys.path`` so ``import pyspark`` resolves here **only
when real pyspark is absent**; with real pyspark installed (CI), the same
tests run against genuine Spark.

Faithfulness notes (semantics mirrored from pyspark, not invented):
- ``RDD`` is lazy for ``mapPartitions``, eager for actions.
- ``rdd.barrier().mapPartitions(fn)`` schedules all tasks concurrently,
  one per free slot (Spark barrier execution) — realized here as the
  LocalEngine's ``spread`` dispatch.
- ``SparkContext`` is a process singleton; ``getOrCreate`` returns it.
- Executor processes import the driver's modules fresh (spawn), exactly
  like Spark python workers.
"""

from __future__ import annotations

import os
import sys
import threading

__version__ = "3.5.0-stub"

_STUB_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class SparkConf:
    def __init__(self):
        self._conf = {}

    def set(self, key, value):
        self._conf[key] = str(value)
        return self

    def setMaster(self, master):
        return self.set("spark.master", master)

    def setAppName(self, name):
        return self.set("spark.app.name", name)

    def get(self, key, defaultValue=None):
        return self._conf.get(key, defaultValue)

    def getAll(self):
        return list(self._conf.items())


class _JavaConfShim:
    """Mimics sc._jsc.hadoopConfiguration().get(...)."""

    def hadoopConfiguration(self):
        return self

    def get(self, key, default=None):
        if key == "fs.defaultFS":
            return "file:///"
        return default


class SparkContext:
    _active = None
    _lock = threading.Lock()

    def __init__(self, master=None, appName=None, conf=None):
        from tensorflowonspark_tpu.engine import LocalEngine

        with SparkContext._lock:
            if SparkContext._active is not None:
                raise ValueError(
                    "Cannot run multiple SparkContexts at once"
                )
            SparkContext._active = self
        self._conf = conf or SparkConf()
        if master:
            self._conf.setMaster(master)
        if appName:
            self._conf.setAppName(appName)
        n = int(self._conf.get("spark.executor.instances", "2"))
        # Executor env: make this stub importable in children, and pin
        # them to the CPU jax platform (a site hook reached through the
        # inherited PYTHONPATH could otherwise force a TPU backend —
        # replacing PYTHONPATH neutralizes it, same as tests/test_pipeline).
        self._engine = LocalEngine(
            n,
            env={
                "PYTHONPATH": _STUB_DIR,
                "TFOS_STUB_POOL_SIZE": str(n),
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            },
        )
        self._jsc = _JavaConfShim()
        self.defaultParallelism = n

    @classmethod
    def getOrCreate(cls, conf=None):
        with cls._lock:
            if cls._active is not None:
                return cls._active
        return cls(conf=conf)

    def getConf(self):
        return self._conf

    def parallelize(self, seq, numSlices=None):
        return RDD(self._engine.parallelize(seq, numSlices), self)

    def union(self, rdds):
        first, rest = rdds[0], rdds[1:]
        return first.union(*rest)

    def cancelAllJobs(self):
        self._engine.cancel_all_jobs()

    def stop(self):
        with SparkContext._lock:
            if SparkContext._active is self:
                SparkContext._active = None
        self._engine.stop()


class RDD:
    """Wraps a LocalDataset behind the pyspark RDD surface."""

    def __init__(self, dataset, sc, barrier=False):
        self._ds = dataset
        self.context = sc
        self._barrier = barrier

    def getNumPartitions(self):
        return self._ds.num_partitions

    def mapPartitions(self, f):
        return RDD(self._ds.map_partitions(f), self.context, self._barrier)

    def map(self, f):
        def _mapper(it, _f=f):
            return [_f(x) for x in it]

        return RDD(self._ds.map_partitions(_mapper), self.context, self._barrier)

    def foreachPartition(self, f):
        self._ds.foreach_partition(f, spread=self._barrier)

    def collect(self):
        return self._ds.collect(spread=self._barrier)

    def count(self):
        return len(self.collect())

    def union(self, *others):
        return RDD(
            self._ds.union(*[o._ds for o in others]), self.context, self._barrier
        )

    def repartition(self, num_partitions):
        return RDD(self._ds.repartition(num_partitions), self.context,
                   self._barrier)

    def barrier(self):
        return RDDBarrier(self)


class RDDBarrier:
    """Parity: pyspark RDDBarrier — mapPartitions under barrier scheduling
    (all tasks concurrent, one per slot)."""

    def __init__(self, rdd):
        self._rdd = rdd

    def mapPartitions(self, f):
        return RDD(self._rdd._ds.map_partitions(f), self._rdd.context, barrier=True)


class TaskContext:
    _ctx = None

    @classmethod
    def get(cls):
        return cls._ctx

    def partitionId(self):
        return int(os.environ.get("TFOS_EXECUTOR_INDEX", "0"))

    @staticmethod
    def resources():
        return {}


class _TaskInfo:
    def __init__(self, address):
        self.address = address


class BarrierTaskContext(TaskContext):
    """Executor-side barrier context; addresses are the executor pool."""

    @classmethod
    def get(cls):
        return cls()

    def getTaskInfos(self):
        n = int(os.environ.get("TFOS_STUB_POOL_SIZE", "1"))
        return [_TaskInfo(f"127.0.0.1:{i}") for i in range(n)]

    def barrier(self):
        pass


def _ensure_stub_warning():
    if "PYTEST_CURRENT_TEST" not in os.environ and not os.environ.get(
        "TFOS_ALLOW_SPARK_STUB"
    ):
        sys.stderr.write(
            "warning: using the tensorflowonspark_tpu pyspark test stub, "
            "not real Spark\n"
        )


_ensure_stub_warning()
