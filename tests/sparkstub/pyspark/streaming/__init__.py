"""pyspark.streaming stand-in: StreamingContext + DStream via queueStream.

Micro-batch loop semantics mirrored from Spark Streaming: ``start()``
launches a driver-side thread that, every ``batchDuration`` seconds,
takes the next RDD from each queue stream and invokes the registered
``foreachRDD`` callbacks; ``awaitTerminationOrTimeout`` blocks up to the
timeout and returns True once the context stopped; ``stop(...,
stopGraceFully=True)`` lets the in-flight batch finish first.
"""

from __future__ import annotations

import threading
import time


class DStream:
    def __init__(self, ssc, rdd_queue, oneAtATime=True, default=None):
        self._ssc = ssc
        self._queue = list(rdd_queue)
        self._one = oneAtATime
        self._default = default
        self._callbacks = []

    def foreachRDD(self, func):
        self._callbacks.append(func)

    def _next_rdd(self):
        if self._queue:
            return self._queue.pop(0) if self._one else self._queue[-1]
        return self._default

    def _tick(self, batch_time):
        rdd = self._next_rdd()
        if rdd is None:
            return
        for cb in self._callbacks:
            try:
                cb(batch_time, rdd)
            except TypeError:
                cb(rdd)


class StreamingContext:
    def __init__(self, sparkContext, batchDuration=1):
        self.sparkContext = sparkContext
        self._duration = batchDuration
        self._streams = []
        self._stopped = threading.Event()
        self._thread = None

    def queueStream(self, rdds, oneAtATime=True, default=None):
        ds = DStream(self, rdds, oneAtATime, default)
        self._streams.append(ds)
        return ds

    def start(self):
        assert self._thread is None, "StreamingContext already started"

        def _loop():
            while not self._stopped.is_set():
                t = time.time()
                for ds in self._streams:
                    if self._stopped.is_set():
                        break
                    ds._tick(t)
                self._stopped.wait(self._duration)

        self._thread = threading.Thread(
            target=_loop, name="stub-streaming", daemon=True
        )
        self._thread.start()

    def awaitTerminationOrTimeout(self, timeout):
        """True if the context terminated within ``timeout`` seconds."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._stopped.is_set() and (
                self._thread is None or not self._thread.is_alive()
            ):
                return True
            time.sleep(0.05)
        return self._stopped.is_set() and (
            self._thread is None or not self._thread.is_alive()
        )

    def stop(self, stopSparkContext=True, stopGraceFully=False):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=30 if stopGraceFully else 5)
        if stopSparkContext:
            self.sparkContext.stop()
