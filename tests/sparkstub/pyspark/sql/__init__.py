"""pyspark.sql stand-in: Row, DataFrame, SparkSession (see package doc)."""

from __future__ import annotations

import pyspark as _ps


class Row(tuple):
    """Tuple with named fields (parity: pyspark.sql.Row)."""

    def __new__(cls, **kwargs):
        row = super().__new__(cls, tuple(kwargs.values()))
        row.__fields__ = list(kwargs)
        return row

    def asDict(self):
        return dict(zip(self.__fields__, self))

    def __getattr__(self, name):
        try:
            return self[self.__fields__.index(name)]
        except (ValueError, AttributeError):
            raise AttributeError(name) from None

    def __reduce__(self):
        return (_row_from_pairs, (self.__fields__, tuple(self)))

    def __repr__(self):
        return "Row(%s)" % ", ".join(
            f"{k}={v!r}" for k, v in zip(self.__fields__, self)
        )


def _row_from_pairs(fields, values):
    return Row(**dict(zip(fields, values)))


class DataFrame:
    def __init__(self, rdd, columns, session):
        self._row_rdd = rdd  # RDD of Row
        self.columns = list(columns)
        self.sparkSession = session

    @property
    def rdd(self):
        return self._row_rdd

    def select(self, *cols):
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = list(cols[0])
        cols = list(cols)

        def _project(it, _cols=tuple(cols)):
            return [
                _row_from_pairs(list(_cols), tuple(r.asDict()[c] for c in _cols))
                for r in it
            ]

        return DataFrame(self._row_rdd.mapPartitions(_project), cols,
                         self.sparkSession)

    def collect(self):
        return self._row_rdd.collect()

    def count(self):
        return self._row_rdd.count()


class _Builder:
    def __init__(self):
        self._conf = _ps.SparkConf()

    def master(self, m):
        self._conf.setMaster(m)
        return self

    def appName(self, n):
        self._conf.setAppName(n)
        return self

    def config(self, key, value):
        self._conf.set(key, value)
        return self

    def getOrCreate(self):
        sc = _ps.SparkContext.getOrCreate(self._conf)
        return SparkSession(sc)


class SparkSession:
    def __init__(self, sc):
        self.sparkContext = sc

    builder = None  # class-level property installed below

    def createDataFrame(self, data, schema=None):
        """data: list of tuples/dicts/Rows, or an RDD of Rows; schema: list
        of column names (the subset of createDataFrame this project uses)."""
        if isinstance(data, _ps.RDD):
            first = data.collect()[:1]
            if not first:
                raise ValueError("cannot infer schema from empty RDD")
            cols = schema or list(first[0].__fields__)
            rdd = data.mapPartitions(
                lambda it, _c=tuple(cols): [
                    r if isinstance(r, Row)
                    else _row_from_pairs(list(_c), tuple(r))
                    for r in it
                ]
            )
            return DataFrame(rdd, cols, self)
        rows = []
        cols = list(schema) if schema else None
        for item in data:
            if isinstance(item, Row):
                if cols is None:
                    cols = list(item.__fields__)
                rows.append(item)
            elif isinstance(item, dict):
                if cols is None:
                    cols = list(item)
                rows.append(_row_from_pairs(cols, tuple(item[c] for c in cols)))
            else:
                assert cols is not None, "schema required for tuple rows"
                rows.append(_row_from_pairs(cols, tuple(item)))
        rdd = self.sparkContext.parallelize(rows)
        return DataFrame(rdd, cols, self)

    def stop(self):
        self.sparkContext.stop()


class _BuilderDescriptor:
    def __get__(self, obj, objtype=None):
        return _Builder()


SparkSession.builder = _BuilderDescriptor()
