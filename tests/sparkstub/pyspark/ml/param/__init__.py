"""pyspark.ml.param stand-in: Param/Params with the pyspark surface."""

from __future__ import annotations

import copy as _copy


class Param:
    def __init__(self, parent, name, doc, typeConverter=None):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter

    def __repr__(self):
        return f"Param({self.name})"


class TypeConverters:
    toInt = staticmethod(int)
    toFloat = staticmethod(float)
    toString = staticmethod(str)

    @staticmethod
    def toBoolean(v):
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes")
        return bool(v)

    @staticmethod
    def identity(v):
        return v


class Params:
    def __init__(self):
        self._paramMap = {}
        self._defaultParamMap = {}

    @property
    def params(self):
        out = []
        for klass in type(self).__mro__:
            for val in vars(klass).values():
                if isinstance(val, Param):
                    out.append(val)
        return out

    def _resolveParam(self, param):
        if isinstance(param, Param):
            return param
        for p in self.params:
            if p.name == param:
                return p
        raise KeyError(f"no param {param}")

    def _set(self, **kwargs):
        for name, value in kwargs.items():
            p = self._resolveParam(name)
            if value is not None and p.typeConverter is not None:
                value = p.typeConverter(value)
            self._paramMap[p] = value
        return self

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            self._defaultParamMap[self._resolveParam(name)] = value
        return self

    def isDefined(self, param):
        p = self._resolveParam(param)
        return p in self._paramMap or p in self._defaultParamMap

    def hasDefault(self, param):
        return self._resolveParam(param) in self._defaultParamMap

    def getOrDefault(self, param):
        p = self._resolveParam(param)
        if p in self._paramMap:
            return self._paramMap[p]
        return self._defaultParamMap[p]

    def extractParamMap(self, extra=None):
        out = dict(self._defaultParamMap)
        out.update(self._paramMap)
        out.update(extra or {})
        return out

    def copy(self, extra=None):
        dup = _copy.copy(self)
        dup._paramMap = dict(self._paramMap)
        dup._defaultParamMap = dict(self._defaultParamMap)
        for key, value in (extra or {}).items():
            dup._set(**{key.name if isinstance(key, Param) else key: value})
        return dup
