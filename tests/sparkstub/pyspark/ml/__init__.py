"""pyspark.ml stand-in: Estimator/Transformer/Model/Pipeline skeletons.

Mirrors the entry-point semantics the compat layer relies on:
``Estimator.fit(dataset[, params])`` dispatches to ``_fit`` (after
``copy(params)``), ``Transformer.transform`` to ``_transform``;
``Pipeline.fit`` walks stages in order, transforming through fitted
models, and returns a ``PipelineModel``.
"""

from __future__ import annotations

import uuid

from pyspark.ml.param import Params


class Identifiable:
    def __init__(self):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"


class Estimator(Params, Identifiable):
    def __init__(self):
        Params.__init__(self)
        Identifiable.__init__(self)

    def fit(self, dataset, params=None):
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def _fit(self, dataset):
        raise NotImplementedError


class Transformer(Params, Identifiable):
    def __init__(self):
        Params.__init__(self)
        Identifiable.__init__(self)

    def transform(self, dataset, params=None):
        if params:
            return self.copy(params)._transform(dataset)
        return self._transform(dataset)

    def _transform(self, dataset):
        raise NotImplementedError


class Model(Transformer):
    pass


class Pipeline(Estimator):
    def __init__(self, stages=None):
        super().__init__()
        self._stages = list(stages or [])

    def setStages(self, stages):
        self._stages = list(stages)
        return self

    def getStages(self):
        return list(self._stages)

    def _fit(self, dataset):
        # pyspark semantics: intermediate results are only materialized
        # for stages BEFORE the last Estimator (a trailing estimator's
        # model is never asked to transform the training data)
        last_est = max(
            (i for i, s in enumerate(self._stages) if isinstance(s, Estimator)),
            default=-1,
        )
        transformers = []
        df = dataset
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                model = stage.fit(df)
                transformers.append(model)
                if i < last_est:
                    df = model.transform(df)
            elif isinstance(stage, Transformer):
                transformers.append(stage)
                if i < last_est:
                    df = stage.transform(df)
            else:
                raise TypeError(f"pipeline stage is not Estimator/Transformer: {stage!r}")
        return PipelineModel(transformers)


class PipelineModel(Model):
    def __init__(self, stages):
        super().__init__()
        self.stages = list(stages)

    def _transform(self, dataset):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df
