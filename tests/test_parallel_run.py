"""TFParallel-parity tests: N independent single-node jobs, barrier-style
concurrency, and per-worker TPU chip partitioning
(reference surface: TFParallel.py:17-64)."""

import os
import time

import pytest

from tensorflowonspark_tpu import parallel_run


def _engine(n, chips_per_host=None):
    from tensorflowonspark_tpu.engine import LocalEngine

    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    if chips_per_host is not None:
        env["TFOS_TPU_CHIPS_PER_HOST"] = str(chips_per_host)
    return LocalEngine(n, env=env)


def ctx_probe(args, ctx):
    return {
        "executor_id": ctx.executor_id,
        "job_name": ctx.job_name,
        "num_workers": ctx.num_workers,
        "visible_chips": os.environ.get("TPU_VISIBLE_CHIPS"),
        "args": args,
    }


def barrier_probe(args, ctx):
    """Wait for every peer's marker file: proves all workers run
    concurrently (the barrier-execution guarantee)."""
    d = args["dir"]
    mine = os.path.join(d, f"worker-{ctx.executor_id}")
    with open(mine, "w") as f:
        f.write("up")
    deadline = time.time() + 15
    want = {f"worker-{i}" for i in range(ctx.num_workers)}
    while time.time() < deadline:
        if want.issubset(set(os.listdir(d))):
            return ctx.executor_id
        time.sleep(0.05)
    raise TimeoutError(f"peers never arrived: {sorted(os.listdir(d))}")


def test_run_executes_one_job_per_worker():
    eng = _engine(2)
    try:
        out = parallel_run.run(eng, ctx_probe, {"k": "v"}, num_executors=2)
        assert len(out) == 2
        assert sorted(r["executor_id"] for r in out) == [0, 1]
        assert all(r["job_name"] == "worker" for r in out)
        assert all(r["num_workers"] == 2 for r in out)
        assert all(r["args"] == {"k": "v"} for r in out)
    finally:
        eng.stop()


def test_workers_run_concurrently(tmp_path):
    eng = _engine(2)
    try:
        out = parallel_run.run(eng, barrier_probe, {"dir": str(tmp_path)}, 2)
        assert sorted(out) == [0, 1]
    finally:
        eng.stop()


def test_chip_partitioning_is_disjoint_per_cohosted_worker():
    """Each co-hosted worker must claim a disjoint chip block
    (parity: gpu_info.py:81-91 index placement)."""
    eng = _engine(2, chips_per_host=4)
    try:
        out = parallel_run.run(
            eng, ctx_probe, {}, num_executors=2, num_chips=2
        )
        chips = sorted(r["visible_chips"] for r in out)
        assert chips == ["0,1", "2,3"]
    finally:
        eng.stop()


def test_more_workers_than_executors_rejected():
    eng = _engine(2)
    try:
        with pytest.raises(ValueError, match="requires 4 executors"):
            parallel_run.run(eng, ctx_probe, {}, num_executors=4)
    finally:
        eng.stop()


def test_chip_oversubscription_fails():
    eng = _engine(2, chips_per_host=2)
    try:
        with pytest.raises(Exception, match="exceeds supply|unable to claim"):
            parallel_run.run(eng, ctx_probe, {}, num_executors=2, num_chips=2)
    finally:
        eng.stop()
