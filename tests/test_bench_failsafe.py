"""bench.py must produce a parseable artifact even when the TPU tunnel
is dead (VERDICT r4 weak #2: BENCH_r03 AND BENCH_r04 both ended rc=124
with parsed=null because a dead relay wedged jax backend init for the
driver's whole timeout).

Fail-safe is the DEFAULT now: a dead relay yields the one JSON line with
value null + "error":"tunnel_dead" within the grace window (no env
opt-in), and a post-probe wedge is cut by the init watchdog.  These
tests run the real bench.py as a subprocess with a simulated dead relay
(an "axon" entry on PYTHONPATH engages the tunnel heuristics; the probe
port is a closed localhost port).
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _closed_port():
    # bind-then-close: nothing listens on it afterwards
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env(**overrides):
    """Host env minus every tunnel/backend family that could leak into
    a bench subprocess, plus explicit overrides."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TFOS_", "JAX_", "XLA_", "PALLAS_"))}
    env.update(overrides)
    return env


def _dead_tunnel_env(**extra):
    return _clean_env(
        # the substring check in bench._tunnel_in_play; the path does not
        # exist, so no real site hook runs in the child
        PYTHONPATH="/nonexistent/axon_site_for_test",
        TFOS_TUNNEL_PORT=str(_closed_port()),
        TFOS_BENCH_TUNNEL_WAIT="1",
        **extra,
    )


def _last_json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line in stdout: {stdout!r}"
    return json.loads(lines[-1])


def test_dead_relay_emits_failsafe_line_fast():
    """The driver's round-end contract: dead tunnel -> rc=0 + one JSON
    line (value null, error tunnel_dead) in well under 2 minutes."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=_dead_tunnel_env(), capture_output=True, text=True,
        timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 110, f"fail-safe exit took {elapsed:.0f}s"
    line = _last_json_line(proc.stdout)
    assert line["metric"] == "resnet50_train_mfu"
    assert line["value"] is None and line["vs_baseline"] is None
    assert line["error"] == "tunnel_dead"
    assert "not listening" in proc.stderr


@pytest.mark.slow
def test_dead_relay_ignore_env_presses_on():
    """TFOS_BENCH_IGNORE_TUNNEL=1 restores the old press-on behavior
    (needed when the operator KNOWS the probe heuristic is wrong).  With
    JAX_PLATFORMS=cpu downstream the run then proceeds as a CPU bench;
    here we only assert it gets PAST the tunnel gate (no tunnel_dead
    exit) — the fed/compute lanes are covered by the slow-lane smoke."""
    env = _dead_tunnel_env(
        TFOS_BENCH_IGNORE_TUNNEL="1",
        # keep the child cheap and deterministic: skip every lane, and
        # the tunnel gate must have run BEFORE jax init (cpu platform)
        TFOS_BENCH_FED="0", TFOS_BENCH_TRANSFORMER="0",
        TFOS_BENCH_TFRECORD_READ="0", TFOS_BENCH_SEGMENTATION="0",
        TFOS_BENCH_BATCH_INFERENCE="0", TFOS_BENCH_SERVE="0",
        TFOS_BENCH_ELASTIC_SERVE="0",
        TFOS_BENCH_DECODE="0", TFOS_BENCH_DATA="0",
        TFOS_BENCH_ELASTIC="0", TFOS_BENCH_ACTORS="0",
        TFOS_BENCH_STEPS="1",
    )
    # note: JAX_PLATFORMS stays unset so the gate engages; the fake
    # PYTHONPATH hook does not exist, so jax falls back to CPU
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pressing on anyway" in proc.stderr
    line = _last_json_line(proc.stdout)
    assert line.get("error") != "tunnel_dead"
    assert line["value"] is not None


@pytest.mark.slow
def test_fed_lane_vs_device_resident_regression():
    """The fed pipeline's CPU regression (VERDICT r4 #4): feeder
    process -> shm ring -> DataFeed -> per-dispatch train must reach
    ~the device-resident comparator's throughput when the link is free
    (measured 0.98 on this image; gate at 0.75 for CI noise), and the
    transfer-ceiling ratio must be recorded.  On hardware the same
    fields prove the framework against the link (vs_transfer_ceiling)."""
    env = _clean_env(
        PYTHONPATH="", JAX_PLATFORMS="cpu",
        TFOS_BENCH_TRANSFORMER="0", TFOS_BENCH_TFRECORD_READ="0",
        TFOS_BENCH_SEGMENTATION="0", TFOS_BENCH_BATCH_INFERENCE="0",
        TFOS_BENCH_SERVE="0", TFOS_BENCH_ELASTIC_SERVE="0",
        TFOS_BENCH_DECODE="0",
        TFOS_BENCH_DATA="0", TFOS_BENCH_ELASTIC="0",
        TFOS_BENCH_ACTORS="0",
        TFOS_BENCH_FED_AB="0",  # one lane is enough for the gate
        # keep the lane's own stall diagnostics reachable BEFORE the
        # subprocess timeout kills the child opaquely
        TFOS_BENCH_FED_DEADLINE="120",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fed = _last_json_line(proc.stdout)["extra"]["fed"]
    assert "error" not in fed and "setup_error" not in fed, fed
    assert not fed.get("deadline_hit"), fed
    assert fed["vs_device_resident"] >= 0.75, fed
    assert fed["vs_transfer_ceiling"] is not None, fed
    assert fed["infeed_stall_frac"] < 0.5, fed


def test_init_watchdog_fires_on_relay_death():
    """Relay alive at probe time, dead during init (the r4 post-probe
    death mode): the port trigger must fire in ~15-21s — ahead of
    with_tunnel_watchdog.sh's ~45-60s SIGKILL — not wait out the 900s
    init cap."""
    code = (
        "import sys, time; sys.path.insert(0, %r); import bench; "
        "bench._arm_init_watchdog(); time.sleep(120)" % REPO)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=_dead_tunnel_env(),  # default TFOS_BENCH_INIT_TIMEOUT (900s)
        capture_output=True, text=True, timeout=90)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 40, f"port trigger took {elapsed:.0f}s"
    line = _last_json_line(proc.stdout)
    assert line["error"] == "tunnel_died_during_init"
    assert line["value"] is None


def test_tunnel_grace_honors_sub_5s_wait():
    """TFOS_BENCH_TUNNEL_WAIT below the 5s probe tick must be honored:
    the old sleep(5)-then-probe loop turned wait=1 into a 5s+ stall
    (and wait=7 into 10s).  The loop now probes first and sleeps only
    min(5, remaining)."""
    code = (
        "import sys, time; sys.path.insert(0, %r); import bench; "
        "t0 = time.monotonic()\n"
        "try:\n"
        "    bench._tunnel_note()\n"
        "except SystemExit:\n"
        "    pass\n"
        "print('GRACE_ELAPSED', time.monotonic() - t0)" % REPO)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, env=_dead_tunnel_env(),  # helper pins TUNNEL_WAIT=1
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    elapsed = float(proc.stdout.split("GRACE_ELAPSED")[1].strip())
    assert 0.9 <= elapsed < 4.0, f"grace loop took {elapsed:.1f}s for wait=1"
    assert _last_json_line(proc.stdout)["error"] == "tunnel_dead"


def test_init_watchdog_ignore_tunnel_skips_port_trigger():
    """TFOS_BENCH_IGNORE_TUNNEL=1 means the operator overruled the probe
    heuristic — the port trigger (would fire ~15s in) must stand down,
    while the wedge time cap stays armed.  With the cap at 18s, a still-
    armed port trigger would fire FIRST with tunnel_died_during_init."""
    code = (
        "import sys, time; sys.path.insert(0, %r); import bench; "
        "bench._arm_init_watchdog(); time.sleep(120)" % REPO)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=_dead_tunnel_env(TFOS_BENCH_IGNORE_TUNNEL="1",
                             TFOS_BENCH_INIT_TIMEOUT="18"),
        capture_output=True, text=True, timeout=90)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = _last_json_line(proc.stdout)
    assert line["error"] == "backend_init_timeout", line
    assert line["value"] is None


def test_run_watchdog_fires_with_partial_results():
    """Relay death AFTER _init_done() (mid-lane): the run watchdog must
    emit the fail-safe line carrying the lane results accumulated so
    far, then hard-exit 0."""
    code = (
        "import sys, time; sys.path.insert(0, %r); import bench; "
        "extra = {'images_per_sec_per_chip': 123.4}; "
        "bench._arm_run_watchdog(extra); time.sleep(120)" % REPO)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, env=_dead_tunnel_env(),
        capture_output=True, text=True, timeout=90)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 40, f"run watchdog took {elapsed:.0f}s"
    line = _last_json_line(proc.stdout)
    assert line["error"] == "tunnel_died_mid_run"
    assert line["value"] is None
    assert line["extra"]["partial"] is True
    assert line["extra"]["images_per_sec_per_chip"] == 123.4


def test_run_watchdog_noop_under_ignore_tunnel():
    """The press-on opt-out disarms the mid-run port watchdog too (no
    background thread at all, so nothing can fire later)."""
    code = (
        "import sys, threading; sys.path.insert(0, %r); import bench; "
        "n0 = threading.active_count(); "
        "disarm = bench._arm_run_watchdog({}); "
        "assert threading.active_count() == n0, 'watchdog thread started'; "
        "disarm(); print('NO_THREAD')" % REPO)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, env=_dead_tunnel_env(TFOS_BENCH_IGNORE_TUNNEL="1"),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "NO_THREAD" in proc.stdout


def test_init_watchdog_fires_on_wedge():
    """A relay that dies between probe and backend init wedges the jax
    import (r4: 26 min inside the driver timeout).  The watchdog must
    emit the fail-safe line and hard-exit 0."""
    code = (
        "import sys, time; sys.path.insert(0, %r); import bench; "
        "bench._arm_init_watchdog(); time.sleep(60)" % REPO)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=_dead_tunnel_env(TFOS_BENCH_INIT_TIMEOUT="1"),
        capture_output=True, text=True, timeout=60)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 30, f"watchdog exit took {elapsed:.0f}s"
    line = _last_json_line(proc.stdout)
    assert line["error"] == "backend_init_timeout"
    assert line["value"] is None
    assert "watchdog firing" in proc.stderr
