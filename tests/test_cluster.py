"""End-to-end cluster tests (parity: reference test/test_TFCluster.py).

Runs real multi-process clusters on the LocalEngine: independent node
programs, the InputMode.SPARK inference round-trip (squares of 0..999,
sum == 332,833,500 — the reference's functional baseline), and the two
fault-injection scenarios (failure during and after feeding).
"""

import os

import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine, TaskError


@pytest.fixture()
def engine():
    e = LocalEngine(2)
    yield e
    e.stop()


# --- node programs (module-level: shipped to executor processes) -----------

def _independent_fn(args, ctx):
    # each node computes on its own, no cluster comm (test_TFCluster.py:16-27)
    with open("result", "w") as f:
        f.write(str(sum(x * x for x in range(10))))


def _squares_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(100)
        if batch:
            feed.batch_results([x * x for x in batch])


def _fail_during_feed_fn(args, ctx):
    raise RuntimeError("deliberate failure during feeding")


def _fail_after_feed_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        feed.next_batch(100)
    raise RuntimeError("deliberate failure after feeding")


def _terminate_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    feed.next_batch(10)
    feed.terminate()


def _stream_consumer_fn(args, ctx):
    # online-training consumer: terminate after enough records arrive
    # (parity: the streaming examples' StopFeedHook behavior)
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
        batch = feed.next_batch(50)
        total += len(batch)
        if total >= 200:
            feed.terminate()
            break
    with open("stream_total", "w") as f:
        f.write(str(total))


def _role_marker_fn(args, ctx):
    with open(f"role_{ctx.job_name}_{ctx.task_index}", "w") as f:
        f.write(str(ctx.executor_id))


# --- tests ------------------------------------------------------------------

def test_driver_ps_nodes(engine, tmp_path, monkeypatch):
    """driver_ps_nodes=True hosts ps on driver threads with executor ids
    past the engine pool (parity: TFCluster.py:229,240-241,296-314)."""
    monkeypatch.chdir(tmp_path)  # the driver-hosted ps writes marker here
    cluster = TFCluster.run(
        engine, _role_marker_fn, [], num_executors=2, num_ps=1,
        driver_ps_nodes=True, input_mode=InputMode.TENSORFLOW,
    )
    jobs = {(m["job_name"], m["task_index"]): m for m in cluster.cluster_info}
    assert ("ps", 0) in jobs
    # ps occupies the id *after* the engine executors (reference contract:
    # cluster_size = num_executors + num_ps when driver-hosted)
    assert jobs[("ps", 0)]["executor_id"] == 2
    assert len(jobs) == 3
    cluster.shutdown()  # must stop the ps via its remote manager, not hang
    assert (tmp_path / "role_ps_0").exists(), "ps user fn never ran"

def test_independent_nodes(engine):
    cluster = TFCluster.run(
        engine, _independent_fn, [], num_executors=2,
        input_mode=InputMode.TENSORFLOW,
    )
    cluster.shutdown()
    found = (
        engine.parallelize(range(2), 2)
        .map_partitions(lambda it: [open("result").read()])
        .collect()
    )
    assert found == ["285", "285"]


def test_inference_roundtrip(engine):
    cluster = TFCluster.run(
        engine, _squares_fn, [], num_executors=2, input_mode=InputMode.SPARK,
    )
    ds = engine.parallelize(range(1000), 4)
    results = cluster.inference(ds).collect()
    cluster.shutdown()
    assert len(results) == 1000
    assert sum(results) == 332833500  # reference baseline test_TFCluster.py:44-47


def test_failure_during_feeding(engine):
    cluster = TFCluster.run(
        engine, _fail_during_feed_fn, [], num_executors=2,
        input_mode=InputMode.SPARK,
    )
    ds = engine.parallelize(range(1000), 4)
    with pytest.raises(TaskError):
        cluster.train(ds, feed_timeout=3)
    # the feeder consumed & re-raised the error, so shutdown may be clean
    try:
        cluster.shutdown()
    except (TaskError, SystemExit):
        pass


def test_failure_after_feeding(engine):
    cluster = TFCluster.run(
        engine, _fail_after_feed_fn, [], num_executors=2,
        input_mode=InputMode.SPARK,
    )
    ds = engine.parallelize(range(100), 2)
    cluster.train(ds)
    with pytest.raises((TaskError, SystemExit)):
        cluster.shutdown(grace_secs=3)


def test_datafeed_terminate_requests_stop(engine):
    cluster = TFCluster.run(
        engine, _terminate_fn, [], num_executors=2, input_mode=InputMode.SPARK,
    )
    ds = engine.parallelize(range(2000), 2)
    cluster.train(ds)
    assert cluster.server.done.wait(15)
    cluster.shutdown()


def test_train_stream_feeds_until_stop(engine):
    """Streaming micro-batches stop gracefully when a consumer terminates
    (parity: DStream feeding + stop_streaming, TFCluster.py:83-85,146-153)."""
    cluster = TFCluster.run(
        engine, _stream_consumer_fn, [], num_executors=2,
        input_mode=InputMode.SPARK,
    )

    def micro_batches():
        for _ in range(200):  # long but finite: a hang fails the test, not CI
            yield engine.parallelize(range(100), 2)

    cluster.train_stream(micro_batches(), feed_timeout=30)
    assert cluster.server.done.is_set(), "stream should end via STOP"
    cluster.shutdown()
    totals = (
        engine.parallelize(range(2), 2)
        .map_partitions(lambda it: [int(open("stream_total").read())])
        .collect(spread=True)  # pin task i to executor i's working dir
    )
    # at least the terminating consumer saw its 200 records
    assert max(totals) >= 200, totals


def test_stop_streaming_utility(engine):
    """Driver-external STOP via the rendezvous address (parity:
    examples/utils/stop_streaming.py)."""
    from tensorflowonspark_tpu import rendezvous

    cluster = TFCluster.run(
        engine, _stream_consumer_fn, [], num_executors=2,
        input_mode=InputMode.SPARK,
    )
    host, port = cluster.cluster_meta["server_addr"]
    client = rendezvous.Client((host, port))
    client.request_stop()
    client.close()
    assert cluster.server.done.wait(15)

    def micro_batches():
        while True:  # never consumed: STOP already set
            yield engine.parallelize(range(10), 2)

    cluster.train_stream(micro_batches())  # returns immediately
    cluster.shutdown()
