"""scripts/bench_check.py: the perf-regression gate over BENCH lines.

Pins the two on-disk bench-file shapes (bare line, driver wrapper with
the line inside ``tail``), fail-safe skipping, direction-aware
tolerance (throughput up = good, serve p99 up = bad), and the exit
codes the session scripts' ``host_run`` wiring reports.
"""

import importlib.util
import json
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_CHECK = os.path.join(REPO, "scripts", "bench_check.py")


def _load():
    spec = importlib.util.spec_from_file_location("bench_check", BENCH_CHECK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _line(img_s=None, p99=None, tok_s=None, fabric=None, value=0.4):
    extra = {}
    if img_s is not None:
        extra["images_per_sec_per_chip"] = img_s
    if p99 is not None:
        extra["serve"] = {"p99_ms": p99, "req_per_sec": 900.0}
    if tok_s is not None:
        extra["transformer"] = {"tokens_per_sec_per_chip": tok_s}
    if fabric is not None:
        extra["serve_fabric"] = dict(
            {"req_per_sec": 40.0, "p99_ms": 250.0, "dropped": 0,
             "affinity_hit_rate": 0.5, "scale_ups": 2}, **fabric)
    return {"metric": "resnet_train_mfu", "value": value, "unit": "frac",
            "extra": extra}


def _write(tmp_path, name, doc):
    (tmp_path / name).write_text(json.dumps(doc))


def _run(tmp_path, *args):
    env = dict(os.environ, PYTHONPATH="")
    env.pop("TFOS_BENCH_TOL", None)
    proc = subprocess.run(
        [sys.executable, BENCH_CHECK, "--dir", str(tmp_path), *args],
        capture_output=True, text=True, env=env, timeout=60)
    return proc.returncode, proc.stdout + proc.stderr


def test_ok_within_tolerance(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500, p99=20, tok_s=70e3))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2450, p99=21, tok_s=72e3))
    rc, out = _run(tmp_path)
    assert rc == 0, out
    assert "bench_check: OK" in out
    assert "newest=BENCH_r02.json prior=BENCH_r01.json" in out


def test_throughput_regression_fails(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2000))  # -20%
    rc, out = _run(tmp_path)
    assert rc == 1
    assert "REGRESSION resnet.img_s -20.0%" in out


def test_serve_p99_direction_is_lower_better(tmp_path):
    # latency DOWN 20% is an improvement, not a regression ...
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500, p99=25))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2500, p99=20))
    rc, out = _run(tmp_path)
    assert rc == 0, out
    # ... latency UP 50% is one
    _write(tmp_path, "BENCH_r03.json", _line(img_s=2500, p99=30))
    rc, out = _run(tmp_path)
    assert rc == 1
    assert "serve.p99_ms" in out


def test_tolerance_flag(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2200))  # -12%
    assert _run(tmp_path)[0] == 1
    assert _run(tmp_path, "--tolerance", "0.15")[0] == 0


def test_wrapper_and_failsafe_shapes(tmp_path):
    """Driver-wrapper files parse via ``tail``; dead-tunnel fail-safe
    lines (value null, no lanes) are skipped when picking rounds."""
    good = _line(img_s=2500)
    _write(tmp_path, "BENCH_r01.json",
           {"n": 1, "cmd": "python bench.py", "rc": 0,
            "tail": "noise\n" + json.dumps(good) + "\n"})
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2490))
    _write(tmp_path, "BENCH_r03.json",  # rc=124 wedge: no line at all
           {"n": 3, "cmd": "python bench.py", "rc": 124, "tail": "killed"})
    _write(tmp_path, "BENCH_r04.json",  # fail-safe: parses, but no lanes
           {"metric": "resnet_train_mfu", "value": None,
            "extra": {"error": "tunnel_dead"}})
    rc, out = _run(tmp_path)
    assert rc == 0, out
    assert "newest=BENCH_r02.json prior=BENCH_r01.json" in out


def test_fewer_than_two_usable_is_skip(tmp_path):
    rc, out = _run(tmp_path)
    assert rc == 0 and "SKIP (0 usable" in out
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500))
    rc, out = _run(tmp_path)
    assert rc == 0 and "SKIP (1 usable" in out


def test_disjoint_lanes_is_skip(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500, value=None))
    _write(tmp_path, "BENCH_r02.json", _line(tok_s=70e3, value=None))
    rc, out = _run(tmp_path)
    assert rc == 0 and "SKIP (no lane present in both" in out


def test_run_stamp_keys_are_ignored_by_lanes(tmp_path, monkeypatch):
    """bench.py stamps ``run_id``/``telemetry_dir`` into its line so a
    BENCH file can be joined to its trace directory; bench_check must
    treat those as non-lane metadata (ISSUE 12 satellite)."""
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    monkeypatch.setenv("TFOS_TELEMETRY_DIR", str(tmp_path / "tel"))
    stamp = bench.run_stamp()
    assert re.fullmatch(r"\d{8}T\d{6}-[0-9a-f]{6}", stamp["run_id"])
    assert stamp["telemetry_dir"] == str(tmp_path / "tel")
    assert bench.run_stamp()["run_id"] == stamp["run_id"]  # stable per run

    bc = _load()
    plain = _line(img_s=2500, p99=20)
    stamped = dict(_line(img_s=2500, p99=20), **stamp)
    assert bc.lanes_of(stamped) == bc.lanes_of(plain)

    _write(tmp_path, "old.json", plain)
    _write(tmp_path, "new.json", stamped)
    rc, out = _run(tmp_path, "--baseline", str(tmp_path / "old.json"),
                   "--latest", str(tmp_path / "new.json"))
    assert rc == 0, out


def test_fabric_dropped_ceiling_is_pinned_at_zero(tmp_path):
    """The fabric lane's zero-drop contract: any client-visible error
    fails the gate even when the PRIOR round was just as bad (absolute
    ceiling, not a trend)."""
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500,
                                             fabric={"dropped": 3}))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2500,
                                             fabric={"dropped": 3}))
    rc, out = _run(tmp_path)
    assert rc == 1
    assert "fabric.dropped" in out and "above ceiling" in out
    # dropped back at 0: the trend lanes take over and pass
    _write(tmp_path, "BENCH_r03.json", _line(img_s=2500, fabric={}))
    _write(tmp_path, "BENCH_r04.json", _line(img_s=2500, fabric={}))
    rc, out = _run(tmp_path)
    assert rc == 0, out


def test_fabric_scale_ups_floor_and_p99_trend(tmp_path):
    """scale_ups < 1 means the autoscaler never actuated — an absolute
    floor on the newest line; it is NOT compared round-over-round (how
    many steps the load shape needed is not a trend).  fabric.p99_ms
    is a plain lower-is-better trend lane."""
    _write(tmp_path, "BENCH_r01.json", _line(img_s=2500,
                                             fabric={"scale_ups": 4}))
    _write(tmp_path, "BENCH_r02.json", _line(img_s=2500,
                                             fabric={"scale_ups": 0}))
    rc, out = _run(tmp_path)
    assert rc == 1
    assert "fabric.scale_ups" in out and "below floor" in out
    # fewer scale_ups than last round but >= 1: not a regression
    _write(tmp_path, "BENCH_r03.json", _line(img_s=2500,
                                             fabric={"scale_ups": 1}))
    rc, out = _run(tmp_path)
    assert rc == 0, out
    assert "fabric.scale_ups" not in out
    # p99 blowing up past tolerance IS one
    _write(tmp_path, "BENCH_r04.json", _line(img_s=2500,
                                             fabric={"p99_ms": 400.0}))
    rc, out = _run(tmp_path)
    assert rc == 1
    assert "fabric.p99_ms" in out


def test_real_repo_bench_files_are_comparable():
    """The checked-in BENCH history must stay parseable: r01/r02 wrappers
    and the session_r4 bare line are usable; the wedged/fail-safe rounds
    are not."""
    bc = _load()
    usable = {os.path.basename(p) for p, _ in bc.discover(REPO)}
    assert {"BENCH_r01.json", "BENCH_r02.json",
            "BENCH_session_r4.json"} <= usable
    assert "BENCH_r03.json" not in usable  # rc=124, no bench line
    lanes, _ = bc.load_bench(os.path.join(REPO, "BENCH_session_r4.json"))
    assert lanes["resnet.img_s"] > 0 and lanes["fed.img_s"] > 0
