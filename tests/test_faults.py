"""Unit tests for the deterministic fault-injection harness
(tensorflowonspark_tpu/utils/faults.py) and its wiring into each runtime
injection point."""

import os
import time

import pytest

from tensorflowonspark_tpu import rendezvous
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.EXECUTOR_ENV, raising=False)
    monkeypatch.delenv("TFOS_EXECUTOR_INDEX", raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


def _arm(monkeypatch, plan, executor=None):
    monkeypatch.setenv(faults.PLAN_ENV, plan)
    if executor is not None:
        monkeypatch.setenv(faults.EXECUTOR_ENV, str(executor))
    faults._reset_for_tests()


# --- parser -----------------------------------------------------------------

def test_parse_plan_variants():
    fs = faults.parse_plan(
        "engine.task:exc@2, node.boot:hang(0.5)@3+ ,feed.get:delay(2)@*,"
        "checkpoint.save:kill"
    )
    assert [f.site for f in fs] == [
        "engine.task", "node.boot", "feed.get", "checkpoint.save"]
    assert (fs[0].kind, fs[0].first, fs[0].last) == ("exc", 2, 2)
    assert (fs[1].kind, fs[1].arg, fs[1].first, fs[1].last) == (
        "hang", 0.5, 3, None)
    assert (fs[2].kind, fs[2].arg, fs[2].first, fs[2].last) == (
        "delay", 2.0, 1, None)
    assert (fs[3].kind, fs[3].first, fs[3].last) == ("kill", 1, 1)


def test_parse_plan_empty():
    assert faults.parse_plan("") == []
    assert faults.parse_plan(None) == []
    assert faults.parse_plan(" , ,") == []


@pytest.mark.parametrize("bad", [
    "engine.task",               # no kind
    "nosite:exc",                # unknown site
    "engine.task:boom",          # unknown kind
    "engine.task:exc@0",         # hits are 1-based
    "engine.task:hang(x)",       # non-numeric arg
    "engine.task:hang(1",        # unclosed arg
])
def test_parse_plan_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_plan(bad)


# --- hit semantics ----------------------------------------------------------

def test_exc_fires_on_exact_hit(monkeypatch):
    _arm(monkeypatch, "engine.task:exc@2")
    faults.check("engine.task")  # hit 1: no fire
    with pytest.raises(faults.FaultInjected):
        faults.check("engine.task")  # hit 2: fire
    faults.check("engine.task")  # hit 3: past the window


def test_open_ended_and_star_hits(monkeypatch):
    _arm(monkeypatch, "engine.task:exc@2+")
    faults.check("engine.task")
    for _ in range(3):
        with pytest.raises(faults.FaultInjected):
            faults.check("engine.task")
    _arm(monkeypatch, "node.boot:exc@*")
    for _ in range(3):
        with pytest.raises(faults.FaultInjected):
            faults.check("node.boot")


def test_sites_count_independently(monkeypatch):
    _arm(monkeypatch, "engine.task:exc@2,node.boot:exc@1")
    with pytest.raises(faults.FaultInjected):
        faults.check("node.boot")
    faults.check("engine.task")  # engine.task still at hit 1
    with pytest.raises(faults.FaultInjected):
        faults.check("engine.task")


def test_unplanned_sites_free(monkeypatch):
    _arm(monkeypatch, "engine.task:exc@1")
    for _ in range(5):
        faults.check("feed.get")


def test_delay_sleeps_then_continues(monkeypatch):
    _arm(monkeypatch, "feed.get:delay(0.2)@1")
    t0 = time.monotonic()
    faults.check("feed.get")
    assert time.monotonic() - t0 >= 0.2
    faults.check("feed.get")  # hit 2: no delay


def test_hang_expires_into_exception(monkeypatch):
    _arm(monkeypatch, "node.main:hang(0.1)@1")
    with pytest.raises(faults.FaultInjected, match="hang"):
        faults.check("node.main")


def test_invalid_plan_injects_nothing(monkeypatch):
    _arm(monkeypatch, "engine.task:definitely-not-a-kind")
    faults.check("engine.task")  # logged, not raised


# --- scoping ----------------------------------------------------------------

def test_executor_scope_filters(monkeypatch):
    _arm(monkeypatch, "engine.task:exc@1", executor=1)
    monkeypatch.setenv("TFOS_EXECUTOR_INDEX", "0")
    faults.check("engine.task")  # wrong executor: no fire
    monkeypatch.setenv("TFOS_EXECUTOR_INDEX", "1")
    with pytest.raises(faults.FaultInjected):
        faults.check("engine.task")


# --- chaos plan generator ---------------------------------------------------

def test_random_plan_deterministic_and_valid():
    a = faults.random_plan(1234)
    assert a == faults.random_plan(1234)
    assert a != faults.random_plan(1235) or True  # may collide; parse matters
    for seed in range(20):
        plan = faults.random_plan(seed)
        for f in faults.parse_plan(plan):
            assert f.site in faults.CHAOS_SITES
            assert f.kind == "exc"


# --- telemetry --------------------------------------------------------------

def test_fired_fault_emits_telemetry(monkeypatch, tmp_path):
    from tensorflowonspark_tpu.utils import telemetry

    # earlier in-process cluster tests may leave a stale spool/identity in
    # os.environ, which would redirect the event away from tmp_path
    for var in (telemetry.SPOOL_ENV, telemetry.NODE_ENV, telemetry.ROLE_ENV):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv(telemetry.DIR_ENV, str(tmp_path))
    telemetry.configure(node_id="t", role="test")
    _arm(monkeypatch, "engine.task:exc@1")
    with pytest.raises(faults.FaultInjected):
        faults.check("engine.task", job=7, task=3)
    text = "".join(
        p.read_text() for p in tmp_path.rglob("*") if p.is_file())
    assert "fault/injected" in text
    assert '"job": 7' in text or '"job":7' in text


# --- integration: each wired site actually fires ----------------------------

def test_checkpoint_save_site(monkeypatch, tmp_path):
    from tensorflowonspark_tpu.utils import checkpoint

    _arm(monkeypatch, "checkpoint.save:exc@1")
    with pytest.raises(faults.FaultInjected):
        checkpoint.save_checkpoint(str(tmp_path / "ck"), {"w": 1.0}, 1)
    # hit 2: save succeeds (counter advanced by the failed attempt)
    path = checkpoint.save_checkpoint(str(tmp_path / "ck"), {"w": 1.0}, 2)
    assert path.endswith("ckpt-00000002.npz")


def test_rendezvous_register_and_query_sites(monkeypatch):
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        _arm(monkeypatch, "rendezvous.register:exc@1")
        client = rendezvous.Client(addr)
        meta = {"executor_id": 0, "host": "h", "job_name": "worker",
                "task_index": 0, "port": 1, "addr": ["h", 1], "authkey": ""}
        with pytest.raises(faults.FaultInjected):
            client.register(meta)
        _arm(monkeypatch, "rendezvous.query:exc@1")
        client.register(meta)
        with pytest.raises(faults.FaultInjected):
            client.await_reservations(timeout=5)
        _arm(monkeypatch, "")  # disarm: query now completes
        assert len(client.await_reservations(timeout=5)) == 1
        client.close()
    finally:
        server.stop()


def test_feed_get_site(monkeypatch):
    from tensorflowonspark_tpu.feed import DataFeed

    class _KV:
        def __init__(self):
            self._q = None

        def get(self, key):
            return None  # no shm ring advertised

        def get_queue(self, name):
            import queue as q

            if self._q is None:
                self._q = q.Queue()
            return self._q

    mgr = _KV()
    mgr.get_queue("input").put([1, 2, 3])
    _arm(monkeypatch, "feed.get:exc@1")
    feed = DataFeed(mgr, train_mode=True)
    with pytest.raises(faults.FaultInjected):
        feed.next_batch(3)


def test_engine_task_site(monkeypatch):
    from tensorflowonspark_tpu.engine import LocalEngine, TaskError

    monkeypatch.setenv(faults.PLAN_ENV, "engine.task:exc@1")
    monkeypatch.setenv("TFOS_TASK_RETRIES", "0")
    eng = LocalEngine(1)
    try:
        with pytest.raises(TaskError, match="FaultInjected"):
            eng.parallelize(range(4), 1).foreach_partition(
                lambda it: list(it))
    finally:
        eng.stop()
