"""Training-health watchtower (obs/health.py) acceptance.

Parity framing: the reference's failure story for a diverging run is
"read the executor logs" (SURVEY.md §5); these tests pin the watching
replacement — edge-triggered streaming detectors over the existing
``TrainMetrics`` feed, deterministic NaN injection through the fault
plan's ``poison`` channel, configurable reactions
(``TFOS_HEALTH_ACTION=checkpoint|halt``), driver-side straggler
analysis on ``/statusz``, and the on-demand profiling control plane
(``POST /profilez`` / ``/flightz``).

Fast lane: detector math, re-arm semantics, reactions, fault grammar,
profiler degrade, straggler report, endpoint rendering, ``tfos-top
--health``, the bench ``health`` block contract, and a CPU control-plane
round trip.  Slow lane: the two ISSUE 16 e2e scenarios — a seeded NaN
halting a cluster run with a checkpoint at the last finite step, and a
seeded-slow executor named by the straggler table.
"""

import glob
import importlib.util
import io
import json
import logging
import math
import os
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine, TaskError
from tensorflowonspark_tpu.obs import health
from tensorflowonspark_tpu.obs import http as obs_http
from tensorflowonspark_tpu.obs import publish as obs_publish
from tensorflowonspark_tpu.obs import top as obs_top
from tensorflowonspark_tpu.utils import faults, telemetry
from tensorflowonspark_tpu.utils import metrics_registry as reg
from tensorflowonspark_tpu.utils.metrics import TrainMetrics

pytestmark = pytest.mark.health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_CHECK = os.path.join(REPO, "scripts", "bench_check.py")

_ENV_KEYS = (
    reg.PORT_ENV, reg.INTERVAL_ENV, obs_http.HOST_ENV,
    health.ENABLE_ENV, health.ACTION_ENV, health.GRADNORM_ENV,
    health.SPIKE_SIGMA_ENV, health.WARMUP_ENV, health.STEP_FACTOR_ENV,
    health.STEP_PATIENCE_ENV, health.STALL_FRAC_ENV,
    faults.PLAN_ENV, faults.EXECUTOR_ENV, "TFOS_EXECUTOR_INDEX",
    telemetry.DIR_ENV, telemetry.SPOOL_ENV, telemetry.NODE_ENV,
    telemetry.ROLE_ENV,
)


@pytest.fixture(autouse=True)
def _health_env():
    """Every test starts with the obs gate off, no fault plan, no
    telemetry, default detector knobs, and clean per-process caches."""
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    reg.reset()
    faults._reset_for_tests()
    health._LAST_STRAGGLERS.clear()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    reg.reset()
    faults._reset_for_tests()
    health._LAST_STRAGGLERS.clear()


def _enable(port="0", interval="0.2"):
    os.environ[reg.PORT_ENV] = port
    os.environ[reg.INTERVAL_ENV] = interval
    reg.reset()


def _get(url, timeout=30):
    """GET that returns (code, body) even for error statuses (503 from a
    degraded /healthz must be readable, not an exception)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _post(url, timeout=90):
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _read_all(root):
    text = ""
    for path in glob.glob(os.path.join(str(root), "**", "*"),
                          recursive=True):
        if os.path.isfile(path):
            with open(path, errors="replace") as f:
                text += f.read()
    return text


# --- detectors: edge trigger + re-arm ---------------------------------------

def test_nan_gate_edge_trigger_and_rearm():
    m = health.HealthMonitor(action="none")
    assert m.observe_step(loss=1.0, step=1) == []
    assert m.last_finite_step == 1
    assert m.observe_step(loss=float("nan"), step=2) == ["nan"]
    assert m.status == "degraded"
    # still anomalous: edge-triggered means no second firing
    assert m.observe_step(loss=float("nan"), step=3) == []
    # recovery re-arms ...
    assert m.observe_step(loss=0.9, step=4) == []
    assert m.status == "ok" and m.last_finite_step == 4
    # ... so the next non-finite value fires again (inf counts too)
    assert m.observe_step(loss=float("inf"), step=5) == ["nan"]
    assert m.counts == {"nan": 2}
    assert m.last_anomaly["kind"] == "nan"
    assert m.last_anomaly["step"] == 5
    assert m.last_anomaly["last_finite_step"] == 4


def test_nan_gate_covers_grad_probe():
    m = health.HealthMonitor(action="none")
    assert m.observe_step(loss=1.0, grad_norm=2.0, grad_finite=True,
                          step=1) == []
    assert m.observe_step(loss=1.0, grad_norm=float("nan"), step=2) == ["nan"]
    assert m.last_anomaly["source"] == "grad_norm"
    assert m.observe_step(loss=1.0, grad_norm=1.0, step=3) == []  # re-arm
    # an all-finite-values step with grad_finite=False (the device-side
    # any-nan-in-tree probe) is still numeric corruption
    assert m.observe_step(loss=1.0, grad_finite=False, step=4) == ["nan"]
    assert m.last_anomaly["source"] == "grad_finite"
    # a non-finite step never advances the finite high-water mark
    assert m.last_finite_step == 3


def test_loss_spike_detector():
    os.environ[health.WARMUP_ENV] = "5"
    m = health.HealthMonitor(action="none")
    for i in range(6):
        assert m.observe_step(loss=1.0, step=i + 1) == []
    assert m.observe_step(loss=100.0, step=7) == ["loss_spike"]
    assert m.last_anomaly["kind"] == "loss_spike"
    assert m.last_anomaly["loss"] == 100.0
    # back under the (spike-inflated) threshold: re-arm
    assert m.observe_step(loss=1.0, step=8) == []
    assert m.status == "ok"
    # a second excursion fires a second event
    assert m.observe_step(loss=1000.0, step=9) == ["loss_spike"]
    assert m.counts["loss_spike"] == 2


def test_slow_step_patience_and_baseline_exclusion():
    os.environ[health.WARMUP_ENV] = "3"
    os.environ[health.STEP_PATIENCE_ENV] = "2"
    m = health.HealthMonitor(action="none")
    for i in range(4):
        assert m.observe_step(step_time_s=0.01, step=i + 1) == []
    # one slow step is tolerated (patience=2) ...
    assert m.observe_step(step_time_s=0.05, step=5) == []
    # ... the second consecutive one fires
    assert m.observe_step(step_time_s=0.05, step=6) == ["slow_step"]
    assert m.status == "degraded"
    assert m.observe_step(step_time_s=0.05, step=7) == []  # edge
    # slow steps never entered the EWMA: the baseline is still the
    # healthy 10ms, not converging toward the regression
    assert m._time_mean == pytest.approx(0.01)
    assert m.observe_step(step_time_s=0.01, step=8) == []  # re-arm
    assert m.status == "ok" and m._slow_run == 0


def test_infeed_stall_detector():
    os.environ[health.WARMUP_ENV] = "6"
    m = health.HealthMonitor(action="none")
    # warmup counts loss+time observations; quiet until it is met
    for i in range(2):
        assert m.observe_step(loss=1.0, step_time_s=0.01, infeed_frac=0.9,
                              step=i + 1) == []
    assert m.observe_step(loss=1.0, step_time_s=0.01, infeed_frac=0.9,
                          step=3) == ["infeed_stall"]
    assert m.observe_step(loss=1.0, step_time_s=0.01, infeed_frac=0.1,
                          step=4) == []  # recovered: re-arm
    assert m.status == "ok"
    assert m.observe_step(loss=1.0, step_time_s=0.01, infeed_frac=0.8,
                          step=5) == ["infeed_stall"]
    assert m.counts["infeed_stall"] == 2


# --- reactions --------------------------------------------------------------

def test_reaction_checkpoint_and_halt():
    calls = []
    m = health.HealthMonitor(action="checkpoint",
                             checkpoint_fn=lambda: calls.append("ck"))
    m.observe_step(loss=1.0, step=1)
    assert m.observe_step(loss=float("nan"), step=2) == ["nan"]
    assert calls == ["ck"]  # checkpointed, run continues

    halts = []
    m2 = health.HealthMonitor(action="halt",
                              checkpoint_fn=lambda: halts.append("ck"))
    m2.observe_step(loss=2.0, step=1)
    with pytest.raises(health.HealthHalt, match="last finite step 1"):
        m2.observe_step(loss=float("nan"), step=2)
    assert halts == ["ck"]  # checkpoint BEFORE the halt


def test_advisory_kinds_never_react():
    os.environ[health.WARMUP_ENV] = "2"
    m = health.HealthMonitor(action="halt")
    for i in range(3):
        m.observe_step(loss=1.0, step=i + 1)
    # a loss spike under action=halt is advisory: fires, no HealthHalt
    assert m.observe_step(loss=100.0, step=4) == ["loss_spike"]


def test_halt_survives_broken_checkpoint_fn():
    def boom():
        raise OSError("disk full")

    m = health.HealthMonitor(action="halt", checkpoint_fn=boom)
    m.observe_step(loss=1.0, step=1)
    with pytest.raises(health.HealthHalt):
        m.observe_step(loss=float("nan"), step=2)


def test_action_env_and_enable_gate():
    os.environ[health.ACTION_ENV] = "explode"
    assert health.action_from_env() == "none"  # typo warns, never halts
    os.environ[health.ACTION_ENV] = "halt"
    assert health.action_from_env() == "halt"
    assert health.HealthMonitor().action == "halt"
    with pytest.raises(ValueError):
        health.HealthMonitor(action="explode")
    os.environ[health.ENABLE_ENV] = "0"
    assert not health.enabled()
    assert health.monitor_from_env() is None
    assert TrainMetrics(health=False).health is None
    os.environ.pop(health.ENABLE_ENV)
    assert health.enabled()  # default on
    assert isinstance(health.monitor_from_env(), health.HealthMonitor)
    assert isinstance(TrainMetrics().health, health.HealthMonitor)


# --- a firing lands on all three planes -------------------------------------

def test_fire_lands_metrics_telemetry_and_flight(tmp_path):
    _enable()
    os.environ[telemetry.DIR_ENV] = str(tmp_path)
    m = health.HealthMonitor(action="none", node="worker-7")
    m.observe_step(loss=1.0, grad_norm=1.5, step=1)
    m.observe_step(loss=float("nan"), step=2)

    snap = reg.snapshot()
    assert health.snapshot_anomaly_total(snap) == 1
    (s,) = snap["tfos_health_anomalies_total"]["series"]
    assert s["labels"] == {"kind": "nan"} and s["value"] == 1.0
    assert obs_http._metric_gauge(snap, "tfos_health_status") == 1.0
    assert obs_http._metric_gauge(snap, "tfos_health_last_anomaly_step") == 2.0
    assert obs_http._metric_gauge(snap, "tfos_health_grad_norm") == 1.5
    summary = obs_http.node_summary(snap)
    assert summary["health"] == "degraded"
    assert summary["health_anomalies"] == 1
    assert summary["grad_norm"] == 1.5

    telemetry.flush()
    assert '"health/nan"' in _read_all(tmp_path)
    # the flight ring froze while the anomaly was fresh (ISSUE 16
    # satellite: health/* joins the supervision dump triggers)
    (dump_path,) = glob.glob(str(tmp_path / "flight-*.json"))
    dump = json.loads(open(dump_path).read())
    assert dump["trigger"] == "health/nan"
    assert dump["node"] == "worker-7"
    assert "nan at step 2" in dump["reason"]


def test_process_summary_is_bench_ready():
    _enable()
    m = health.HealthMonitor(action="none")
    m.observe_step(loss=float("nan"), step=1)
    ps = health.process_summary()
    assert ps["anomalies"].get("nan", 0) >= 1
    assert ps["total"] >= 1
    assert ps["status"] == "degraded"
    assert ps["max_skew"] is None  # no straggler report yet
    json.dumps(ps)  # bench.py embeds it in the JSON line verbatim


# --- fault grammar: the nan poison channel ----------------------------------

def test_fault_plan_nan_parse():
    (f,) = faults.parse_plan("train.step:nan@3")
    assert f.site == "train.step" and f.kind == "nan"
    assert f.first == 3 and f.last == 3
    with pytest.raises(ValueError):
        faults.parse_plan("train.step:nan@x")
    with pytest.raises(ValueError):
        faults.parse_plan("nowhere.site:nan@1")


def test_poison_counts_separately_from_check():
    os.environ[faults.PLAN_ENV] = "train.step:nan@3"
    faults._reset_for_tests()
    # check() must neither fire a nan entry nor consume its hits
    for _ in range(10):
        faults.check("train.step")
    assert faults.poison("train.step", 1.5) == 1.5       # hit 1
    assert faults.poison("train.step", 1.5) == 1.5       # hit 2
    assert math.isnan(faults.poison("train.step", 1.5))  # hit 3 fires
    assert faults.poison("train.step", 1.5) == 1.5       # hit 4: done


def test_poison_honors_executor_scope():
    os.environ[faults.PLAN_ENV] = "train.step:nan@1"
    os.environ[faults.EXECUTOR_ENV] = "1"
    os.environ["TFOS_EXECUTOR_INDEX"] = "0"
    faults._reset_for_tests()
    assert faults.poison("train.step", 2.0) == 2.0  # scoped out
    os.environ["TFOS_EXECUTOR_INDEX"] = "1"
    faults._reset_for_tests()
    assert math.isnan(faults.poison("train.step", 2.0))


def test_train_metrics_poison_to_halt():
    """The deterministic NaN path end to end in one process: the fault
    plan poisons the 3rd recorded loss, TrainMetrics hands it to the
    monitor, the halt reaction checkpoints at the last finite step and
    raises out of ``step()``."""
    os.environ[faults.PLAN_ENV] = "train.step:nan@3"
    os.environ[health.ACTION_ENV] = "halt"
    faults._reset_for_tests()
    saved = []
    mon = health.monitor_from_env()
    mon.checkpoint_fn = lambda: saved.append(mon.last_finite_step)
    tm = TrainMetrics(health=mon)
    tm.step(items=1, loss=1.0)
    tm.step(items=1, loss=0.9)
    with pytest.raises(health.HealthHalt):
        tm.step(items=1, loss=0.8)
    assert saved == [2]
    assert mon.last_finite_step == 2


# --- profiler degrade-to-noop (satellite a) ---------------------------------

def test_profiler_degrades_to_noop(monkeypatch, caplog, tmp_path):
    jax = pytest.importorskip("jax")
    from tensorflowonspark_tpu.utils import profiler

    def boom(*a, **k):
        raise RuntimeError("no capture backend")

    monkeypatch.setattr(profiler, "_degraded_warned", False)
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    stops = []
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stops.append(1))
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.utils.profiler"):
        assert profiler.start_trace(str(tmp_path)) is False
        assert profiler.start_trace(str(tmp_path)) is False
    warns = [r for r in caplog.records
             if "capture unavailable" in r.getMessage()
             and r.levelno >= logging.WARNING]
    assert len(warns) == 1  # warned once, then quiet
    ran = []
    with profiler.trace(str(tmp_path)):
        ran.append(1)
    assert ran == [1]      # the body always runs
    assert stops == []     # a trace that never started is never stopped


class _FakeCtlMgr:
    """Just the control-channel surface serve_control touches."""

    def __init__(self):
        self.kv_ = {}

    def obs_control_take(self, nid):
        return self.kv_.pop("ctl:" + nid, None)

    def obs_control_ack(self, nid, res):
        self.kv_["ack:" + nid] = res


def test_serve_control_acks_degraded_capture(monkeypatch):
    """A node without a profiler backend acks the degrade reason instead
    of dying, and counts the degraded capture."""
    jax = pytest.importorskip("jax")
    from tensorflowonspark_tpu.utils import profiler

    _enable()
    monkeypatch.setattr(profiler, "_degraded_warned", True)
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("nope")))
    fm = _FakeCtlMgr()
    fm.kv_["ctl:w0"] = {"cmd": "profile", "ms": 10, "seq": 7}
    ack = obs_publish.serve_control(fm, "w0")
    assert ack["ok"] is False and ack["seq"] == 7
    assert ack["error"] == "profiler capture unavailable (no-op)"
    assert fm.kv_["ack:w0"] == ack
    (s,) = reg.snapshot()["tfos_health_captures_total"]["series"]
    assert s["labels"] == {"kind": "profile", "status": "degraded"}
    # an unknown command still acks (the driver's 200 must carry why)
    fm.kv_["ctl:w0"] = {"cmd": "zap", "seq": 8}
    ack = obs_publish.serve_control(fm, "w0")
    assert ack["seq"] == 8 and "unknown cmd" in ack["error"]
    assert obs_publish.serve_control(fm, "w0") is None  # empty slot


# --- driver-side straggler analysis -----------------------------------------

def _hist(counts, count):
    return {"labels": {}, "bounds": [10.0, 100.0], "counts": counts,
            "count": count, "sum": 0.0}


def _entry(h):
    return {"metrics": {
        "tfos_train_step_ms": {"type": "histogram", "series": [h]}}}


def test_straggler_report_math():
    entries = {
        "worker-0": _entry(_hist([4, 0, 0], 4)),   # p50 ~5ms
        "worker-1": _entry(_hist([0, 4, 0], 4)),   # p50 ~55ms
        "worker-2": _entry(_hist([1, 0, 0], 1)),   # < min_count: excluded
        "ps-0": {"metrics": {}},                   # no histogram: excluded
    }
    rep = health.straggler_report(entries, emit=False)
    assert rep["slowest"] == "worker-1" and rep["fastest"] == "worker-0"
    assert rep["skew"] > 1.5
    rows = {r["node"]: r for r in rep["nodes"]}
    assert set(rows) == {"worker-0", "worker-1"}
    assert rows["worker-0"]["rel"] == 1.0
    assert rows["worker-1"]["rel"] == rep["skew"]
    assert rows["worker-1"]["p50_ms"] > rows["worker-0"]["p50_ms"]
    # a single comparable node is no cross-node statement
    assert health.straggler_report(
        {"worker-0": _entry(_hist([4, 0, 0], 4))}) is None
    assert health.straggler_report({}) is None


def test_straggler_emit_sets_gauge_and_summary_cache():
    _enable()
    entries = {"worker-0": _entry(_hist([4, 0, 0], 4)),
               "worker-1": _entry(_hist([0, 4, 0], 4))}
    rep = health.straggler_report(entries)  # emit=True default
    assert obs_http._metric_gauge(
        reg.snapshot(), "tfos_node_skew") == rep["skew"]
    ps = health.process_summary()
    assert ps["max_skew"] == rep["skew"]
    assert ps["slowest_node"] == "worker-1"


# --- /statusz stragglers + /healthz degraded --------------------------------

def test_statusz_stragglers_and_healthz_degraded():
    _enable()
    srv = obs_http.ObsServer(cluster=None, port=0, interval=999).start()
    try:
        now = time.time()
        snap_fast = {"tfos_train_step_ms": {
            "type": "histogram", "series": [_hist([4, 0, 0], 4)]}}
        snap_slow = {
            "tfos_train_step_ms": {
                "type": "histogram", "series": [_hist([0, 4, 0], 4)]},
            "tfos_health_anomalies_total": {"type": "counter", "series": [
                {"labels": {"kind": "slow_step"}, "value": 2.0}]},
            "tfos_health_status": {"type": "gauge", "series": [
                {"labels": {}, "value": 1.0}]},
        }
        with srv._lock:
            srv._nodes["worker-0"] = {
                "node_id": "worker-0", "role": "worker",
                "heartbeat_age_s": 0.1, "last_seen": now,
                "metrics": snap_fast, "polled_ts": now}
            srv._nodes["worker-1"] = {
                "node_id": "worker-1", "role": "worker",
                "heartbeat_age_s": 0.1, "last_seen": now,
                "metrics": snap_slow, "polled_ts": now}

        code, body = _get(srv.url + "/statusz")
        assert code == 200
        doc = json.loads(body)
        strag = doc["stragglers"]
        assert strag["slowest"] == "worker-1" and strag["skew"] > 1.5
        assert doc["nodes"]["worker-1"]["summary"]["health"] == "degraded"
        assert doc["nodes"]["worker-1"]["summary"]["health_anomalies"] == 2
        assert "health" not in doc["nodes"]["worker-0"]["summary"]

        # anomalies flip /healthz to degraded (still 503: don't route
        # work at a sick cluster) even with every heartbeat live
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        doc = json.loads(body)
        assert doc["status"] == "degraded"
        assert doc["nodes"]["worker-1"]["anomalies"] == 2
        assert "anomalies" not in doc["nodes"]["worker-0"]
        assert all(n["alive"] for n in doc["nodes"].values())

        # GET /statusz recomputes without emitting; the poll thread owns
        # the driver-registry gauge
        assert obs_http._metric_gauge(
            reg.snapshot() or {}, "tfos_node_skew") is None
        srv.poll_once()
        assert obs_http._metric_gauge(
            reg.snapshot(), "tfos_node_skew") == strag["skew"]
    finally:
        srv.stop()


def test_healthz_degrades_on_driver_own_registry():
    _enable()
    reg.inc("tfos_health_anomalies_total", kind="nan")
    srv = obs_http.ObsServer(cluster=None, port=0, interval=999).start()
    try:
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert json.loads(body)["status"] == "degraded"
    finally:
        srv.stop()


# --- tfos-top --health (satellite d) ----------------------------------------

_CANNED_HEALTH = {
    "cluster": {"id": "abcd1234", "epoch": 0, "num_executors": 2,
                "restarts": 0, "restarts_used": 0},
    "nodes": {
        "worker-0": {"role": "worker", "alive": True,
                     "summary": {"steps": 50, "health": "degraded",
                                 "health_anomalies": 3, "grad_norm": 12.25}},
        "worker-1": {"role": "worker", "alive": True,
                     "summary": {"steps": 50}},  # no health report
    },
    "stragglers": {
        "skew": 2.4, "slowest": "worker-1", "fastest": "worker-0",
        "nodes": [
            {"node": "worker-0", "p50_ms": 10.0, "steps": 50, "rel": 1.0},
            {"node": "worker-1", "p50_ms": 24.0, "steps": 50, "rel": 2.4},
        ]},
}


class _StatuszStub(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):  # noqa: N802 - http.server API
        body = json.dumps(_CANNED_HEALTH).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_tfos_top_health_pane():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StatuszStub)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        out = io.StringIO()
        assert obs_top.main(["--url", url, "--once", "--health"],
                            out=out) == 0
        text = out.getvalue()
        assert "health (obs/health.py):" in text
        assert "ANOMALIES" in text and "GRAD-NORM" in text
        lines = text.splitlines()
        hdr = lines.index("health (obs/health.py):")
        pane = lines[hdr:]
        (w0,) = [ln for ln in pane if ln.startswith("worker-0")
                 and "degraded" in ln]
        assert "3" in w0
        # worker-1 never reported health: no row in the health table
        # (its only pane appearance is the straggler table)
        assert ("stragglers: skew=2.40x slowest=worker-1 "
                "fastest=worker-0") in text
        (sl,) = [ln for ln in pane if ln.startswith("worker-1")
                 and "2.40x" in ln]
        assert "24" in sl
        # without --health the pane stays hidden
        out2 = io.StringIO()
        assert obs_top.main(["--url", url, "--once"], out=out2) == 0
        assert "health (obs/health.py):" not in out2.getvalue()
    finally:
        httpd.shutdown()
        httpd.server_close()
    empty = obs_top.render_health({})
    assert "(no health reports)" in empty
    assert "stragglers: (not enough per-node step data)" in empty


# --- bench "health" block is non-lane metadata (satellite c) ----------------

def test_bench_health_block_ignored_by_bench_check(tmp_path):
    spec = importlib.util.spec_from_file_location("bench_check", BENCH_CHECK)
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)

    plain = {"metric": "resnet_train_mfu", "value": 0.4, "unit": "frac",
             "extra": {"images_per_sec_per_chip": 2500.0}}
    stamped = dict(plain, health={
        "anomalies": {"loss_spike": 1}, "total": 1, "status": "degraded",
        "max_skew": 1.2, "slowest_node": "worker-1"})
    assert bc.lanes_of(stamped) == bc.lanes_of(plain)

    (tmp_path / "old.json").write_text(json.dumps(plain))
    (tmp_path / "new.json").write_text(json.dumps(stamped))
    proc = subprocess.run(
        [sys.executable, BENCH_CHECK, "--dir", str(tmp_path),
         "--baseline", str(tmp_path / "old.json"),
         "--latest", str(tmp_path / "new.json")],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=""), timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- on-demand control plane round trip (CPU) -------------------------------

def test_profilez_flightz_roundtrip(tmp_path, monkeypatch):
    """The acceptance scenario on CPU: POST /profilez round-trips a
    capture directive through the manager KV to a live publish daemon
    and back; /flightz returns an on-demand flight dump path; the
    failure codes (404 unknown node, 400 missing param, 405 on GET) are
    pinned."""
    pytest.importorskip("jax")
    _enable(port="0", interval="0.2")
    monkeypatch.setenv(telemetry.DIR_ENV, str(tmp_path))
    mgr = tfmanager.start(b"hp-secret", ("control",), "local")
    stop = None
    srv = None
    try:
        meta = {"job_name": "worker", "task_index": 0, "executor_id": 0,
                "host": "127.0.0.1", "addr": list(mgr.address),
                "authkey": b"hp-secret".hex()}
        fake_cluster = types.SimpleNamespace(cluster_info=[meta])
        node_mgr = tfmanager.connect(tuple(mgr.address), b"hp-secret")
        reg.inc("tfos_engine_jobs_total")  # something worth publishing
        stop = obs_publish.start_publisher(node_mgr, "worker-0",
                                           role="worker", interval=0.2)
        assert stop is not None
        srv = obs_http.ObsServer(cluster=fake_cluster, port=0,
                                 interval=0.2).start()

        code, body = _post(srv.url + "/profilez?node=worker-0&ms=100"
                           "&wait_s=60")
        res = json.loads(body)
        assert code == 200, res
        assert res["cmd"] == "profile" and res["node_id"] == "worker-0"
        assert res["ok"] is True, res
        assert res["ms"] == 100
        # the capture landed in the telemetry sink dir for the drain
        assert res["capture"].startswith(str(tmp_path))

        code, body = _post(srv.url + "/flightz?node=worker-0&wait_s=60")
        res = json.loads(body)
        assert code == 200 and res["cmd"] == "flight"
        assert res["ok"] is True, res
        dump = json.loads(open(res["capture"]).read())
        assert dump["trigger"] == "health/on_demand"
        assert dump["node"] == "worker-0"

        snap = reg.snapshot()
        caps = {(s["labels"]["kind"], s["labels"]["status"]): s["value"]
                for s in snap["tfos_health_captures_total"]["series"]}
        assert caps[("profile", "ok")] == 1.0
        assert caps[("flight", "ok")] == 1.0

        code, body = _post(srv.url + "/profilez?node=worker-9")
        assert code == 404 and "unknown node" in body
        code, body = _post(srv.url + "/profilez")
        assert code == 400 and "node" in body
        code, body = _get(srv.url + "/profilez")
        assert code == 405
    finally:
        if srv is not None:
            srv.stop()
        if stop is not None:
            stop.set()
        mgr.shutdown()


# --- e2e (slow lane): seeded NaN halt + seeded straggler --------------------

def _nan_halt_main(args, ctx):
    import numpy as np

    from tensorflowonspark_tpu.obs import health as H
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics as TM

    ckpt_dir = os.path.join(args["model_dir"], f"worker-{ctx.task_index}")
    mon = H.monitor_from_env(node=f"worker-{ctx.task_index}")
    mon.checkpoint_fn = lambda: ckpt.save_checkpoint(
        ckpt_dir, {"w": np.zeros(1, np.float32)},
        step=mon.last_finite_step)
    tm = TM(health=mon)
    for i in range(600):
        tm.step(items=1, loss=1.0 + 0.001 * i)
        time.sleep(0.02)


@pytest.mark.slow
def test_e2e_nan_halt_checkpoints_last_finite_step(tmp_path, monkeypatch):
    """ISSUE 16 acceptance: a NaN injected at a known step fires
    ``health/nan``, writes a flight dump, flips /healthz to degraded,
    and TFOS_HEALTH_ACTION=halt stops the run with a checkpoint at the
    last finite step."""
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    telemetry_dir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(telemetry_dir))
    monkeypatch.setenv(reg.PORT_ENV, "0")
    monkeypatch.setenv(reg.INTERVAL_ENV, "0.1")
    monkeypatch.chdir(tmp_path)
    reg.reset()
    engine = LocalEngine(2, env={
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",
        faults.PLAN_ENV: "train.step:nan@8",
        health.ACTION_ENV: "halt",
    })
    degraded = None
    try:
        cluster = TFCluster.run(
            engine, _nan_halt_main, {"model_dir": str(tmp_path / "model")},
            num_executors=2, input_mode=InputMode.TENSORFLOW)
        assert cluster.obs is not None
        base = cluster.obs.url
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            code, body = _get(base + "/healthz")
            doc = json.loads(body)
            if code == 503 and doc["status"] == "degraded":
                degraded = doc
                break
            time.sleep(0.3)
        assert degraded is not None, "healthz never degraded"
        assert any(n.get("anomalies") for n in degraded["nodes"].values())
        try:
            cluster.shutdown(grace_secs=2)
        except (TaskError, RuntimeError, SystemExit):
            pass  # halted workers skipped the exit barrier: acceptable
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)

    # the flight recorder froze the ring at the anomaly; dumps spool
    # under $TFOS_TELEMETRY_DIR (NOT the engine scratch — deleted by
    # engine.stop()), so they survive full teardown by construction
    dumps = glob.glob(os.path.join(str(telemetry_dir), "spool-*",
                                   "flight-*.json"))
    assert dumps, "no flight dump survived engine stop on health/nan"
    assert any(json.loads(open(p).read())["trigger"] == "health/nan"
               for p in dumps)

    # nan@8 poisons the 8th recorded loss: both workers checkpointed at
    # the last finite step, 7 — deterministically
    for i in range(2):
        step = ckpt.latest_step(str(tmp_path / "model" / f"worker-{i}"))
        assert step == 7, f"worker-{i} checkpointed at {step}, wanted 7"

    raw = _read_all(tmp_path)
    assert "health/nan" in raw       # the anomaly event
    assert "health/halt" in raw      # wrapper_fn's clean-stop event
    assert "fault/injected" in raw   # the poison left its injection mark


def _straggler_main(args, ctx):
    from tensorflowonspark_tpu.utils.metrics import TrainMetrics as TM

    tm = TM(health=False)
    for _ in range(args["steps"]):
        tm.step(items=1)
        time.sleep(0.005)


@pytest.mark.slow
def test_e2e_straggler_named_in_statusz(tmp_path, monkeypatch):
    """ISSUE 16 acceptance: a seeded-slow node in a multiprocess run is
    named by the straggler table with the skew attributed."""
    monkeypatch.setenv(reg.PORT_ENV, "0")
    monkeypatch.setenv(reg.INTERVAL_ENV, "0.1")
    monkeypatch.chdir(tmp_path)
    reg.reset()
    engine = LocalEngine(2, env={
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",
        faults.PLAN_ENV: "train.step:delay(0.05)@*",
        faults.EXECUTOR_ENV: "1",   # only worker-1 drags
    })
    try:
        cluster = TFCluster.run(
            engine, _straggler_main, {"steps": 120},
            num_executors=2, input_mode=InputMode.TENSORFLOW)
        assert cluster.obs is not None
        base = cluster.obs.url
        strag = None
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            _, body = _get(base + "/statusz")
            doc = json.loads(body)
            s = doc.get("stragglers")
            if s and s["slowest"] == "worker-1" and s["skew"] > 2.0:
                # the poll thread (the only emitter) must also have
                # exported the skew gauge on the driver /metrics series —
                # its tick can trail the statusz view by one interval
                _, text = _get(base + "/metrics")
                if "tfos_node_skew" in text:
                    strag = s
                    break
            time.sleep(0.3)
        assert strag is not None, "straggler table never named worker-1"
        assert strag["fastest"] == "worker-0"
        rows = {r["node"]: r for r in strag["nodes"]}
        assert rows["worker-1"]["p50_ms"] > rows["worker-0"]["p50_ms"]
        assert rows["worker-0"]["rel"] == 1.0
        cluster.shutdown(grace_secs=2)
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)
