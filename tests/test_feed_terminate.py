"""DataFeed.terminate drain protocol on the shm ring.

The drain must be ended by the producer flock (no feeder mid-partition),
never by a timeout guess: a producer that pauses longer than any consumer
poll interval must not strand its queued data (reference guessed with an
empty+timeout heuristic, TFNode.py:307-329)."""

import os
import threading
import time

import pytest

from tensorflowonspark_tpu.recordio import shm

pytestmark = pytest.mark.skipif(not shm.available(), reason="no native lib")


class FakeMgr:
    """KV + queue stub speaking the manager protocol DataFeed/node use."""

    def __init__(self, kv=None):
        self.kv = dict(kv or {})

    def get(self, key):
        return self.kv.get(key)

    def set(self, key, value):
        self.kv[key] = value

    def get_queue(self, name):
        if name == "error":  # _await_consumption polls this
            class _Empty:
                @staticmethod
                def empty():
                    return True

            return _Empty()
        raise AssertionError("ring path must not touch manager data queues")


def test_producer_active_tracks_flock():
    name = f"/tfosq-term-{os.getpid()}-a"
    ring = shm.ShmQueue(name, capacity=1 << 14, create=True)
    try:
        assert not shm.producer_active(name)
        prod = shm.ShmQueue(name, create=False, producer=True)
        assert shm.producer_active(name)
        prod.close()
        assert not shm.producer_active(name)
    finally:
        ring.close()


def test_terminate_waits_for_slow_producer():
    """A producer stalled >5s mid-partition (longer than the old drain
    heuristic) still gets fully drained before terminate() returns."""
    from tensorflowonspark_tpu.feed import DataFeed

    name = f"/tfosq-term-{os.getpid()}-b"
    ring = shm.ShmQueue(name, capacity=1 << 14, create=True)
    mgr = FakeMgr({"shm_input": name})
    drained = []

    def producer():
        q = shm.ShmQueue(name, create=False, producer=True)
        q.put(["r1"])
        time.sleep(6.0)  # longer than any drain-poll interval
        q.put(["r2"])
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.2)  # let the producer take the flock
    try:
        feed = DataFeed(mgr)
        orig_get = feed._ring.get

        def spy_get(timeout_ms=-1):
            v = orig_get(timeout_ms)
            drained.append(v)
            return v

        feed._ring.get = spy_get
        feed.terminate()
        assert mgr.kv["state"] == "terminating"
        assert ["r1"] in drained and ["r2"] in drained
        assert feed._ring.qsize_bytes() == 0
    finally:
        t.join(10)
        ring.close()


def test_terminate_while_prefetch_thread_blocked():
    """terminate() from the main thread while a prefetch thread is blocked
    inside next_batch must not race the single-consumer ring: the blocked
    get turns into end-of-feed and the drain proceeds under the shared
    lock (the infeed.synchronized early-stop path)."""
    from tensorflowonspark_tpu.feed import DataFeed
    from tensorflowonspark_tpu.infeed import batch_iterator

    name = f"/tfosq-term-{os.getpid()}-d"
    ring = shm.ShmQueue(name, capacity=1 << 16, create=True)
    mgr = FakeMgr({"shm_input": name})
    prod = shm.ShmQueue(name, create=False, producer=True)
    try:
        for i in range(3):
            prod.put([(float(i),)] * 8)

        feed = DataFeed(mgr)
        got = []
        done = threading.Event()

        def consume():
            # 8-record batches: consumes the 3 chunks then BLOCKS on the
            # empty ring (no end-of-feed None was sent)
            for b in batch_iterator(feed, 8):
                got.append(b)
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        deadline = time.time() + 10
        while len(got) < 3 and time.time() < deadline:
            time.sleep(0.05)
        assert len(got) == 3, got
        # consumer is now blocked inside _get_chunk; terminate
        # concurrently while the producer is still mid-partition
        term_done = threading.Event()

        def do_term():
            feed.terminate()
            term_done.set()

        tt = threading.Thread(target=do_term, daemon=True)
        tt.start()
        time.sleep(0.4)  # flag set; consumer has left its pending get
        prod.put([(99.0,)] * 8)  # data the drain must absorb, not consume
        prod.close()  # release the flock so the drain can finish
        assert term_done.wait(10), "terminate did not finish draining"
        assert done.wait(5), "prefetch thread did not exit after terminate"
        assert len(got) == 3  # post-terminate data was drained, not consumed
        assert feed.should_stop()
        assert ring.qsize_bytes() == 0
    finally:
        prod.close()
        ring.close()


def test_feeder_put_bails_on_termination(monkeypatch):
    """A feeder blocked on a full ring notices state='terminating' and
    returns instead of deadlocking against a consumer that stopped
    draining (node.train put loop)."""
    from tensorflowonspark_tpu import node

    name = f"/tfosq-term-{os.getpid()}-c"
    ring = shm.ShmQueue(name, capacity=1 << 12, create=True)
    mgr = FakeMgr({"shm_input": name, "state": "running"})

    stops = []

    class FakeClient:
        def __init__(self, addr):
            pass

        def request_stop(self):
            stops.append(True)

    monkeypatch.setattr(node, "FEED_CHUNK_RECORDS", 4)
    monkeypatch.setattr(node, "_get_manager", lambda *a, **kw: mgr)
    monkeypatch.setattr(node, "read_executor_id", lambda *a, **kw: 0)
    monkeypatch.setattr(node, "get_ip_address", lambda: "127.0.0.1")
    monkeypatch.setattr(node.rendezvous, "Client", FakeClient)

    feeder = node.train({}, {"server_addr": ("127.0.0.1", 0)}, feed_timeout=30)
    records = [b"x" * 256] * 200  # far more than the 4KiB ring holds

    done = threading.Event()

    def run():
        feeder(iter(records))
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(1.0)  # feeder is now blocked on the full ring
    assert not done.is_set()
    mgr.kv["state"] = "terminating"
    assert done.wait(15), "feeder did not bail after termination"
    assert stops, "feeder skipped the STOP handshake"
    ring.close()
