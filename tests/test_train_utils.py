"""utils/train.py: gradient accumulation equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.utils.train import accumulated_value_and_grad


def _loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _setup(n=32, d=4):
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(d,)), jnp.float32),
              "b": jnp.asarray(0.1, jnp.float32)}
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    return params, x, y


@pytest.mark.parametrize("accum", [1, 2, 8])
def test_accumulated_grads_match_full_batch(accum):
    params, x, y = _setup()
    full_loss, full_grads = jax.value_and_grad(_loss)(params, x, y)
    loss, grads = jax.jit(
        accumulated_value_and_grad(_loss, accum))(params, x, y)
    np.testing.assert_allclose(float(loss), float(full_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads, full_grads)


def test_accumulated_has_aux():
    def loss_aux(params, x, y):
        l = _loss(params, x, y)
        return l, {"seen": x.shape[0]}

    params, x, y = _setup()
    (loss, aux), grads = accumulated_value_and_grad(
        loss_aux, 4, has_aux=True)(params, x, y)
    assert aux["seen"] == 8  # per-microbatch aux (last microbatch's)
    full_loss, _ = jax.value_and_grad(_loss)(params, x, y)
    np.testing.assert_allclose(float(loss), float(full_loss), rtol=1e-5)


def test_indivisible_batch_raises():
    params, x, y = _setup(n=30)
    with pytest.raises(ValueError, match="divisible"):
        accumulated_value_and_grad(_loss, 8)(params, x, y)


def test_transformer_accum_matches():
    """End-to-end on the real model: accumulated grads == full-batch."""
    from tensorflowonspark_tpu.models import transformer

    cfg = transformer.Config(vocab_size=64, dim=32, n_layers=2, n_heads=2,
                             max_seq=16, dtype="float32",
                             attn_impl="reference")
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (8, 16)), jnp.int32)

    def loss(p, t):
        return transformer.loss_fn(p, t, cfg)

    full_loss, full_grads = jax.value_and_grad(loss)(params, tokens)
    loss_a, grads_a = jax.jit(
        accumulated_value_and_grad(loss, 4))(params, tokens)
    np.testing.assert_allclose(float(loss_a), float(full_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads_a, full_grads)


def test_resnet_train_step_accum_matches():
    import optax

    from tensorflowonspark_tpu.models import resnet

    params, state = resnet.init(jax.random.PRNGKey(0), depth=20,
                                num_classes=10, width=8, small_inputs=True)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((8, 32, 32, 3), np.float32))
    y = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)

    step1 = resnet.make_train_step(opt, depth=20, small_inputs=True,
                                   compute_dtype=jnp.float32)
    step4 = resnet.make_train_step(opt, depth=20, small_inputs=True,
                                   compute_dtype=jnp.float32, accum_steps=4)
    p1, _, _, l1, _ = step1(params, state, opt_state, x, y)
    p4, _, _, l4, a4 = step4(params, state, opt_state, x, y)
    # BN statistics are per-microbatch under accumulation, so the
    # one-big-batch step only agrees loosely...
    np.testing.assert_allclose(float(l1), float(l4), rtol=5e-2)
    assert 0.0 <= float(a4) <= 1.0

    # ...but a manual microbatch loop (same BN semantics, running stats
    # threaded per microbatch) must match the accumulated step exactly
    from tensorflowonspark_tpu.models import layers as L

    def loss_fn(p, st, xs, ys):
        logits, new_state = resnet.apply(
            p, st, xs, 20, True, True, jnp.float32)
        return L.softmax_cross_entropy(logits, ys), new_state

    grads_sum = jax.tree.map(jnp.zeros_like, params)
    loss_sum = 0.0
    st = state
    for i in range(4):
        (l, st), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, st, x[i * 2:(i + 1) * 2], y[i * 2:(i + 1) * 2])
        loss_sum += float(l)
        grads_sum = jax.tree.map(jnp.add, grads_sum, g)
    import optax as _optax

    updates, _ = opt.update(
        jax.tree.map(lambda g: g / 4, grads_sum), opt_state, params)
    p_ref = _optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(l4), loss_sum / 4, rtol=1e-5)
    # rtol/atol sized for f32 reduction-order variance between the jitted
    # scan and this eager loop (XLA CPU fusion reorders the sums; one
    # build measured 1.2e-5 abs drift through the lr=0.1 update)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5), p4, p_ref)

    # the accumulated step's BN state must equal the sequential chain's
    # final state (EMA advanced once per microbatch, not once per step)
    _, s4, _, _, _ = step4(params, state, opt_state, x, y)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), s4, st)
