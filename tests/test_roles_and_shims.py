"""Role placement (ps/evaluator/chief) + reference API aliases/shims."""

import os
import warnings

import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine


def _role_writer_fn(args, ctx):
    path = os.path.join(args["dir"], f"{ctx.job_name}-{ctx.task_index}")
    with open(path, "w") as f:
        f.write(str(ctx.executor_id))


def test_ps_eval_chief_roles_run_and_stop(tmp_path):
    """num_ps + eval_node + chief template: ps/evaluator run the user fn
    in a background process and block their slot until the driver's
    shutdown control message (reference TFSparkNode.py:411-438
    semantics)."""
    engine = LocalEngine(4)
    try:
        cluster = TFCluster.run(
            engine, _role_writer_fn, {"dir": str(tmp_path)},
            num_executors=4, num_ps=1, eval_node=True,
            master_node="chief", input_mode=InputMode.TENSORFLOW,
        )
        jobs = sorted(m["job_name"] for m in cluster.cluster_info)
        assert jobs == ["chief", "evaluator", "ps", "worker"]
        cluster.shutdown(grace_secs=1)
    finally:
        engine.stop()
    wrote = sorted(os.listdir(tmp_path))
    assert wrote == ["chief-0", "evaluator-0", "ps-0", "worker-0"], wrote


def test_dfutil_camelcase_aliases():
    from tensorflowonspark_tpu import dfutil

    assert dfutil.saveAsTFRecords is dfutil.save_as_tfrecords
    assert dfutil.loadTFRecords is dfutil.load_tfrecords
    assert dfutil.toTFExample is dfutil.to_example
    assert dfutil.fromTFExample is dfutil.from_example
    assert dfutil.inferSchema is dfutil.infer_schema
    assert dfutil.isLoadedDF is dfutil.is_loaded_df


def test_deprecated_tfnode_shims(tmp_path):
    import jax.numpy as jnp

    from tensorflowonspark_tpu import feed

    class Ctx:
        job_name, task_index = "chief", 0
        cluster_spec = {"chief": [{}]}

        def jax_initialize(self):
            return {"coordinator_address": None, "num_processes": 1,
                    "process_id": 0}

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        server = feed.start_cluster_server(Ctx())
        assert any(issubclass(x.category, DeprecationWarning) for x in w)
    with pytest.raises(RuntimeError):
        server.join()

    export_dir = str(tmp_path / "exp")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        feed.export_saved_model(
            export_dir=export_dir, params={"w": jnp.zeros((2,))}, ctx=Ctx()
        )
        assert any(issubclass(x.category, DeprecationWarning) for x in w)
    assert os.path.isdir(export_dir)
