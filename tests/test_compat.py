"""Compat-shim tests (reference surface: compat.py:10-31)."""

import os

import numpy as np

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.node import TFNodeContext
from tensorflowonspark_tpu.utils.checkpoint import load_exported


def _ctx(job_name, task_index=0):
    return TFNodeContext(
        executor_id=task_index,
        job_name=job_name,
        task_index=task_index,
        cluster_spec={},
        default_fs="file://",
        working_dir="/tmp",
        mgr=None,
    )


def test_export_saved_model_chief_only(tmp_path):
    params = {"w": np.ones((2, 2), np.float32)}
    chief_dir = str(tmp_path / "chief")
    worker_dir = str(tmp_path / "worker")

    assert compat.export_saved_model(params, chief_dir, _ctx("chief")) == chief_dir
    assert os.path.exists(os.path.join(chief_dir, "params.npz"))

    # non-chief: no export, and no dummy dir either (unlike the reference)
    assert compat.export_saved_model(params, worker_dir, _ctx("worker", 1)) is None
    assert not os.path.exists(worker_dir)

    loaded, _meta = load_exported(chief_dir)
    np.testing.assert_array_equal(loaded["w"], params["w"])


def test_export_saved_model_unwraps_model_objects(tmp_path):
    class Model:
        params = {"b": np.zeros(3, np.float32)}

    out = compat.export_saved_model(Model(), str(tmp_path / "m"))
    loaded, _ = load_exported(out)
    np.testing.assert_array_equal(loaded["b"], np.zeros(3))


def test_disable_auto_shard_is_passthrough():
    sentinel = object()
    assert compat.disable_auto_shard(sentinel) is sentinel


def test_is_gpu_available_reflects_chip_count(monkeypatch):
    monkeypatch.setenv("TFOS_TPU_CHIPS_PER_HOST", "4")
    assert compat.is_gpu_available() is True
    monkeypatch.setenv("TFOS_TPU_CHIPS_PER_HOST", "0")
    assert compat.is_gpu_available() is False
