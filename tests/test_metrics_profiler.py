"""utils.metrics counters + profiler trace capture + DataFeed wiring."""

import os
import time

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.utils import metrics as M
from tensorflowonspark_tpu.utils import profiler


def test_train_metrics_rates_and_mfu():
    os.environ["TFOS_PEAK_FLOPS"] = "1e9"
    try:
        m = M.TrainMetrics(flops_per_item=1e6)
        m.step()  # arm
        for _ in range(3):
            time.sleep(0.01)
            m.infeed_wait(0.002)
            m.step(items=10)
        rep = m.report()
    finally:
        del os.environ["TFOS_PEAK_FLOPS"]
    assert rep["steps"] == 4 and rep["items"] == 30
    assert rep["step_time_avg_s"] > 0
    assert 0 < rep["infeed_stall_frac"] < 1
    # mfu = items*flops / time / peak — sane positive number
    assert rep["mfu"] > 0


def test_transformer_flops_estimator():
    from tensorflowonspark_tpu.models import transformer

    cfg = transformer.Config(vocab_size=100, dim=64, n_layers=2, n_heads=4,
                             max_seq=128)
    per_tok = M.transformer_flops_per_token(cfg)
    assert per_tok > 6 * 100 * 64 * 2  # at least the embedding term


def test_profiler_trace_writes_events(tmp_path):
    log_dir = str(tmp_path / "trace")
    with profiler.trace(log_dir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    found = []
    for root, _dirs, files in os.walk(log_dir):
        found.extend(os.path.join(root, f) for f in files)
    assert found, "profiler trace produced no files"


def test_datafeed_accounts_infeed_wait():
    from tensorflowonspark_tpu.feed import DataFeed

    class FakeQueue:
        def __init__(self, items):
            self.items = list(items)

        def get(self, block=True, timeout=None):
            time.sleep(0.005)
            return self.items.pop(0)

        def task_done(self):
            pass

    class FakeMgr:
        def __init__(self, items):
            self.q = FakeQueue(items)

        def get(self, key):
            return None  # no shm ring

        def get_queue(self, name):
            return self.q

    m = M.TrainMetrics()
    feed = DataFeed(FakeMgr([[1, 2, 3], None]), metrics=m)
    batch = feed.next_batch(3)
    assert batch == [1, 2, 3]
    assert m.report()["infeed_wait_s"] > 0
