"""Checkpoint/resume: unit roundtrips, orbax async checkpointing, and the
kill-and-resume e2e (SURVEY.md §5: recovery = restart from checkpoint).
"""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu.utils import checkpoint as ckpt


# -- unit: npz format, step math, pytree packing ------------------------------

def test_save_restore_latest_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    assert ckpt.restore_latest(d) == (None, 0)
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.zeros(3)}
    ckpt.save_checkpoint(d, tree, step=7)
    ckpt.save_checkpoint(d, {"w": tree["w"] + 1, "b": tree["b"]}, step=12)
    restored, step = ckpt.restore_latest(d)
    assert step == 12
    np.testing.assert_allclose(restored["w"], tree["w"] + 1)


def test_step_of():
    assert ckpt.step_of("/x/ckpt-00000042.npz") == 42


def test_pack_unpack_optax_state():
    import jax
    import optax

    params = {"w": np.ones((3,), np.float32)}
    opt = optax.sgd(0.1, momentum=0.9)
    st = opt.init(params)
    packed = ckpt.pack_pytree(st)
    assert all(isinstance(v, np.ndarray) for v in packed.values())
    rebuilt = ckpt.unpack_pytree(packed, st)
    assert jax.tree_util.tree_structure(rebuilt) == jax.tree_util.tree_structure(st)
    # roundtrips through save_checkpoint (nested under a dict key)
    assert ckpt._flatten({"opt": packed})


def test_save_restore_remote_fs():
    """Checkpoints and exports must work on fsspec URLs (model_dir on
    gs://... is the north-star workflow; memory:// exercises the same
    code path)."""
    pytest.importorskip("fsspec")
    d = "memory://tfos-ckpt-test/ckpt"
    tree = {"w": np.arange(4, dtype=np.float32), "b": np.zeros(2)}
    ckpt.save_checkpoint(d, tree, step=3)
    ckpt.save_checkpoint(d, {"w": tree["w"] * 2, "b": tree["b"]}, step=9)
    restored, step = ckpt.restore_latest(d)
    assert step == 9
    np.testing.assert_allclose(restored["w"], tree["w"] * 2)
    # keep=3 pruning across saves on the remote store
    for s in (11, 12, 13):
        ckpt.save_checkpoint(d, tree, step=s, keep=2)
    import fsspec

    fs, p = fsspec.core.url_to_fs(d)
    names = [n for n in fs.ls(p, detail=False) if "ckpt-" in n]
    assert len(names) == 2

    e = "memory://tfos-ckpt-test/export"
    ckpt.export_model(e, tree, metadata={"predict": "m:f"})
    params, meta = ckpt.load_exported(e)
    np.testing.assert_allclose(params["w"], tree["w"])
    assert meta["predict"] == "m:f"


def test_async_checkpointer_orbax(tmp_path):
    """The orbax path must actually save and restore (round-1 finding:
    it was an untested 6-line wrapper)."""
    pytest.importorskip("orbax.checkpoint")
    d = str(tmp_path / "orbax")
    mngr = ckpt.AsyncCheckpointer(d, keep=2)
    tree = {"w": np.arange(4, dtype=np.float32), "b": np.float32(3.0)}
    assert mngr.restore_latest() == (None, 0)
    mngr.save(1, tree)
    mngr.save(5, {"w": tree["w"] * 2, "b": tree["b"]})
    mngr.wait()
    assert mngr.latest_step() == 5
    restored, step = mngr.restore_latest()
    assert step == 5
    np.testing.assert_allclose(restored["w"], tree["w"] * 2)
    mngr.close()
    # a fresh manager over the same dir resumes
    again = ckpt.AsyncCheckpointer(d)
    _, step = again.restore_latest()
    assert step == 5
    again.close()


# -- e2e: kill mid-training, restart, resume ---------------------------------

def _resumable_train_fn(args, ctx):
    """Linear-model training that checkpoints every step and (first run)
    crashes partway — the restarted run must pick up where it left off."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.models import linear
    from tensorflowonspark_tpu.utils import checkpoint as C

    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"x": "features", "y": "label"}
    )
    params = linear.init_params()
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)
    step_fn = jax.jit(linear.make_train_step(opt))

    restored, step = C.restore_latest(args["model_dir"])
    if restored is not None:
        params = restored["params"]
        # a stateless optimizer packs to {} and the key vanishes from the
        # npz; unpack from an empty dict rebuilds the empty state
        opt_state = C.unpack_pytree(restored.get("opt", {}), opt_state)
    if C.is_chief(ctx):
        with open(os.path.join(args["model_dir"], "starts.log"), "a") as f:
            f.write(f"{step}\n")

    while not feed.should_stop():
        batch = feed.next_batch(16)
        if not batch["features"]:
            continue
        x = np.asarray(batch["features"], dtype=np.float32)
        y = np.asarray(batch["label"], dtype=np.float32)
        params, opt_state, loss = step_fn(params, opt_state, x, y)
        step += 1
        if C.is_chief(ctx):
            C.save_checkpoint(
                args["model_dir"],
                {"params": params, "opt": C.pack_pytree(opt_state)},
                step, keep=2,
            )
        if args["crash_at"] and step >= args["crash_at"]:
            raise RuntimeError(f"deliberate crash at step {step}")

    if C.is_chief(ctx):
        with open(os.path.join(args["model_dir"], "final.log"), "w") as f:
            f.write(f"{step} {float(loss)}")


@pytest.mark.slow
def test_kill_and_resume(tmp_path):
    """Run 1 crashes at step 3 (after checkpointing); run 2 with the same
    model_dir resumes from the checkpointed step, not from zero, and
    finishes training."""
    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine, TaskError

    model_dir = str(tmp_path)
    rng = np.random.default_rng(7)
    x = rng.random((256, 2)).astype(np.float32)
    y = x @ np.array([3.14, 1.618], dtype=np.float32)
    rows = [(list(map(float, xi)), float(yi)) for xi, yi in zip(x, y)]

    def run_once(crash_at):
        engine = LocalEngine(2, env={
            "JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        try:
            cluster = TFCluster.run(
                engine, _resumable_train_fn,
                {"model_dir": model_dir, "crash_at": crash_at},
                num_executors=2, input_mode=InputMode.SPARK,
                master_node="chief",
            )
            ds = engine.parallelize(rows, 4)
            try:
                cluster.train(ds, num_epochs=2, feed_timeout=20)
                cluster.shutdown(grace_secs=3)
            except (TaskError, SystemExit):
                if crash_at is None:
                    raise
        finally:
            engine.stop()

    run_once(crash_at=3)   # dies mid-training, checkpoints exist
    assert ckpt.latest_checkpoint(model_dir) is not None
    _, step_after_crash = ckpt.restore_latest(model_dir)
    assert step_after_crash >= 3

    run_once(crash_at=None)  # restart: must resume, then finish

    starts = [int(s) for s in
              open(os.path.join(model_dir, "starts.log")).read().split()]
    assert starts[0] == 0, "first run must start from scratch"
    assert starts[-1] >= 3, f"resumed run must continue from checkpoint: {starts}"
    final_step, final_loss = open(
        os.path.join(model_dir, "final.log")).read().split()
    assert int(final_step) > starts[-1]
    assert float(final_loss) < 1.0, "training did not progress after resume"
