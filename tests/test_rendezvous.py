"""Rendezvous unit tests (parity: reference test/test_reservation.py)."""

import os
import threading
from unittest import mock

import pytest

from tensorflowonspark_tpu import rendezvous
from tensorflowonspark_tpu.rendezvous import Client, Reservations, Server


class TestReservations:
    def test_counting(self):
        r = Reservations(3)
        assert not r.done()
        assert r.remaining() == 3
        r.add({"node": 0})
        r.add({"node": 1})
        assert r.remaining() == 1
        assert not r.done()
        r.add({"node": 2})
        assert r.done()
        assert r.remaining() == 0
        assert [m["node"] for m in r.get()] == [0, 1, 2]


class TestServerClient:
    def test_single_registration(self):
        server = Server(1)
        addr = server.start()
        client = Client(addr)
        client.register({"executor_id": 0, "host": "h", "port": 1234})
        got = client.await_reservations(timeout=10)
        assert got == [{"executor_id": 0, "host": "h", "port": 1234}]
        client.request_stop()
        assert server.done.wait(5)
        server.stop()

    def test_concurrent_registration(self):
        n = 4
        server = Server(n)
        addr = server.start()

        def reg(i):
            c = Client(addr)
            c.register({"executor_id": i})
            c.await_reservations(timeout=10)
            c.close()

        threads = [threading.Thread(target=reg, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        got = server.await_reservations(timeout=10)
        assert sorted(m["executor_id"] for m in got) == list(range(n))
        server.stop()

    def test_driver_await_sees_error(self):
        server = Server(2)
        server.start()
        status = {"error": "boom"}
        with pytest.raises(RuntimeError, match="boom"):
            server.await_reservations(status=status, timeout=5)
        server.stop()

    def test_driver_await_timeout(self):
        server = Server(1)
        server.start()
        with pytest.raises(TimeoutError):
            server.await_reservations(timeout=0.3)
        server.stop()


class TestEnvOverrides:
    def test_fixed_host(self):
        with mock.patch.dict(os.environ, {rendezvous.TFOS_SERVER_HOST: "127.0.0.1"}):
            server = Server(1)
            host, port = server.start()
            assert host == "127.0.0.1"
            assert port > 0
            server.stop()

    def test_port_range(self):
        with mock.patch.dict(
            os.environ,
            {
                rendezvous.TFOS_SERVER_HOST: "127.0.0.1",
                rendezvous.TFOS_SERVER_PORT: "27710-27719",
            },
        ):
            s1 = Server(1)
            _, p1 = s1.start()
            assert 27710 <= p1 <= 27719
            s2 = Server(1)
            _, p2 = s2.start()
            assert 27710 <= p2 <= 27719 and p2 != p1
            s1.stop()
            s2.stop()

    def test_port_list(self):
        with mock.patch.dict(
            os.environ,
            {
                rendezvous.TFOS_SERVER_HOST: "127.0.0.1",
                rendezvous.TFOS_SERVER_PORT: "27730,27731",
            },
        ):
            s = Server(1)
            _, p = s.start()
            assert p in (27730, 27731)
            s.stop()

    def test_exhausted_port_range(self):
        with mock.patch.dict(
            os.environ,
            {
                rendezvous.TFOS_SERVER_HOST: "127.0.0.1",
                rendezvous.TFOS_SERVER_PORT: "27740",
            },
        ):
            s1 = Server(1)
            s1.start()
            s2 = Server(1)
            with pytest.raises(OSError):
                s2.start()
            s1.stop()


def test_oversized_frame_is_rejected_and_server_survives():
    """A corrupt/hostile length prefix must not buffer gigabytes on the
    driver: the connection is dropped and legitimate clients still
    register afterwards."""
    import socket
    import struct

    from tensorflowonspark_tpu import rendezvous

    server = rendezvous.Server(1)
    addr = server.start()
    try:
        s = socket.create_connection(addr, timeout=5)
        s.sendall(struct.pack(">I", 0xFFFFFFF0))  # claim a ~4GB frame
        s.sendall(b"junk")
        s.close()

        client = rendezvous.Client(addr)
        client.register({"executor_id": 0, "host": "h", "job_name": "worker",
                         "task_index": 0, "port": 1})
        info = client.await_reservations()
        assert len(info) == 1
        client.close()
    finally:
        server.stop()
