"""Sequence parallelism: ring / Ulysses attention vs single-device reference,
on the 8-virtual-device CPU mesh (the multi-chip test fixture)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu import ops
from tensorflowonspark_tpu.parallel import (
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

from tensorflowonspark_tpu.parallel.ring import shard_map


def _qkv(key, b, s, h, d):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


def _seq_mesh(devs, n=4):
    return Mesh(np.array(devs[:n]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sequence_parallel_matches_reference(eight_devices, impl, causal):
    mesh = _seq_mesh(eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, 8)
    ref = ops.mha_reference(q, k, v, causal=causal)

    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    out = jax.jit(
        shard_map(
            lambda q, k, v: fn(q, k, v, "seq", causal=causal),
            mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads_match(eight_devices):
    mesh = _seq_mesh(eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 32, 2, 8)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    g1 = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(
            ops.mha_reference(q, k, v, causal=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_sequence_parallel_attention_wrapper(eight_devices):
    # 2x2x2 mesh: data x seq x model — the wrapper must place specs on
    # the right axes and return the same sharding it consumed.
    mesh = Mesh(np.array(eight_devices).reshape(2, 2, 2),
                ("data", "seq", "model"))
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 32, 4, 8)
    call = sequence_parallel_attention(mesh, "ring", causal=True)
    spec = NamedSharding(mesh, P("data", "seq", "model", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(call)(qs, ks, vs)
    ref = ops.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_permutation_roundtrip():
    from tensorflowonspark_tpu.parallel import (
        inverse_permutation, zigzag_permutation,
    )

    perm = zigzag_permutation(32, 4)  # 8 stripes of 4
    assert sorted(np.asarray(perm).tolist()) == list(range(32))
    # device 0's shard = stripes (0, 7), device 1's = (1, 6), ...
    assert np.asarray(perm)[:8].tolist() == [0, 1, 2, 3, 28, 29, 30, 31]
    inv = inverse_permutation(perm)
    x = np.arange(32)
    np.testing.assert_array_equal(x[np.asarray(perm)][np.asarray(inv)], x)


@pytest.mark.parametrize("causal", [False, True])
def test_zigzag_ring_matches_reference(eight_devices, causal):
    from tensorflowonspark_tpu.parallel import (
        inverse_permutation, zigzag_permutation, zigzag_ring_attention,
    )

    mesh = _seq_mesh(eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 64, 4, 8)
    ref = ops.mha_reference(q, k, v, causal=causal)

    perm = zigzag_permutation(64, 4)
    inv = inverse_permutation(perm)
    zz = jax.jit(
        shard_map(
            lambda q, k, v: zigzag_ring_attention(
                q, k, v, "seq", causal=causal),
            mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
    )
    out = zz(q[:, perm], k[:, perm], v[:, perm])[:, inv]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_ring_grads_match(eight_devices):
    from tensorflowonspark_tpu.parallel import (
        inverse_permutation, zigzag_permutation, zigzag_ring_attention,
    )

    mesh = _seq_mesh(eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 2, 8)
    perm = zigzag_permutation(32, 4)
    inv = inverse_permutation(perm)

    zz = shard_map(
        lambda q, k, v: zigzag_ring_attention(q, k, v, "seq", causal=True),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )

    def loss_zz(q, k, v):
        return jnp.sum(zz(q[:, perm], k[:, perm], v[:, perm])[:, inv] ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ops.mha_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_zigzag_end_to_end_lm_training_matches(eight_devices):
    """Production zigzag: permuted tokens + explicit positions/labels +
    zigzag attention must give the SAME loss and gradients as the
    standard contiguous path — no per-layer gathers needed."""
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.parallel import (
        sequence_parallel_attention, zigzag_permutation,
    )

    mesh = Mesh(np.array(eight_devices[:4]).reshape(1, 4, 1),
                ("data", "seq", "model"))
    cfg = transformer.Config(vocab_size=64, dim=32, n_layers=2, n_heads=2,
                             max_seq=32, dtype="float32",
                             attn_impl="reference")
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32)

    base_loss, base_grads = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, cfg)

    perm = zigzag_permutation(32, 4)
    toks_p, labels_p, positions = transformer.zigzag_lm_batch(tokens, perm)
    zz_attn = sequence_parallel_attention(mesh, "zigzag", causal=True)

    def zz_loss(p, t):
        return transformer.loss_fn(
            p, t, cfg, attn_fn=zz_attn, labels=labels_p,
            positions=positions)

    zz_l, zz_grads = jax.value_and_grad(zz_loss)(params, toks_p)
    np.testing.assert_allclose(float(zz_l), float(base_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        zz_grads, base_grads)
