"""Model + sharding unit tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def cpu_devices():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= 8
    return devs[:8]


def test_mnist_step_learns():
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import mnist

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    step = jax.jit(mnist.make_train_step(opt))
    images, labels = mnist.synthetic_batch(jax.random.PRNGKey(1), 128)
    losses = []
    for _ in range(15):
        params, opt_state, loss, _ = step(params, opt_state, images, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("depth,small,size,classes", [
    (50, False, 64, 1000),
    (56, True, 32, 10),
])
def test_resnet_shapes(depth, small, size, classes):
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import resnet

    width = 16 if small else 64
    params, state = resnet.init(
        jax.random.PRNGKey(0), depth=depth, num_classes=classes,
        width=width, small_inputs=small,
    )
    x = jnp.ones((2, size, size, 3), jnp.float32)
    logits, new_state = resnet.apply(
        params, state, x, depth=depth, train=True, small_inputs=small
    )
    assert logits.shape == (2, classes)
    assert logits.dtype == jnp.float32
    # running stats updated in train mode
    stem = new_state["bn_stem"]["mean"]
    assert not np.allclose(np.asarray(stem), 0.0)


def test_resnet_stem_s2d_exact():
    """The space-to-depth stem must compute exactly the 7x7/s2 conv
    (MXU-tiling transform, resnet._stem_space_to_depth)."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import layers as L
    from tensorflowonspark_tpu.models import resnet

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((7, 7, 3, 16)) * 0.1, jnp.float32)
    ref = L.conv({"w": w}, x, stride=2)
    s2d = resnet._stem_space_to_depth(w, x)
    assert ref.shape == s2d.shape
    np.testing.assert_allclose(np.asarray(ref), np.asarray(s2d), atol=1e-4)
    # end-to-end: apply() with and without the transform agree
    params, state = resnet.init(jax.random.PRNGKey(0), depth=18,
                                num_classes=10)
    img = jnp.asarray(rng.standard_normal((2, 64, 64, 3)), jnp.float32)
    a, _ = resnet.apply(params, state, img, depth=18, train=False,
                        compute_dtype=jnp.float32, stem_s2d=False)
    b, _ = resnet.apply(params, state, img, depth=18, train=False,
                        compute_dtype=jnp.float32, stem_s2d=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_resnet56_cifar_train_step(cpu_devices):
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import resnet
    from tensorflowonspark_tpu.parallel import (
        batch_sharding, make_mesh, shard_train_state,
    )

    mesh = make_mesh({"data": 4, "fsdp": 2}, devices=cpu_devices)
    params, state = resnet.init(
        jax.random.PRNGKey(0), depth=20, num_classes=10, width=16,
        small_inputs=True,
    )
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    (params, state, opt_state), (p_sh, s_sh, o_sh) = shard_train_state(
        mesh, params, state, opt_state
    )
    step = jax.jit(
        resnet.make_train_step(opt, depth=20, small_inputs=True),
        in_shardings=(p_sh, s_sh, o_sh, batch_sharding(mesh), batch_sharding(mesh)),
        out_shardings=(p_sh, s_sh, o_sh, None, None),
    )
    x = jnp.ones((16, 32, 32, 3), jnp.float32)
    y = jnp.asarray(np.arange(16) % 10, jnp.int32)
    params, state, opt_state, loss, acc = step(params, state, opt_state, x, y)
    assert np.isfinite(float(loss))


def test_fsdp_sharding_rules(cpu_devices):
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.parallel import fsdp_sharding, make_mesh

    mesh = make_mesh({"data": 2, "fsdp": 4}, devices=cpu_devices)
    tree = {
        "big": jnp.zeros((256, 128)),     # shardable on dim 0 (256 % 4 == 0)
        "small": jnp.zeros((8,)),          # below min size -> replicated
        "odd": jnp.zeros((510, 129)),      # big but indivisible -> replicated
    }
    sh = fsdp_sharding(mesh, tree, min_shard_elems=64)
    assert sh["big"].spec == jax.sharding.PartitionSpec("fsdp", None)
    assert sh["small"].spec == jax.sharding.PartitionSpec()
    assert sh["odd"].spec == jax.sharding.PartitionSpec()


def test_batchnorm_fused_vjp_parity():
    """The custom-VJP BN core must match the plain autodiff path exactly
    (same math, f32) in value, running stats, and all three gradients."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import layers as L

    key = jax.random.PRNGKey(42)
    x = jax.random.normal(key, (8, 6, 6, 16), jnp.float32) * 3.0 + 1.5
    params = {"scale": jnp.linspace(0.5, 2.0, 16),
              "bias": jnp.linspace(-1.0, 1.0, 16)}
    state = {"mean": jnp.zeros(16), "var": jnp.ones(16)}

    def loss(p, x, fused):
        y, new = L.batchnorm(p, state, x, train=True, fused=fused)
        # touch y nonlinearly AND the EMA state so every output is used
        return (jnp.sum(jnp.tanh(y)) + jnp.sum(new["mean"])
                + jnp.sum(new["var"]))

    for fused in (True, False):
        yv, newv = L.batchnorm(params, state, x, train=True, fused=fused)
        if fused:
            y_f, new_f = yv, newv
        else:
            np.testing.assert_allclose(yv, y_f, rtol=1e-6, atol=1e-6)
            np.testing.assert_allclose(newv["mean"], new_f["mean"], rtol=1e-6)
            np.testing.assert_allclose(newv["var"], new_f["var"], rtol=1e-6)

    gf = jax.grad(loss, argnums=(0, 1))(params, x, True)
    gp = jax.grad(loss, argnums=(0, 1))(params, x, False)
    np.testing.assert_allclose(gf[0]["scale"], gp[0]["scale"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[0]["bias"], gp[0]["bias"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[1], gp[1], rtol=1e-5, atol=1e-5)


def test_batchnorm_relu_fused_vjp_parity():
    """The combined BN→ReLU custom VJP must match relu(batchnorm(...))
    in value, running stats, and all gradients — including jnp.maximum's
    1/2-subgradient convention where the pre-activation is exactly 0."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import layers as L

    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (8, 6, 6, 16), jnp.float32) * 2.0 - 0.5
    # scale=0 on some channels forces pre-activation == 0 everywhere
    # there, exercising the tie path of the recomputed gate
    params = {"scale": jnp.linspace(0.5, 2.0, 16).at[3].set(0.0).at[11].set(0.0),
              "bias": jnp.linspace(-1.0, 1.0, 16).at[3].set(0.0).at[11].set(0.0)}
    state = {"mean": jnp.zeros(16), "var": jnp.ones(16)}

    def loss(p, x, fused):
        y, new = L.batchnorm_relu(p, state, x, train=True, fused=fused)
        return (jnp.sum(jnp.tanh(y)) + jnp.sum(new["mean"])
                + jnp.sum(new["var"]))

    y_f, new_f = L.batchnorm_relu(params, state, x, train=True, fused=True)
    y_p, new_p = L.batchnorm_relu(params, state, x, train=True, fused=False)
    np.testing.assert_allclose(y_f, y_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(new_f["mean"], new_p["mean"], rtol=1e-6)
    np.testing.assert_allclose(new_f["var"], new_p["var"], rtol=1e-6)
    assert float(jnp.min(y_f)) >= 0.0

    gf = jax.grad(loss, argnums=(0, 1))(params, x, True)
    gp = jax.grad(loss, argnums=(0, 1))(params, x, False)
    np.testing.assert_allclose(gf[0]["scale"], gp[0]["scale"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[0]["bias"], gp[0]["bias"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[1], gp[1], rtol=1e-5, atol=1e-5)
    # eval mode must be the plain path (identical either way)
    ye, _ = L.batchnorm_relu(params, state, x, train=False, fused=True)
    yep, _ = L.batchnorm_relu(params, state, x, train=False, fused=False)
    np.testing.assert_allclose(ye, yep, rtol=0, atol=0)


def test_batchnorm_relu6_fused_vjp_parity():
    """BN→ReLU6 fused VJP vs jax.nn.relu6(batchnorm(...)): value,
    stats, gradients — including both saturation boundaries, where
    jax.nn.relu6's gradient is exactly 0 (strict inequalities)."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import layers as L

    key = jax.random.PRNGKey(11)
    x = jax.random.normal(key, (6, 4, 4, 8), jnp.float32) * 4.0 + 2.0
    # scale=0/bias=0 -> pre==0 everywhere on ch 1 (lower tie);
    # scale=0/bias=6 -> pre==6 everywhere on ch 5 (upper tie)
    params = {"scale": jnp.linspace(0.5, 2.0, 8).at[1].set(0.0).at[5].set(0.0),
              "bias": jnp.zeros(8).at[5].set(6.0)}
    state = {"mean": jnp.zeros(8), "var": jnp.ones(8)}

    def loss(p, x, fused):
        y, new = L.batchnorm_relu6(p, state, x, train=True, fused=fused)
        return (jnp.sum(jnp.tanh(y)) + jnp.sum(new["mean"])
                + jnp.sum(new["var"]))

    y_f, new_f = L.batchnorm_relu6(params, state, x, train=True, fused=True)
    y_p, new_p = L.batchnorm_relu6(params, state, x, train=True, fused=False)
    np.testing.assert_allclose(y_f, y_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(new_f["mean"], new_p["mean"], rtol=1e-6)
    assert float(jnp.min(y_f)) >= 0.0 and float(jnp.max(y_f)) <= 6.0

    gf = jax.grad(loss, argnums=(0, 1))(params, x, True)
    gp = jax.grad(loss, argnums=(0, 1))(params, x, False)
    np.testing.assert_allclose(gf[0]["scale"], gp[0]["scale"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[0]["bias"], gp[0]["bias"], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gf[1], gp[1], rtol=1e-5, atol=1e-5)


def test_batchnorm_add_relu_fused_vjp_parity():
    """relu(bn(x) + shortcut) fused VJP vs the plain path: value,
    running stats, and gradients for x, shortcut, scale, bias —
    including the tie case via zeroed channels."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import layers as L

    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (4, 5, 5, 8), jnp.float32) * 1.5
    shortcut = jax.random.normal(k2, (4, 5, 5, 8), jnp.float32)
    # a zeroed channel in BOTH scale/bias and shortcut → pre exactly 0
    shortcut = shortcut.at[..., 2].set(0.0)
    params = {"scale": jnp.linspace(0.5, 1.5, 8).at[2].set(0.0),
              "bias": jnp.linspace(-0.5, 0.5, 8).at[2].set(0.0)}
    state = {"mean": jnp.zeros(8), "var": jnp.ones(8)}

    def loss(p, x, sc, fused):
        y, new = L.batchnorm_add_relu(p, state, x, sc, train=True,
                                      fused=fused)
        return (jnp.sum(jnp.tanh(y)) + jnp.sum(new["mean"])
                + jnp.sum(new["var"]))

    y_f, new_f = L.batchnorm_add_relu(params, state, x, shortcut,
                                      train=True, fused=True)
    y_p, new_p = L.batchnorm_add_relu(params, state, x, shortcut,
                                      train=True, fused=False)
    np.testing.assert_allclose(y_f, y_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(new_f["mean"], new_p["mean"], rtol=1e-6)
    np.testing.assert_allclose(new_f["var"], new_p["var"], rtol=1e-6)
    assert float(jnp.min(y_f)) >= 0.0

    gf = jax.grad(loss, argnums=(0, 1, 2))(params, x, shortcut, True)
    gp = jax.grad(loss, argnums=(0, 1, 2))(params, x, shortcut, False)
    for a, b in ((gf[0]["scale"], gp[0]["scale"]),
                 (gf[0]["bias"], gp[0]["bias"]),
                 (gf[1], gp[1]), (gf[2], gp[2])):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    # eval mode: identical plain path either way
    ye, _ = L.batchnorm_add_relu(params, state, x, shortcut, train=False,
                                 fused=True)
    yep, _ = L.batchnorm_add_relu(params, state, x, shortcut, train=False,
                                  fused=False)
    np.testing.assert_allclose(ye, yep, rtol=0, atol=0)


def test_fused_bn_family_under_remat():
    """The three fused-BN custom VJPs must compose with jax.checkpoint
    (the sweep's *_remat_bnf configs): same loss with and without remat."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import resnet

    params, state = resnet.init(jax.random.PRNGKey(0), depth=20,
                                num_classes=10, width=16, small_inputs=True)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((8, 32, 32, 3), np.float32))
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    losses = {}
    for remat in (False, True):
        step = jax.jit(resnet.make_train_step(
            opt, depth=20, small_inputs=True, remat=remat, bn_fused=True))
        _, _, _, loss, _ = step(params, state, opt_state, images, labels)
        losses[remat] = float(loss)
    assert abs(losses[True] - losses[False]) < 1e-2, losses


def test_batchnorm_fused_bf16_train_step_parity():
    """Full ResNet train step: fused-BN gradients track the autodiff path
    in bf16 within bf16 noise, and the step still learns."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import resnet

    params, state = resnet.init(jax.random.PRNGKey(0), depth=20,
                                num_classes=10, width=16, small_inputs=True)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.random((16, 32, 32, 3), np.float32))
    labels = jnp.asarray(rng.integers(0, 10, 16), jnp.int32)

    losses = {}
    for fused in (True, False):
        step = jax.jit(resnet.make_train_step(
            opt, depth=20, small_inputs=True, bn_fused=fused))
        p, s, o = params, state, opt_state
        ls = []
        for _ in range(8):
            p, s, o, loss, _ = step(p, s, o, images, labels)
            ls.append(float(loss))
        losses[fused] = ls
    # identical math modulo bf16 rounding: first-step losses must agree
    # tightly, trajectories loosely, and both must learn
    assert abs(losses[True][0] - losses[False][0]) < 1e-2
    assert abs(losses[True][-1] - losses[False][-1]) < 0.3
    assert losses[True][-1] < losses[True][0]
