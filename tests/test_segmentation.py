"""Segmentation family: shape contract, train step, sharded batch."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu.models import segmentation
from tensorflowonspark_tpu.parallel import make_mesh


def _batch(key, b=2, hw=32, classes=3):
    ki, km = jax.random.split(key)
    images = jax.random.normal(ki, (b, hw, hw, 3))
    masks = jax.random.randint(km, (b, hw, hw), 0, classes)
    return images, masks


def test_logits_shape_matches_input_resolution():
    params, state = segmentation.init(jax.random.PRNGKey(0), num_classes=3)
    images, _ = _batch(jax.random.PRNGKey(1))
    logits, ns = segmentation.apply(params, state, images, train=True)
    assert logits.shape == (2, 32, 32, 3)
    assert set(ns) == set(state)


def test_train_step_decreases_loss():
    params, state = segmentation.init(
        jax.random.PRNGKey(0), num_classes=3, width=0.5
    )
    images, masks = _batch(jax.random.PRNGKey(1))
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(segmentation.make_train_step(opt))
    first = None
    for _ in range(5):
        params, state, opt_state, loss = step(
            params, state, opt_state, images, masks
        )
        first = first if first is not None else float(loss)
    assert float(loss) < first, (first, float(loss))


def test_data_parallel_step_on_mesh(eight_devices):
    mesh = make_mesh({"data": 4}, devices=eight_devices[:4])
    params, state = segmentation.init(
        jax.random.PRNGKey(0), num_classes=3, width=0.5
    )
    images, masks = _batch(jax.random.PRNGKey(1), b=8)
    bsh = NamedSharding(mesh, P("data"))
    images = jax.device_put(images, bsh)
    masks = jax.device_put(masks, bsh)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(segmentation.make_train_step(opt))
    params, state, opt_state, loss = step(
        params, state, opt_state, images, masks
    )
    assert np.isfinite(float(loss))
