"""examples/mnist/mnist_eval.py: the sidecar-evaluator example
(eval_node=True) runs end-to-end on a CPU LocalEngine.

Closes VERDICT r4 missing #2 — the reference demonstrates the evaluator
role in a runnable example (reference
examples/mnist/estimator/mnist_tf.py:107); until now eval_node existed
only in the cluster API and role-placement tests.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_mnist_eval_example_e2e(tmp_path):
    model_dir = tmp_path / "model"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TFOS_", "JAX_", "XLA_"))}
    env.update(PYTHONPATH="", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/mnist/mnist_eval.py"),
         "--cluster_size", "3", "--steps", "30", "--ckpt_steps", "10",
         "--num_examples", "512", "--model_dir", str(model_dir)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    evals = [json.loads(ln)
             for ln in (model_dir / "eval_results.jsonl").read_text().splitlines()]
    steps = [e["step"] for e in evals]
    # a sidecar evaluator only guarantees the NEWEST checkpoint: strictly
    # increasing steps, and the final step is always drained before DONE
    # is honored (the chief blocks on the EVAL_DONE ack)
    assert steps == sorted(set(steps)) and steps, evals
    assert steps[-1] == 30, evals
    assert all(0.0 <= e["accuracy"] <= 1.0 for e in evals)
    assert (model_dir / "DONE").exists()
    assert "evaluator: DONE" in proc.stdout
