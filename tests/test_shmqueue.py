"""Shared-memory ring queue tests: cross-process, wrap-around, EOF."""

import multiprocessing as mp
import os
import time

import pytest

from tensorflowonspark_tpu.recordio import shm

pytestmark = pytest.mark.skipif(not shm.available(), reason="no native lib")


def test_basic_roundtrip():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-a", capacity=1 << 16, create=True)
    try:
        q.put({"x": 1, "data": b"abc"})
        q.put_bytes(b"raw")
        q.put_bytes(b"")  # empty payload is data, not EOF
        assert q.get() == {"x": 1, "data": b"abc"}
        assert q.get_bytes() == b"raw"
        assert q.get_bytes() == b""
        q.close_write()
        assert q.get() is None  # EOF after close + drain
    finally:
        q.close()


def test_wraparound_many_messages():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-b", capacity=1 << 12, create=True)
    try:
        payload = b"z" * 500
        for i in range(100):  # far more data than capacity; interleave
            q.put_bytes(payload + str(i).encode(), timeout_ms=1000)
            got = q.get_bytes(timeout_ms=1000)
            assert got == payload + str(i).encode()
    finally:
        q.close()


def test_full_queue_times_out():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-c", capacity=1 << 12, create=True)
    try:
        with pytest.raises(ValueError):
            q.put_bytes(b"x" * (1 << 13))  # bigger than ring
        q.put_bytes(b"x" * 3000)
        with pytest.raises(TimeoutError):
            q.put_bytes(b"y" * 3000, timeout_ms=100)
    finally:
        q.close()


def _producer(name, n):
    q = shm.ShmQueue(name, create=False)
    for i in range(n):
        q.put_bytes(b"msg-%06d" % i)
    q.close_write()
    q.close()


def test_cross_process_stream():
    name = f"/tfosq-test-{os.getpid()}-d"
    q = shm.ShmQueue(name, capacity=1 << 14, create=True)
    try:
        n = 5000
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_producer, args=(name, n))
        p.start()
        got = 0
        while True:
            data = q.get_bytes(timeout_ms=30000)
            if data is None:
                break
            assert data == b"msg-%06d" % got
            got += 1
        assert got == n
        p.join(10)
        assert p.exitcode == 0
    finally:
        q.close()


def _make_chunk(n=24, hw=6):
    import numpy as np

    from tensorflowonspark_tpu import node as tfnode

    rng = np.random.default_rng(0)
    rows = [(rng.integers(0, 256, (hw, hw, 3), dtype=np.uint8), int(i))
            for i in range(n)]
    enc = tfnode._make_chunk_encoder()
    chunk = enc(rows)
    from tensorflowonspark_tpu import marker

    assert isinstance(chunk, marker.ColumnChunk)  # precondition
    return rows, chunk


def test_columnar_fast_path_roundtrip():
    """The round-4 scatter-gather wire (put -> shq_push_iov -> TFC frame
    -> shq_peek_len/shq_pop_into -> _decode_columnar): exact bytes back,
    shapes metadata intact, every column 8-byte ALIGNED (views over the
    popped buffer must not hit numpy's unaligned paths), and legacy
    pickled messages coexist on the same ring."""
    import numpy as np

    from tensorflowonspark_tpu import marker

    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-f", capacity=1 << 22,
                     create=True)
    try:
        rows, chunk = _make_chunk()
        q.put(chunk)
        q.put(["legacy", ("row", 1)])     # classic pickle, same ring
        q.put(marker.EndPartition())
        q.put(None)

        got = q.get(timeout_ms=5000)
        assert isinstance(got, marker.ColumnChunk)
        assert got.spec == chunk.spec and got.shapes == chunk.shapes
        for a, b in zip(got.columns, chunk.columns):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)
            assert a.ctypes.data % 8 == 0, "column view not 8-byte aligned"
        # views share one buffer (zero-copy decode), not fresh copies
        assert got.columns[0].base is not None

        assert q.get(timeout_ms=5000) == ["legacy", ("row", 1)]
        assert isinstance(q.get(timeout_ms=5000), marker.EndPartition)
        assert q.get(timeout_ms=5000) is None  # classic-pickle None
    finally:
        q.close()


def test_columnar_fast_path_wraparound_stream():
    """Many columnar frames through a ring smaller than the total volume
    (wrap-around inside the iov push) — every frame decodes exactly."""
    import numpy as np

    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-g", capacity=1 << 16,
                     create=True)
    try:
        _, chunk = _make_chunk(n=12, hw=4)
        for i in range(200):
            q.put(chunk, timeout_ms=2000)
            got = q.get(timeout_ms=2000)
            for a, b in zip(got.columns, chunk.columns):
                np.testing.assert_array_equal(a, b)
    finally:
        q.close()


def _columnar_producer(name, n):
    q = shm.ShmQueue(name, create=False, producer=True)
    _, chunk = _make_chunk(n=16, hw=5)
    for _ in range(n):
        q.put(chunk, timeout_ms=30000)
    q.put(None)
    q.close_write()
    q.close()


def test_columnar_cross_process_stream():
    """Producer process pushes ColumnChunks via the iov fast path; this
    process decodes them — the exact transport the fed bench lane uses."""
    import numpy as np

    name = f"/tfosq-test-{os.getpid()}-h"
    q = shm.ShmQueue(name, capacity=1 << 20, create=True)
    try:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_columnar_producer, args=(name, 50))
        p.start()
        _, want = _make_chunk(n=16, hw=5)
        got_n = 0
        while True:
            item = q.get(timeout_ms=30000)
            if item is None:
                break
            for a, b in zip(item.columns, want.columns):
                np.testing.assert_array_equal(a, b)
            got_n += 1
        assert got_n == 50
        p.join(10)
        assert p.exitcode == 0
    finally:
        q.close()


def test_throughput_smoke():
    """The ring should clear 100 MB/s same-process (sanity, not a
    bench — real hardware does GB/s).  Best-of-3: a single scheduler
    stall on a loaded box must not flake a functional suite."""
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-e", capacity=64 << 20, create=True)
    try:
        chunk = b"x" * (1 << 20)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(64):
                q.put_bytes(chunk)
                q.get_bytes()
            dt = time.perf_counter() - t0
            best = max(best, 64 / dt)
            if best > 100:
                break
        assert best > 100, f"shm ring too slow: {best:.0f} MB/s"
    finally:
        q.close()
