"""Shared-memory ring queue tests: cross-process, wrap-around, EOF."""

import multiprocessing as mp
import os
import time

import pytest

from tensorflowonspark_tpu.recordio import shm

pytestmark = pytest.mark.skipif(not shm.available(), reason="no native lib")


def test_basic_roundtrip():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-a", capacity=1 << 16, create=True)
    try:
        q.put({"x": 1, "data": b"abc"})
        q.put_bytes(b"raw")
        q.put_bytes(b"")  # empty payload is data, not EOF
        assert q.get() == {"x": 1, "data": b"abc"}
        assert q.get_bytes() == b"raw"
        assert q.get_bytes() == b""
        q.close_write()
        assert q.get() is None  # EOF after close + drain
    finally:
        q.close()


def test_wraparound_many_messages():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-b", capacity=1 << 12, create=True)
    try:
        payload = b"z" * 500
        for i in range(100):  # far more data than capacity; interleave
            q.put_bytes(payload + str(i).encode(), timeout_ms=1000)
            got = q.get_bytes(timeout_ms=1000)
            assert got == payload + str(i).encode()
    finally:
        q.close()


def test_full_queue_times_out():
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-c", capacity=1 << 12, create=True)
    try:
        with pytest.raises(ValueError):
            q.put_bytes(b"x" * (1 << 13))  # bigger than ring
        q.put_bytes(b"x" * 3000)
        with pytest.raises(TimeoutError):
            q.put_bytes(b"y" * 3000, timeout_ms=100)
    finally:
        q.close()


def _producer(name, n):
    q = shm.ShmQueue(name, create=False)
    for i in range(n):
        q.put_bytes(b"msg-%06d" % i)
    q.close_write()
    q.close()


def test_cross_process_stream():
    name = f"/tfosq-test-{os.getpid()}-d"
    q = shm.ShmQueue(name, capacity=1 << 14, create=True)
    try:
        n = 5000
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_producer, args=(name, n))
        p.start()
        got = 0
        while True:
            data = q.get_bytes(timeout_ms=30000)
            if data is None:
                break
            assert data == b"msg-%06d" % got
            got += 1
        assert got == n
        p.join(10)
        assert p.exitcode == 0
    finally:
        q.close()


def test_throughput_smoke():
    """The ring should clear 100 MB/s same-process (sanity, not a
    bench — real hardware does GB/s).  Best-of-3: a single scheduler
    stall on a loaded box must not flake a functional suite."""
    q = shm.ShmQueue(f"/tfosq-test-{os.getpid()}-e", capacity=64 << 20, create=True)
    try:
        chunk = b"x" * (1 << 20)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(64):
                q.put_bytes(chunk)
                q.get_bytes()
            dt = time.perf_counter() - t0
            best = max(best, 64 / dt)
            if best > 100:
                break
        assert best > 100, f"shm ring too slow: {best:.0f} MB/s"
    finally:
        q.close()
