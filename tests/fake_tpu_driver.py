"""Dry-run driver for the perf sweep scripts (VERDICT r3 next #6a).

Runs scripts/sweep_resnet.py or scripts/sweep_transformer.py in-process
with tiny shapes (TFOS_SWEEP_TINY, set by the caller) and — when asked —
a FAKED TPU device identity, so the promote/merge/refusal branches that
normally only execute during a live chip claim are exercised off-chip.
Real file with a __main__ guard (spawn start method; CLAUDE.md).

Usage: python fake_tpu_driver.py {sweep_resnet|sweep_transformer}
                                 {faketpu|cpu} [script args...]
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeTpuDevice:
    """Quacks like a jax TPU device for identity checks; computation
    still runs on the genuine default (CPU) backend."""

    platform = "tpu"
    device_kind = "TPU v5e (faked for dry-run)"
    id = 0

    def __repr__(self):
        return "FakeTpuDevice(TPU v5e, dry-run)"


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    which, mode, rest = sys.argv[1], sys.argv[2], sys.argv[3:]
    assert which in ("sweep_resnet", "sweep_transformer"), which
    assert mode in ("faketpu", "cpu"), mode

    import jax

    if mode == "faketpu":
        jax.devices = lambda *a, **k: [FakeTpuDevice()]

    mod = _load_script(which)
    sys.argv = [which + ".py"] + rest
    mod.main()


if __name__ == "__main__":
    main()
