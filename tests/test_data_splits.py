"""Dynamic-split data service (data/splits.py + DynamicDataService),
shared epoch cache (data/cache.py), and the stall autoscaler
(data/autoscale.py).

The guarantees under test are the ISSUE-19 acceptance criteria:

- FCFS split dispatch with discovered eof and pure-arithmetic epochs;
- exactly-once per split id on the PDONE/PQUERY ledger: exact cover,
  permutation-invariant across concurrent claimants, preserved under a
  provider requeue of a dead claimant's splits;
- consumer-side dedup of a re-served split's already-consumed prefix;
- the epoch cache decodes once (memory + spill) and is shared by
  signature;
- the autoscaler's hysteresis decision kernel.

The full-cluster SIGKILL e2e (worker killed mid-split, engine respawn,
record multiset vs the single-process oracle) is the slow lane's
``test_dynamic_service_survives_worker_kill``.
"""

import collections
import os
import secrets
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import data, rendezvous
from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.actors import liveness
from tensorflowonspark_tpu.data import autoscale as ascale
from tensorflowonspark_tpu.data import cache as dcache
from tensorflowonspark_tpu.data import service as dsvc
from tensorflowonspark_tpu.data import splits as S
from tensorflowonspark_tpu.feed import DataFeed
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.data


def _arrays(n, width=4):
    x = (np.arange(n * width, dtype=np.float32).reshape(n, width)) / 7.0
    y = np.arange(n, dtype=np.int64)
    return {"x": x, "y": y}


def _trainer_meta(m, executor_id, authkey):
    return {"executor_id": executor_id, "host": "localhost",
            "job_name": "worker", "addr": list(m.address),
            "authkey": authkey.hex()}


def _drain_ids(q):
    ids = []
    while not q.empty():
        c = q.get()
        q.task_done()
        if c is not None:
            ids.extend(int(v) for v in c.columns[1])
    return ids


# -- sid packing -------------------------------------------------------------


def test_sid_part_roundtrip():
    for sid in [(0, 0), (0, 7), (3, 0), (12, 2**31 + 5)]:
        assert S.part_to_sid(S.sid_to_part(sid)) == sid
    # distinct sids -> distinct ledger parts (the exactly-once key)
    parts = {S.sid_to_part((e, k)) for e in range(4) for k in range(100)}
    assert len(parts) == 400


# -- provider protocol -------------------------------------------------------


class _Board:
    """Board over a bare test manager (no ActorSystem needed)."""

    def __init__(self, qname="input"):
        self.authkey = secrets.token_bytes(8)
        self.mgr = tfmanager.start(self.authkey, [])
        self.board = S.SplitBoard(self.mgr, qname)

    def close(self):
        self.mgr.shutdown()


class _Ctx:
    """Minimal ActorContext stand-in for driving SplitProvider inline."""

    def __init__(self, mgr):
        self.mgr = mgr
        self._kv = {}

    def kv_get(self, key):
        return self._kv.get(key)

    def kv_set(self, key, value):
        self._kv[key] = value


def _provider(ctx, server_addr, num_epochs=1, window=4, stale_secs=None):
    p = S.SplitProvider("input", server_addr=server_addr,
                        num_epochs=num_epochs, window=window,
                        stale_secs=stale_secs)
    p.on_start(ctx)
    return p


def test_provider_posts_fcfs_discovers_eof_and_completes():
    """One simulated worker: FCFS order, eof clamp at the discovered
    split count, epoch advance by id arithmetic, ledger-driven
    completion."""
    tb = _Board()
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        ctx = _Ctx(tb.mgr)
        p = _provider(ctx, addr, num_epochs=2, window=4)
        client = rendezvous.Client(addr)
        got = []
        for _ in range(200):
            p.on_tick(ctx)
            if tb.board.complete():
                break
            sid = tb.board.claim_next()
            if sid is None:
                continue
            tb.board.set_claim(sid, 0)
            if sid[1] >= 3:            # the dataset "has 3 splits"
                tb.board.set_eof(sid[1])
            else:
                got.append(sid)
            client.partition_done(S.split_feed("input"), S.sid_to_part(sid))
        assert tb.board.complete(), "provider never declared completion"
        assert tb.board.eof() == 3
        assert got[:3] == [(0, 0), (0, 1), (0, 2)]  # FCFS posting order
        assert sorted(got) == [(e, k) for e in range(2) for k in range(3)]
        client.close()
    finally:
        server.stop()
        tb.close()


def test_provider_requeues_dead_claimants_splits_to_pin_queue():
    """A claimed-but-never-recorded split whose claimant stopped
    heartbeating goes back on the queue — pinned requeues target the
    originally chosen trainer's pin queue."""
    tb = _Board()
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        # stale window must clear the manager's per-RPC latency (a KV
        # set can cost ~0.2s here), else a live beat still looks stale
        ctx = _Ctx(tb.mgr)
        p = _provider(ctx, addr, num_epochs=1, window=2, stale_secs=1.0)
        p.on_tick(ctx)
        sid = tb.board.claim_next()
        assert sid == (0, 0)
        tb.board.set_claim(sid, 7)       # worker 7 claims...
        tb.board.set_pin(sid, 1)         # ...pins it to trainer 1...
        time.sleep(1.2)                  # ...and dies (no heartbeat ever)
        p.on_tick(ctx)
        # requeued to trainer 1's pin queue, not the shared queue
        assert tb.board.claim_next(ranks=[1]) == sid
        assert tb.board.claim_of(sid) is None
        # a live claimant is NOT swept, however old the claim
        sid2 = tb.board.claim_next()
        tb.board.set_claim(sid2, 3)
        stop = liveness.start_heartbeat(tb.mgr, tb.board.beat_key(3),
                                        interval=0.1)
        time.sleep(1.2)
        p.on_tick(ctx)
        claim = tb.board.claim_of(sid2)
        assert claim is not None and claim[0] == 3
        stop.set()
    finally:
        server.stop()
        tb.close()


def test_provider_resume_skips_ledger_done_splits():
    """Cross-recovery half of exactly-once: a fresh provider (new board,
    durable ledger) never re-posts what the ledger already has."""
    tb = _Board()
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        client = rendezvous.Client(addr)
        # previous incarnation served (0,0) and (0,2); eof was 3
        for k in (0, 2):
            client.partition_done(S.split_feed("input"),
                                  S.sid_to_part((0, k)))
        tb.board.set_eof(3)
        ctx = _Ctx(tb.mgr)
        p = _provider(ctx, addr, num_epochs=1, window=8)
        served = []
        for _ in range(100):
            p.on_tick(ctx)
            if tb.board.complete():
                break
            sid = tb.board.claim_next()
            if sid is None:
                continue
            tb.board.set_claim(sid, 0)
            served.append(sid)
            client.partition_done(S.split_feed("input"), S.sid_to_part(sid))
        assert tb.board.complete()
        assert served == [(0, 1)]   # only the missing split re-posted
        client.close()
    finally:
        server.stop()
        tb.close()


# -- dynamic service: exact cover across concurrent workers ------------------


N_RECORDS = 120
BLOCK = 6          # 20 blocks
SPLIT_BLOCKS = 4   # -> 5 splits per epoch


def _run_dynamic_workers(n_workers, n_trainers, num_epochs=1,
                         use_cache=False):
    """Board + provider + ``n_workers`` DynamicDataService threads over
    ``n_trainers`` bare trainer managers; returns per-trainer id lists."""
    keys = [secrets.token_bytes(8) for _ in range(n_trainers)]
    mgrs = [tfmanager.start(k, ["input", "output", "error"]) for k in keys]
    tb = _Board()
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        tb.board.set_plan(range(n_workers))
        ctx = _Ctx(tb.mgr)
        p = _provider(ctx, addr, num_epochs=num_epochs, window=8)
        cluster_info = [_trainer_meta(m, i, k)
                        for i, (m, k) in enumerate(zip(mgrs, keys))]
        meta = {
            "server_addr": addr,
            dsvc.SPLIT_BOARD_META: {"address": tuple(tb.mgr.address),
                                    "authkey": tb.authkey},
        }
        pipe = data.from_arrays(_arrays(N_RECORDS), block_size=BLOCK)

        stop_ticking = threading.Event()

        def _tick():
            while not stop_ticking.is_set() and not tb.board.complete():
                p.on_tick(ctx)
                time.sleep(0.02)

        ticker = threading.Thread(target=_tick, daemon=True)
        ticker.start()
        workers = [
            dsvc.DynamicDataService(
                pipe, cluster_info, meta, worker_index=w,
                split_blocks=SPLIT_BLOCKS, feed_timeout=60,
                use_cache=use_cache)
            for w in range(n_workers)
        ]
        for w in workers:
            # nothing drains the trainer queues until the workers are
            # done, so the cap must exceed one whole run's chunk count
            w.queue_cap = 4 * (N_RECORDS // BLOCK) * num_epochs
        threads = [threading.Thread(target=w.run, daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
            assert not t.is_alive(), "dynamic worker wedged"
        stop_ticking.set()
        ticker.join(timeout=5)
        assert tb.board.complete()
        return [_drain_ids(m.get_queue("input")) for m in mgrs]
    finally:
        server.stop()
        tb.close()
        for m in mgrs:
            m.shutdown()


def test_dynamic_exact_cover_single_worker():
    per_trainer = _run_dynamic_workers(1, 2)
    allids = [v for ids in per_trainer for v in ids]
    assert sorted(allids) == list(range(N_RECORDS))
    # both trainers actually receive splits (round-robin tie-break)
    assert all(ids for ids in per_trainer), [len(i) for i in per_trainer]


def test_dynamic_exact_cover_two_workers_and_epochs():
    """Permutation-invariance gate: whatever FCFS interleaving two
    concurrent claimants land on, the union of delivered records is an
    exact cover — each id exactly ``num_epochs`` times."""
    per_trainer = _run_dynamic_workers(2, 2, num_epochs=2)
    counts = collections.Counter(
        v for ids in per_trainer for v in ids)
    assert counts == {i: 2 for i in range(N_RECORDS)}


def test_dynamic_exact_cover_through_shared_cache():
    """Same exactness when blocks replay from the shared epoch cache."""
    dcache.clear()
    try:
        per_trainer = _run_dynamic_workers(1, 1, num_epochs=2,
                                           use_cache=True)
        counts = collections.Counter(per_trainer[0])
        assert counts == {i: 2 for i in range(N_RECORDS)}
    finally:
        dcache.clear()


# -- consumer dedup of re-served prefixes ------------------------------------


def test_datafeed_drops_reserved_split_prefix():
    """A re-served split (worker died after pushing, before recording)
    arrives tagged with the same (sid, seq) pairs; the feed keeps one
    copy of each chunk and never double-delivers a record."""
    from tensorflowonspark_tpu import marker

    authkey = secrets.token_bytes(8)
    m = tfmanager.start(authkey, ["input", "output", "error"])
    try:
        pipe = data.from_arrays(_arrays(24), block_size=6)
        chunks = list(pipe.chunks())
        q = m.get_queue("input")
        sid = (0, 0)
        for seq, c in enumerate(chunks[:2]):
            c.meta = ("split", sid, seq, seq + 1)
            q.put(c)
        # worker died; split re-served WHOLE to the same trainer
        for seq, c in enumerate(chunks[:4]):
            c2 = marker.ColumnChunk(c.spec, c.columns, shapes=c.shapes,
                                    meta=("split", sid, seq, seq + 1))
            q.put(c2)
        q.put(None)
        feed = DataFeed(m, train_mode=True,
                        input_mapping={"x": "x", "y": "y"})
        got = []
        while not feed.should_stop():
            got.extend(int(v) for v in feed.next_batch_columns(6)["y"])
        assert got == list(range(24)), got
    finally:
        m.shutdown()


# -- kill-mid-split at the transport level -----------------------------------


def test_dynamic_service_fault_mid_split_requeues_and_stays_exact(
        monkeypatch):
    """A worker faulted mid-split (after pushing a chunk, before the
    record) leaves the split claimed-but-undone; the provider sweeps it
    back (pinned), a fresh worker re-serves it whole, and the feed-level
    dedup keeps delivery exact."""
    faults._reset_for_tests()
    monkeypatch.setenv(faults.PLAN_ENV, "data.split_serve:exc@6")
    authkey = secrets.token_bytes(8)
    m = tfmanager.start(authkey, ["input", "output", "error"])
    tb = _Board()
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        tb.board.set_plan([0])
        ctx = _Ctx(tb.mgr)
        p = _provider(ctx, addr, num_epochs=1, window=8, stale_secs=0.8)
        cluster_info = [_trainer_meta(m, 0, authkey)]
        meta = {
            "server_addr": addr,
            dsvc.SPLIT_BOARD_META: {"address": tuple(tb.mgr.address),
                                    "authkey": tb.authkey},
        }
        pipe = data.from_arrays(_arrays(N_RECORDS), block_size=BLOCK)
        for _ in range(30):
            p.on_tick(ctx)
            if tb.board.queue_depth():
                break
        svc = dsvc.DynamicDataService(
            pipe, cluster_info, meta, worker_index=0,
            split_blocks=SPLIT_BLOCKS, feed_timeout=60, use_cache=False)
        with pytest.raises(faults.FaultInjected):
            svc.run()
        monkeypatch.delenv(faults.PLAN_ENV)
        faults._reset_for_tests()
        # let the claim of the faulted worker go stale, then sweep it
        time.sleep(1.0)
        p.on_tick(ctx)
        svc2 = dsvc.DynamicDataService(
            pipe, cluster_info, meta, worker_index=0,
            split_blocks=SPLIT_BLOCKS, feed_timeout=60, use_cache=False)
        done = threading.Event()

        def _tick():
            while not done.is_set() and not tb.board.complete():
                p.on_tick(ctx)
                time.sleep(0.02)

        t = threading.Thread(target=_tick, daemon=True)
        t.start()
        svc2.run()
        done.set()
        t.join(timeout=5)
        assert tb.board.complete()
        # the QUEUE holds duplicates of the re-served prefix by design;
        # the consumer-side feed is what must stay exact
        feed = DataFeed(m, train_mode=True,
                        input_mapping={"x": "x", "y": "y"})
        m.get_queue("input").put(None)
        got = []
        while not feed.should_stop():
            got.extend(int(v) for v in feed.next_batch_columns(6)["y"])
        assert sorted(got) == list(range(N_RECORDS))
        assert len(got) == N_RECORDS  # zero duplicates delivered
    finally:
        monkeypatch.delenv(faults.PLAN_ENV, raising=False)
        faults._reset_for_tests()
        server.stop()
        tb.close()
        m.shutdown()


# -- shared epoch cache ------------------------------------------------------


def test_epoch_cache_incremental_fill_and_random_access():
    pipe = data.from_arrays(_arrays(60), block_size=7)  # 9 blocks
    c = dcache.EpochCache(pipe, memory_bytes=1 << 30)
    try:
        # random-ish access fills incrementally, never recomputes
        b5 = c.block(5)
        assert [int(v) for v in b5["y"]] == list(range(35, 42))
        assert c.num_blocks is None          # end not discovered yet
        assert c.block(0) is not None
        assert c.block(9) is None            # past EOF
        assert c.num_blocks == 9
        ids = [int(v) for b in c.blocks_range(2, 3) for v in b["y"]]
        assert ids == list(range(14, 35))
    finally:
        c.close()


def test_epoch_cache_spills_past_memory_budget(tmp_path):
    pipe = data.from_arrays(_arrays(80), block_size=8)  # 10 blocks
    c = dcache.EpochCache(pipe, memory_bytes=1,  # force immediate spill
                          spill_dir=str(tmp_path))
    try:
        ids = [int(v) for b in c.blocks_range() for v in b["y"]]
        assert ids == list(range(80))
        assert c._spill_path and os.path.exists(c._spill_path)
        # replay out of the spill, including seeks into the middle
        again = [int(v) for b in c.blocks_range(4, 2) for v in b["y"]]
        assert again == list(range(32, 48))
    finally:
        c.close()
    assert not os.path.exists(c._spill_path or "")


def test_shared_cache_registry_keys_by_signature():
    dcache.clear()
    try:
        arrays = _arrays(40)
        p1 = data.from_arrays(arrays, block_size=5)
        p2 = data.from_arrays(arrays, block_size=5)   # same content
        p3 = data.from_arrays(arrays, block_size=8)   # different graph
        c1 = dcache.shared(p1)
        assert dcache.shared(p2) is c1                # hit by signature
        assert dcache.shared(p3) is not c1
        assert p1.signature() == p2.signature()
        assert p1.signature() != p3.signature()
    finally:
        dcache.clear()


# -- pipeline: blocks_range / signature / chunksize --------------------------


def test_blocks_range_slices_match_oracle():
    import itertools

    pipe = data.from_arrays(_arrays(50), block_size=6).map(lambda b: b)
    oracle = list(pipe.blocks())
    for skip, num in [(0, None), (0, 3), (4, 2), (7, 100), (9, 1)]:
        got = list(pipe.blocks_range(skip, num))
        want = list(itertools.islice(oracle, skip,
                                     None if num is None else skip + num))
        assert [list(map(int, b["y"])) for b in got] == \
            [list(map(int, b["y"])) for b in want], (skip, num)


def test_signature_stable_across_stages():
    base = _arrays(30)
    p = data.from_arrays(base, block_size=5)
    assert p.signature() == data.from_arrays(base, block_size=5).signature()
    assert p.signature() != p.shuffle(7, seed=1).signature()
    assert (p.shuffle(7, seed=1).signature()
            != p.shuffle(7, seed=2).signature())
    assert p.batch(10).signature() != p.batch(10, True).signature()


def test_parallel_map_chunksize_env(monkeypatch):
    from tensorflowonspark_tpu.data import pipeline as dpipe

    monkeypatch.setenv(dpipe.CHUNKSIZE_ENV, "3")
    pipe = data.from_arrays(_arrays(48), block_size=4).parallel_map(
        lambda b: {"x": b["x"], "y": b["y"] + 1000}, num_workers=2)
    ids = [int(v) for b in pipe.blocks() for v in b["y"]]
    assert ids == [i + 1000 for i in range(48)]


# -- autoscaler --------------------------------------------------------------


def test_autoscaler_hysteresis_and_clamps():
    stall = {"v": 0.5}
    ups, downs = [], []
    a = ascale.StallAutoscaler(
        lambda: stall["v"], ups.append, downs.append,
        min_workers=1, max_workers=3, high=0.25, low=0.05, cooldown=10.0)
    t = 100.0
    assert a.step(t) == "up" and ups == [1]
    assert a.step(t + 1) is None          # cooldown
    t += 20
    assert a.step(t) == "up" and ups == [1, 2]
    t += 20
    assert a.step(t) is None              # max_workers clamp
    assert a.workers == 3
    stall["v"] = 0.01
    t += 20
    assert a.step(t) == "down" and downs == [2]   # LIFO retirement
    t += 20
    assert a.step(t) == "down" and downs == [2, 1]
    t += 20
    assert a.step(t) is None              # min_workers clamp
    assert a.workers == 1
    stall["v"] = 0.15                     # inside the deadband
    t += 20
    assert a.step(t) is None
    a2 = ascale.StallAutoscaler(lambda: None, ups.append, downs.append,
                                min_workers=1, max_workers=2)
    assert a2.step(1000.0) is None        # no signal -> no action


def test_obs_stall_reader_computes_windowed_ratio():
    snaps = {"t0": {"role": "worker", "metrics": {
        "tfos_feed_wait_seconds_total": {"series": [{"value": 0.0}]}}}}
    read = ascale.obs_stall_reader(lambda: snaps)
    assert read() is None                 # first call only baselines
    snaps["t0"]["metrics"]["tfos_feed_wait_seconds_total"][
        "series"][0]["value"] = 0.05
    time.sleep(0.1)
    ratio = read()
    assert ratio is not None and 0.0 < ratio <= 1.0
    # data-worker and driver snapshots never count as trainer stall
    snaps["d0"] = {"role": "data", "metrics": {
        "tfos_feed_wait_seconds_total": {"series": [{"value": 999.0}]}}}
    time.sleep(0.05)
    assert read() < 10.0


# -- full-cluster SIGKILL e2e (slow lane) ------------------------------------


E2E_N = 200
E2E_BLOCK = 10


def dynamic_consume_main(args, ctx):
    """Trainer that records every delivered id (exactness oracle)."""
    feed = ctx.get_data_feed(train_mode=True,
                             input_mapping={"x": "x", "y": "y"})
    ids = []
    while not feed.should_stop():
        b = feed.next_batch_columns(16)
        ids.extend(int(v) for v in b["y"])
    out = os.path.join(args["out_dir"], f"ids-{ctx.task_index}.txt")
    with open(out, "w") as f:
        f.write("\n".join(str(i) for i in ids))


@pytest.mark.slow
@pytest.mark.faults
def test_dynamic_service_survives_worker_kill(tmp_path, monkeypatch):
    """The dynamic-dispatch e2e acceptance (ISSUE 19): the data worker is
    SIGKILLed mid-split (data.split_serve:kill@3 — after pushing part of
    a split, before recording it), the engine respawns it, the provider
    requeues the orphaned split pinned to its original trainer, and the
    union of delivered ids is STILL exactly one copy per record — zero
    loss, zero duplicates."""
    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine

    monkeypatch.chdir(tmp_path)
    out_dir = tmp_path / "ids"
    out_dir.mkdir()
    engine = LocalEngine(3, env={
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",  # drop the TPU-tunnel site hook
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TFOS_DATA_SPLIT_BLOCKS": "4",
        faults.PLAN_ENV: "data.split_serve:kill@3",
        faults.EXECUTOR_ENV: "2",  # only the data-worker slot
    })
    try:
        cluster = TFCluster.run(
            engine, dynamic_consume_main, {"out_dir": str(out_dir)},
            num_executors=2, input_mode=InputMode.SPARK, restarts=1,
            data_workers=1)
        pipe = data.from_arrays(_arrays(E2E_N), block_size=E2E_BLOCK)
        cluster.train(pipe, num_epochs=1, feed_timeout=240)
        cluster.shutdown(grace_secs=2)
    finally:
        engine.stop()

    ids = []
    for i in range(2):
        with open(out_dir / f"ids-{i}.txt") as f:
            ids.extend(int(v) for v in f.read().split())
    counts = collections.Counter(ids)
    assert counts == {i: 1 for i in range(E2E_N)}, (
        f"exactness violated: missing="
    f"{[k for k in range(E2E_N) if counts.get(k, 0) < 1][:10]} "
        f"dup={[k for k, v in counts.items() if v > 1][:10]}")


# -- /statusz data section -------------------------------------------------


def test_statusz_data_summary_rolls_up_across_processes():
    """The /statusz "data" section sums split/cache counters across the
    provider, workers and trainers, sums per-process cache gauges, and
    takes the largest reporter for the singleton gauges (queue depth,
    worker count).  Static-shard runs — records but no split/cache
    activity — get no section at all."""
    from tensorflowonspark_tpu.obs import http as obs_http

    def snap(**kv):
        return {name: {"series": [{"value": float(v)}]}
                for name, v in kv.items()}

    provider = snap(tfos_data_splits_posted_total=10,
                    tfos_data_splits_requeued_total=1,
                    tfos_data_split_queue_depth=3)
    w1 = snap(tfos_data_splits_claimed_total=4,
              tfos_data_splits_served_total=4,
              tfos_data_records_total=400,
              tfos_data_cache_bytes=100,
              tfos_data_cache_blocks=2)
    w2 = snap(tfos_data_splits_claimed_total=5,
              tfos_data_splits_served_total=5,
              tfos_data_records_total=500,
              tfos_data_cache_bytes=50,
              tfos_data_cache_blocks=1)
    scaler = snap(tfos_data_workers=2)
    got = obs_http.data_summary([provider, w1, w2, scaler, None])
    assert got == {
        "splits_posted": 10.0, "splits_claimed": 9.0,
        "splits_served": 9.0, "splits_requeued": 1.0,
        "records": 900.0, "cache_bytes": 150.0, "cache_blocks": 3.0,
        "split_queue_depth": 3.0, "workers": 2.0,
    }
    # records alone (static service) doesn't rate a section
    assert obs_http.data_summary(
        [snap(tfos_data_records_total=5)]) is None
    assert obs_http.data_summary([None, {}]) is None
