"""The framework under a pyspark SparkContext (parity: reference test/
run_tests.sh running the suite on a local Standalone cluster).

With real pyspark installed (CI), these tests run against genuine Spark.
Without it, ``import pyspark`` resolves to tests/sparkstub — a faithful
stand-in whose executors are separate LocalEngine processes — so the
SparkEngine/SparkDataset/spark_ml/streaming adapter code paths are
exercised either way.
"""

import importlib
import os
import sys

import numpy as np
import pytest

STUB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sparkstub")


def _have_real_pyspark():
    try:
        import pyspark  # noqa: F401

        return "sparkstub" not in os.path.dirname(pyspark.__file__)
    except ImportError:
        return False


@pytest.fixture(scope="module")
def spark():
    """A SparkContext (real if installed, stub otherwise) with 2 executors."""
    added = False
    if not _have_real_pyspark() and STUB_DIR not in sys.path:
        sys.path.insert(0, STUB_DIR)
        added = True
    importlib.invalidate_caches()
    import pyspark

    conf = pyspark.SparkConf().set("spark.executor.instances", "2")
    if _have_real_pyspark():
        conf.setMaster(os.environ.get("MASTER", "local[2]"))
        conf.setAppName("tfos-tpu-tests")
    sc = pyspark.SparkContext(conf=conf)
    yield sc
    sc.stop()
    if added:
        sys.path.remove(STUB_DIR)
        for name in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
            del sys.modules[name]


# --- node programs (module-level: shipped to executor processes) -----------

def _squares_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(100)
        if batch:
            feed.batch_results([x * x for x in batch])


def _stream_consumer_fn(args, ctx):
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
        batch = feed.next_batch(50)
        total += len(batch)
        if total >= 100:
            feed.terminate()
            break


# --- engine adapter ---------------------------------------------------------

def test_as_engine_wraps_sparkcontext(spark):
    from tensorflowonspark_tpu.engine import SparkEngine, as_engine

    eng = as_engine(spark)
    assert isinstance(eng, SparkEngine)
    assert eng.num_executors == 2
    assert eng.default_fs.startswith("file")


def test_spark_dataset_spread_uses_barrier(spark):
    """spread=True must schedule one concurrent task per executor slot
    (engine.py maps it to rdd.barrier())."""
    from tensorflowonspark_tpu.engine import as_dataset

    rdd = spark.parallelize(range(2), 2)
    seen = as_dataset(rdd).map_partitions(
        lambda it: [os.environ.get("TFOS_EXECUTOR_INDEX", "real-spark")]
    )
    out = seen.collect(spread=True)
    assert len(out) == 2
    if not _have_real_pyspark():
        assert sorted(out) == ["0", "1"], "tasks must land on distinct slots"


def test_cluster_inference_roundtrip_on_spark(spark):
    """The reference functional baseline (sum of squares of 0..999) run
    through TFCluster over a SparkContext (test_TFCluster.py:29-48)."""
    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode

    cluster = TFCluster.run(
        spark, _squares_fn, [], num_executors=2, input_mode=InputMode.SPARK,
    )
    results = cluster.inference(spark.parallelize(range(1000), 4)).collect()
    cluster.shutdown()
    assert len(results) == 1000
    assert sum(results) == 332833500


def test_streaming_dstream_feed_and_ssc_shutdown(spark):
    """DStream feeding + shutdown(ssc=...) stop loop (parity:
    TFCluster.py:83-85,146-153)."""
    from pyspark.streaming import StreamingContext

    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode

    cluster = TFCluster.run(
        spark, _stream_consumer_fn, [], num_executors=2,
        input_mode=InputMode.SPARK,
    )
    ssc = StreamingContext(spark, batchDuration=1)
    rdds = [spark.parallelize(range(100), 2) for _ in range(60)]
    stream = ssc.queueStream(rdds)
    cluster.train(stream, feed_timeout=30)  # registers foreachRDD
    ssc.start()
    cluster.shutdown(ssc=ssc, grace_secs=1)
    assert cluster.server.done.is_set(), "consumer STOP never propagated"
    assert ssc._stopped.is_set() if hasattr(ssc, "_stopped") else True


# --- pyspark.ml interop -----------------------------------------------------

W1, W2 = 3.14, 1.618


def linreg_main(args, ctx):
    """Trains y = w.x from the DataFeed; chief exports (same shape as the
    reference CI gate, test_pipeline.py:89-172)."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.models import linear
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    feed = ctx.get_data_feed(train_mode=True, input_mapping=args.input_mapping)
    params = linear.init_params()
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)
    step = jax.jit(linear.make_train_step(opt))
    while not feed.should_stop():
        batch = feed.next_batch(args.batch_size)
        if not batch["features"]:
            continue
        x = np.asarray(batch["features"], dtype=np.float32)
        y = np.asarray(batch["label"], dtype=np.float32)
        params, opt_state, loss = step(params, opt_state, x, y)
    ckpt.export_model(
        args.export_dir, params, ctx,
        metadata={"predict": "tensorflowonspark_tpu.models.linear:predict"},
    )


@pytest.mark.slow
def test_pipeline_fit_transform_on_spark(spark, tmp_path):
    """Pipeline([TFEstimator]).fit(df) -> PipelineModel.transform(df):
    genuine pyspark.ml stage composition (VERDICT round-1 item 5)."""
    from pyspark.ml import Pipeline
    from pyspark.sql import SparkSession

    from tensorflowonspark_tpu.spark_ml import TFEstimator, TFModel

    session = SparkSession(spark)
    rng = np.random.default_rng(42)
    x = rng.random((1024, 2)).astype(np.float32)
    y = x @ np.array([W1, W2], dtype=np.float32)
    df = session.createDataFrame(
        [(list(map(float, xi)), float(yi)) for xi, yi in zip(x, y)],
        schema=["x", "y"],
    )

    export_dir = str(tmp_path / "export")
    est = (
        TFEstimator(linreg_main, {})
        .setInputMapping({"x": "features", "y": "label"})
        .setClusterSize(2)
        .setMasterNode("chief")
        .setEpochs(12)
        .setBatchSize(32)
        .setExportDir(export_dir)
        .setGraceSecs(5)
    )
    pipeline_model = Pipeline(stages=[est]).fit(df)
    model = pipeline_model.stages[0]
    assert isinstance(model, TFModel)

    infer_df = session.createDataFrame([([1.0, 1.0],)] * 8, schema=["x"])
    preds_df = (
        model.copy()
        .setInputMapping({"x": "features"})
        .setOutputMapping({"prediction": "preds"})
        .setBatchSize(4)
        .transform(infer_df)
    )
    assert "preds" in preds_df.columns
    preds = preds_df.collect()
    assert len(preds) == 8
    for row in preds:
        assert round(float(row.preds), 2) == round(W1 + W2, 2)
