"""Columnar feed path: feeder-side encoding (node._make_chunk_encoder) and
DataFeed's ColumnChunk consumption must be byte-equivalent to the row path
(the marshalling redesign of the reference's per-record pickle hop,
TFSparkNode.py:480-482)."""

import secrets

import numpy as np
import pytest

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu import marker, node
from tensorflowonspark_tpu.feed import DataFeed

ROWS = [([float(i), float(2 * i)], i % 7) for i in range(100)]


def test_encoder_numeric_rows_go_columnar():
    enc = node._make_chunk_encoder()
    chunk = enc(list(ROWS))
    assert isinstance(chunk, marker.ColumnChunk)
    assert len(chunk) == len(ROWS)
    assert chunk.spec == [("d", 2), ("l", 0)]
    np.testing.assert_allclose(chunk.columns[0][3], [3.0, 6.0])
    assert chunk.columns[1][3] == 3


def test_encoder_string_rows_stay_rows():
    enc = node._make_chunk_encoder()
    rows = [("hello", 1), ("world", 2)]
    assert enc(rows) is rows
    # and the encoder stays off for later chunks
    assert enc(list(ROWS)) is not None
    assert not isinstance(enc(list(ROWS)), marker.ColumnChunk)


def test_encoder_ragged_rows_fall_back():
    enc = node._make_chunk_encoder()
    rows = [([1.0], 1), ([1.0, 2.0], 2)]
    out = enc(rows)
    assert out is rows


def test_encoder_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TFOS_COLUMNAR_FEED", "0")
    enc = node._make_chunk_encoder()
    assert enc(list(ROWS)) is not None
    assert not isinstance(enc(list(ROWS)), marker.ColumnChunk)


@pytest.fixture
def mgr():
    m = tfmanager.start(secrets.token_bytes(8), ["input", "output", "error"])
    yield m
    m.shutdown()


def _feed_chunks(mgr, chunks):
    q = mgr.get_queue("input")
    for c in chunks:
        q.put(c)
    q.put(None)


def _drain_batches(feed, batch_size):
    out = []
    while not feed.should_stop():
        out.append(feed.next_batch(batch_size))
    return out


def test_datafeed_columnar_mapping_equals_row_path(mgr):
    enc = node._make_chunk_encoder()
    # batch size 16 deliberately misaligned with chunk size 24
    _feed_chunks(mgr, [enc(ROWS[i:i + 24]) for i in range(0, 100, 24)])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"x": "features", "y": "label"})
    batches = _drain_batches(feed, 16)
    xs, ys = [], []
    for b in batches:
        assert isinstance(b["features"], list)
        xs.extend(np.asarray(v) for v in b["features"])
        ys.extend(int(v) for v in b["label"])
    np.testing.assert_allclose(np.stack(xs), [r[0] for r in ROWS])
    assert ys == [r[1] for r in ROWS]


def test_datafeed_columnar_no_mapping_roundtrip(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [enc(ROWS[:50]), enc(ROWS[50:])])
    feed = DataFeed(mgr, train_mode=True)
    records = []
    while not feed.should_stop():
        records.extend(feed.next_batch(13))
    assert len(records) == len(ROWS)
    for got, want in zip(records, ROWS):
        np.testing.assert_allclose(got[0], want[0])
        assert got[1] == want[1]


def test_datafeed_mixed_row_and_columnar_chunks(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [ROWS[:30], enc(ROWS[30:60]), ROWS[60:]])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"x": "features", "y": "label"})
    total = 0
    for b in _drain_batches(feed, 10):
        n = len(b["label"])
        assert len(b["features"]) == n
        total += n
    assert total == len(ROWS)
