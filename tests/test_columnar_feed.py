"""Columnar feed path: feeder-side encoding (node._make_chunk_encoder) and
DataFeed's ColumnChunk consumption must be byte-equivalent to the row path
(the marshalling redesign of the reference's per-record pickle hop,
TFSparkNode.py:480-482)."""

import secrets

import numpy as np
import pytest

from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu import marker, node
from tensorflowonspark_tpu.feed import DataFeed

ROWS = [([float(i), float(2 * i)], i % 7) for i in range(100)]


def test_encoder_numeric_rows_go_columnar():
    enc = node._make_chunk_encoder()
    chunk = enc(list(ROWS))
    assert isinstance(chunk, marker.ColumnChunk)
    assert len(chunk) == len(ROWS)
    assert chunk.spec == [("d", 2), ("l", 0)]
    np.testing.assert_allclose(chunk.columns[0][3], [3.0, 6.0])
    assert chunk.columns[1][3] == 3


def test_encoder_string_rows_stay_rows():
    enc = node._make_chunk_encoder()
    rows = [("hello", 1), ("world", 2)]
    assert enc(rows) is rows
    # and the encoder stays off for later chunks
    assert enc(list(ROWS)) is not None
    assert not isinstance(enc(list(ROWS)), marker.ColumnChunk)


def test_encoder_ragged_rows_fall_back():
    enc = node._make_chunk_encoder()
    rows = [([1.0], 1), ([1.0, 2.0], 2)]
    out = enc(rows)
    assert out is rows


def test_encoder_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TFOS_COLUMNAR_FEED", "0")
    enc = node._make_chunk_encoder()
    assert enc(list(ROWS)) is not None
    assert not isinstance(enc(list(ROWS)), marker.ColumnChunk)


@pytest.fixture
def mgr():
    m = tfmanager.start(secrets.token_bytes(8), ["input", "output", "error"])
    yield m
    m.shutdown()


def _feed_chunks(mgr, chunks):
    q = mgr.get_queue("input")
    for c in chunks:
        q.put(c)
    q.put(None)


def _drain_batches(feed, batch_size):
    out = []
    while not feed.should_stop():
        out.append(feed.next_batch(batch_size))
    return out


def test_datafeed_columnar_mapping_equals_row_path(mgr):
    enc = node._make_chunk_encoder()
    # batch size 16 deliberately misaligned with chunk size 24
    _feed_chunks(mgr, [enc(ROWS[i:i + 24]) for i in range(0, 100, 24)])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"x": "features", "y": "label"})
    batches = _drain_batches(feed, 16)
    xs, ys = [], []
    for b in batches:
        assert isinstance(b["features"], list)
        xs.extend(np.asarray(v) for v in b["features"])
        ys.extend(int(v) for v in b["label"])
    np.testing.assert_allclose(np.stack(xs), [r[0] for r in ROWS])
    assert ys == [r[1] for r in ROWS]


def test_datafeed_columnar_no_mapping_roundtrip(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [enc(ROWS[:50]), enc(ROWS[50:])])
    feed = DataFeed(mgr, train_mode=True)
    records = []
    while not feed.should_stop():
        records.extend(feed.next_batch(13))
    assert len(records) == len(ROWS)
    for got, want in zip(records, ROWS):
        np.testing.assert_allclose(got[0], want[0])
        assert got[1] == want[1]


IMG_ROWS = [(np.full((4, 6, 3), i, np.uint8), i % 10) for i in range(64)]


def test_encoder_flattens_nd_image_fields():
    """n-D ndarray fields (images) go columnar as flattened width columns
    with the original shape carried in ColumnChunk.shapes — the wire
    format for the fed hot path (PERF.md 12k img/s np.stack wall)."""
    enc = node._make_chunk_encoder()
    chunk = enc(list(IMG_ROWS[:32]))
    assert isinstance(chunk, marker.ColumnChunk)
    assert chunk.shapes == ((4, 6, 3), None)
    assert chunk.spec[0] == ("B", 4 * 6 * 3)
    assert chunk.columns[0].shape == (32, 72)
    np.testing.assert_array_equal(
        chunk.columns[0][5].reshape(4, 6, 3), IMG_ROWS[5][0])


def test_encoder_nd_shape_drift_falls_back_to_rows():
    enc = node._make_chunk_encoder()
    assert isinstance(enc(list(IMG_ROWS[:8])), marker.ColumnChunk)
    drift = [(np.zeros((6, 4, 3), np.uint8), 1)] * 4  # transposed shape
    out = enc(drift)
    assert out is drift  # row path, not a silently mis-shaped column


def test_datafeed_nd_columnar_row_consumers_see_original_shape(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [enc(list(IMG_ROWS[:40])), enc(list(IMG_ROWS[40:]))])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    got_imgs, got_labels = [], []
    for b in _drain_batches(feed, 16):
        for v in b["image"]:
            assert v.shape == (4, 6, 3)
            got_imgs.append(v)
        got_labels.extend(int(v) for v in b["label"])
    np.testing.assert_array_equal(
        np.stack(got_imgs), np.stack([r[0] for r in IMG_ROWS]))
    assert got_labels == [r[1] for r in IMG_ROWS]


def test_datafeed_nd_columnar_no_mapping_roundtrip(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [enc(list(IMG_ROWS))])
    feed = DataFeed(mgr, train_mode=True)
    records = []
    while not feed.should_stop():
        records.extend(feed.next_batch(24))
    assert len(records) == len(IMG_ROWS)
    for got, want in zip(records, IMG_ROWS):
        assert got[0].shape == (4, 6, 3)
        np.testing.assert_array_equal(got[0], want[0])
        assert got[1] == want[1]


def test_next_batch_columns_dense_and_zero_copy(mgr):
    """Aligned chunk -> zero-copy dense batch; spanning chunks -> one
    concatenate; short tail returned as-is."""
    enc = node._make_chunk_encoder()
    chunks = [enc(list(IMG_ROWS[:32])), enc(list(IMG_ROWS[32:56])),
              enc(list(IMG_ROWS[56:]))]
    _feed_chunks(mgr, chunks)
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"image": "image", "label": "label"})

    b1 = feed.next_batch_columns(32)  # exactly chunk 1: zero copy
    assert b1["image"].shape == (32, 4, 6, 3)
    assert b1["image"].dtype == np.uint8  # narrow wire dtype preserved
    # a VIEW of the received chunk's column (reshape of a slice), not a
    # freshly stacked copy (the queue itself pickles, so identity with
    # the producer-side array is out of scope)
    assert b1["image"].base is not None
    np.testing.assert_array_equal(
        b1["image"], np.stack([r[0] for r in IMG_ROWS[:32]]))

    b2 = feed.next_batch_columns(32)  # spans chunks 2+3: one concat
    assert b2["image"].shape == (32, 4, 6, 3)
    np.testing.assert_array_equal(
        b2["image"], np.stack([r[0] for r in IMG_ROWS[32:]]))
    assert list(b2["label"]) == [r[1] for r in IMG_ROWS[32:]]

    tail = feed.next_batch_columns(32)  # end of feed: empty
    assert feed.should_stop() and len(tail["image"]) == 0


def test_next_batch_columns_row_chunk_fallback(mgr):
    """Non-columnar feeders (plain row lists) still work through the
    dense consumer, via per-segment np.stack."""
    _feed_chunks(mgr, [list(IMG_ROWS[:20]), list(IMG_ROWS[20:48])])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    b = feed.next_batch_columns(48)
    assert b["image"].shape == (48, 4, 6, 3)
    np.testing.assert_array_equal(
        b["image"], np.stack([r[0] for r in IMG_ROWS[:48]]))


def test_next_batch_columns_requires_mapping(mgr):
    feed = DataFeed(mgr, train_mode=True)
    with pytest.raises(ValueError, match="input_mapping"):
        feed.next_batch_columns(8)


def test_datafeed_mixed_row_and_columnar_chunks(mgr):
    enc = node._make_chunk_encoder()
    _feed_chunks(mgr, [ROWS[:30], enc(ROWS[30:60]), ROWS[60:]])
    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"x": "features", "y": "label"})
    total = 0
    for b in _drain_batches(feed, 10):
        n = len(b["label"])
        assert len(b["features"]) == n
        total += n
    assert total == len(ROWS)
