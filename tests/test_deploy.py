"""Blessed-checkpoint deployment loop (docs/deployment.md).

Fast units for the integrity-manifest contract (bless / verify /
tombstone), the hardened restore paths (truncated or quarantined newest
step falls back to the previous one), canary routing, and the
promote/rollback state machine; the slow lane holds the train → gate →
canary → rollback e2e.  No reference counterpart — the reference stops
at the TF Serving hand-off (SURVEY §1 L7).
"""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu.utils import checkpoint as ckpt
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.deploy

TREE = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.zeros(3, np.float32)}


def _save(d, step, scale=1.0):
    return ckpt.save_checkpoint(
        d, {"w": TREE["w"] * scale, "b": TREE["b"]}, step=step)


# -- manifest write / verify / tombstone -------------------------------------

def test_bless_writes_verifiable_manifest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 5)
    path = ckpt.bless_checkpoint(d, 5, score=0.42, eval_metrics={"loss": 0.42})
    assert os.path.basename(path) == "bless-00000005.json"
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["format"] == ckpt.MANIFEST_FORMAT
    assert manifest["step"] == 5
    assert manifest["score"] == pytest.approx(0.42)
    assert manifest["eval"] == {"loss": 0.42}
    assert manifest["tombstone"] is None
    assert manifest["files"]["ckpt-00000005.npz"]["bytes"] > 0
    ok, reason = ckpt.verify_manifest(d, 5)
    assert ok, reason


def test_bless_missing_step_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.bless_checkpoint(str(tmp_path), 7)


def test_verify_detects_corruption_and_absence(tmp_path):
    d = str(tmp_path / "ckpt")
    path = _save(d, 3)
    assert ckpt.verify_manifest(d, 3) == (False, "unblessed")
    ckpt.bless_checkpoint(d, 3)
    # flip one byte: digest must catch silent corruption in place
    with open(path, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    ok, reason = ckpt.verify_manifest(d, 3)
    assert not ok and "digest mismatch" in reason
    os.remove(path)
    ok, reason = ckpt.verify_manifest(d, 3)
    assert not ok and "missing file" in reason


def test_tombstone_quarantines(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 4)
    ckpt.bless_checkpoint(d, 4)
    assert ckpt.blessed_steps(d) == [4]
    ckpt.tombstone_checkpoint(d, 4, reason="canary slo breach")
    assert ckpt.blessed_steps(d) == []
    ok, reason = ckpt.verify_manifest(d, 4)
    assert not ok and "tombstoned" in reason
    # tombstoning a never-blessed step creates the quarantine marker too
    _save(d, 6)
    ckpt.tombstone_checkpoint(d, 6, reason="eval regression")
    assert not ckpt.verify_manifest(d, 6)[0]


def test_latest_blessed_picks_newest_verifying(tmp_path):
    d = str(tmp_path / "ckpt")
    assert ckpt.latest_blessed(d) == (None, None)
    _save(d, 2)
    _save(d, 8)
    ckpt.bless_checkpoint(d, 2)
    ckpt.bless_checkpoint(d, 8)
    step, path = ckpt.latest_blessed(d)
    assert step == 8 and path.endswith("ckpt-00000008.npz")
    ckpt.tombstone_checkpoint(d, 8, reason="bad")
    assert ckpt.latest_blessed(d)[0] == 2


# -- hardened restore: skip truncated / tombstoned, fall back a step ---------

def test_restore_falls_back_past_truncated_newest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    newest = _save(d, 2, scale=2.0)
    # truncate the newest file: the torn-write case the manifest guards
    size = os.path.getsize(newest)
    with open(newest, "r+b") as f:
        f.truncate(size // 2)
    assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001.npz")
    tree, step = ckpt.restore_latest(d)
    assert step == 1
    np.testing.assert_allclose(tree["w"], TREE["w"])
    tree, step = ckpt.restore_any(d)
    assert step == 1


def test_restore_skips_tombstoned_newest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.tombstone_checkpoint(d, 2, reason="rolled back")
    tree, step = ckpt.restore_any(d)
    assert step == 1
    tree, step = ckpt.restore_latest(d)
    assert step == 1
    assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001.npz")


def test_restore_any_blessed_only(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.bless_checkpoint(d, 1)
    # serving contract: only blessed checkpoints may serve
    tree, step = ckpt.restore_any(d, blessed_only=True)
    assert step == 1
    # trainer resume still takes the newer unblessed step
    tree, step = ckpt.restore_any(d)
    assert step == 2
    np.testing.assert_allclose(tree["w"], TREE["w"] * 2)


def test_restore_step_pinned(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    tree = ckpt.restore_step(d, 1)
    np.testing.assert_allclose(tree["w"], TREE["w"])
    with pytest.raises(FileNotFoundError):
        ckpt.restore_step(d, 99)


def test_digest_drift_skipped_on_restore(tmp_path):
    """A blessed checkpoint whose bytes drifted after blessing must not
    restore — the manifest is the arbiter, not mtime."""
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.bless_checkpoint(d, 2)
    _save(d, 2, scale=3.0)  # rewrite after blessing: digest drift
    tree, step = ckpt.restore_any(d)
    assert step == 1


# -- fault sites -------------------------------------------------------------

def test_deploy_fault_sites_registered():
    assert set(faults.DEPLOY_CHAOS_SITES) <= set(faults.SITES)
    plan = faults.random_plan(7, sites=faults.DEPLOY_CHAOS_SITES)
    assert any(s in plan for s in faults.DEPLOY_CHAOS_SITES)
