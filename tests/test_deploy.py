"""Blessed-checkpoint deployment loop (docs/deployment.md).

Fast units for the integrity-manifest contract (bless / verify /
tombstone), the hardened restore paths (truncated or quarantined newest
step falls back to the previous one), canary routing, and the
promote/rollback state machine; the slow lane holds the train → gate →
canary → rollback e2e.  No reference counterpart — the reference stops
at the TF Serving hand-off (SURVEY §1 L7).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.utils import checkpoint as ckpt
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.deploy


def _serve_version(params, inputs):
    """Module-level probe predict (cloudpickled into replica procs)."""
    x = np.asarray(inputs["x"])
    return {"version": np.full(x.shape[0],
                               float(np.asarray(params["version"])))}

TREE = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.zeros(3, np.float32)}


def _save(d, step, scale=1.0):
    return ckpt.save_checkpoint(
        d, {"w": TREE["w"] * scale, "b": TREE["b"]}, step=step)


# -- manifest write / verify / tombstone -------------------------------------

def test_bless_writes_verifiable_manifest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 5)
    path = ckpt.bless_checkpoint(d, 5, score=0.42, eval_metrics={"loss": 0.42})
    assert os.path.basename(path) == "bless-00000005.json"
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["format"] == ckpt.MANIFEST_FORMAT
    assert manifest["step"] == 5
    assert manifest["score"] == pytest.approx(0.42)
    assert manifest["eval"] == {"loss": 0.42}
    assert manifest["tombstone"] is None
    assert manifest["files"]["ckpt-00000005.npz"]["bytes"] > 0
    ok, reason = ckpt.verify_manifest(d, 5)
    assert ok, reason


def test_bless_missing_step_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.bless_checkpoint(str(tmp_path), 7)


def test_verify_detects_corruption_and_absence(tmp_path):
    d = str(tmp_path / "ckpt")
    path = _save(d, 3)
    assert ckpt.verify_manifest(d, 3) == (False, "unblessed")
    ckpt.bless_checkpoint(d, 3)
    # flip one byte: digest must catch silent corruption in place
    with open(path, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    ok, reason = ckpt.verify_manifest(d, 3)
    assert not ok and "digest mismatch" in reason
    os.remove(path)
    ok, reason = ckpt.verify_manifest(d, 3)
    assert not ok and "missing file" in reason


def test_tombstone_quarantines(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 4)
    ckpt.bless_checkpoint(d, 4)
    assert ckpt.blessed_steps(d) == [4]
    ckpt.tombstone_checkpoint(d, 4, reason="canary slo breach")
    assert ckpt.blessed_steps(d) == []
    ok, reason = ckpt.verify_manifest(d, 4)
    assert not ok and "tombstoned" in reason
    # tombstoning a never-blessed step creates the quarantine marker too
    _save(d, 6)
    ckpt.tombstone_checkpoint(d, 6, reason="eval regression")
    assert not ckpt.verify_manifest(d, 6)[0]


def test_latest_blessed_picks_newest_verifying(tmp_path):
    d = str(tmp_path / "ckpt")
    assert ckpt.latest_blessed(d) == (None, None)
    _save(d, 2)
    _save(d, 8)
    ckpt.bless_checkpoint(d, 2)
    ckpt.bless_checkpoint(d, 8)
    step, path = ckpt.latest_blessed(d)
    assert step == 8 and path.endswith("ckpt-00000008.npz")
    ckpt.tombstone_checkpoint(d, 8, reason="bad")
    assert ckpt.latest_blessed(d)[0] == 2


# -- hardened restore: skip truncated / tombstoned, fall back a step ---------

def test_restore_falls_back_past_truncated_newest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    newest = _save(d, 2, scale=2.0)
    # truncate the newest file: the torn-write case the manifest guards
    size = os.path.getsize(newest)
    with open(newest, "r+b") as f:
        f.truncate(size // 2)
    assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001.npz")
    tree, step = ckpt.restore_latest(d)
    assert step == 1
    np.testing.assert_allclose(tree["w"], TREE["w"])
    tree, step = ckpt.restore_any(d)
    assert step == 1


def test_restore_skips_tombstoned_newest(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.tombstone_checkpoint(d, 2, reason="rolled back")
    tree, step = ckpt.restore_any(d)
    assert step == 1
    tree, step = ckpt.restore_latest(d)
    assert step == 1
    assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001.npz")


def test_restore_any_blessed_only(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.bless_checkpoint(d, 1)
    # serving contract: only blessed checkpoints may serve
    tree, step = ckpt.restore_any(d, blessed_only=True)
    assert step == 1
    # trainer resume still takes the newer unblessed step
    tree, step = ckpt.restore_any(d)
    assert step == 2
    np.testing.assert_allclose(tree["w"], TREE["w"] * 2)


def test_restore_step_pinned(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    tree = ckpt.restore_step(d, 1)
    np.testing.assert_allclose(tree["w"], TREE["w"])
    with pytest.raises(FileNotFoundError):
        ckpt.restore_step(d, 99)


def test_digest_drift_skipped_on_restore(tmp_path):
    """A blessed checkpoint whose bytes drifted after blessing must not
    restore — the manifest is the arbiter, not mtime."""
    d = str(tmp_path / "ckpt")
    _save(d, 1, scale=1.0)
    _save(d, 2, scale=2.0)
    ckpt.bless_checkpoint(d, 2)
    _save(d, 2, scale=3.0)  # rewrite after blessing: digest drift
    tree, step = ckpt.restore_any(d)
    assert step == 1


# -- fault sites -------------------------------------------------------------

def test_deploy_fault_sites_registered():
    assert set(faults.DEPLOY_CHAOS_SITES) <= set(faults.SITES)
    plan = faults.random_plan(7, sites=faults.DEPLOY_CHAOS_SITES)
    assert any(s in plan for s in faults.DEPLOY_CHAOS_SITES)


# -- canary routing (serving/replicas.py) ------------------------------------

def test_canary_arm_split_deterministic():
    from tensorflowonspark_tpu.serving import replicas as R

    ids = list(range(2000))
    arms = [R.canary_arm(i, 10) for i in ids]
    # deterministic: the same id always lands on the same arm
    assert arms == [R.canary_arm(i, 10) for i in ids]
    frac = sum(arms) / len(arms)
    assert 0.05 < frac < 0.15  # ~10% with crc32 uniformity slack
    assert not any(R.canary_arm(i, 0) for i in ids)
    assert all(R.canary_arm(i, 100) for i in ids)
    # string and int ids hash identically (route ids cross IPC as either)
    assert R.canary_arm(42, 37) == R.canary_arm("42", 37)


def _bare_pool(live_idxs):
    """A ReplicaPool skeleton with just the routing state: enough to
    unit-test `_route` without spinning up an engine job."""
    from tensorflowonspark_tpu.actors.dispatch import InFlightTable
    from tensorflowonspark_tpu.serving import replicas as R

    pool = R.ReplicaPool.__new__(R.ReplicaPool)
    pool._lock = threading.Lock()
    pool._table = InFlightTable(max(live_idxs) + 1)
    for i in live_idxs:
        pool._table.up(i, 1000 + i)
    pool._canary = None
    pool._watermark = None
    pool._arm_stats = None
    return pool


def test_route_restricts_to_arm():
    pool = _bare_pool([0, 1, 2])
    pool._canary = {"replicas": (2,), "version": 9, "pct": 100.0}
    for rid in range(8):  # pct=100: every route id is canary
        entry = {"t": time.monotonic()}
        idx = pool._route(("batch", rid), entry, rid)
        assert idx == 2 and entry["arm"] == "canary"
    pool2 = _bare_pool([0, 1, 2])
    pool2._canary = {"replicas": (2,), "version": 9, "pct": 0.0}
    owners = set()
    for rid in range(8):  # pct=0: everything stays on the baseline
        entry = {"t": time.monotonic()}
        owners.add(pool2._route(("batch", rid), entry, rid))
        assert entry["arm"] == "baseline"
    assert owners <= {0, 1}
    # least-loaded inside the arm: 8 requests spread across 2 replicas
    assert owners == {0, 1}


def test_route_empty_arm_degrades_not_drops():
    pool = _bare_pool([0, 1])
    # the whole canary arm died: requests hashed to it must still land
    pool._canary = {"replicas": (7,), "version": 9, "pct": 100.0}
    for rid in range(4):
        idx = pool._route(("batch", rid), {"t": time.monotonic()}, rid)
        assert idx in (0, 1)


def test_accept_mirror_watermark_rule():
    from tensorflowonspark_tpu.serving import elastic as E

    def accept(watermark, mirror, version, reload_wm=None):
        pool = E.ElasticReplicaPool.__new__(E.ElasticReplicaPool)
        pool._lock = threading.Lock()
        pool._watermark = watermark
        pool._reload_watermark = reload_wm
        pool._mirror_version = mirror
        return pool._accept_mirror(version)

    # no watermark at all: plain latest-wins
    assert accept(None, None, 5)
    assert accept(None, 3, 5)
    assert not accept(None, 5, 3)
    # watermark 10: blessed-side syncs are latest-wins up to the mark
    assert accept(10, None, 8)
    assert accept(10, 6, 8)
    assert not accept(10, 8, 6)
    # the unblessed candidate (12 > wm) must NOT displace a blessed
    # mirror — a regrown replica adopts the blessed params
    assert not accept(10, 8, 12)
    # ...unless there is nothing blessed to keep (empty mirror), or the
    # mirror is already past the mark
    assert accept(10, None, 12)
    assert accept(10, 12, 14)
    # a blessed sync pulls a candidate-tainted mirror back under the mark
    assert accept(10, 12, 8)
    # no promotion watermark but the reload watcher broadcast step 10:
    # the same rule applies against the hot-reload watermark, so a
    # respawn's never-broadcast checkpoint can't displace the mirror
    assert accept(None, 6, 8, reload_wm=10)
    assert not accept(None, 8, 12, reload_wm=10)
    assert accept(None, None, 12, reload_wm=10)


# -- staged rollout end-to-end against a live pool ---------------------------

def _wait_versions(pool, want, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pool.versions() == want:
            return
        time.sleep(0.1)
    raise AssertionError(f"versions {pool.versions()} never became {want}")


def test_canary_promote_and_rollback_live_pool(tmp_path, monkeypatch):
    """Staged rollout against a real 3-replica pool: watermark pin
    suppresses latest-wins, a pinned canary serves the candidate to
    100% of hashed traffic, rollback re-pins the arm at the blessed
    step, and promotion converges the whole pool."""
    from tensorflowonspark_tpu.serving import replicas as R
    from tensorflowonspark_tpu.serving import server as S

    d = str(tmp_path / "ckpt")
    ckpt.save_checkpoint(d, {"version": np.array(1.0)}, step=1)
    monkeypatch.setenv("TFOS_SERVE_RELOAD_SECS", "0.2")
    spec = R.ModelSpec(predict=_serve_version, ckpt_dir=d, jit=False)
    with S.Server(spec, num_replicas=3, max_batch=8, max_delay_ms=5) as srv:
        pool = srv.pool
        c = srv.client()
        assert set(pool.versions().values()) == {1}
        pool.set_watermark(1)
        ckpt.save_checkpoint(d, {"version": np.array(2.0)}, step=2)
        # watermark pins the pool: the latest-wins watcher stands down
        time.sleep(0.8)
        assert set(pool.versions().values()) == {1}

        arm = pool.set_canary([0], version=2, pct=100)
        assert arm == (0,)
        _wait_versions(pool, {0: 2, 1: 1, 2: 1})
        got = [float(c.predict({"x": np.ones(1, np.float32)},
                               timeout=60)["version"])
               for _ in range(6)]
        assert got == [2.0] * 6  # pct=100: every request hits the canary
        stats = pool.canary_stats()
        assert stats["canary"]["n"] >= 1 and stats["canary"]["errors"] == 0
        assert stats["canary"]["p50_ms"] is not None
        assert stats["baseline"]["n"] == 0

        # candidate loses: the arm re-pins at the blessed watermark
        assert pool.rollback_canary() == 1
        assert pool.canary() is None and pool.watermark() == 1
        _wait_versions(pool, {0: 1, 1: 1, 2: 1})
        got = [float(c.predict({"x": np.ones(1, np.float32)},
                               timeout=60)["version"])
               for _ in range(4)]
        assert got == [1.0] * 4

        # second attempt wins: promotion converges the whole pool
        pool.set_canary([1], version=2, pct=0)
        _wait_versions(pool, {0: 1, 1: 2, 2: 1})
        got = [float(c.predict({"x": np.ones(1, np.float32)},
                               timeout=60)["version"])
               for _ in range(4)]
        assert got == [1.0] * 4  # pct=0: traffic stays on the baseline
        assert pool.canary_stats()["baseline"]["n"] >= 1
        assert pool.promote_canary() == 2
        assert pool.watermark() == 2 and pool.canary() is None
        _wait_versions(pool, {0: 2, 1: 2, 2: 2})
        got = [float(c.predict({"x": np.ones(1, np.float32)},
                               timeout=60)["version"])
               for _ in range(4)]
        assert got == [2.0] * 4


def test_set_canary_validates_arm(tmp_path):
    pool = _bare_pool([0, 1])
    pool._inqs = {}
    with pytest.raises(ValueError):
        pool.set_canary([5], version=2, pct=10)  # not live
    with pytest.raises(ValueError):
        pool.set_canary([0, 1], version=2, pct=10)  # no baseline left
    with pytest.raises(RuntimeError):
        pool.promote_canary()  # nothing open
    with pytest.raises(RuntimeError):
        pool.rollback_canary()


# -- promotion gate (workloads/deploy_loop.py PromotionController) -----------

class _FakeLedger:
    def __init__(self):
        self.seen = set()

    def done(self, feed, unit):
        return (feed, unit) in self.seen

    def record(self, feed, unit):
        if (feed, unit) in self.seen:
            return False
        self.seen.add((feed, unit))
        return True

    def done_units(self, feed):
        return sorted(u for f, u in self.seen if f == feed)


class _FakeMgr:
    def __init__(self):
        self.kv = {}

    def get(self, key):
        return self.kv.get(key)

    def set(self, key, value):
        self.kv[key] = value


class _FakeCtx:
    """Just enough ActorContext for PromotionController.on_tick."""

    def __init__(self, group="deploy"):
        self.group = group
        self.ledger = _FakeLedger()
        self.mgr = _FakeMgr()
        self.events = []

    def kv_set(self, key, value):
        self.mgr.set(f"actor_kv:{self.group}:{key}", value)

    def emit(self, kind, payload=None):
        self.events.append((kind, payload))


def _eval_result(ctx, step, metrics):
    ctx.mgr.set(f"actor_kv:eval:eval_result:{step}",
                {"step": step, "metrics": metrics})


def test_controller_blesses_passing_step_once(tmp_path):
    from tensorflowonspark_tpu.workloads.deploy_loop import (
        PromotionController,
    )

    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ctrl = PromotionController(d, eval_group="eval")
    ctx = _FakeCtx()
    ctrl.on_tick(ctx)  # no eval result yet: waits
    assert ckpt.read_manifest(d, 1) is None
    _eval_result(ctx, 1, {"loss": 0.5})
    ctrl.on_tick(ctx)
    ok, reason = ckpt.verify_manifest(d, 1)
    assert ok, reason
    assert ckpt.read_manifest(d, 1)["score"] == pytest.approx(0.5)
    assert ctrl.last == {"step": 1, "blessed": True, "score": 0.5,
                         "why": "pass"}
    assert [k for k, _p in ctx.events] == ["deploy/gate"]
    ctrl.on_tick(ctx)  # exactly-once: no duplicate gate event
    assert len(ctx.events) == 1


def test_controller_quarantines_nan_and_gate_max(tmp_path, monkeypatch):
    from tensorflowonspark_tpu.workloads.deploy_loop import (
        PromotionController,
    )

    d = str(tmp_path / "ckpt")
    ctrl = PromotionController(d, eval_group="eval")
    ctx = _FakeCtx()
    _save(d, 1)
    _eval_result(ctx, 1, {"loss": float("nan")})
    ctrl.on_tick(ctx)
    assert not ckpt.verify_manifest(d, 1)[0]
    assert "tombstoned" in ckpt.verify_manifest(d, 1)[1]
    assert ctrl.last["blessed"] is False
    monkeypatch.setenv("TFOS_DEPLOY_GATE_MAX", "1.0")
    _save(d, 2)
    _eval_result(ctx, 2, {"loss": 3.0})
    ctrl.on_tick(ctx)
    assert "tombstoned" in ckpt.verify_manifest(d, 2)[1]
    _save(d, 3)
    _eval_result(ctx, 3, {"loss": 0.9})
    ctrl.on_tick(ctx)
    assert ckpt.verify_manifest(d, 3)[0]
    assert ckpt.blessed_steps(d) == [3]


def test_controller_skips_prejudged_manifest(tmp_path):
    """A manifest already on disk (prior incarnation died between
    effect and ledger record) is adopted, not re-judged."""
    from tensorflowonspark_tpu.workloads.deploy_loop import (
        PromotionController,
    )

    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.7)
    ctrl = PromotionController(d, eval_group="eval")
    ctx = _FakeCtx()
    ctrl.on_tick(ctx)
    assert ctx.ledger.done("deploy_gate", 1)
    assert ctx.events == []  # adopted silently, no duplicate gate event
    assert ckpt.read_manifest(d, 1)["score"] == pytest.approx(0.7)


# -- rollout state machine (workloads/deploy_loop.py DeployLoop) -------------

def _sm_pool(live=(0, 1, 2)):
    """A routing-state ReplicaPool skeleton whose in-band reload queues
    are plain queues — the full canary/promote/rollback surface with no
    engine underneath."""
    import queue

    pool = _bare_pool(list(live))
    pool._inqs = {i: queue.Queue() for i in live}
    return pool


def _feed(pool, arm, ok=0, errors=0, ms=5.0):
    for _ in range(ok):
        pool._account({"t": time.monotonic() - ms / 1e3, "arm": arm},
                      ok=True)
    for _ in range(errors):
        pool._account({"t": time.monotonic() - ms / 1e3, "arm": arm},
                      ok=False)


def _loop(pool, d, **kw):
    from tensorflowonspark_tpu.workloads.deploy_loop import DeployLoop

    kw.setdefault("pct", 50)
    kw.setdefault("burn_secs", 5.0)
    kw.setdefault("min_samples", 3)
    return DeployLoop(pool, d, **kw)


def test_deploy_bootstrap_promotes_first_blessed(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d)
    row = loop.pump(now=0.0)
    assert row["state"] == "idle" and row["watermark"] == 1
    assert loop.promotions == 1
    assert loop.last_verdict["reasons"] == ["bootstrap"]
    # whole pool pinned: every replica got a targeted reload
    assert all(q.get_nowait() == ("reload", 1)
               for q in pool._inqs.values())


def test_deploy_promotes_clean_candidate(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d)
    loop.recover()
    assert pool.watermark() == 1 and loop.promotions == 0
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=0.45)
    row = loop.pump(now=100.0)
    assert row["state"] == "burn"
    assert pool.canary() == {"replicas": (0,), "version": 2, "pct": 50.0}
    _feed(pool, "canary", ok=10)
    _feed(pool, "baseline", ok=10)
    assert loop.pump(now=101.0)["state"] == "burn"  # window still open
    row = loop.pump(now=200.0)
    assert row["state"] == "idle"
    assert pool.watermark() == 2 and loop.promotions == 1
    assert loop.last_verdict["verdict"] == "promote"
    assert ckpt.verify_manifest(d, 2)[0]  # promoted, NOT tombstoned


def test_deploy_rolls_back_on_eval_regression(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=5.0)  # way past the 10% tol
    pool = _sm_pool()
    loop = _loop(pool, d)
    pool.set_watermark(1)
    loop.pump(now=0.0)
    _feed(pool, "canary", ok=10)
    _feed(pool, "baseline", ok=10)
    row = loop.pump(now=50.0)
    assert row["state"] == "idle"
    assert loop.rollbacks == 1 and loop.promotions == 0
    assert pool.watermark() == 1 and pool.canary() is None
    assert any("eval regression" in r
               for r in loop.last_verdict["reasons"])
    # the candidate is quarantined and never re-offered
    assert "tombstoned" in ckpt.verify_manifest(d, 2)[1]
    assert loop.pump(now=60.0)["state"] == "idle"


def test_deploy_rolls_back_on_slo_breach(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d)
    pool.set_watermark(1)
    loop.pump(now=0.0)
    # canary errors half its traffic; the baseline is clean — the
    # availability objective (99% ok) breaches on the canary arm only
    _feed(pool, "canary", ok=10, errors=10)
    _feed(pool, "baseline", ok=20)
    loop.pump(now=50.0)
    assert loop.rollbacks == 1
    assert any("slo deploy_availability" in r
               for r in loop.last_verdict["reasons"])
    assert "tombstoned" in ckpt.verify_manifest(d, 2)[1]


def test_deploy_insufficient_traffic_fails_safe(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d)
    pool.set_watermark(1)
    loop.pump(now=0.0)
    loop.pump(now=50.0)  # burn expired with zero canary samples
    assert loop.rollbacks == 1
    assert any("insufficient canary traffic" in r
               for r in loop.last_verdict["reasons"])


def test_deploy_latency_regression_guard(tmp_path):
    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d, lat_tol=0.5)
    pool.set_watermark(1)
    loop.pump(now=0.0)
    _feed(pool, "canary", ok=10, ms=500.0)   # 10x the baseline p95
    _feed(pool, "baseline", ok=10, ms=20.0)
    loop.pump(now=50.0)
    assert loop.rollbacks == 1
    assert any("latency regression" in r
               for r in loop.last_verdict["reasons"])


def test_deploy_fault_sites_rearm_and_retry(tmp_path, monkeypatch):
    """An injected fault at a deploy site leaves the state machine
    unchanged; the next pump retries the same transition."""
    from tensorflowonspark_tpu.utils.faults import FaultInjected

    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    _save(d, 2)
    ckpt.bless_checkpoint(d, 2, score=0.45)
    pool = _sm_pool()
    loop = _loop(pool, d)
    pool.set_watermark(1)
    monkeypatch.setenv("TFOS_FAULT_PLAN",
                       "deploy.canary:exc@1,deploy.promote:exc@1")
    with pytest.raises(FaultInjected):
        loop.pump(now=0.0)
    assert loop.state == "idle" and pool.canary() is None  # unchanged
    assert loop.pump(now=1.0)["state"] == "burn"  # re-armed, retried
    _feed(pool, "canary", ok=10)
    _feed(pool, "baseline", ok=10)
    with pytest.raises(FaultInjected):
        loop.pump(now=50.0)  # promote commit faulted
    assert loop.state == "burn" and pool.watermark() == 1
    assert loop.pump(now=51.0)["state"] == "idle"  # retried and won
    assert pool.watermark() == 2 and loop.promotions == 1


def test_deploy_table_reports_live_loops(tmp_path):
    from tensorflowonspark_tpu.workloads.deploy_loop import deploy_table

    d = str(tmp_path / "ckpt")
    _save(d, 1)
    ckpt.bless_checkpoint(d, 1, score=0.5)
    pool = _sm_pool()
    loop = _loop(pool, d)
    loop.pump(now=0.0)
    rows = [r for r in deploy_table() if r["ckpt_dir"] == d]
    assert len(rows) == 1
    assert rows[0]["watermark"] == 1 and rows[0]["state"] == "idle"
    assert rows[0]["promotions"] == 1


# -- seeded chaos smoke: deploy fault sites, convergence guaranteed ----------

def test_deploy_chaos_smoke_converges(tmp_path, monkeypatch):
    """A seeded random fault plan over the deploy sites must never wedge
    the rollout: every candidate eventually promotes or rolls back, the
    pool ends consistent, and the regressed step stays quarantined."""
    from tensorflowonspark_tpu.utils.faults import FaultInjected

    script = [(1, 0.50), (2, 0.45), (3, 5.0), (4, 0.40)]
    for seed in (3, 11, 29):
        d = str(tmp_path / f"ckpt-{seed}")
        faults._reset_for_tests()
        monkeypatch.setenv(
            "TFOS_FAULT_PLAN",
            faults.random_plan(seed, sites=faults.DEPLOY_CHAOS_SITES))
        pool = _sm_pool()
        loop = _loop(pool, d)
        now = 0.0
        for step, score in script:
            _save(d, step)
            ckpt.bless_checkpoint(d, step, score=score)
            for _ in range(200):
                now += 1.0
                if loop.state == "burn":
                    _feed(pool, "canary", ok=2)
                    _feed(pool, "baseline", ok=2)
                try:
                    loop.pump(now=now)
                except FaultInjected:
                    continue  # the chaos contract: retry next pump
                ok, why = ckpt.verify_manifest(d, step)
                if loop.state == "idle" and (
                        pool.watermark() == step
                        or (not ok and "tombstoned" in (why or ""))):
                    break
            else:
                raise AssertionError(
                    f"seed {seed}: step {step} never resolved "
                    f"(state={loop.state}, wm={pool.watermark()})")
        assert pool.watermark() == 4, f"seed {seed}"
        assert loop.promotions == 3 and loop.rollbacks == 1, f"seed {seed}"
        assert pool.canary() is None
        assert "tombstoned" in ckpt.verify_manifest(d, 3)[1]


# -- slow lane: full loop e2e ------------------------------------------------

def _eval_loss(tree, step):
    """Module-level eval_fn (cloudpickled into the sidecar process)."""
    return {"loss": float(np.asarray(tree["loss"])), "step": step}


def _save_versioned(d, step, loss):
    return ckpt.save_checkpoint(
        d, {"version": np.array(float(step)),
            "loss": np.array(float(loss))}, step=step)


def _wait_for(cond, timeout=90, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"{what} not reached within {timeout}s")


def _pump_until(loop, cond, timeout=90, what="state"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        loop.pump()
        if cond(loop):
            return
        time.sleep(0.1)
    raise AssertionError(
        f"{what} not reached within {timeout}s (status={loop.status()})")


@pytest.mark.slow
def test_deploy_e2e_gate_canary_rollback(tmp_path, monkeypatch):
    """The whole loop against a live 3-replica pool: checkpoints gated
    exactly-once, bootstrap pin, clean candidate canaried and promoted,
    a regressed candidate canaried and auto-rolled back (tombstone +
    flight dump + rollback telemetry), a NaN candidate quarantined at
    the gate — with client traffic running throughout and ZERO dropped
    requests."""
    from tensorflowonspark_tpu.actors import ActorSystem, SupervisionPolicy
    from tensorflowonspark_tpu.serving import server as S
    from tensorflowonspark_tpu.utils import metrics_registry, telemetry
    from tensorflowonspark_tpu.workloads.deploy_loop import (
        DeployLoop, PromotionController,
    )
    from tensorflowonspark_tpu.workloads.eval_sidecar import EvalSidecar

    d = str(tmp_path / "ckpt")
    tdir = str(tmp_path / "telemetry")
    monkeypatch.setenv(telemetry.DIR_ENV, tdir)
    monkeypatch.setenv(telemetry.NODE_ENV, "deploy-driver")
    monkeypatch.delenv(telemetry.SPOOL_ENV, raising=False)
    monkeypatch.delenv(telemetry.ROLE_ENV, raising=False)
    monkeypatch.setenv(metrics_registry.PORT_ENV, "0")
    metrics_registry.reset()
    monkeypatch.setenv("TFOS_SERVE_RELOAD_SECS", "0.2")

    _save_versioned(d, 1, loss=0.5)
    pol = SupervisionPolicy(heartbeat_secs=0.2, stale_secs=5.0,
                            tick_secs=0.1)
    spec = S.ModelSpec(predict=_serve_version, ckpt_dir=d, jit=False)
    stop = threading.Event()
    served, drops = [], []

    with S.Server(spec, num_replicas=3, max_batch=8,
                  max_delay_ms=5) as srv, ActorSystem(2) as sys_:
        pool, c = srv.pool, srv.client()

        def traffic():
            while not stop.is_set():
                try:
                    out = c.predict({"x": np.ones(1, np.float32)},
                                    timeout=60)
                    served.append(float(out["version"]))
                except Exception as e:  # noqa: BLE001 - any loss counts
                    drops.append(repr(e))

        sys_.spawn(EvalSidecar(d, _eval_loss), "eval", policy=pol)
        sys_.spawn(PromotionController(d), "deploy", policy=pol)
        loop = DeployLoop(pool, d, pct=60, canary_count=1, burn_secs=3.0,
                          min_samples=3, lat_tol=10.0)
        assert loop.recover() is None  # nothing blessed yet

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            # step 1: gated, blessed, bootstrap-pinned fleet-wide
            _wait_for(lambda: ckpt.read_manifest(d, 1) is not None,
                      what="step 1 gate")
            assert ckpt.verify_manifest(d, 1)[0]
            _pump_until(loop, lambda lp: pool.watermark() == 1,
                        what="bootstrap watermark")
            assert loop.promotions == 1

            # step 2: clean improvement -> canary -> burn -> promote
            _save_versioned(d, 2, loss=0.4)
            _wait_for(lambda: ckpt.read_manifest(d, 2) is not None,
                      what="step 2 gate")
            _pump_until(loop, lambda lp: lp.promotions >= 2
                        and lp.state == "idle", what="step 2 promotion")
            assert pool.watermark() == 2
            assert ckpt.verify_manifest(d, 2)[0]  # NOT tombstoned
            _wait_versions(pool, {0: 2, 1: 2, 2: 2})

            # step 3: finite eval regression -> passes the gate, loses
            # the burn verdict -> auto-rollback
            _save_versioned(d, 3, loss=30.0)
            _wait_for(lambda: ckpt.read_manifest(d, 3) is not None,
                      what="step 3 gate")
            assert ckpt.verify_manifest(d, 3)[0]  # blessed: gate passed
            _pump_until(loop, lambda lp: lp.rollbacks >= 1,
                        what="step 3 rollback")
            assert pool.watermark() == 2 and pool.canary() is None
            assert any("eval regression" in r
                       for r in loop.last_verdict["reasons"])
            assert "tombstoned" in ckpt.verify_manifest(d, 3)[1]
            _wait_versions(pool, {0: 2, 1: 2, 2: 2})

            # step 4: NaN loss -> quarantined at the gate, never canaried
            _save_versioned(d, 4, loss=float("nan"))
            _wait_for(lambda: ckpt.read_manifest(d, 4) is not None,
                      what="step 4 gate")
            assert "tombstoned" in ckpt.verify_manifest(d, 4)[1]
            for _ in range(5):
                row = loop.pump()
                assert row["state"] == "idle" and row["candidate"] is None
                time.sleep(0.1)
            assert pool.watermark() == 2
            assert float(c.predict({"x": np.ones(1, np.float32)},
                                   timeout=60)["version"]) == 2.0
        finally:
            stop.set()
            t.join(timeout=30)

    # zero dropped requests across bootstrap, promote and rollback
    assert not drops, f"dropped requests: {drops[:5]}"
    assert len(served) > 20
    assert {1.0, 2.0} <= set(served)  # traffic crossed the promotion
    assert 3.0 in served  # ...and the canary arm really took traffic

    # driver metrics: the loop's commit counters moved
    snap = metrics_registry.snapshot()
    total = lambda name: sum(  # noqa: E731 - tiny local reducer
        s["value"] for s in snap.get(name, {}).get("series", ()))
    assert total("tfos_deploy_promotions_total") >= 2
    assert total("tfos_deploy_rollbacks_total") == 1

    # rollback evidence: a flight dump under the telemetry dir with the
    # deploy/rollback trigger, and version-tagged serve spans
    telemetry.flush()
    dumps = []
    for root, _dirs, files in os.walk(tdir):
        for name in files:
            if name.startswith("flight-") and name.endswith(".json"):
                with open(os.path.join(root, name), encoding="utf-8") as f:
                    dumps.append(json.load(f))
    assert any(dp["trigger"] == telemetry.DEPLOY_ROLLBACK
               and "eval regression" in (dp["reason"] or "")
               for dp in dumps), f"no rollback flight dump in {tdir}"
    versions = set()
    for root, _dirs, files in os.walk(tdir):
        for name in files:
            if not name.endswith(".jsonl"):
                continue
            with open(os.path.join(root, name), encoding="utf-8") as f:
                for line in f:
                    rec = json.loads(line)
                    attrs = rec.get("attrs") or {}
                    if (rec.get("name") == telemetry.SERVE_REQUEST
                            and "version" in attrs):
                        versions.add(int(attrs["version"]))
    assert {1, 2, 3} <= versions  # spans split by the serving version


@pytest.mark.slow
def test_run_deploy_loop_absorbs_faults(tmp_path, monkeypatch):
    """The batteries-included driver: spawns sidecar + controller into
    its own system, recovers, and absorbs an injected promote fault
    (retries next pump) — the summary shows the landed promotion."""
    from tensorflowonspark_tpu.workloads.deploy_loop import run_deploy_loop

    d = str(tmp_path / "ckpt")
    _save_versioned(d, 1, loss=0.5)
    faults._reset_for_tests()
    monkeypatch.setenv("TFOS_FAULT_PLAN", "deploy.promote:exc@1")
    pool = _sm_pool()
    summary = run_deploy_loop(
        pool, d, _eval_loss, duration=60.0, poll_secs=0.1,
        stop_when=lambda lp: lp.promotions >= 1,
        pct=50, burn_secs=1.0, min_samples=1)
    assert summary["watermark"] == 1
    assert summary["promotions"] == 1 and summary["rollbacks"] == 0
    assert pool.watermark() == 1
    assert ckpt.verify_manifest(d, 1)[0]
