"""Online-serving tests: micro-batcher units, replica pool + hot reload,
SLO stats/telemetry, HTTP frontend, and the PR's acceptance smoke
(64 concurrent clients against 2 replicas; coalescing + one compile per
shape bucket).  Slow lane: a SIGKILLed replica under load (respawn,
zero dropped non-shed requests)."""

import json
import os
import sys
import threading
import time
import types
import urllib.request

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import batcher as B
from tensorflowonspark_tpu.serving import replicas as R
from tensorflowonspark_tpu.serving import server as S

pytestmark = pytest.mark.serve


# --- probe predicts (module-level: shipped to executor processes) -----------

def _double_predict(params, inputs):
    x = np.asarray(inputs["x"])
    return {"y": x * params["scale"]}


def _version_predict(params, inputs):
    x = np.asarray(inputs["x"])
    time.sleep(0.01)
    return {"version": np.full(x.shape[0], float(np.asarray(params["version"])))}


def _slow_predict(params, inputs):
    x = np.asarray(inputs["x"])
    time.sleep(0.05)
    return {"y": x * 1.0}


# --- batcher units ----------------------------------------------------------

def test_bucket_size_pow2_and_cap():
    assert [B.bucket_size(n, 64) for n in (1, 2, 3, 5, 9, 33, 64, 100)] == \
        [1, 2, 4, 8, 16, 64, 64, 64]
    # the cap itself is a legal bucket even when not a power of two
    assert B.bucket_size(48, 48) == 48
    assert B.bucket_size(49, 48) == 48
    assert B.bucket_size(3, 48) == 4


def test_pad_rows_edge_replication_and_errors():
    a = np.arange(6, dtype=np.float32).reshape(3, 2)
    padded = B.pad_rows(a, 5)
    assert padded.shape == (5, 2)
    assert (padded[3] == a[-1]).all() and (padded[4] == a[-1]).all()
    assert B.pad_rows(a, 3) is a  # no-op returns the input
    with pytest.raises(ValueError):
        B.pad_rows(a, 2)  # cannot shrink
    with pytest.raises(ValueError):
        B.pad_rows(np.zeros((0, 2)), 4)  # nothing to replicate
    with pytest.raises(ValueError):
        B.pad_rows(np.float32(3.0), 4)  # scalar has no batch axis


def test_pad_columns_preserves_container():
    d = B.pad_columns({"a": np.zeros((2, 3)), "b": np.ones((2,))}, 4)
    assert set(d) == {"a", "b"}
    assert d["a"].shape == (4, 3) and d["b"].shape == (4,)
    t = B.pad_columns((np.zeros((3, 1)),), 8)
    assert isinstance(t, tuple) and t[0].shape == (8, 1)


def test_batcher_coalesces_concurrent_requests():
    batches = []
    done = threading.Event()

    def dispatch(batch):
        batches.append(batch)
        batch.complete({"y": batch.inputs["x"] + 1})
        if sum(b.n_valid for b in batches) >= 16:
            done.set()

    mb = B.MicroBatcher(dispatch, max_batch=32, max_delay_ms=50,
                        queue_max=100)
    # queue a wave BEFORE starting the batcher thread so the first gather
    # sees them all at once — deterministic coalescing
    reqs = [mb.submit({"x": np.full((2,), float(i))}) for i in range(16)]
    mb.start()
    results = [r.result(timeout=10) for r in reqs]
    assert done.wait(5)
    mb.close()
    assert len(batches) == 1 and batches[0].n_valid == 16
    assert batches[0].bucket == 16
    assert batches[0].inputs["x"].shape == (16, 2)
    for i, row in enumerate(results):
        assert (row["y"] == i + 1).all()
    # timing attrs ride on the resolved request
    attrs = reqs[0].attrs
    assert attrs["batch"] == 16 and attrs["bucket"] == 16
    assert attrs["total_ms"] >= 0 and attrs["queue_ms"] >= 0


def test_batcher_deadline_flush_single_request():
    batches = []

    def dispatch(batch):
        batches.append(batch)
        batch.complete({"y": batch.inputs["x"]})

    mb = B.MicroBatcher(dispatch, max_batch=64, max_delay_ms=20,
                        queue_max=10).start()
    t0 = time.perf_counter()
    row = mb.submit({"x": np.ones(3)}).result(timeout=5)
    waited = time.perf_counter() - t0
    mb.close()
    assert row["y"].shape == (3,)
    # a lone request is padded to bucket 1 and flushed at the deadline,
    # not held until a batch fills
    assert batches[0].bucket == 1 and batches[0].n_valid == 1
    assert waited < 5.0


def test_batcher_groups_by_signature():
    batches = []

    def dispatch(batch):
        batches.append(batch)
        batch.complete({"y": batch.inputs["x"]})

    mb = B.MicroBatcher(dispatch, max_batch=32, max_delay_ms=50,
                        queue_max=100)
    small = [mb.submit({"x": np.zeros((2,))}) for _ in range(3)]
    big = [mb.submit({"x": np.zeros((4,))}) for _ in range(5)]
    mb.start()
    for r in small + big:
        r.result(timeout=10)
    mb.close()
    shapes = sorted((b.n_valid, b.inputs["x"].shape) for b in batches)
    assert shapes == [(3, (4, 2)), (5, (8, 4))]


def test_batcher_sheds_past_queue_max():
    sheds = []
    mb = B.MicroBatcher(lambda b: None, max_batch=8, max_delay_ms=5,
                        queue_max=2, on_shed=lambda d, l: sheds.append((d, l)))
    # not started: nothing drains the queue, so depth is deterministic
    mb.submit({"x": np.ones(1)})
    mb.submit({"x": np.ones(1)})
    with pytest.raises(B.Overloaded) as ei:
        mb.submit({"x": np.ones(1)})
    assert ei.value.depth >= 2 and ei.value.limit == 2
    assert ei.value.retry_after >= 0.05
    assert sheds == [(ei.value.depth, 2)]
    mb.close()


def test_batcher_close_fails_queued_requests():
    mb = B.MicroBatcher(lambda b: None, queue_max=10)  # never started
    req = mb.submit({"x": np.ones(1)})
    mb.close()
    with pytest.raises(RuntimeError, match="shut down"):
        req.result(timeout=1)
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit({"x": np.ones(1)})


def test_batch_resolves_once():
    req = B.PendingResult({"x": np.ones(1)})
    batch = B.Batch(1, [req], {"x": np.ones((1, 1))}, 1, 0.0)
    assert batch.complete({"y": np.array([[1.0]])})
    assert not batch.complete({"y": np.array([[9.0]])})  # duplicate: no-op
    assert not batch.fail(RuntimeError("late"))
    assert (req.result(timeout=1)["y"] == 1.0).all()


# --- pipeline partial-batch padding (satellite b) ---------------------------

def test_pipeline_pads_partial_batch(tmp_path):
    from tensorflowonspark_tpu import pipeline as P
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    shapes = []
    probe = types.ModuleType("_tfos_pad_probe")

    def probe_predict(params, inputs):
        (x,) = inputs.values()
        shapes.append(np.asarray(x).shape)
        return {"out": np.asarray(x).sum(axis=1)}

    probe.predict = probe_predict
    sys.modules["_tfos_pad_probe"] = probe
    try:
        export = str(tmp_path / "export")
        ckpt.export_model(export, {"w": np.ones(1)},
                          metadata={"predict": "_tfos_pad_probe:predict"})
        rows = [(list(map(float, r)),)
                for r in np.arange(20, dtype=np.float32).reshape(10, 2)]
        args = P.Namespace({
            "export_dir": export, "batch_size": 4,
            "input_mapping": {"features": "x"},
            "output_mapping": {"out": "s"},
        })
        out = P._run_model(args)(iter(rows))
        # 10 rows / batch 4 -> 4,4,2; the final 2 are padded up to 4 so
        # the predict only ever sees ONE shape
        assert set(shapes) == {(4, 2)}
        assert len(out) == 10  # padded rows sliced back off
        expect = np.arange(20, dtype=np.float32).reshape(10, 2).sum(axis=1)
        assert [r["s"] for r in out] == pytest.approx(list(expect))

        # opt-out: --no_pad_partial exposes the ragged final batch
        shapes.clear()
        P._model_cache.clear()
        args_nopad = P.Namespace(dict(args.items(), pad_partial=False))
        out = P._run_model(args_nopad)(iter(rows))
        assert (2, 2) in shapes and len(out) == 10
    finally:
        del sys.modules["_tfos_pad_probe"]
        P._model_cache.clear()


def test_inference_cli_pad_partial_flag():
    from tensorflowonspark_tpu import inference

    p = inference.build_parser()
    base = ["--export_dir", "/e", "--input", "/i", "--output", "/o"]
    assert p.parse_args(base).pad_partial is True
    assert p.parse_args(base + ["--no_pad_partial"]).pad_partial is False


# --- replica pool: end-to-end numpy service ---------------------------------

def test_server_numpy_predict_roundtrip():
    spec = R.ModelSpec(predict=_double_predict, params={"scale": 3.0},
                       jit=False)
    with S.Server(spec, num_replicas=2, max_batch=8, max_delay_ms=5) as srv:
        c = srv.client()
        out = c.predict({"x": np.array([1.0, 2.0], np.float32)}, timeout=60)
        assert out["y"] == pytest.approx([3.0, 6.0])
        results = {}

        def burst(i):
            r = c.predict({"x": np.full((2,), float(i), np.float32)},
                          timeout=60)
            results[i] = r["y"]

        ts = [threading.Thread(target=burst, args=(i,)) for i in range(12)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(results) == 12
        for i, y in results.items():
            assert y == pytest.approx([3.0 * i] * 2)
        summ = srv.summary()
        assert summ["completed"] == 13 and summ["errors"] == 0
        assert summ["p99_ms"] > 0
        assert srv.pool.live_replicas() == [0, 1]


def test_model_spec_requires_a_model():
    with pytest.raises(ValueError):
        R.ModelSpec()
    # string predict specs resolve without an export_dir
    spec = R.ModelSpec(predict="tensorflowonspark_tpu.models.mnist:predict")
    assert spec.predict.endswith(":predict")


# --- checkpoint hot-reload (satellite c) ------------------------------------

def test_checkpoint_hot_reload(tmp_path, monkeypatch):
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    ckpt_dir = str(tmp_path / "ckpts")
    ckpt.save_checkpoint(ckpt_dir, {"version": np.array(1.0)}, step=1)
    assert ckpt.latest(ckpt_dir)[0] == 1
    monkeypatch.setenv("TFOS_SERVE_RELOAD_SECS", "0.2")
    spec = R.ModelSpec(predict=_version_predict, ckpt_dir=ckpt_dir,
                       jit=False)
    with S.Server(spec, num_replicas=2, max_batch=8, max_delay_ms=5) as srv:
        c = srv.client()
        first = [c.predict({"x": np.ones(1, np.float32)}, timeout=60)
                 for _ in range(4)]
        # per-request rows are sliced from the (n,) column -> scalars
        assert all(float(r["version"]) == 1.0 for r in first)
        assert set(srv.pool.versions().values()) == {1}

        ckpt.save_checkpoint(ckpt_dir, {"version": np.array(2.0)}, step=2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if set(srv.pool.versions().values()) == {2}:
                break
            time.sleep(0.1)
        assert set(srv.pool.versions().values()) == {2}, srv.pool.versions()
        # requests after the ack see the new params on every replica
        later = [c.predict({"x": np.ones(1, np.float32)}, timeout=60)
                 for _ in range(4)]
        assert all(float(r["version"]) == 2.0 for r in later)


# --- acceptance smoke: coalescing + compile-per-bucket under load -----------

def test_acceptance_smoke_64_clients_2_replicas(tmp_path, monkeypatch):
    """ISSUE acceptance: 2-replica CPU service, 64 concurrent in-process
    clients; batcher demonstrably coalesces (mean device batch > 4),
    exactly one compile per shape bucket, SLO telemetry emitted and
    summarized by trace_merge."""
    import jax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    from tensorflowonspark_tpu.utils import telemetry

    tdir = str(tmp_path / "telemetry")
    monkeypatch.setenv("TFOS_TELEMETRY_DIR", tdir)
    # a prior test's spool override would silently reroute our spans
    monkeypatch.delenv("TFOS_TELEMETRY_SPOOL", raising=False)
    telemetry.configure(node_id="driver", role="driver")

    export = str(tmp_path / "export")
    ckpt.export_model(export, mnist.init_params(jax.random.PRNGKey(0)),
                      metadata={
        "predict": "tensorflowonspark_tpu.models.mnist:serve_predict",
    })
    spec = R.ModelSpec(export_dir=export)
    rng = np.random.default_rng(0)
    images = rng.random((64, 28, 28, 1)).astype(np.float32)
    with S.Server(spec, num_replicas=2, max_batch=32,
                  max_delay_ms=10) as srv:
        c = srv.client()
        # warmup (jax import + first compiles happen here)
        for _ in range(2):
            c.predict({"image": images[0]}, timeout=300)
        errors = []

        def burst(i):
            try:
                out = c.predict({"image": images[i]}, timeout=300)
                assert out["logits"].shape == (10,)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=burst, args=(i,)) for i in range(64)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors, errors[:3]
        summ = srv.summary(include_replicas=True)
        telemetry.flush()

    assert summ["completed"] == 66 and summ["shed"] == 0
    # coalescing: 64 near-simultaneous requests must form real batches
    assert summ["mean_device_batch"] > 4, summ
    # every bucket is a power of two (or the cap)
    for b in summ["buckets"]:
        assert b == 32 or (b & (b - 1)) == 0, summ["buckets"]
    assert summ["p99_ms"] > 0 and summ["p50_ms"] > 0
    # exactly one jit compile per (replica, shape bucket): the AOT
    # compile-count hook increments once per first-seen signature
    total_compiles = 0
    for st in summ["replica_stats"].values():
        for sig, count in st["compiles"].items():
            assert count == 1, (sig, st["compiles"])
            total_compiles += count
    n_buckets_seen = len(summ["buckets"])
    assert 0 < total_compiles <= 2 * n_buckets_seen

    # the telemetry spool carries serve/request spans; trace_merge
    # summarizes them into the serving SLO section
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "scripts"))
    try:
        import trace_merge
    finally:
        sys.path.pop(0)
    pairs, _skipped = trace_merge.load_records(tdir)
    text, stats = trace_merge.summarize(pairs)
    assert stats["serving"]["requests"] >= 66
    assert "-- serving" in text
    assert stats["serving"]["p99_ms"] > 0


# --- HTTP frontend ----------------------------------------------------------

class _StubPool:
    def live_replicas(self):
        return [0]

    def versions(self):
        return {0: 0}


class _ShedStub:
    pool = _StubPool()

    def predict(self, example, timeout=None, trace=None):
        raise B.Overloaded(5, 4, retry_after=0.25)

    def summary(self, include_replicas=False):
        return {"requests": 0}


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_overload_maps_to_503_retry_after():
    httpd = S.serve_http(_ShedStub(), port=0, block=False)
    try:
        host, port = httpd.server_address
        code, headers, body = _post(
            f"http://{host}:{port}/v1/predict", {"inputs": {"x": [1.0]}})
        assert code == 503
        assert body["error"] == "overloaded"
        assert float(headers["Retry-After"]) == pytest.approx(0.25)
        # malformed body -> 400, not a crash
        code, _, body = _post(f"http://{host}:{port}/v1/predict",
                              {"nope": 1})
        assert code == 400
    finally:
        httpd.shutdown()


def test_http_predict_and_health_roundtrip():
    spec = R.ModelSpec(predict=_double_predict, params={"scale": 2.0},
                       jit=False)
    with S.Server(spec, num_replicas=1, max_batch=4, max_delay_ms=5) as srv:
        httpd = S.serve_http(srv, port=0, block=False)
        try:
            host, port = httpd.server_address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz") as resp:
                assert resp.status == 200
                assert json.loads(resp.read())["status"] == "ok"
            code, _, body = _post(
                f"http://{host}:{port}/v1/predict",
                {"inputs": {"x": [1.0, 2.0, 3.0]}})
            assert code == 200
            assert body["outputs"]["y"] == pytest.approx([2.0, 4.0, 6.0])
            with urllib.request.urlopen(
                    f"http://{host}:{port}/stats") as resp:
                stats = json.loads(resp.read())
            assert stats["completed"] >= 1
        finally:
            httpd.shutdown()


def test_serve_cli_parser():
    p = S.build_parser()
    args = p.parse_args(["--export_dir", "/e", "--port", "9000"])
    assert args.export_dir == "/e" and args.port == 9000
    assert args.num_replicas is None


# --- child-pid ledger satellite (a) -----------------------------------------

def test_manager_start_keeps_cwd_clean(tmp_path, monkeypatch):
    """Regression: driver-side manager.start used to drop tfos_child_pids
    into the launch CWD (the repo root, typically)."""
    from tensorflowonspark_tpu import manager as tfmanager
    from tensorflowonspark_tpu.utils import hostinfo

    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("TFOS_EXECUTOR_INDEX", raising=False)
    monkeypatch.delenv(hostinfo.CHILD_PIDS_DIR_ENV, raising=False)
    mgr = tfmanager.start(b"test-key-serving", ["q"])
    try:
        assert not (tmp_path / "tfos_child_pids").exists()
        pids = hostinfo.read_child_pids()  # ledger in the tempdir default
        assert pids, "manager server pid should be tracked"
    finally:
        mgr.shutdown()
        hostinfo.clear_child_pids()


def test_child_pids_dir_override_and_executor_contract(tmp_path, monkeypatch):
    from tensorflowonspark_tpu.utils import hostinfo

    monkeypatch.setenv(hostinfo.CHILD_PIDS_DIR_ENV, str(tmp_path / "ovr"))
    assert hostinfo.child_pids_dir() == str(tmp_path / "ovr")
    monkeypatch.delenv(hostinfo.CHILD_PIDS_DIR_ENV)
    # executors keep the original working-dir contract
    monkeypatch.setenv("TFOS_EXECUTOR_INDEX", "0")
    monkeypatch.chdir(tmp_path)
    assert hostinfo.child_pids_dir() == str(tmp_path)
    monkeypatch.delenv("TFOS_EXECUTOR_INDEX")
    assert "tfos-pids-" in hostinfo.child_pids_dir()


# --- slow lane: replica SIGKILL under load (satellite e) --------------------

@pytest.mark.slow
def test_replica_sigkill_respawn_zero_drop():
    """A 2-replica service survives one SIGKILLed replica under load:
    the engine respawns it, orphaned batches are re-dispatched, and no
    non-shed request is dropped."""
    spec = R.ModelSpec(predict=_slow_predict, params={}, jit=False)
    with S.Server(spec, num_replicas=2, max_batch=8, max_delay_ms=5,
                  queue_max=10_000) as srv:
        c = srv.client()
        c.predict({"x": np.ones(2, np.float32)}, timeout=60)  # warm
        victim = srv.pool.replica_pids()[0]
        results, errors = [], []

        def burst(i):
            for j in range(10):
                try:
                    r = c.predict(
                        {"x": np.full((2,), float(i), np.float32)},
                        timeout=120)
                    results.append((i, r["y"]))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        ts = [threading.Thread(target=burst, args=(i,)) for i in range(16)]
        for t in ts:
            t.start()
        time.sleep(0.3)  # let batches land on both replicas
        os.kill(victim, 9)
        for t in ts:
            t.join()
        assert not errors, errors[:3]
        assert len(results) == 160
        for i, y in results:
            assert y == pytest.approx([float(i)] * 2)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (srv.pool.respawns_observed >= 1
                    and srv.pool.live_replicas() == [0, 1]):
                break
            time.sleep(0.2)
        assert srv.pool.respawns_observed >= 1
        assert srv.pool.live_replicas() == [0, 1]
        summ = srv.summary()
        assert summ["errors"] == 0 and summ["completed"] >= 161
