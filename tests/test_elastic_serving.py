"""Elastic serving tier (serving/elastic.py, docs/serving.md "Degrade by
resize"): slot-assignment math, pid-keyed compile-cache invalidation,
declared degraded admission (proportional shed + Retry-After floor),
quiesced dispatch, the boot/adopt handshake helpers, graceful drain, and
the new chaos sites.  Slow lane: the acceptance e2e — SIGKILL one of two
elastic replicas under live predict+decode traffic, assert zero
dropped/duplicated work, a declared degraded window, and a re-grow that
ADOPTS the survivors' live params (checkpoint files already deleted) —
plus a seeded chaos smoke over ``faults.SERVE_CHAOS_SITES``."""

import os
import queue as _queue
import threading
import time

import cloudpickle
import numpy as np
import pytest

from tensorflowonspark_tpu.actors.dispatch import InFlightTable
from tensorflowonspark_tpu.serving import batcher as B
from tensorflowonspark_tpu.serving import elastic as E
from tensorflowonspark_tpu.serving import replicas as R
from tensorflowonspark_tpu.serving import server as S
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.serve


def _double_predict(params, inputs):
    del params
    return {"y": inputs["x"] * 2.0}


def _echo_version(params, inputs):
    n = inputs["x"].shape[0]
    return {"version": np.full((n,), float(params["version"]), np.float32)}


# --- slot assignment ---------------------------------------------------------

def test_assign_slots_even_with_remainder():
    assert E.assign_slots(4, [0, 1]) == {0: 2, 1: 2}
    # remainder goes to the lowest indices, deterministically
    assert E.assign_slots(5, [2, 0, 1]) == {0: 2, 1: 2, 2: 1}
    assert E.assign_slots(3, [1]) == {1: 3}
    assert E.assign_slots(3, []) == {}
    covered = E.assign_slots(7, [0, 1, 2])
    assert sum(covered.values()) == 7


# --- quiesced dispatch (drain primitive) ------------------------------------

def test_inflight_quiesce_and_owned_count():
    t = InFlightTable(pool_size=2)
    t.up(0, 100)
    t.up(1, 101)
    t.quiesce(0)
    for i in range(4):
        owner = t.add(("batch", i), {"blob": b""})
        assert owner == 1  # quiesced member takes no NEW work
    assert t.owned_count(1) == 4 and t.owned_count(0) == 0
    # when every live member is draining they still beat a blind guess
    t.quiesce(1)
    assert t.add(("batch", 9), {"blob": b""}) in (0, 1)
    t.unquiesce(0)
    t.pop(("batch", 9))
    assert t.add(("batch", 10), {"blob": b""}) == 0


# --- compile cache keyed by mesh shape (small-fix satellite) ----------------

def test_predictor_compile_cache_keyed_by_mesh_shape():
    pred = R._Predictor(_double_predict, {}, 0, False)
    x = {"x": np.ones((4, 2), np.float32)}
    pred(x)
    pred(x)
    assert len(pred.compiles) == 1  # same bucket, same mesh: one entry
    # an elastic resize changes the mesh shape: the same bucket must
    # key a NEW executable (stale-sharding reuse would be silent
    # wrong-placement)
    before = pred.mesh_shape
    ms = E.apply_resize(pred, covered=3, logical=4)
    assert isinstance(ms, float) and ms >= 0
    assert pred.mesh_shape is not None and pred.mesh_shape != before
    pred(x)
    assert len(pred.compiles) == 2
    # resizing to a different share re-keys again
    E.apply_resize(pred, covered=1, logical=4)
    pred(x)
    assert len(pred.compiles) == 3


# --- declared degraded admission --------------------------------------------

def test_batcher_capacity_scales_shed_with_retry_after_floor():
    sheds = []
    mb = B.MicroBatcher(lambda b: None, max_batch=8, max_delay_ms=5,
                        queue_max=4,
                        on_shed=lambda d, l: sheds.append((d, l)))
    # never started: nothing drains, so queue depth is deterministic
    assert not mb.degraded and mb.effective_queue_max() == 4
    mb.set_capacity(0.5)
    assert mb.degraded and mb.effective_queue_max() == 2
    mb.submit({"x": np.ones(1)})
    mb.submit({"x": np.ones(1)})
    with pytest.raises(B.Overloaded) as ei:
        mb.submit({"x": np.ones(1)})
    assert ei.value.limit == 2
    # degraded sheds tell clients to come back AFTER the resize window,
    # not after one batch flush
    assert ei.value.retry_after >= 0.25
    assert sheds == [(ei.value.depth, 2)]
    # capacity 0: shed everything, explicitly (pool has no live replica)
    mb.set_capacity(0.0)
    assert mb.effective_queue_max() == 0
    # the bound never rounds below 1 while ANY capacity remains
    mb.set_capacity(0.01)
    assert mb.effective_queue_max() == 1
    mb.set_capacity(1.0)
    assert not mb.degraded and mb.effective_queue_max() == 4
    mb.close()


# --- boot/adopt handshake helpers -------------------------------------------

def test_await_boot_directives_and_timeout():
    q = _queue.Queue()
    q.put(("batch", 1, b"stale"))  # inherited inbox junk is discarded
    q.put(("boot", "adopt", 7, cloudpickle.dumps({"w": 3})))
    assert E.await_boot(q, timeout=5) == ("adopt", 7, {"w": 3})
    q.put(("boot", "cold"))
    assert E.await_boot(q, timeout=5) == ("cold",)
    q.put(("stop",))
    assert E.await_boot(q, timeout=5) == ("stop",)
    # no directive: boot cold rather than wedge the replica
    assert E.await_boot(_queue.Queue(), timeout=0.3) == ("cold",)


def test_adopt_predictor_uses_mirror_not_disk():
    payload = {"predict": _echo_version, "jit": False}
    pred = E.adopt_predictor(payload, 7, {"version": 7.0})
    assert pred.version == 7
    out, _ms = pred({"x": np.ones((2, 1), np.float32)})
    assert out["version"] == pytest.approx([7.0, 7.0])
    with pytest.raises(ValueError):
        E.adopt_predictor(payload, 7, None)


def test_elastic_pool_validates_logical_capacity():
    spec = R.ModelSpec(predict=_double_predict, params={}, jit=False)
    with pytest.raises(ValueError):
        E.ElasticReplicaPool(spec, num_replicas=2, logical_replicas=1)


# --- chaos sites (satellite) ------------------------------------------------

@pytest.mark.faults
def test_serve_chaos_sites_registered_and_fire(monkeypatch):
    assert set(faults.SERVE_CHAOS_SITES) <= set(faults.SITES)
    plan = faults.random_plan(123, sites=faults.SERVE_CHAOS_SITES)
    assert any(s in plan for s in faults.SERVE_CHAOS_SITES)
    monkeypatch.setenv("TFOS_FAULT_PLAN", "serve.dispatch:exc@2")
    faults._reset_for_tests()
    try:
        faults.check("serve.dispatch", what="batch")       # hit 1: armed @2
        with pytest.raises(faults.FaultInjected):
            faults.check("serve.dispatch", what="batch")   # hit 2: fires
        faults.check("serve.resize", reason="formed")      # other site: quiet
        faults.check("decode.step", replica=0)
    finally:
        monkeypatch.delenv("TFOS_FAULT_PLAN")
        faults._reset_for_tests()


# --- graceful drain (in-process, real replicas) -----------------------------

def test_drain_degrades_and_refuses_last_replica():
    spec = R.ModelSpec(predict=_double_predict, params={}, jit=False)
    with S.Server(spec, num_replicas=2, max_batch=8, max_delay_ms=5,
                  elastic=True) as srv:
        c = srv.client()
        c.predict({"x": np.ones(2, np.float32)}, timeout=60)
        assert srv.pool.generation >= 1 and not srv.pool.degraded
        assert srv.pool.capacity_frac == pytest.approx(1.0)
        assert sum(srv.pool._assignments.values()) == 2

        assert srv.pool.drain(0, timeout=30) is True
        assert srv.pool.live_replicas() == [1]
        assert srv.pool.degraded
        assert srv.pool.capacity_frac == pytest.approx(0.5)
        assert srv.pool.generation >= 2
        assert srv.batcher.degraded  # admission follows the pool
        # the survivor keeps serving
        out = c.predict({"x": np.full(2, 3.0, np.float32)}, timeout=60)
        assert out["y"] == pytest.approx([6.0, 6.0])

        desc = srv.summary()["pool"]
        assert desc["degraded"] and desc["live"] == [1]
        assert 0 in desc["draining"] or "0" not in desc["assignments"]
        assert [row["generation"] for row in E.pool_table()
                if row["live"] == [1]]
        with pytest.raises(ValueError):
            srv.pool.drain(1)  # never drain the last live replica
        with pytest.raises(ValueError):
            srv.pool.drain(5)  # not live at all


# --- slow lane: the acceptance e2e ------------------------------------------

def _cfg():
    from tensorflowonspark_tpu.models import transformer as T
    return T.Config(vocab_size=61, dim=32, n_layers=2, n_heads=2,
                    max_seq=32, dtype="float32", attn_impl="reference")


@pytest.mark.slow
@pytest.mark.faults
def test_elastic_sigkill_adopt_regrow_zero_drop(tmp_path, monkeypatch):
    """SIGKILL one of two elastic replicas under live predict+decode
    traffic.  Asserts: zero dropped/duplicated work (predicts exact,
    decode token streams oracle-identical), a declared degraded window,
    generation bumps for shrink AND regrow, and — with the checkpoint
    files deleted before the kill — the respawned replica ADOPTS the
    survivors' live params at the original version (no cold reload)."""
    import functools
    import shutil

    import jax

    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer as T
    from tensorflowonspark_tpu.serving import decode as D
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    params = T.init(jax.random.PRNGKey(0), cfg)
    ckpt_dir = str(tmp_path / "ckpts")
    ckpt.save_checkpoint(ckpt_dir, params, step=7)
    monkeypatch.setenv("TFOS_SERVE_RELOAD_SECS", "3600")  # watcher idles
    spec = R.ModelSpec(predict=_double_predict, ckpt_dir=ckpt_dir,
                       jit=False,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=16))
    prompt = [2, 3, 5, 7]
    oracle = T.greedy_decode_reference(
        params, prompt, cfg,
        attn_fn=functools.partial(ops.mha_reference, causal=True),
        max_tokens=12)

    with S.Server(spec, num_replicas=2, elastic=True, max_batch=8,
                  max_delay_ms=5, queue_max=10_000,
                  request_timeout=300) as srv:
        c = srv.client()
        c.predict({"x": np.ones(2, np.float32)}, timeout=300)
        srv.generate(prompt, max_tokens=2, timeout=300)   # warm compiles
        assert set(srv.pool.versions().values()) == {7}
        gen0 = srv.pool.generation
        assert gen0 >= 1

        # the no-cold-reload proof: after this, step 7 exists ONLY as
        # the survivors' live params + the pool's adoption mirror
        shutil.rmtree(ckpt_dir)

        degraded_seen = threading.Event()

        def watch():
            while not degraded_seen.is_set():
                if srv.pool.degraded:
                    degraded_seen.set()
                time.sleep(0.01)

        results, gens, errors = [], {}, []

        def burst(i):
            for _ in range(12):
                try:
                    r = c.predict({"x": np.full((2,), float(i),
                                               np.float32)}, timeout=300)
                    results.append((i, r["y"]))
                except Exception as e:  # noqa: BLE001 - asserted below
                    errors.append(e)

        def gen_one(i):
            try:
                gens[i] = srv.generate(prompt, max_tokens=12, timeout=300)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=watch, daemon=True)]
        threads += [threading.Thread(target=burst, args=(i,))
                    for i in range(8)]
        threads += [threading.Thread(target=gen_one, args=(i,))
                    for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let traffic land on both replicas
        victim = sorted(srv.pool.replica_pids())[0]
        os.kill(srv.pool.replica_pids()[victim], 9)
        for t in threads[1:]:
            t.join()

        assert not errors, errors[:3]
        assert len(results) == 96  # zero dropped predicts
        for i, y in results:
            assert y == pytest.approx([2.0 * i] * 2)
        assert len(gens) == 3      # zero dropped decode sessions
        for i, out in gens.items():
            # zero-dup: re-prefilled orphans re-stream the exact oracle
            assert out["tokens"] == oracle, (i, out["tokens"])

        # re-grow: adopted, resharded back, full capacity — no reload
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if (srv.pool.live_replicas() == [0, 1]
                    and not srv.pool.degraded
                    and srv.pool.adoptions >= 1):
                break
            time.sleep(0.2)
        assert srv.pool.live_replicas() == [0, 1]
        assert not srv.pool.degraded
        assert srv.pool.adoptions >= 1
        # formation + shrink + regrow, epoch-fenced
        assert srv.pool.generation >= gen0 + 2
        assert degraded_seen.wait(timeout=1), \
            "the shrunk window was never declared degraded"
        # the adopted incarnation serves the pool's version, with the
        # checkpoint gone — cold reload would have left version 0
        assert set(srv.pool.versions().values()) == {7}, srv.pool.versions()
        after = c.predict({"x": np.full((2,), 9.0, np.float32)},
                          timeout=300)
        assert after["y"] == pytest.approx([18.0, 18.0])
        assert srv.generate(prompt, max_tokens=12,
                            timeout=300)["tokens"] == oracle
        desc = srv.pool.describe()
        assert desc["capacity"] == pytest.approx(1.0)
        assert desc["last_resize_ms"] is None or desc["last_resize_ms"] >= 0


@pytest.mark.slow
@pytest.mark.faults
def test_serve_chaos_smoke_keeps_serving(monkeypatch):
    """Seeded chaos over the serving sites: faulted requests may error,
    but the tier must keep serving afterwards (supervisor retries a
    failed resize; a failed dispatch fails only that batch)."""
    seed = int(os.environ.get("TFOS_CHAOS_SEED", "2024"))
    plan = faults.random_plan(seed, sites=faults.SERVE_CHAOS_SITES)
    print(f"chaos plan (seed {seed}): {plan}")
    monkeypatch.setenv("TFOS_FAULT_PLAN", plan)
    faults._reset_for_tests()
    spec = R.ModelSpec(predict=_double_predict, params={}, jit=False)
    try:
        with S.Server(spec, num_replicas=2, max_batch=8, max_delay_ms=5,
                      elastic=True) as srv:
            c = srv.client()
            errors = 0
            for i in range(20):
                try:
                    out = c.predict({"x": np.full((2,), float(i),
                                                  np.float32)}, timeout=120)
                    assert out["y"] == pytest.approx([2.0 * i] * 2)
                except Exception:  # noqa: BLE001 - injected
                    errors += 1
            # chaos plans carry at most 2 one-shot faults; the tier must
            # absorb them and keep answering
            assert errors <= 2
            monkeypatch.delenv("TFOS_FAULT_PLAN")
            faults._reset_for_tests()
            out = c.predict({"x": np.ones(2, np.float32)}, timeout=120)
            assert out["y"] == pytest.approx([2.0, 2.0])
            assert srv.pool.live_replicas() == [0, 1]
    finally:
        monkeypatch.delenv("TFOS_FAULT_PLAN", raising=False)
        faults._reset_for_tests()
