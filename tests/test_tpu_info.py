"""TPU discovery/arbitration tests (parity: reference test_TFSparkNode GPU table)."""

import os
from unittest import mock

import pytest

from tensorflowonspark_tpu import tpu_info


def test_zero_chips_is_noop():
    assert tpu_info.get_chips(0) == []


def test_override_env_count():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        assert tpu_info.count_chips() == 4
        assert tpu_info.is_tpu_available()


def test_worker_index_placement_disjoint():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        assert tpu_info.get_chips(2, worker_index=0) == [0, 1]
        assert tpu_info.get_chips(2, worker_index=1) == [2, 3]


def test_oversubscription_raises():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        with pytest.raises(RuntimeError, match="demand exceeds supply"):
            tpu_info.get_chips(2, worker_index=2)


def test_unavailable_retries_then_raises():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "1"}):
        with mock.patch.object(tpu_info.time, "sleep") as slept:
            with pytest.raises(RuntimeError, match="unable to claim"):
                tpu_info.get_chips(2)
            assert slept.call_count == tpu_info.MAX_RETRIES - 1


def test_set_visible_chips_env():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "8"}, clear=False):
        chips = tpu_info.set_visible_chips(4, worker_index=1)
        assert chips == [4, 5, 6, 7]
        assert os.environ["TPU_VISIBLE_CHIPS"] == "4,5,6,7"
        for var in ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_PROCESS_BOUNDS", "TPU_PROCESS_BOUNDS"):
            os.environ.pop(var, None)
