"""TPU discovery/arbitration tests (parity: reference test_TFSparkNode GPU table)."""

import os
from unittest import mock

import pytest

from tensorflowonspark_tpu import tpu_info


def test_zero_chips_is_noop():
    assert tpu_info.get_chips(0) == []


def test_override_env_count():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        assert tpu_info.count_chips() == 4
        assert tpu_info.is_tpu_available()


def test_worker_index_placement_disjoint():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        assert tpu_info.get_chips(2, worker_index=0) == [0, 1]
        assert tpu_info.get_chips(2, worker_index=1) == [2, 3]


def test_oversubscription_raises():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "4"}):
        with pytest.raises(RuntimeError, match="demand exceeds supply"):
            tpu_info.get_chips(2, worker_index=2)


def test_unavailable_retries_then_raises():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "1"}):
        with mock.patch.object(tpu_info.time, "sleep") as slept:
            with pytest.raises(RuntimeError, match="unable to claim"):
                tpu_info.get_chips(2)
            assert slept.call_count == tpu_info.MAX_RETRIES - 1


def test_set_visible_chips_env():
    with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "8"}, clear=False):
        chips = tpu_info.set_visible_chips(4, worker_index=1)
        assert chips == [4, 5, 6, 7]
        assert os.environ["TPU_VISIBLE_CHIPS"] == "4,5,6,7"
        for var in ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_PROCESS_BOUNDS", "TPU_PROCESS_BOUNDS"):
            os.environ.pop(var, None)


# -- claim_chips decision table (parity: reference test_TFSparkNode.py:49-187
#    GPU-allocation matrix over mocked TaskContext.resources / K8s env) ------

def _clear_visible():
    for var in ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                "TPU_PROCESS_BOUNDS"):
        os.environ.pop(var, None)


@pytest.fixture
def clean_env():
    _clear_visible()
    yield
    _clear_visible()


def test_claim_scheduler_resources_win(clean_env):
    """Spark-3 resources API addresses beat the host scan."""
    with mock.patch.object(tpu_info, "_task_resources",
                           return_value={"tpu": ["2", "3"]}):
        with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "8"}):
            assert tpu_info.claim_chips(2, worker_index=0) == ["2", "3"]
            assert os.environ["TPU_VISIBLE_CHIPS"] == "2,3"


def test_claim_scheduler_truncates_to_request(clean_env):
    """Explicit num_chips < scheduler assignment truncates (ref :193-197)."""
    with mock.patch.object(tpu_info, "_task_resources",
                           return_value={"tpu": ["0", "1", "2", "3"]}):
        assert tpu_info.claim_chips(2) == ["0", "1"]
    assert os.environ["TPU_VISIBLE_CHIPS"] == "0,1"


def test_claim_scheduler_implicit_takes_all(clean_env):
    """No explicit request: every scheduler-assigned address is claimed."""
    with mock.patch.object(tpu_info, "_task_resources",
                           return_value={"tpu": ["0", "1", "2", "3"]}):
        assert tpu_info.claim_chips(0) == ["0", "1", "2", "3"]
    assert os.environ["TPU_VISIBLE_CHIPS"] == "0,1,2,3"


def test_claim_gpu_resource_name_accepted(clean_env):
    """Clusters configured with the generic 'gpu' resource name still work."""
    with mock.patch.object(tpu_info, "_task_resources",
                           return_value={"gpu": ["5"]}):
        assert tpu_info.claim_chips(1) == ["5"]


def test_claim_host_scan_fallback(clean_env):
    """No scheduler info: index-placed block from the host scan."""
    with mock.patch.object(tpu_info, "_task_resources", return_value=None):
        with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "8"}):
            assert tpu_info.claim_chips(2, worker_index=1) == ["2", "3"]
            assert os.environ["TPU_VISIBLE_CHIPS"] == "2,3"


def test_claim_k8s_pod_skips_host_scan(clean_env):
    """Inside a K8s pod the host probe is skipped (device-plugin
    over-report guard, ref TFSparkNode.py:201-203): explicit request fails
    rather than claiming phantom chips."""
    with mock.patch.object(tpu_info, "_task_resources", return_value=None):
        with mock.patch.dict(os.environ, {
            "TFOS_TPU_CHIPS_PER_HOST": "8",
            "SPARK_EXECUTOR_POD_IP": "10.0.0.7",
        }):
            with pytest.raises(RuntimeError, match="unable to allocate"):
                tpu_info.claim_chips(2)


def test_claim_k8s_with_scheduler_resources(clean_env):
    """K8s + resources API: the scheduler's explicit assignment is trusted."""
    with mock.patch.object(tpu_info, "_task_resources",
                           return_value={"tpu": ["0"]}):
        with mock.patch.dict(os.environ, {"SPARK_EXECUTOR_POD_IP": "10.0.0.7"}):
            assert tpu_info.claim_chips(1) == ["0"]


def test_claim_unrequested_no_export(clean_env):
    """No request + no scheduler info: natural full-host visibility —
    nothing exported (TPU-first divergence from the reference's
    default-to-1-GPU)."""
    with mock.patch.object(tpu_info, "_task_resources", return_value=None):
        with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "8"}):
            assert tpu_info.claim_chips(0) == []
    assert "TPU_VISIBLE_CHIPS" not in os.environ


def test_claim_unsatisfiable_request_raises(clean_env):
    with mock.patch.object(tpu_info, "_task_resources", return_value=None):
        with mock.patch.dict(os.environ, {"TFOS_TPU_CHIPS_PER_HOST": "0"}):
            with pytest.raises(RuntimeError, match="unable to allocate"):
                tpu_info.claim_chips(1)


def test_no_pyspark_resource_api_probe():
    """Outside any Spark task (no pyspark installed) discovery degrades
    to None without raising."""
    assert tpu_info._task_resources() is None or isinstance(
        tpu_info._task_resources(), dict
    )


def test_slice_health_on_live_backend():
    """On the 8-device virtual CPU platform: healthy, counts match, and
    expectation mismatches are reported without raising."""
    from tensorflowonspark_tpu import tpu_info

    h = tpu_info.slice_health(expected_processes=1,
                              expected_local_devices=8)
    assert h["healthy"], h
    assert h["local_devices"] == 8 and h["global_devices"] == 8
    assert h["platform"] == "cpu"

    sick = tpu_info.slice_health(expected_processes=2,
                                 expected_local_devices=4)
    assert not sick["healthy"]
    assert any("process count" in e for e in sick["errors"])
    assert any("local devices" in e for e in sick["errors"])


def test_unhealthy_slice_is_fatal_at_bring_up(monkeypatch):
    """jax_initialize must RAISE on an unhealthy slice (routing through
    the node wrapper's error queue), unless TFOS_SLICE_HEALTH=warn."""
    import pytest

    from tensorflowonspark_tpu import node as N
    from tensorflowonspark_tpu import tpu_info

    ctx = N.TFNodeContext.__new__(N.TFNodeContext)
    monkeypatch.setattr(
        N.TFNodeContext, "distributed_env",
        lambda self: {"num_processes": 2, "process_id": 0,
                      "coordinator_address": "127.0.0.1:1"})

    import jax.distributed

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: None)
    sick = {"healthy": False, "errors": ["device 0 smoke hung"],
            "local_devices": 0, "global_devices": 0, "platform": None,
            "process_index": None}
    monkeypatch.setattr(tpu_info, "slice_health", lambda **kw: sick)

    with pytest.raises(RuntimeError, match="unhealthy accelerator slice"):
        ctx.jax_initialize()

    monkeypatch.setenv("TFOS_SLICE_HEALTH", "warn")
    env = N.TFNodeContext.jax_initialize(ctx)
    assert env["slice_health"] is sick  # reported, not fatal


def test_slice_health_timeout_env_and_snapshot(monkeypatch):
    """ADVICE r3: the probe window is env-tunable via
    TFOS_SLICE_HEALTH_TIMEOUT, a hung probe sets ``timed_out``, and the
    returned dict is a snapshot the late-finishing probe cannot mutate."""
    import time

    from tensorflowonspark_tpu import tpu_info

    # force the probe to out-sleep a tiny env-provided window
    real_local_devices = __import__("jax").local_devices

    def slow_local_devices():
        time.sleep(2)
        return real_local_devices()

    monkeypatch.setattr(__import__("jax"), "local_devices",
                        slow_local_devices)
    monkeypatch.setenv("TFOS_SLICE_HEALTH_TIMEOUT", "0.2")
    h = tpu_info.slice_health(expected_processes=1,
                              expected_local_devices=8)
    assert h["timed_out"] and not h["healthy"]
    assert any("TFOS_SLICE_HEALTH_TIMEOUT" in e for e in h["errors"])
    n_errors = len(h["errors"])
    time.sleep(2.5)  # let the probe finish in the background
    # snapshot: the caller's dict must not have changed under it
    assert len(h["errors"]) == n_errors and "done" not in h


def test_probe_timeout_is_warn_only_at_bring_up(monkeypatch):
    """ADVICE r3 (medium): a probe that merely timed out (slow pool /
    first-contact compile) must NOT hard-fail bring-up; definite errors
    still must (covered by test_unhealthy_slice_is_fatal_at_bring_up)."""
    from tensorflowonspark_tpu import node as N
    from tensorflowonspark_tpu import tpu_info

    ctx = N.TFNodeContext.__new__(N.TFNodeContext)
    monkeypatch.setattr(
        N.TFNodeContext, "distributed_env",
        lambda self: {"num_processes": 1, "process_id": 0,
                      "coordinator_address": "127.0.0.1:1"})
    slow = {"healthy": False, "timed_out": True, "bare_timeout": True,
            "errors": ["health probe still hung after 0.2s"],
            "local_devices": 0, "global_devices": 0, "platform": None,
            "process_index": None}
    monkeypatch.setattr(tpu_info, "slice_health", lambda **kw: slow)
    env = N.TFNodeContext.jax_initialize(ctx)  # must not raise
    assert env["slice_health"] is slow


def test_probe_timeout_fatal_in_strict_mode(monkeypatch):
    """TFOS_SLICE_HEALTH=strict keeps probe timeouts fatal: fail-fast
    beats a possible wedge in the first collective for deployments that
    opt into it."""
    import pytest

    from tensorflowonspark_tpu import node as N
    from tensorflowonspark_tpu import tpu_info

    ctx = N.TFNodeContext.__new__(N.TFNodeContext)
    monkeypatch.setattr(
        N.TFNodeContext, "distributed_env",
        lambda self: {"num_processes": 1, "process_id": 0,
                      "coordinator_address": "127.0.0.1:1"})
    slow = {"healthy": False, "timed_out": True, "bare_timeout": True,
            "errors": ["health probe still hung after 0.2s"],
            "local_devices": 0, "global_devices": 0, "platform": None,
            "process_index": None}
    monkeypatch.setattr(tpu_info, "slice_health", lambda **kw: slow)
    monkeypatch.setenv("TFOS_SLICE_HEALTH", "strict")
    with pytest.raises(RuntimeError, match="unhealthy accelerator slice"):
        ctx.jax_initialize()


def test_definite_errors_survive_a_hung_probe(monkeypatch):
    """Errors found BEFORE the probe hangs must appear in the timeout
    snapshot (flushed under the lock as found), so a definitely-broken
    slice is never downgraded to a bare timeout."""
    import time

    import jax

    from tensorflowonspark_tpu import tpu_info

    def hang_device_put(x, d):
        time.sleep(5)
        return __import__("numpy").int32(42)

    jax.local_devices()  # warm the backend so 0.4s is all probe time
    monkeypatch.setattr(jax, "device_put", hang_device_put)
    monkeypatch.setenv("TFOS_SLICE_HEALTH_TIMEOUT", "0.4")
    h = tpu_info.slice_health(expected_processes=7)  # wrong on purpose
    assert h["timed_out"]
    assert any("process count" in e for e in h["errors"]), h["errors"]
    assert len(h["errors"]) >= 2  # definite finding + timeout message


def test_slice_health_flags_silent_cpu_fallback(monkeypatch):
    """TPU chips present + jax backend 'cpu' without an explicit
    JAX_PLATFORMS=cpu means the accelerator runtime failed to load —
    must be unhealthy.  An explicit cpu platform (this test suite's own
    environment) is intentional and stays healthy."""
    from tensorflowonspark_tpu import tpu_info

    monkeypatch.setattr(tpu_info, "count_chips", lambda: 4)
    # conftest sets JAX_PLATFORMS=cpu -> intentional, healthy
    assert tpu_info.slice_health(expected_processes=1,
                                 expected_local_devices=8)["healthy"]
    monkeypatch.setenv("JAX_PLATFORMS", "")
    sick = tpu_info.slice_health(expected_processes=1,
                                 expected_local_devices=8)
    assert not sick["healthy"]
    assert any("accelerator runtime" in e for e in sick["errors"])
