"""Global-stop on uneven partitions (SURVEY.md §7 hard parts): two real
jax.distributed processes with different amounts of local data must stop
on the same step — no stranded collective, no hang — via
infeed.synchronized."""

import multiprocessing as mp
import os
import socket

import pytest


def _worker(rank, port, counts, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PYTHONPATH", None)
    import jax

    # multi-process SPMD on the CPU backend needs the gloo collectives
    # implementation (same fix as node.jax_initialize); without it every
    # collective raises "Multiprocess computations aren't implemented"
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=len(counts),
        process_id=rank,
    )
    import numpy as np
    from jax.experimental import multihost_utils

    from tensorflowonspark_tpu.infeed import synchronized

    seen = list(synchronized(iter(range(counts[rank]))))

    # alignment proof: a cross-process collective still completes after
    # the uneven stop (this is exactly what hangs without the wrapper);
    # it also asserts both ranks consumed the same number of items
    all_counts = multihost_utils.process_allgather(np.asarray(len(seen)))
    assert int(np.asarray(all_counts).min()) == int(
        np.asarray(all_counts).max()
    ), all_counts
    q.put((rank, len(seen)))


def _worker_main(rank, port, counts, q):
    try:
        _worker(rank, port, counts, q)
    except Exception as e:  # noqa: BLE001 - surface in the parent
        q.put((rank, f"ERROR: {e!r}"))


@pytest.mark.slow
def test_uneven_feeds_stop_together():
    ctx = mp.get_context("spawn")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    counts = (5, 3)  # rank 0 has more data than rank 1
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_worker_main, args=(r, port, counts, q))
        for r in range(2)
    ]
    try:
        for p in procs:
            p.start()
        results = {}
        for _ in procs:
            rank, n = q.get(timeout=120)
            results[rank] = n
        for p in procs:
            p.join(timeout=30)
            assert p.exitcode == 0, (p.exitcode, results)
    finally:
        # a deadlocked rank must not wedge pytest's exit (non-daemon
        # children are joined by multiprocessing's atexit handler)
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
            if p.is_alive():
                p.kill()
                p.join(timeout=5)
    # both stopped after the shorter feed's 3 items
    assert results == {0: 3, 1: 3}, results
