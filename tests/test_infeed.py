"""Infeed pipelining: batching, collation, prefetch ordering, errors."""

import numpy as np
import pytest

from tensorflowonspark_tpu import infeed


class FakeFeed:
    """DataFeed stand-in delivering scripted batches."""

    def __init__(self, batches):
        self.batches = list(batches)

    def should_stop(self):
        return not self.batches

    def next_batch(self, n):
        return self.batches.pop(0)


def test_batch_iterator_drops_short_tail():
    feed = FakeFeed([[1] * 8, [2] * 8, [3] * 3])
    got = list(infeed.batch_iterator(feed, 8))
    assert got == [[1] * 8, [2] * 8]


def test_batch_iterator_dict_records_and_collate():
    feed = FakeFeed([{"x": [1, 2], "y": [3, 4]}])
    got = list(infeed.batch_iterator(
        feed, 2, collate=lambda r: np.asarray(r["x"]) + np.asarray(r["y"])
    ))
    np.testing.assert_array_equal(got[0], [4, 6])


def test_prefetch_preserves_order_and_values():
    batches = [np.full((4,), i) for i in range(10)]
    out = list(infeed.prefetch_to_device(iter(batches), depth=3))
    assert len(out) == 10
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b), np.full((4,), i))


def test_prefetch_forwards_worker_exception():
    def gen():
        yield np.zeros((2,))
        raise ValueError("boom in feed")

    it = infeed.prefetch_to_device(gen(), depth=2)
    next(it)
    with pytest.raises(ValueError, match="boom in feed"):
        list(it)


def test_device_feed_places_on_sharding(eight_devices):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 4}, devices=eight_devices[:4])
    sharding = NamedSharding(mesh, P("data"))
    feed = FakeFeed([[float(i) for i in range(8)]])
    out = list(infeed.device_feed(
        feed, 8, collate=lambda r: np.asarray(r, np.float32),
        placement=sharding,
    ))
    assert len(out) == 1
    assert out[0].sharding.is_equivalent_to(sharding, out[0].ndim)
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(8.0))


def test_prefetch_abandon_poisons_source_and_reaps_worker():
    """Early break from device_feed must stop the worker quickly (via the
    feed's poison hook) instead of leaving it blocked/polling forever."""
    import threading
    import time

    unblocked = threading.Event()

    class BlockingFeed(FakeFeed):
        def __init__(self):
            super().__init__([[1] * 4])
            self._poisoned = False

        def poison(self):
            self._poisoned = True
            unblocked.set()

        def should_stop(self):
            # poison is the ONLY stop signal: the iterator must keep
            # calling next_batch after the scripted batch so the worker
            # genuinely blocks there (the scenario under test)
            return self._poisoned

        def next_batch(self, n):
            if self.batches:
                return self.batches.pop(0)
            unblocked.wait(timeout=10)  # models a _get_chunk poll loop
            return []

    feed = BlockingFeed()
    it = infeed.device_feed(feed, 4)
    assert next(it) == [1] * 4
    t0 = time.monotonic()
    it.close()  # abandon mid-stream: worker is blocked in next_batch
    assert feed._poisoned
    assert time.monotonic() - t0 < 5  # no 15s drain/join stall
    live = [t.name for t in threading.enumerate()
            if t.name == "tfos-prefetch" and t.is_alive()]
    assert not live, f"prefetch worker leaked: {live}"


def test_prefetch_abandon_idle_exit_with_failing_hook():
    """ADVICE r3: when the on_abandon hook does NOT unblock the source
    (here: it raises), the drain loop must take the idle-worker early
    exit instead of paying the full 3s drain deadline + 2s join."""
    import time

    def forever():
        yield np.zeros(2)
        # just above the ~2.6s drain window: blocks the worker for the
        # test without leaking a 30s daemon into later thread-leak checks
        time.sleep(6)
        yield np.zeros(2)

    def bad_hook():
        raise RuntimeError("hook failed to unblock the source")

    it = infeed.prefetch_to_device(forever(), depth=2, on_abandon=bad_hook)
    next(it)
    t0 = time.monotonic()
    it.close()
    dt = time.monotonic() - t0
    # early exit: ~3 idle polls (0.6s) + join(2) = ~2.6s; the old path
    # paid the full 3s deadline first (~5s)
    assert dt < 4, f"abandon with failing hook took {dt:.2f}s"


def test_prefetch_clean_end_has_no_drain_penalty():
    import time

    list(infeed.prefetch_to_device(iter([np.zeros(2)]), depth=2))  # warm imports
    t0 = time.monotonic()
    for _ in range(5):
        out = list(infeed.prefetch_to_device(iter([np.zeros(2)] * 3), depth=2))
        assert len(out) == 3
    dt = time.monotonic() - t0
    # normal end-of-stream must skip the abandon drain: the old code paid
    # a fixed ~0.2s q.get poll per stream (>=1.0s over 5 streams); amortize
    # over several streams so one scheduler stall can't flake the bound
    assert dt < 0.75, f"5 clean ends took {dt:.3f}s"


def test_tfrecord_device_feed_streams_to_device(tmp_path):
    from tensorflowonspark_tpu import dfutil

    from tensorflowonspark_tpu import recordio

    d = tmp_path / "tfr"
    d.mkdir()
    rows = [{"x": [float(i), float(i)], "y": i} for i in range(20)]
    for path, chunk in ((d / "part-r-00000", rows[:12]),
                        (d / "part-r-00001", rows[12:])):
        with recordio.TFRecordWriter(str(path)) as w:
            for r in chunk:
                w.write(dfutil.to_example(r))

    got = list(infeed.tfrecord_device_feed(
        [str(d / "part-r-00000"), str(d / "part-r-00001")], 8,
        collate=lambda b: (np.asarray(b["x"]), np.asarray(b["y"])),
    ))
    assert len(got) == 2  # 20 rows -> 2 full batches, remainder dropped
    xs = np.concatenate([np.asarray(x) for x, _ in got])
    assert xs.shape == (16, 2)
    ys = np.concatenate([np.asarray(y) for _, y in got])
    assert sorted(ys.tolist()) == list(range(16))
