"""Infeed pipelining: batching, collation, prefetch ordering, errors."""

import numpy as np
import pytest

from tensorflowonspark_tpu import infeed


class FakeFeed:
    """DataFeed stand-in delivering scripted batches."""

    def __init__(self, batches):
        self.batches = list(batches)

    def should_stop(self):
        return not self.batches

    def next_batch(self, n):
        return self.batches.pop(0)


def test_batch_iterator_drops_short_tail():
    feed = FakeFeed([[1] * 8, [2] * 8, [3] * 3])
    got = list(infeed.batch_iterator(feed, 8))
    assert got == [[1] * 8, [2] * 8]


def test_batch_iterator_dict_records_and_collate():
    feed = FakeFeed([{"x": [1, 2], "y": [3, 4]}])
    got = list(infeed.batch_iterator(
        feed, 2, collate=lambda r: np.asarray(r["x"]) + np.asarray(r["y"])
    ))
    np.testing.assert_array_equal(got[0], [4, 6])


def test_prefetch_preserves_order_and_values():
    batches = [np.full((4,), i) for i in range(10)]
    out = list(infeed.prefetch_to_device(iter(batches), depth=3))
    assert len(out) == 10
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b), np.full((4,), i))


def test_prefetch_forwards_worker_exception():
    def gen():
        yield np.zeros((2,))
        raise ValueError("boom in feed")

    it = infeed.prefetch_to_device(gen(), depth=2)
    next(it)
    with pytest.raises(ValueError, match="boom in feed"):
        list(it)


def test_device_feed_places_on_sharding(eight_devices):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 4}, devices=eight_devices[:4])
    sharding = NamedSharding(mesh, P("data"))
    feed = FakeFeed([[float(i) for i in range(8)]])
    out = list(infeed.device_feed(
        feed, 8, collate=lambda r: np.asarray(r, np.float32),
        placement=sharding,
    ))
    assert len(out) == 1
    assert out[0].sharding.is_equivalent_to(sharding, out[0].ndim)
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(8.0))
