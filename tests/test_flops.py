"""utils.flops: shape-exact MAC counting from the traced jaxpr.

Validates the counter against hand-computed primitives and against the
independently published ResNet-50 MAC table (models/resnet.py:233) —
the two denominators must agree or one of the MFU conventions is wrong
(VERDICT r4 weak #6).
"""

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from tensorflowonspark_tpu.utils import metrics as M
from tensorflowonspark_tpu.utils.flops import count_flops


def test_dot_general_exact():
    r = count_flops(jnp.dot, jnp.ones((2, 3)), jnp.ones((3, 4)))
    assert r["macs"] == 2 * 3 * 4
    assert r["flops"] == 2 * r["macs"]


def test_batched_dot_exact():
    f = lambda a, b: jnp.einsum("bij,bjk->bik", a, b)  # noqa: E731
    r = count_flops(f, jnp.ones((5, 2, 3)), jnp.ones((5, 3, 4)))
    assert r["macs"] == 5 * 2 * 3 * 4


def test_conv_exact():
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    r = count_flops(f, jnp.ones((1, 8, 8, 3)), jnp.ones((3, 3, 3, 16)))
    # out (1,4,4,16) x 9 taps x 3 in_ch
    assert r["macs"] == 1 * 4 * 4 * 16 * 9 * 3


def test_depthwise_conv_groups():
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), "SAME", feature_group_count=8,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    r = count_flops(f, jnp.ones((1, 4, 4, 8)), jnp.ones((3, 3, 1, 8)))
    # depthwise: 9 taps per output element, one input channel each
    assert r["macs"] == 1 * 4 * 4 * 8 * 9


def test_batch_grouped_conv_groups():
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), "SAME", batch_group_count=2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    r = count_flops(f, jnp.ones((4, 4, 4, 2)), jnp.ones((3, 3, 2, 4)))
    # batch groups shrink the OUTPUT batch (4/2=2), not the per-output
    # contraction: out (2,4,4,4) x 9 taps x 2 in_ch
    assert r["macs"] == (2 * 4 * 4 * 4) * 9 * 2


def test_conv_transpose_counts_required_work_only():
    def f(x, w):
        return lax.conv_transpose(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    r = count_flops(f, jnp.ones((1, 4, 4, 8)), jnp.ones((3, 3, 8, 4)))
    # output is (1,8,8,4); zero-inserted positions (lhs_dilation 2x2)
    # are not algorithmically required: 9 taps / 4
    assert r["macs"] == (1 * 8 * 8 * 4) * 9 * 8 // 4


def test_scan_multiplies_by_length():
    def f(x):
        def body(c, _):
            return c @ jnp.ones((4, 4)), None
        y, _ = lax.scan(body, x, None, length=5)
        return y

    r = count_flops(f, jnp.ones((2, 4)))
    assert r["macs"] == 5 * 2 * 4 * 4


def test_jit_and_remat_recursed():
    @jax.jit
    def f(x):
        g = jax.checkpoint(lambda y: y @ jnp.ones((4, 4)))
        return g(x)

    r = count_flops(f, jnp.ones((2, 4)))
    assert r["macs"] == 2 * 4 * 4


def test_grad_counts_backward_matmuls():
    # d(xW)/dx and d/dW each cost one matmul: fwd 1x + bwd 2x
    def loss(w, x):
        return jnp.sum(x @ w)

    fwd = count_flops(loss, jnp.ones((4, 4)), jnp.ones((2, 4)))["macs"]
    both = count_flops(jax.value_and_grad(loss, argnums=(0, 1)),
                       jnp.ones((4, 4)), jnp.ones((2, 4)))["macs"]
    assert fwd == 2 * 4 * 4
    assert both == 3 * fwd


def test_resnet50_matches_published_table():
    from tensorflowonspark_tpu.models import resnet

    ps, ss = jax.eval_shape(
        lambda k: resnet.init(k, depth=50, num_classes=1000),
        jax.random.PRNGKey(0))
    img = jax.ShapeDtypeStruct((1, 224, 224, 3), "float32")
    counted = count_flops(
        lambda p, s, x: resnet.apply(p, s, x, train=True)[0],
        ps, ss, img)["flops"]
    table = resnet.flops_per_image(50, 224)
    assert counted == pytest.approx(table, rel=0.02), (counted, table)


def test_segmentation_flops_scale_with_area():
    f256 = M.segmentation_flops_per_image(256)
    f512 = M.segmentation_flops_per_image(512)
    assert f256 > 1e8  # ~0.5 GFLOP forward at 256
    assert f512 == pytest.approx(4 * f256, rel=0.05)


def test_mnist_inference_flops_positive():
    assert M.mnist_inference_flops_per_row() > 1e5
