"""Input-pipeline subsystem (data/): composable graph semantics, the
ColumnChunk wire contract with DataFeed, the disaggregated data service
(exactly-once unit ledger + fault resume), and the telemetry stall
decomposition through scripts/trace_merge.py.

Parity intent: these are the redesigned counterparts of the reference's
DataFeed/TFNode tests (test_TFNode.py) plus the guarantees the reference
never had — deterministic global shuffle, exactly-once epoch accounting,
and a killed data worker resuming at its shard cursor (SURVEY.md §2,
PARITY.md §2.1).
"""

import json
import os
import secrets
import subprocess
import sys

import numpy as np
import pytest

from tensorflowonspark_tpu import data, marker, recordio
from tensorflowonspark_tpu import manager as tfmanager
from tensorflowonspark_tpu.data import service as dsvc
from tensorflowonspark_tpu.feed import DataFeed
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.data


def _arrays(n, width=4):
    """Identifiable records: y[i] == i is the record identity."""
    x = (np.arange(n * width, dtype=np.float32).reshape(n, width)) / 7.0
    y = np.arange(n, dtype=np.int64)
    return {"x": x, "y": y}


def _ids(blocks):
    out = []
    for b in blocks:
        out.extend(int(v) for v in np.asarray(b["y"]).ravel())
    return out


# -- graph semantics ---------------------------------------------------------


def test_sources_and_batch_drop_remainder():
    pipe = data.from_arrays(_arrays(50), block_size=8)
    sizes = [data.block_len(b) for b in pipe.blocks()]
    assert sizes == [8] * 6 + [2]
    assert _ids(pipe.blocks()) == list(range(50))

    kept = list(pipe.batch(16).blocks())
    assert [data.block_len(b) for b in kept] == [16, 16, 16, 2]
    assert _ids(kept) == list(range(50))
    # and the content re-chunks losslessly, not just the ids
    np.testing.assert_allclose(
        np.concatenate([b["x"] for b in kept]), _arrays(50)["x"])

    dropped = list(pipe.batch(16, drop_remainder=True).blocks())
    assert [data.block_len(b) for b in dropped] == [16, 16, 16]
    assert _ids(dropped) == list(range(48))


def test_from_dataset_collects_engine_rows():
    from tensorflowonspark_tpu.engine import LocalEngine

    engine = LocalEngine(2, env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""})
    try:
        rows = [([float(i), float(i)], i) for i in range(20)]
        pipe = data.from_dataset(engine.parallelize(rows, 2), block_size=6)
        got = list(pipe.blocks())
    finally:
        engine.stop()
    assert sum(data.block_len(b) for b in got) == 20


def test_shuffle_exactly_once_and_deterministic():
    pipe = data.from_arrays(_arrays(101), block_size=9).shuffle(37, seed=5)
    run1 = _ids(pipe.blocks())
    # every record exactly once per epoch
    assert sorted(run1) == list(range(101))
    assert run1 != list(range(101))  # it actually shuffled
    # two same-seed runs: identical batch order (determinism contract)
    assert _ids(pipe.blocks()) == run1
    # a different seed is a different order over the same records
    other = _ids(data.from_arrays(_arrays(101), block_size=9)
                 .shuffle(37, seed=6).blocks())
    assert sorted(other) == list(range(101)) and other != run1


def test_shard_partitions_shuffled_stream_exactly_once():
    """The global-shuffle correctness contract (ISSUE satellite): with a
    fixed seed, shard(i, n) consumers each see a deterministic stream and
    the union over one epoch is every record exactly once."""
    base = data.from_arrays(_arrays(97), block_size=8).shuffle(97, seed=3)
    shards = [list(_ids(base.shard(i, 3).blocks())) for i in range(3)]
    # deterministic per consumer
    assert [list(_ids(base.shard(i, 3).blocks())) for i in range(3)] == shards
    # disjoint, exactly-once union
    all_ids = [v for s in shards for v in s]
    assert sorted(all_ids) == list(range(97))
    assert len(set(all_ids)) == len(all_ids)
    # the split is by GLOBAL record index over the (shuffled) stream, so
    # shard sizes are balanced to within one record
    assert sorted(len(s) for s in shards) == [32, 32, 33]


def _write_examples(path, rows):
    with recordio.TFRecordWriter(str(path)) as w:
        for feats in rows:
            w.write(recordio.encode_example(feats))


def _shard_dir(tmp_path, n_shards=4, per_shard=12):
    d = tmp_path / "tfr"
    d.mkdir()
    k = 0
    for s in range(n_shards):
        _write_examples(
            d / f"part-r-{s:05d}",
            [{"x": ("float", [float(k + i), 0.5]),
              "y": ("int64", [k + i])} for i in range(per_shard)])
        k += per_shard
    return d, n_shards * per_shard


def test_tfrecords_interleave_parallel_map(tmp_path):
    d, n = _shard_dir(tmp_path)
    pipe = (data.from_tfrecords(str(d), block_size=5)
            .interleave(cycle_length=2)
            .parallel_map(lambda b: {"x": b["x"] * 2.0, "y": b["y"]},
                          num_workers=2))
    got = list(pipe.blocks())
    assert sorted(_ids(got)) == list(range(n))
    allx = np.concatenate([b["x"] for b in got])
    ally = np.concatenate([np.asarray(b["y"]).ravel() for b in got])
    np.testing.assert_allclose(allx[:, 0], ally * 2.0)  # fn really ran
    # interleave actually alternates shards: the first two blocks come
    # from different source files (ids 0.. and 12..)
    first_two = {int(np.asarray(b["y"]).ravel()[0]) // 12 for b in got[:2]}
    assert len(first_two) == 2

    # unordered mode: same multiset, order free
    unord = (data.from_tfrecords(str(d), block_size=5)
             .interleave(2)
             .parallel_map(lambda b: b, num_workers=2, ordered=False))
    assert sorted(_ids(unord.blocks())) == list(range(n))


def test_interleave_requires_multishard_source():
    with pytest.raises(ValueError, match="multi-shard"):
        data.from_arrays(_arrays(10), block_size=4).interleave(2)


def test_cache_spill_repeat_prefetch(tmp_path):
    pipe = data.from_arrays(_arrays(60), block_size=7)
    # memory budget far below the data size forces the spill file path
    cached = pipe.cache(spill_dir=str(tmp_path), memory_bytes=128)
    first = _ids(cached.blocks())
    assert first == list(range(60))
    assert any(f.startswith("tfos-data-cache") for f in os.listdir(tmp_path))
    # second pass replays from the cache, byte-identical ids
    assert _ids(cached.blocks()) == first
    assert _ids(cached.repeat(3).blocks()) == first * 3
    assert _ids(cached.prefetch(2).blocks()) == first
    cached.purge()
    assert not any(f.startswith("tfos-data-cache")
                   for f in os.listdir(tmp_path))


def test_chunks_and_skip_blocks_resume():
    pipe = data.from_arrays(_arrays(40), block_size=6)
    chunks = list(pipe.chunks())
    assert all(isinstance(c, marker.ColumnChunk) for c in chunks)
    # deterministic resume: skipping k blocks lands exactly on the suffix
    resumed = list(pipe.chunks(skip_blocks=3))
    assert len(resumed) == len(chunks) - 3
    for a, b in zip(resumed, chunks[3:]):
        np.testing.assert_array_equal(a.columns[1], b.columns[1])
    # skipping past the end is an empty stream, not an error
    assert list(pipe.chunks(skip_blocks=99)) == []


# -- the ColumnChunk wire contract with DataFeed -----------------------------


@pytest.fixture
def mgr():
    m = tfmanager.start(secrets.token_bytes(8), ["input", "output", "error"])
    yield m
    m.shutdown()


def test_pipeline_chunks_feed_datafeed_columnar(mgr):
    """Pipeline leaves speak the same ColumnChunk wire format as the
    feeder path: n-D fields round-trip dense through next_batch_columns
    with their original shapes."""
    n = 48
    images = np.arange(n * 4 * 6 * 3, dtype=np.uint8).reshape(n, 4, 6, 3)
    labels = np.arange(n, dtype=np.int64)
    pipe = data.from_arrays({"image": images, "label": labels},
                            block_size=16)
    q = mgr.get_queue("input")
    for c in pipe.chunks():
        assert isinstance(c, marker.ColumnChunk)
        q.put(c)
    q.put(None)

    feed = DataFeed(mgr, train_mode=True,
                    input_mapping={"image": "image", "label": "label"})
    b = feed.next_batch_columns(16)
    assert b["image"].shape == (16, 4, 6, 3)
    assert b["image"].dtype == np.uint8
    np.testing.assert_array_equal(b["image"], images[:16])
    got = [int(v) for v in b["label"]]
    while not feed.should_stop():
        got.extend(int(v) for v in feed.next_batch_columns(16)["label"])
    assert got == list(range(n))


# -- the data service --------------------------------------------------------


def _trainer_meta(m, executor_id, authkey):
    return {"executor_id": executor_id, "host": "localhost",
            "job_name": "worker", "addr": list(m.address),
            "authkey": authkey.hex()}


def _drain_queue(q):
    out = []
    while not q.empty():
        out.append(q.get())
        q.task_done()
    return out


def test_data_service_resumes_at_unit_ledger(monkeypatch):
    """Kill-resume exactly-once proof, transport-level: a data worker
    faulted at the start of unit 1 leaves unit 0 in the PDONE ledger; a
    fresh worker resumes at the cursor and the trainer receives every
    block exactly once, in order."""
    from tensorflowonspark_tpu import rendezvous

    faults._reset_for_tests()
    monkeypatch.setenv(faults.PLAN_ENV, "data.serve:exc@2")
    authkey = secrets.token_bytes(8)
    m = tfmanager.start(authkey, ["input", "output", "error"])
    server = rendezvous.Server(1)
    addr = server.start()
    try:
        cluster_info = [_trainer_meta(m, 0, authkey)]
        cluster_meta = {"server_addr": addr}
        pipe = data.from_arrays(_arrays(100), block_size=10)  # 10 blocks

        svc = dsvc.DataService(pipe, cluster_info, cluster_meta,
                               num_workers=1, worker_index=0, unit_blocks=4)
        with pytest.raises(faults.FaultInjected):
            svc.run()
        # unit 0 (blocks 0-3) was pushed AND recorded before the fault
        assert server.fed_partitions(dsvc.ledger_feed("input", 0)) == [0]

        svc2 = dsvc.DataService(pipe, cluster_info, cluster_meta,
                                num_workers=1, worker_index=0, unit_blocks=4)
        summary = svc2.run()
        assert summary == {0: 60}  # blocks 4-9 only: no re-push of unit 0
        # final partial unit (blocks 8-9) recorded at exhaust
        assert server.fed_partitions(dsvc.ledger_feed("input", 0)) == [0, 1, 2]

        chunks = _drain_queue(m.get_queue("input"))
        assert len(chunks) == 10  # exactly once, no EOF (cluster owns EOF)
        got = [int(v) for c in chunks for v in c.columns[1]]
        assert got == list(range(100))
    finally:
        monkeypatch.delenv(faults.PLAN_ENV)
        faults._reset_for_tests()
        server.stop()
        m.shutdown()


def test_data_service_shards_per_trainer_and_per_worker():
    """rank % num_workers == worker_index assignment + shard(rank, T)
    streams: each trainer sees its strided split exactly once."""
    keys = [secrets.token_bytes(8) for _ in range(2)]
    mgrs = [tfmanager.start(k, ["input", "output", "error"]) for k in keys]
    try:
        cluster_info = [_trainer_meta(m, i, k)
                        for i, (m, k) in enumerate(zip(mgrs, keys))]
        pipe = data.from_arrays(_arrays(40), block_size=5)
        for widx in range(2):  # two workers, one trainer each
            svc = dsvc.DataService(pipe, cluster_info, cluster_meta={},
                                   num_workers=2, worker_index=widx,
                                   unit_blocks=2)
            summary = svc.run()
            assert summary == {widx: 20}
        for rank, m in enumerate(mgrs):
            chunks = _drain_queue(m.get_queue("input"))
            got = [int(v) for c in chunks for v in c.columns[1]]
            assert got == list(range(rank, 40, 2))
    finally:
        for m in mgrs:
            m.shutdown()


def test_data_service_skips_terminating_trainer():
    authkey = secrets.token_bytes(8)
    m = tfmanager.start(authkey, ["input", "output", "error"])
    try:
        m.set("state", "terminating")
        svc = dsvc.DataService(
            data.from_arrays(_arrays(10), block_size=5),
            [_trainer_meta(m, 0, authkey)], cluster_meta={},
            num_workers=1, worker_index=0)
        assert svc.run() == {0: 0}
        assert m.get_queue("input").empty()
    finally:
        m.shutdown()


# -- telemetry: per-stage spans through trace_merge --------------------------


def test_data_stage_spans_and_trace_merge(tmp_path, monkeypatch, mgr):
    from tensorflowonspark_tpu.utils import telemetry

    tdir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(tdir))
    monkeypatch.setenv(telemetry.NODE_ENV, "test-0")
    # earlier in-process cluster tests leak a stale spool/role via
    # telemetry.configure(); a leaked SPOOL_ENV would silently redirect
    # this test's sink away from DIR_ENV
    monkeypatch.delenv(telemetry.SPOOL_ENV, raising=False)
    monkeypatch.delenv(telemetry.ROLE_ENV, raising=False)
    try:
        assert telemetry.enabled()
        pipe = (data.from_arrays(_arrays(64), block_size=8)
                .map(lambda b: b).batch(16).prefetch(2))
        q = mgr.get_queue("input")
        for c in pipe.chunks():
            q.put(c)
        q.put(None)
        feed = DataFeed(mgr, train_mode=True,
                        input_mapping={"x": "x", "y": "y"})
        while not feed.should_stop():
            feed.next_batch_columns(16)
        telemetry.flush()
    finally:
        telemetry.flush()

    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "scripts", "trace_merge.py"),
         str(tdir)],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=""), timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # the per-stage stall table (ISSUE satellite: `-- data --` section)
    assert "-- data (data/stage spans) --" in proc.stdout
    for stage in ("arrays", "map", "batch", "prefetch", "fed_consumer"):
        assert stage in proc.stdout, proc.stdout
    trace = json.loads((tdir / "trace.json").read_text())
    spans = [e for e in trace["traceEvents"]
             if e.get("name") == "data/stage"]
    stages = {e["args"]["stage"] for e in spans}
    assert {"arrays", "map", "batch", "prefetch", "fed_consumer"} <= stages
    # prefetch accounts its block time as WAIT (it only stalls, never
    # computes), so downstream stall attribution stays truthful
    pre = [e for e in spans if e["args"]["stage"] == "prefetch"]
    assert pre and all(e["args"]["wait_ms"] >= 0 for e in pre)
    # every span carries the per-block record count for rec/s math (the
    # end-of-feed consumer pull is a legitimate 0-record span)
    assert all("records" in e["args"] for e in spans)
    assert sum(e["args"]["records"] for e in spans) > 0


# -- slow lane: mnist end-to-end through the data service --------------------

BATCH = 25  # == per-trainer shard block size: the aligned consumer path
SOURCE_BLOCK = 50  # shard(rank, 2) halves each block -> 25-record blocks
N_RECORDS = 800  # 16 source blocks -> 16 thin blocks/trainer -> 2 units of 8


def mnist_ds_main(args, ctx):
    """Trainer consuming the data service via next_batch_columns, with
    checkpoint auto-resume (the data-service twin of mnist_ft_main)."""
    import jax
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    ckpt_dir = os.path.join(args["model_dir"], f"worker-{ctx.task_index}")
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.05, momentum=0.9)
    opt_state = opt.init(params)
    saved, start = ctx.restore_latest(ckpt_dir)
    if saved is not None:
        params = saved
    step_fn = jax.jit(mnist.make_train_step(opt))

    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"image": "image", "label": "label"})
    step = start
    while not feed.should_stop():
        b = feed.next_batch_columns(BATCH)
        if len(b["label"]) < BATCH:
            continue
        images = np.asarray(b["image"], dtype=np.float32)
        labels = np.asarray(b["label"], dtype=np.int32)
        params, opt_state, loss, acc = step_fn(
            params, opt_state, images, labels)
        step += 1
        ckpt.save_checkpoint(ckpt_dir, params, step)


def _synthetic_columns(n):
    rng = np.random.default_rng(0)
    images = rng.random((n, 28, 28, 1), dtype=np.float32)
    q = np.stack(
        [
            images[:, :14, :14, 0].mean((1, 2)),
            images[:, :14, 14:, 0].mean((1, 2)),
            images[:, 14:, :14, 0].mean((1, 2)),
            images[:, 14:, 14:, 0].mean((1, 2)),
        ],
        axis=-1,
    )
    labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
    return images, labels


@pytest.mark.slow
@pytest.mark.faults
def test_mnist_data_service_survives_worker_kill(tmp_path, monkeypatch):
    """The e2e acceptance (ISSUE satellite): mnist trained through
    cluster.run(..., data_workers=1) with the data worker SIGKILLed
    mid-serve.  The engine respawns the executor, the driver recovers the
    cluster, the relaunched worker resumes at its unit ledger
    (data/serve_resume), and the run exits cleanly with checkpoints.

    Kill placement: each trainer's stream is 16 blocks = 2 ledger units,
    and every unit START (plus the exhaust probe) is one data.serve
    check.  Reaching check 5 requires at least two units recorded (a
    unit's start needs its predecessor completed), so after the ledger
    resume the relaunched worker performs at most 4 checks — ``kill@5``
    fires exactly once under any ring-backpressure interleaving."""
    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine
    from tensorflowonspark_tpu.utils import telemetry

    telemetry_dir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(telemetry_dir))
    for k in (telemetry.SPOOL_ENV, telemetry.ROLE_ENV, telemetry.NODE_ENV):
        monkeypatch.delenv(k, raising=False)  # stale leaks misroute sinks
    # this e2e asserts the STATIC service's recovery semantics (unit
    # ledger + shard-cursor resume); the dynamic default has its own
    # kill e2e in test_data_splits.py
    monkeypatch.setenv("TFOS_DATA_DISPATCH", "static")
    monkeypatch.chdir(tmp_path)
    engine = LocalEngine(2, env={
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",  # drop the TPU-tunnel site hook
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TFOS_DATA_DISPATCH": "static",
        faults.PLAN_ENV: "data.serve:kill@5",
    })
    try:
        cluster = TFCluster.run(
            engine, mnist_ds_main, {"model_dir": str(tmp_path / "model")},
            num_executors=2, input_mode=InputMode.SPARK, restarts=1,
            data_workers=1,
        )
        images, labels = _synthetic_columns(N_RECORDS)
        pipe = data.from_arrays({"image": images, "label": labels},
                                block_size=SOURCE_BLOCK)
        cluster.train(pipe, num_epochs=1, feed_timeout=240)
        assert cluster._restarts_used == 1, (
            f"expected one recovery, got {cluster._restarts_used}")
        cluster.shutdown(grace_secs=2)
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)

    # both trainers made it past the kill: checkpoints exist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    steps = [ckpt.latest_step(str(tmp_path / "model" / f"worker-{i}"))
             for i in range(2)]
    assert all(s and s > 0 for s in steps), f"missing checkpoints: {steps}"

    # the kill, the respawn, and the ledger resume are all on the
    # telemetry timeline, and trace_merge accepts the whole run
    import glob

    raw = ""
    for path in glob.glob(str(telemetry_dir / "**" / "*"), recursive=True):
        if os.path.isfile(path):
            with open(path, errors="replace") as f:
                raw += f.read()
    for ev in ("fault/injected", "engine/executor_respawn",
               "cluster/recover_begin", "data/serve_resume"):
        assert ev in raw, f"telemetry event {ev} missing from drained run"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "scripts", "trace_merge.py"),
         str(telemetry_dir)],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=""), timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "-- data (data/stage spans) --" in proc.stdout
