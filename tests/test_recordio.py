"""Record IO tests (parity: reference test_dfutil.py + DFUtilTest.scala —
round-trip the full dtype matrix; native vs pure-Python equivalence)."""

import numpy as np
import pytest

from tensorflowonspark_tpu import dfutil, recordio
from tensorflowonspark_tpu.recordio import native, pyimpl

ROW = {
    "an_int": 7,
    "a_bool": True,
    "a_float": 3.25,              # exactly representable in f32
    "a_string": "hello tpu",
    "a_binary": b"\x00\xffraw",
    "int_array": [1, -2, 3],
    "float_array": [0.5, 1.5, -2.5],
    "str_array": ["a", "b"],
    "neg_int": -42,
}

BINARY_HINT = ("a_binary",)


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8a9136aa
    assert pyimpl.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert pyimpl.crc32c(b"123456789") == 0xE3069283
    lib = native.load()
    if lib is not None:
        assert lib.tfr_crc32c(b"\x00" * 32, 32) == 0x8A9136AA
        assert lib.tfr_crc32c(b"123456789", 9) == 0xE3069283


def test_example_roundtrip_native_and_python():
    feats = {
        "i": ("int64", [1, -5, 2 ** 40]),
        "f": ("float", [1.5, -0.25]),
        "b": ("bytes", [b"abc", b"\x00\x01"]),
    }
    for enc in (recordio.encode_example, pyimpl.encode_example):
        data = enc(feats)
        for dec in (recordio.decode_example, pyimpl.decode_example):
            out = dec(data)
            assert out["i"] == ("int64", [1, -5, 2 ** 40])
            assert out["f"][0] == "float"
            np.testing.assert_allclose(out["f"][1], [1.5, -0.25])
            assert out["b"] == ("bytes", [b"abc", b"\x00\x01"])


def test_tfrecord_file_roundtrip(tmp_path):
    path = tmp_path / "data.tfrecord"
    records = [b"first", b"", b"x" * 100_000]
    with recordio.TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
    assert list(recordio.TFRecordReader(path)) == records
    # pure-python reader agrees with native writer (same format)
    with open(path, "rb") as f:
        assert list(pyimpl.read_records(f)) == records


def test_corruption_detected(tmp_path):
    path = tmp_path / "bad.tfrecord"
    with recordio.TFRecordWriter(path) as w:
        w.write(b"payload-payload")
    raw = bytearray(path.read_bytes())
    raw[14] ^= 0xFF  # flip a data byte
    path.write_bytes(bytes(raw))
    with pytest.raises(IOError):
        list(recordio.TFRecordReader(path))


def test_dfutil_row_roundtrip():
    data = dfutil.to_example(ROW)
    schema = dfutil.infer_schema(data, BINARY_HINT)
    assert schema["an_int"] == ("int64", False)
    assert schema["a_string"] == ("string", False)
    assert schema["a_binary"] == ("bytes", False)
    assert schema["int_array"] == ("int64", True)
    row = dfutil.from_example(data, schema, BINARY_HINT)
    assert row["an_int"] == 7
    assert row["a_bool"] == 1          # bool widens to int64 (reference parity)
    assert abs(row["a_float"] - 3.25) < 1e-6
    assert row["a_string"] == "hello tpu"
    assert row["a_binary"] == b"\x00\xffraw"
    assert row["int_array"] == [1, -2, 3]
    np.testing.assert_allclose(row["float_array"], [0.5, 1.5, -2.5])
    assert row["str_array"] == ["a", "b"]
    assert row["neg_int"] == -42


def test_dfutil_save_load_local(tmp_path):
    rows = [dict(ROW, an_int=i) for i in range(50)]
    out = tmp_path / "tfr"
    dfutil.save_as_tfrecords(rows, out)
    loaded, schema = dfutil.load_tfrecords(None, str(out), BINARY_HINT)
    assert len(loaded) == 50
    assert sorted(r["an_int"] for r in loaded) == list(range(50))
    assert dfutil.is_loaded_df(str(out))
    assert not dfutil.is_loaded_df("/nonexistent")


def test_tfrecord_remote_fs_roundtrip():
    """Remote-FS path: TFRecord framing over an fsspec filesystem
    (parity: reference record IO over any Hadoop FS, dfutil.py:39-81).
    memory:// exercises the exact code path gs://, hdfs://, s3:// take."""
    pytest.importorskip("fsspec")
    path = "memory://tfos-test/data.tfrecord"
    records = [b"first", b"", b"x" * 100_000]
    with recordio.TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
    assert list(recordio.TFRecordReader(path)) == records
    # bytes on the remote store are identical to the local framing
    import io

    from tensorflowonspark_tpu.recordio import fs as rfs

    assert list(pyimpl.read_records(io.BytesIO(rfs.read_bytes(path)))) == records


def test_dfutil_save_load_remote_fs():
    pytest.importorskip("fsspec")
    rows = [dict(ROW, an_int=i) for i in range(20)]
    out = "memory://tfos-test/dfutil-tfr"
    dfutil.save_as_tfrecords(rows, out)
    loaded, schema = dfutil.load_tfrecords(None, out, BINARY_HINT)
    assert sorted(r["an_int"] for r in loaded) == list(range(20))
    assert schema["a_string"] == ("string", False)


def test_gs_paths_route_remote():
    """gs:// URLs must route to the fsspec/mem-codec path end-to-end, not
    to fopen (round-2 finding: `gs://...` strings nothing could open)."""
    from tensorflowonspark_tpu.recordio import fs as rfs

    assert not rfs.is_local("gs://bucket/dir/part-r-00000")
    assert rfs.scheme_of("hdfs://nn:8020/x") == "hdfs"
    assert rfs.is_local("/plain/path") and rfs.is_local("file:///plain/path")
    assert rfs.local_path("file:///plain/path") == "/plain/path"
    assert rfs.join("gs://bucket/dir", "part-r-0") == "gs://bucket/dir/part-r-0"
    pytest.importorskip("gcsfs")

    fs, p = rfs.get_fs("gs://bucket/dir")  # resolves through gcsfs
    assert type(fs).__module__.startswith("gcsfs")


def test_dfutil_save_load_engine(tmp_path):
    from tensorflowonspark_tpu.engine import LocalEngine

    engine = LocalEngine(2)
    try:
        rows = [dict(ROW, an_int=i) for i in range(100)]
        ds = engine.parallelize(rows, 4)
        out = tmp_path / "tfr"
        dfutil.save_as_tfrecords(ds, str(out))
        loaded_ds, schema = dfutil.load_tfrecords(engine, str(out), BINARY_HINT)
        loaded = loaded_ds.collect()
        assert sorted(r["an_int"] for r in loaded) == list(range(100))
        assert schema["a_string"] == ("string", False)
    finally:
        engine.stop()


def test_load_tfrecords_min_partitions_stripes_shards(tmp_path):
    """Fewer shard files than workers: min_partitions stripes each file
    into (path, stride, offset) read units — no row lost or duplicated,
    no driver materialization (VERDICT r3 weak #6)."""
    from tensorflowonspark_tpu.engine import LocalEngine

    engine = LocalEngine(2)
    try:
        rows = [dict(ROW, an_int=i) for i in range(30)]
        ds = engine.parallelize(rows, 1)  # ONE shard file on purpose
        out = tmp_path / "tfr"
        dfutil.save_as_tfrecords(ds, str(out))

        loaded_ds, _ = dfutil.load_tfrecords(
            engine, str(out), BINARY_HINT, min_partitions=4)
        assert loaded_ds.num_partitions >= 4
        got = sorted(r["an_int"] for r in loaded_ds.collect())
        assert got == list(range(30))

        # plenty of shards: behavior unchanged (no striping tuples)
        many = tmp_path / "tfr_many"
        dfutil.save_as_tfrecords(engine.parallelize(rows, 4), str(many))
        ds2, _ = dfutil.load_tfrecords(
            engine, str(many), BINARY_HINT, min_partitions=2)
        assert sorted(r["an_int"] for r in ds2.collect()) == list(range(30))
    finally:
        engine.stop()


def _write_examples(path, rows):
    with recordio.TFRecordWriter(str(path)) as w:
        for feats in rows:
            w.write(recordio.encode_example(feats))


def test_load_columnar_native_and_fallback(tmp_path):
    n = 64
    rng = np.random.default_rng(0)
    feats = rng.random((n, 16)).astype(np.float32)
    path = tmp_path / "part-r-00000"
    _write_examples(path, [{
        "vec": ("float", feats[i].tolist()),
        "label": ("int64", [int(i)]),
        "name": ("bytes", [f"r{i}".encode()]),
    } for i in range(n)])

    cols = recordio.load_columnar(str(path))
    kind, vec = cols["vec"]
    assert kind == "float" and vec.shape == (n, 16)
    np.testing.assert_allclose(vec, feats, rtol=1e-6)
    assert cols["label"][1].shape == (n,) and cols["label"][1][5] == 5
    assert cols["name"][1][7] == b"r7"

    lib = native.load()
    if lib is not None:
        # pure-python fallback produces identical columns
        lib._tfos_colb_api = False
        try:
            cols2 = recordio.load_columnar(str(path))
        finally:
            lib._tfos_colb_api = True
        np.testing.assert_allclose(cols2["vec"][1], vec, rtol=1e-6)
        assert (cols2["label"][1] == cols["label"][1]).all()
        assert cols2["name"][1] == cols["name"][1]


def test_load_columnar_ragged_falls_back(tmp_path):
    path = tmp_path / "part-r-00000"
    _write_examples(path, [
        {"vec": ("float", [1.0, 2.0])},
        {"vec": ("float", [3.0])},  # ragged width
    ])
    cols = recordio.load_columnar(str(path))
    kind, vals = cols["vec"]
    assert kind == "float"
    assert vals[0] == [1.0, 2.0] and vals[1] == 3.0


def test_dfutil_columnar_multi_shard(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    _write_examples(d / "part-r-00000",
                    [{"x": ("int64", [i])} for i in range(10)])
    _write_examples(d / "part-r-00001",
                    [{"x": ("int64", [i])} for i in range(10, 30)])
    cols = dfutil.load_tfrecords_columnar(str(d))
    assert sorted(cols["x"].tolist()) == list(range(30))


def test_dfutil_columnar_schema_drift_raises(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    _write_examples(d / "part-r-00000", [{"x": ("int64", [1])}])
    _write_examples(d / "part-r-00001", [{"y": ("int64", [2])}])
    with pytest.raises(ValueError, match="schema"):
        dfutil.load_tfrecords_columnar(str(d))


def test_dfutil_columnar_dtype_drift_raises(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    _write_examples(d / "part-r-00000", [{"x": ("int64", [1])}])
    _write_examples(d / "part-r-00001", [{"x": ("float", [2.0])}])
    with pytest.raises(ValueError, match="schema"):
        dfutil.load_tfrecords_columnar(str(d))


def test_load_columnar_repeated_key_errors_cleanly(tmp_path):
    # a record with the same feature key twice cannot be columnized
    # (values would shift later rows); the C loader must reject it and
    # the fallback must not crash
    from tensorflowonspark_tpu.recordio import pyimpl

    path = tmp_path / "part-r-00000"
    # concatenating two serialized Examples yields one Example whose
    # feature map contains the key twice on the wire
    dup = (pyimpl.encode_example({"x": ("int64", [1])})
           + pyimpl.encode_example({"x": ("int64", [2])}))
    with recordio.TFRecordWriter(str(path)) as w:
        w.write(dup)
    cols = recordio.load_columnar(str(path))
    # last-wins via the per-row fallback (dict semantics), never misaligned
    assert cols["x"][1].tolist() == [2]


def test_dfutil_columnar_file_list_and_empty_shards(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    _write_examples(d / "part-r-00000",
                    [{"x": ("int64", [i])} for i in range(5)])
    (d / "part-r-00001").write_bytes(b"")  # Hadoop-style empty part
    _write_examples(d / "part-r-00002",
                    [{"x": ("int64", [i])} for i in range(5, 8)])
    # explicit file-subset form (a worker's disjoint shards)
    cols = dfutil.load_tfrecords_columnar(
        [str(d / "part-r-00000"), str(d / "part-r-00001")])
    assert cols["x"].tolist() == list(range(5))
    # dir form still skips the empty part and merges the rest
    cols = dfutil.load_tfrecords_columnar(str(d))
    assert sorted(cols["x"].tolist()) == list(range(8))
    # all-empty yields an empty dict, not a crash
    e = tmp_path / "empty"
    e.mkdir()
    (e / "part-r-00000").write_bytes(b"")
    assert dfutil.load_tfrecords_columnar(str(e)) == {}


def test_decoder_fuzz_no_crash():
    """The hand-rolled proto wire parser consumes untrusted bytes; seeded
    mutations (flips/truncations/insertions) must raise or fail cleanly,
    never corrupt memory.  (A longer 6000-case run was clean; this keeps
    a fast seeded regression in the suite.)"""
    import ctypes

    rng = np.random.default_rng(7)
    base = recordio.encode_example({
        "vec": ("float", [1.0, 2.0, 3.0]),
        "n": ("int64", [7, 8]),
        "s": ("bytes", [b"abc"]),
    })
    for _ in range(300):
        buf = bytearray(base)
        for _ in range(rng.integers(1, 6)):
            op = rng.integers(0, 3)
            if op == 0 and len(buf) > 1:
                buf[rng.integers(0, len(buf))] ^= rng.integers(1, 256)
            elif op == 1 and len(buf) > 2:
                del buf[rng.integers(1, len(buf)):]
            else:
                pos = rng.integers(0, len(buf) + 1)
                buf[pos:pos] = bytes(rng.integers(0, 256, rng.integers(1, 5)))
        try:
            recordio.decode_example(bytes(buf))
        except (ValueError, OverflowError):
            pass

    lib = native.load()
    if lib is None or not getattr(lib, "_tfos_mem_api", False):
        return
    w = lib.tfr_mem_writer_new()
    lib.tfr_mem_writer_write(w, base, len(base))
    n = ctypes.c_uint64()
    p = lib.tfr_mem_writer_data(w, ctypes.byref(n))
    framed = ctypes.string_at(p, n.value)
    lib.tfr_mem_writer_free(w)
    for _ in range(300):
        buf = bytearray(framed)
        for _ in range(rng.integers(1, 4)):
            if rng.integers(0, 2) and len(buf) > 1:
                buf[rng.integers(0, len(buf))] ^= rng.integers(1, 256)
            elif len(buf) > 2:
                del buf[rng.integers(1, len(buf)):]
        data = bytes(buf)
        h = lib.tfr_load_columnar_mem(data, len(data))
        if h:
            lib.colb_free(h)


def test_iter_columnar_streams_batches(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    # three shards with awkward sizes so batches cross shard boundaries
    _write_examples(d / "part-r-00000",
                    [{"x": ("int64", [i]), "v": ("float", [float(i), 0.5])}
                     for i in range(7)])
    (d / "part-r-00001").write_bytes(b"")
    _write_examples(d / "part-r-00002",
                    [{"x": ("int64", [i]), "v": ("float", [float(i), 0.5])}
                     for i in range(7, 12)])

    batches = list(dfutil.iter_tfrecords_columnar(str(d), 4))
    sizes = [len(b["x"]) for b in batches]
    assert sizes == [4, 4, 4]
    got = np.concatenate([b["x"] for b in batches])
    assert got.tolist() == list(range(12))
    assert batches[1]["v"].shape == (4, 2)

    # short remainder kept by default, dropped on request
    batches = list(dfutil.iter_tfrecords_columnar(str(d), 5))
    assert [len(b["x"]) for b in batches] == [5, 5, 2]
    batches = list(dfutil.iter_tfrecords_columnar(str(d), 5,
                                                  drop_remainder=True))
    assert [len(b["x"]) for b in batches] == [5, 5]

    # streamed content == bulk loader content
    bulk = dfutil.load_tfrecords_columnar(str(d))
    assert bulk["x"].tolist() == list(range(12))


def test_mixed_kind_feature_rejected_by_columnar():
    """A Feature whose wire encoding mixes kinds (float_list then
    int64_list under one key) must NOT be columnized — the per-kind
    buffers would disagree with the summed count and the reshape would
    read out of bounds."""
    # hand-build the wire bytes: Example{features{feature{key:"x",
    # value{float_list{1.0} int64_list{1,2}}}}}
    def varint(v):
        out = b""
        while v >= 0x80:
            out += bytes([v & 0x7F | 0x80])
            v >>= 7
        return out + bytes([v])

    def ld(field, payload):  # length-delimited
        return varint(field << 3 | 2) + varint(len(payload)) + payload

    import struct

    floats = ld(1, struct.pack("<f", 1.0))          # FloatList.value
    ints = ld(1, varint(1) + varint(2))             # Int64List.value packed
    feature = ld(2, floats) + ld(3, ints)           # mixed kinds!
    entry = ld(1, b"x") + ld(2, feature)
    example = ld(1, ld(1, entry))

    path = None
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        import os

        path = os.path.join(tmp, "part-r-00000")
        with recordio.TFRecordWriter(path) as w:
            w.write(example)
        lib = native.load()
        if lib is not None and getattr(lib, "_tfos_colb_api", False):
            h = lib.tfr_load_columnar(path.encode())
            try:
                assert not lib.colb_ok(h)  # rejected, falls back per-row
            finally:
                lib.colb_free(h)
        # the public API survives via the row fallback (dict last-kind)
        cols = recordio.load_columnar(path)
        assert "x" in cols


def test_bytes_width_drift_across_shards_raises(tmp_path):
    d = tmp_path / "tfr"
    d.mkdir()
    _write_examples(d / "part-r-00000",
                    [{"tags": ("bytes", [b"a"])}])        # flat
    _write_examples(d / "part-r-00001",
                    [{"tags": ("bytes", [b"b", b"c"])}])  # nested
    with pytest.raises(ValueError, match="schema"):
        dfutil.load_tfrecords_columnar(str(d))
