"""Native JPEG decode (recordio/jpeg.py over native/jpegdec.c):
parity with the PIL path, edge shapes, error handling, batch, fallback.

Parity workload: the host-side image decode the reference does with
tf.image.decode_jpeg in examples/resnet/imagenet_preprocessing.py.
"""

import io

import numpy as np
import pytest

from tensorflowonspark_tpu.recordio import jpeg as J


def _smooth(h, w):
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    return np.stack([
        128 + 100 * np.sin(xx / 40) * np.cos(yy / 60),
        128 + 80 * np.sin((xx + yy) / 50),
        128 + 60 * np.cos(xx / 30),
    ], -1).clip(0, 255).astype(np.uint8)


def _encode(arr, mode=None, quality=90):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr, mode=mode).save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _pil_decode_resized(data, size):
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    return np.asarray(img.resize((size, size), Image.BILINEAR), np.uint8)


def test_parity_with_pil_on_smooth_image():
    data = _encode(_smooth(500, 700))
    nat = J.decode_resized(data, 224)
    pil = _pil_decode_resized(data, 224)
    assert nat.shape == (224, 224, 3) and nat.dtype == np.uint8
    # different IDCT/resample implementations: close, not identical
    assert float(np.abs(nat.astype(int) - pil.astype(int)).mean()) < 2.0


def test_bytearray_and_memoryview_payloads():
    """The single-record paths must accept bytes-like payloads the way
    the batch path always did: a TFRecord Example's bytes feature can
    surface as bytearray/memoryview, and ctypes c_char_p takes only
    bytes (ADVICE r4)."""
    data = _encode(_smooth(60, 44))
    want = J.decode_rgb(data)
    for form in (bytearray(data), memoryview(data)):
        assert np.array_equal(J.decode_rgb(form), want)
        assert J.decode_resized(form, 32).shape == (32, 32, 3)


def test_edge_shapes_and_grayscale():
    for shape in [(7, 5), (224, 224), (1, 1), (40, 1000), (1000, 40)]:
        data = _encode(np.full(shape + (3,), 77, np.uint8))
        out = J.decode_resized(data, 224)
        assert out.shape == (224, 224, 3), shape
        # constant image survives scale+resize within JPEG tolerance
        assert abs(int(out.mean()) - 77) <= 3, (shape, out.mean())
    gray = _encode(np.full((64, 64), 50, np.uint8), mode="L")
    out = J.decode_resized(gray, 96)
    assert out.shape == (96, 96, 3)
    assert abs(int(out.mean()) - 50) <= 3


def test_corrupt_and_truncated_inputs_raise():
    with pytest.raises(ValueError):
        J.decode_rgb(b"\xff\xd8not really a jpeg at all")
    with pytest.raises(ValueError):
        J.decode_resized(b"", 32)
    data = _encode(_smooth(64, 64))
    with pytest.raises(ValueError):
        J.decode_resized(data[: len(data) // 3], 32)
    # truncation INSIDE the scan body: libjpeg pads a fake EOI and
    # decodes gray with only a warning — the strict native path must
    # reject it and PIL arbitration must also raise, never return
    # garbage pixels (PERF-critical data-integrity contract)
    with pytest.raises(ValueError):
        J.decode_resized(data[: int(len(data) * 0.8)], 32)


def test_cmyk_jpeg_decodes_via_arbitration():
    """libjpeg can't emit RGB from CMYK sources; the strict native
    failure must fall back to PIL (ImageNet contains CMYK JPEGs)."""
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.full((80, 60, 4), 120, np.uint8),
                    mode="CMYK").save(buf, "JPEG")
    out = J.decode_resized(buf.getvalue(), 48)
    assert out.shape == (48, 48, 3)


def test_batch_matches_sequential():
    datas = [_encode(_smooth(100 + 13 * i, 90 + 7 * i)) for i in range(6)]
    batch = J.decode_batch(datas, 64, threads=3)
    assert batch.shape == (6, 64, 64, 3)
    for i, d in enumerate(datas):
        np.testing.assert_array_equal(batch[i], J.decode_resized(d, 64))


def test_pil_fallback_path(monkeypatch):
    """With the native lib masked, the same API runs via PIL + numpy
    bilinear and stays close to the native output."""
    data = _encode(_smooth(300, 400))
    native = J.decode_resized(data, 128)
    monkeypatch.setattr(J, "_LIB", None)
    monkeypatch.setattr(J, "_TRIED", True)
    assert not J.available()
    fallback = J.decode_resized(data, 128)
    assert fallback.shape == (128, 128, 3)
    assert float(np.abs(native.astype(int) - fallback.astype(int)).mean()) \
        < 2.0
    batch = J.decode_batch([data, data], 128)
    np.testing.assert_array_equal(batch[0], fallback)


def _imagenet_records():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "imagenet_records",
        os.path.join(os.path.dirname(__file__), "..", "examples", "resnet",
                     "imagenet_records.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_decode_record_jpeg_routes_native():
    mod = _imagenet_records()
    data = _encode(_smooth(256, 256))
    img, label = mod.decode_record({"image": data, "label": 7}, 224)
    assert img.shape == (224, 224, 3) and label == 7


def test_decode_records_batch_mixed_and_errors():
    """Batch decode must match per-record decode across mixed payloads
    (JPEG + raw + TF-official keys) and keep the first-bad-record-raises
    contract."""
    mod = _imagenet_records()
    size = 64
    raw = np.full((size, size, 3), 9, np.uint8)
    recs = [
        {"image": _encode(_smooth(100, 120)), "label": 1},
        {"image": raw.tobytes(), "label": 2},
        {"image/encoded": [_encode(_smooth(90, 70))],
         "image/class/label": [5]},  # 1-based
    ]
    batch = mod.decode_records_batch(recs, size)
    assert [lbl for _, lbl in batch] == [1, 2, 4]
    for (img_b, _), rec in zip(batch, recs):
        img_s, _ = mod.decode_record(rec, size)
        np.testing.assert_array_equal(img_b, img_s)

    with pytest.raises(ValueError):
        mod.decode_records_batch(
            [{"image": b"\xff\xd8broken", "label": 0}], size)
    with pytest.raises(KeyError):
        mod.decode_records_batch([{"label": 0}], size)
