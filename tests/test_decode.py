"""Decode-tier tests: slot-paged KV cache units, sequence-length
bucketing, the open-loop load generator, the CPU parity acceptance gate
(continuous-batched greedy decode token-identical to full-recompute,
including mid-flight admission of staggered mixed-length prompts), and
the Server/HTTP generate surface.  Slow lane: a replica SIGKILLed
mid-decode (sessions re-prefill on the survivor; zero dropped and zero
duplicated tokens)."""

import functools
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import batcher as B
from tensorflowonspark_tpu.serving import decode as D
from tensorflowonspark_tpu.serving import replicas as R
from tensorflowonspark_tpu.serving import server as S

pytestmark = pytest.mark.decode


def _cfg(**kw):
    from tensorflowonspark_tpu.models import transformer as T
    base = dict(vocab_size=61, dim=32, n_layers=2, n_heads=2, max_seq=32,
                dtype="float32", attn_impl="reference")
    base.update(kw)
    return T.Config(**base)


def _params(cfg):
    import jax

    from tensorflowonspark_tpu.models import transformer as T
    return T.init(jax.random.PRNGKey(0), cfg)


def _oracle(params, prompt, cfg, **kw):
    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer as T
    return T.greedy_decode_reference(
        params, prompt, cfg,
        attn_fn=functools.partial(ops.mha_reference, causal=True), **kw)


# --- sequence bucketing (satellite b) ---------------------------------------

def test_bucket_seq_pow2_and_cap():
    assert [B.bucket_seq(n, 64) for n in (1, 2, 3, 5, 9, 33, 64, 100)] == \
        [1, 2, 4, 8, 16, 64, 64, 64]
    # the cap itself is a legal bucket even when not a power of two
    assert B.bucket_seq(48, 48) == 48
    assert B.bucket_seq(49, 48) == 48
    assert B.bucket_seq(3, 48) == 4


def test_pad_seq_edge_replication_and_errors():
    a = np.array([1, 2, 3], dtype=np.int32)
    p = B.pad_seq(a, 8)
    assert p.shape == (8,) and (p[3:] == 3).all()
    assert B.pad_seq(a, 3) is a  # no-op returns the input
    m = np.arange(6, dtype=np.float32).reshape(2, 3)
    p2 = B.pad_seq(m, 5, axis=1)
    assert p2.shape == (2, 5) and (p2[:, 3:] == m[:, -1:]).all()
    with pytest.raises(ValueError):
        B.pad_seq(a, 2)  # cannot shrink
    with pytest.raises(ValueError):
        B.pad_seq(np.zeros((0,), np.int32), 4)  # nothing to replicate
    with pytest.raises(ValueError):
        B.pad_seq(m, 4, axis=2)  # no such axis


def test_batcher_seq_bucketing_groups_pads_and_ships_lengths():
    batches = []

    def dispatch(batch):
        batches.append(batch)
        batch.complete({"y": batch.inputs["tokens"]})

    with pytest.raises(ValueError):
        B.MicroBatcher(dispatch, seq_axis=0)  # seq_axis requires seq_cap
    mb = B.MicroBatcher(dispatch, max_batch=8, max_delay_ms=50,
                        queue_max=100, seq_axis=0, seq_cap=16)
    reqs = [mb.submit({"tokens": np.arange(n, dtype=np.int32)})
            for n in (3, 5, 7, 9)]
    mb.start()
    for r in reqs:
        r.result(timeout=10)
    mb.close()
    # 3 -> bucket 4 alone; 5 and 7 share bucket 8; 9 -> bucket 16
    shapes = sorted(b.inputs["tokens"].shape for b in batches)
    assert shapes == [(1, 4), (1, 16), (2, 8)]
    for b in batches:
        # true lengths ride alongside as an int32 column; padding is
        # edge-replicated so padded ids stay in-vocabulary
        lens = b.inputs["_seq_len"]
        assert lens.dtype == np.int32
        for row, n in zip(b.inputs["tokens"], lens):
            assert (row[:n] == np.arange(n)).all()
            assert (row[n:] == n - 1).all()


# --- open-loop load generator (tentpole harness) ----------------------------

def test_run_open_loop_classifies_and_aggregates():
    def request_fn(i):
        if i == 1:
            raise B.Overloaded(5, 4, retry_after=0.1)
        if i == 2:
            raise RuntimeError("boom")
        time.sleep(0.001)
        return {"ttft_ms": 5.0 + i, "token_ms": [1.0, 2.0], "tokens": 3}

    stats = D.run_open_loop(request_fn, rate_rps=500, n_requests=8,
                            seed=7, shed_exc=B.Overloaded)
    assert stats["requests"] == 8
    assert stats["completed"] == 6
    assert stats["shed"] == 1 and stats["errors"] == 1
    assert stats["tokens"] == 18 and stats["tokens_per_sec"] > 0
    assert stats["latency_p50_ms"] > 0
    assert stats["latency_p99_ms"] >= stats["latency_p50_ms"]
    assert stats["ttft_p50_ms"] >= 5.0
    assert stats["tok_p50_ms"] in (1.0, 2.0)
    assert stats["offered_rps"] == 500
    # seeded arrivals: the same seed replays the same schedule
    again = D.run_open_loop(request_fn, rate_rps=500, n_requests=8,
                            seed=7, shed_exc=B.Overloaded)
    assert again["completed"] == 6 and again["shed"] == 1


# --- KV cache units ---------------------------------------------------------

def test_kvcache_slot_lifecycle_and_insert():
    from tensorflowonspark_tpu.serving.decode import kvcache
    cfg = _cfg()
    cache = kvcache.SlotKVCache(cfg, slots=3)
    assert cache.k.shape == (3, cfg.n_layers, cfg.n_heads, cfg.max_seq,
                             cfg.dim // cfg.n_heads)
    assert cache.free_slots == 3 and cache.occupancy == 0
    got = [cache.alloc() for _ in range(3)]
    assert got == [0, 1, 2]  # lowest slot first
    assert cache.alloc() is None  # full
    k = np.ones((cfg.n_layers, cfg.n_heads, 5, cfg.dim // cfg.n_heads),
                np.float32)
    cache.insert(1, k, k, 5)
    assert cache.lengths[1] == 5 and cache.occupancy == 3
    cache.retire(1)
    assert cache.lengths[1] == 0 and cache.free_slots == 1
    with pytest.raises(ValueError):
        cache.retire(1)  # double retire
    assert cache.alloc() == 1  # freed slot is reusable


def test_engine_submit_rejects_bad_prompts_via_emit():
    events = []
    cfg = _cfg()
    eng = D.DecodeEngine(params=None, spec=D.DecodeSpec(cfg, slots=2),
                         emit=lambda kind, sid, *rest: events.append(
                             (kind, sid) + rest))
    eng.submit("s-empty", [])
    eng.submit("s-long", list(range(cfg.max_seq)))
    kinds = [(k, sid) for k, sid, *_ in events]
    assert ("error", "s-empty") in kinds and ("error", "s-long") in kinds


# --- THE acceptance gate: token-identical continuous batching ---------------

def test_parity_staggered_mixed_length_token_identical():
    """Seeded multi-request trace with staggered arrivals and mixed
    prompt lengths; every session's streamed tokens must be
    token-identical to a full-recompute greedy decode of the same
    prompt, with each token index emitted exactly once."""
    cfg = _cfg()
    params = _params(cfg)
    rng = np.random.default_rng(3)
    prompts = {f"s{i}": rng.integers(0, cfg.vocab_size, size=n).tolist()
               for i, n in enumerate((5, 3, 9, 12))}

    events = {sid: {"tokens": [], "done": None, "error": None}
              for sid in prompts}
    lock = threading.Lock()

    def emit(kind, sid, *rest):
        with lock:
            if kind == "token":
                events[sid]["tokens"].append(rest)  # (index, token)
            elif kind == "done":
                events[sid]["done"] = rest[0]
            else:
                events[sid]["error"] = rest[0]

    eng = D.DecodeEngine(_params(cfg), D.DecodeSpec(cfg, slots=2,
                                                    max_tokens=6), emit)
    eng.start(timeout=300)
    try:
        # staggered admission: s0 decodes alone first, then the rest
        # arrive mid-flight (slots=2 also forces queueing)
        eng.submit("s0", prompts["s0"])
        deadline = time.time() + 300
        while not events["s0"]["tokens"] and time.time() < deadline:
            time.sleep(0.01)
        assert events["s0"]["tokens"], "no first token within deadline"
        for sid in ("s1", "s2", "s3"):
            eng.submit(sid, prompts[sid])
        while (any(e["done"] is None and e["error"] is None
                   for e in events.values())
               and time.time() < deadline):
            time.sleep(0.01)
    finally:
        eng.stop()

    for sid, prompt in prompts.items():
        ev = events[sid]
        assert ev["error"] is None, (sid, ev["error"])
        ref = _oracle(params, prompt, cfg, max_tokens=6)
        assert ev["done"] == ref, (sid, ev["done"], ref)
        # streamed (index, token) pairs: exactly once per index, in order
        idxs = [i for i, _ in ev["tokens"]]
        assert idxs == list(range(len(ref))), (sid, idxs)
        assert [t for _, t in ev["tokens"]] == ref, sid


def test_parity_eos_stops_early():
    cfg = _cfg()
    params = _params(cfg)
    prompt = [7, 11, 13, 17, 19]
    free_run = _oracle(params, prompt, cfg, max_tokens=8)
    eos = free_run[2]  # a token the free run provably emits
    ref = _oracle(params, prompt, cfg, max_tokens=8, eos_id=eos)
    # decode stops at (and includes) the FIRST occurrence of eos
    assert ref == free_run[:free_run.index(eos) + 1]

    events = {}
    eng = D.DecodeEngine(params, D.DecodeSpec(cfg, slots=2, max_tokens=8),
                         lambda kind, sid, *rest: events.setdefault(
                             kind, []).append(rest))
    eng.start(timeout=300)
    try:
        eng.submit("s", prompt, eos_id=eos)
        deadline = time.time() + 300
        while "done" not in events and "error" not in events and \
                time.time() < deadline:
            time.sleep(0.01)
    finally:
        eng.stop()
    assert "error" not in events, events
    assert events["done"][0][0] == ref


# --- Server / HTTP e2e ------------------------------------------------------

def test_server_generate_and_http_roundtrip(tmp_path):
    import jax

    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    params = _params(cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=8))
    prompt = [2, 3, 5, 7]
    ref = _oracle(params, prompt, cfg, max_tokens=6)
    with S.Server(spec, num_replicas=1, request_timeout=300) as srv:
        out = srv.generate(prompt, max_tokens=6, timeout=300)
        assert out["tokens"] == ref
        assert out["ttft_ms"] >= 0
        # gaps only exist between adjacent streamed tokens
        assert len(out["token_ms"]) == len(ref) - 1
        # predict on a decode-only spec is a clear error, not a hang
        with pytest.raises(Exception):
            srv.predict({"x": np.ones(1)}, timeout=30)
        httpd = S.serve_http(srv, port=0, block=False)
        try:
            host, port = httpd.server_address
            req = urllib.request.Request(
                f"http://{host}:{port}/v1/generate",
                data=json.dumps({"prompt": prompt,
                                 "max_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read())
            assert doc["tokens"] == ref
            # malformed body -> 400, not a crash
            bad = urllib.request.Request(
                f"http://{host}:{port}/v1/generate",
                data=json.dumps({"nope": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=30)
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
        summ = srv.summary()
    dec = summ["decode"]
    assert dec["completed"] >= 2 and dec["ttft_p99_ms"] >= 0


class _GenShedStub:
    pool = None

    def generate(self, prompt, max_tokens=None, eos_id=None, timeout=None,
                 **sampling_kw):
        raise B.Overloaded(65, 64, retry_after=0.5)


def test_http_generate_overload_maps_to_503():
    httpd = S.serve_http(_GenShedStub(), port=0, block=False)
    try:
        host, port = httpd.server_address
        req = urllib.request.Request(
            f"http://{host}:{port}/v1/generate",
            data=json.dumps({"prompt": [1, 2]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) == pytest.approx(0.5)
    finally:
        httpd.shutdown()


# --- slow lane: replica SIGKILL mid-decode (satellite c) --------------------

@pytest.mark.slow
def test_replica_sigkill_mid_decode_zero_drop_zero_dup(tmp_path):
    """A 2-replica decode service survives one SIGKILLed replica with
    sessions in flight: orphans re-prefill on the survivor and the
    resolve-once ledger dedupes the replayed stream, so every session
    still returns the exact oracle tokens — zero dropped, zero
    duplicated."""
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    params = _params(cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=24))
    rng = np.random.default_rng(11)
    with S.Server(spec, num_replicas=2, request_timeout=300) as srv:
        # warm both replicas' compile caches first so the kill lands
        # mid-stream, not mid-compile
        srv.generate([1, 2, 3], max_tokens=2, timeout=300)
        results, errors = {}, {}

        def one(i):
            p = rng.integers(0, cfg.vocab_size, size=3 + i % 5).tolist()
            try:
                results[i] = (p, srv.generate(p, max_tokens=20,
                                              timeout=300))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors[i] = e

        ts = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        deadline = time.time() + 120
        while srv.pool.outstanding_sessions() < 3 and \
                time.time() < deadline:
            time.sleep(0.01)
        pids = srv.pool.replica_pids()
        os.kill(pids[sorted(pids)[0]], 9)
        for t in ts:
            t.join()
        assert not errors, errors
        assert len(results) == 6
        for i, (p, out) in results.items():
            ref = _oracle(params, p, cfg, max_tokens=20)
            assert out["tokens"] == ref, (i, out["tokens"], ref)
