"""Decode-tier tests: slot- and block-paged KV cache units (refcount
lint, prefix-trie match/reclaim), sequence-length bucketing, the
open-loop load generator, seeded sampling, the CPU parity acceptance
gates (paged == slot-paged == full-recompute greedy; seeded-sampling
replay token-identical; speculative == non-speculative at the same
seed), and the Server/HTTP generate surface incl. oversized-prompt
400s.  Slow lane: a replica SIGKILLed mid-decode (sessions re-prefill
on the survivor; zero dropped and zero duplicated tokens)."""

import functools
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import batcher as B
from tensorflowonspark_tpu.serving import decode as D
from tensorflowonspark_tpu.serving import replicas as R
from tensorflowonspark_tpu.serving import server as S

pytestmark = pytest.mark.decode


def _cfg(**kw):
    from tensorflowonspark_tpu.models import transformer as T
    base = dict(vocab_size=61, dim=32, n_layers=2, n_heads=2, max_seq=32,
                dtype="float32", attn_impl="reference")
    base.update(kw)
    return T.Config(**base)


def _params(cfg):
    import jax

    from tensorflowonspark_tpu.models import transformer as T
    return T.init(jax.random.PRNGKey(0), cfg)


def _oracle(params, prompt, cfg, **kw):
    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer as T
    return T.greedy_decode_reference(
        params, prompt, cfg,
        attn_fn=functools.partial(ops.mha_reference, causal=True), **kw)


# --- sequence bucketing (satellite b) ---------------------------------------

def test_bucket_seq_pow2_and_cap():
    assert [B.bucket_seq(n, 64) for n in (1, 2, 3, 5, 9, 33, 64, 100)] == \
        [1, 2, 4, 8, 16, 64, 64, 64]
    # the cap itself is a legal bucket even when not a power of two
    assert B.bucket_seq(48, 48) == 48
    assert B.bucket_seq(49, 48) == 48
    assert B.bucket_seq(3, 48) == 4


def test_pad_seq_edge_replication_and_errors():
    a = np.array([1, 2, 3], dtype=np.int32)
    p = B.pad_seq(a, 8)
    assert p.shape == (8,) and (p[3:] == 3).all()
    assert B.pad_seq(a, 3) is a  # no-op returns the input
    m = np.arange(6, dtype=np.float32).reshape(2, 3)
    p2 = B.pad_seq(m, 5, axis=1)
    assert p2.shape == (2, 5) and (p2[:, 3:] == m[:, -1:]).all()
    with pytest.raises(ValueError):
        B.pad_seq(a, 2)  # cannot shrink
    with pytest.raises(ValueError):
        B.pad_seq(np.zeros((0,), np.int32), 4)  # nothing to replicate
    with pytest.raises(ValueError):
        B.pad_seq(m, 4, axis=2)  # no such axis


def test_batcher_seq_bucketing_groups_pads_and_ships_lengths():
    batches = []

    def dispatch(batch):
        batches.append(batch)
        batch.complete({"y": batch.inputs["tokens"]})

    with pytest.raises(ValueError):
        B.MicroBatcher(dispatch, seq_axis=0)  # seq_axis requires seq_cap
    mb = B.MicroBatcher(dispatch, max_batch=8, max_delay_ms=50,
                        queue_max=100, seq_axis=0, seq_cap=16)
    reqs = [mb.submit({"tokens": np.arange(n, dtype=np.int32)})
            for n in (3, 5, 7, 9)]
    mb.start()
    for r in reqs:
        r.result(timeout=10)
    mb.close()
    # 3 -> bucket 4 alone; 5 and 7 share bucket 8; 9 -> bucket 16
    shapes = sorted(b.inputs["tokens"].shape for b in batches)
    assert shapes == [(1, 4), (1, 16), (2, 8)]
    for b in batches:
        # true lengths ride alongside as an int32 column; padding is
        # edge-replicated so padded ids stay in-vocabulary
        lens = b.inputs["_seq_len"]
        assert lens.dtype == np.int32
        for row, n in zip(b.inputs["tokens"], lens):
            assert (row[:n] == np.arange(n)).all()
            assert (row[n:] == n - 1).all()


# --- open-loop load generator (tentpole harness) ----------------------------

def test_run_open_loop_classifies_and_aggregates():
    def request_fn(i):
        if i == 1:
            raise B.Overloaded(5, 4, retry_after=0.1)
        if i == 2:
            raise RuntimeError("boom")
        time.sleep(0.001)
        return {"ttft_ms": 5.0 + i, "token_ms": [1.0, 2.0], "tokens": 3}

    stats = D.run_open_loop(request_fn, rate_rps=500, n_requests=8,
                            seed=7, shed_exc=B.Overloaded)
    assert stats["requests"] == 8
    assert stats["completed"] == 6
    assert stats["shed"] == 1 and stats["errors"] == 1
    assert stats["tokens"] == 18 and stats["tokens_per_sec"] > 0
    assert stats["latency_p50_ms"] > 0
    assert stats["latency_p99_ms"] >= stats["latency_p50_ms"]
    assert stats["ttft_p50_ms"] >= 5.0
    assert stats["tok_p50_ms"] in (1.0, 2.0)
    assert stats["offered_rps"] == 500
    # seeded arrivals: the same seed replays the same schedule
    again = D.run_open_loop(request_fn, rate_rps=500, n_requests=8,
                            seed=7, shed_exc=B.Overloaded)
    assert again["completed"] == 6 and again["shed"] == 1


# --- KV cache units ---------------------------------------------------------

def test_kvcache_slot_lifecycle_and_insert():
    from tensorflowonspark_tpu.serving.decode import kvcache
    cfg = _cfg()
    cache = kvcache.SlotKVCache(cfg, slots=3)
    assert cache.k.shape == (3, cfg.n_layers, cfg.n_heads, cfg.max_seq,
                             cfg.dim // cfg.n_heads)
    assert cache.free_slots == 3 and cache.occupancy == 0
    got = [cache.alloc() for _ in range(3)]
    assert got == [0, 1, 2]  # lowest slot first
    assert cache.alloc() is None  # full
    k = np.ones((cfg.n_layers, cfg.n_heads, 5, cfg.dim // cfg.n_heads),
                np.float32)
    cache.insert(1, k, k, 5)
    assert cache.lengths[1] == 5 and cache.occupancy == 3
    cache.retire(1)
    assert cache.lengths[1] == 0 and cache.free_slots == 1
    with pytest.raises(ValueError):
        cache.retire(1)  # double retire
    assert cache.alloc() == 1  # freed slot is reusable


def test_paged_kvcache_lifecycle_refcounts_and_prefix_match():
    from tensorflowonspark_tpu.serving.decode import kvcache
    cfg = _cfg()
    cache = kvcache.PagedKVCache(cfg, slots=2, block_size=4)
    hd = cfg.dim // cfg.n_heads
    assert cache.k.shape == (cache.num_blocks, cfg.n_layers, cfg.n_heads,
                             4, hd)
    assert cache.blocks_per_slot == 8  # ceil(32 / 4)
    assert cache.blocks_in_use == 0 and cache.leaked_blocks() == []

    prompt = list(range(1, 11))  # 10 tokens -> 2 whole blocks + tail
    assert cache.match_prefix(prompt) == ([], 0)  # cold trie
    slot = cache.alloc()
    own = cache.alloc_blocks(3)
    assert 0 not in own  # the sentinel is never handed out
    cache.map_session(slot, [], own, 10)
    k = np.zeros((cfg.n_layers, cfg.n_heads, 10, hd), np.float32)
    cache.insert_tail(slot, k, k, 0, 10)
    cache.register_prompt(slot, prompt)
    assert cache.blocks_in_use == 3 and cache.leaked_blocks() == []

    # a follower matches whole blocks only, capped one token short of
    # the full prompt so admission always has a real tail to prefill
    shared, mtoks = cache.match_prefix(prompt)
    assert mtoks == 8 and shared == own[:2]
    slot2 = cache.alloc()
    own2 = cache.alloc_blocks(1)
    cache.map_session(slot2, shared, own2, 10)
    assert cache.blocks_in_use == 4  # leader's 3 + follower's tail block
    assert cache.leaked_blocks() == []

    # tail writes must start block-aligned (copy-on-write contract)
    with pytest.raises(ValueError):
        cache.insert_tail(slot2, k, k, 9, 1)

    # retiring both sessions keeps the registered prefix trie-resident
    cache.retire(slot)
    cache.retire(slot2)
    assert cache.occupancy == 0
    assert cache.blocks_in_use == 2  # the two whole-prefix blocks
    assert cache.leaked_blocks() == []
    assert cache.match_prefix(prompt)[1] == 8  # still a hit


def test_paged_kvcache_trie_reclaim_lru_and_oom():
    from tensorflowonspark_tpu.serving.decode import kvcache
    cfg = _cfg()
    with pytest.raises(ValueError):
        # below sentinel + slots*blocks_per_slot: live sessions starve
        kvcache.PagedKVCache(cfg, slots=1, block_size=4, num_blocks=8)
    cache = kvcache.PagedKVCache(cfg, slots=1, block_size=4, num_blocks=9)
    prompt = list(range(1, 11))
    slot = cache.alloc()
    cache.map_session(slot, [], cache.alloc_blocks(3), 10)
    cache.register_prompt(slot, prompt)
    cache.retire(slot)
    assert cache.blocks_in_use == 2  # trie-only now

    # one block over the free list: the LRU *leaf* is evicted, the
    # parent (shorter prefix) stays matchable
    got = cache.alloc_blocks(7)
    assert cache.match_prefix(prompt)[1] == 4
    # live references hold everything else: reclaim can't satisfy this
    with pytest.raises(kvcache.CacheOOM):
        cache.alloc_blocks(2)
    # ... but the attempt drained the remaining (fully freed) trie path
    assert cache.match_prefix(prompt) == ([], 0)
    for b in got:
        cache._release(b)
    assert cache.blocks_in_use == 0 and cache.leaked_blocks() == []


def test_sampling_make_validation_and_pure_function():
    from tensorflowonspark_tpu.serving.decode import sampling
    assert sampling.make() is None
    assert sampling.make(temperature=0.0, top_k=5, seed=3) is None  # greedy
    for bad in (dict(temperature=-0.5), dict(temperature=1.0, top_k=0),
                dict(temperature=1.0, top_p=0.0),
                dict(temperature=1.0, top_p=1.5)):
        with pytest.raises(ValueError):
            sampling.make(**bad)
    logits = np.random.default_rng(0).normal(size=61)
    assert sampling.sample_token(logits, None, 4) == int(np.argmax(logits))
    p = sampling.make(temperature=0.8, top_k=12, top_p=0.9, seed=42)
    a = [sampling.sample_token(logits, p, i) for i in range(16)]
    b = [sampling.sample_token(logits, p, i) for i in range(16)]
    assert a == b  # pure in (logits, params, index): replayable
    p2 = sampling.make(temperature=0.8, top_k=12, top_p=0.9, seed=43)
    assert [sampling.sample_token(logits, p2, i) for i in range(16)] != a


def test_engine_submit_rejects_bad_prompts_via_emit():
    events = []
    cfg = _cfg()
    eng = D.DecodeEngine(params=None, spec=D.DecodeSpec(cfg, slots=2),
                         emit=lambda kind, sid, *rest: events.append(
                             (kind, sid) + rest))
    eng.submit("s-empty", [])
    eng.submit("s-long", list(range(cfg.max_seq)))
    kinds = [(k, sid) for k, sid, *_ in events]
    assert ("error", "s-empty") in kinds and ("error", "s-long") in kinds


# --- THE acceptance gate: token-identical continuous batching ---------------

def test_parity_staggered_mixed_length_token_identical():
    """Seeded multi-request trace with staggered arrivals and mixed
    prompt lengths; every session's streamed tokens must be
    token-identical to a full-recompute greedy decode of the same
    prompt, with each token index emitted exactly once."""
    cfg = _cfg()
    params = _params(cfg)
    rng = np.random.default_rng(3)
    prompts = {f"s{i}": rng.integers(0, cfg.vocab_size, size=n).tolist()
               for i, n in enumerate((5, 3, 9, 12))}

    events = {sid: {"tokens": [], "done": None, "error": None}
              for sid in prompts}
    lock = threading.Lock()

    def emit(kind, sid, *rest):
        with lock:
            if kind == "token":
                events[sid]["tokens"].append(rest)  # (index, token)
            elif kind == "done":
                events[sid]["done"] = rest[0]
            else:
                events[sid]["error"] = rest[0]

    eng = D.DecodeEngine(_params(cfg), D.DecodeSpec(cfg, slots=2,
                                                    max_tokens=6), emit)
    eng.start(timeout=300)
    try:
        # staggered admission: s0 decodes alone first, then the rest
        # arrive mid-flight (slots=2 also forces queueing)
        eng.submit("s0", prompts["s0"])
        deadline = time.time() + 300
        while not events["s0"]["tokens"] and time.time() < deadline:
            time.sleep(0.01)
        assert events["s0"]["tokens"], "no first token within deadline"
        for sid in ("s1", "s2", "s3"):
            eng.submit(sid, prompts[sid])
        while (any(e["done"] is None and e["error"] is None
                   for e in events.values())
               and time.time() < deadline):
            time.sleep(0.01)
    finally:
        eng.stop()

    for sid, prompt in prompts.items():
        ev = events[sid]
        assert ev["error"] is None, (sid, ev["error"])
        ref = _oracle(params, prompt, cfg, max_tokens=6)
        assert ev["done"] == ref, (sid, ev["done"], ref)
        # streamed (index, token) pairs: exactly once per index, in order
        idxs = [i for i, _ in ev["tokens"]]
        assert idxs == list(range(len(ref))), (sid, idxs)
        assert [t for _, t in ev["tokens"]] == ref, sid


def test_parity_eos_stops_early():
    cfg = _cfg()
    params = _params(cfg)
    prompt = [7, 11, 13, 17, 19]
    free_run = _oracle(params, prompt, cfg, max_tokens=8)
    eos = free_run[2]  # a token the free run provably emits
    ref = _oracle(params, prompt, cfg, max_tokens=8, eos_id=eos)
    # decode stops at (and includes) the FIRST occurrence of eos
    assert ref == free_run[:free_run.index(eos) + 1]

    events = {}
    eng = D.DecodeEngine(params, D.DecodeSpec(cfg, slots=2, max_tokens=8),
                         lambda kind, sid, *rest: events.setdefault(
                             kind, []).append(rest))
    eng.start(timeout=300)
    try:
        eng.submit("s", prompt, eos_id=eos)
        deadline = time.time() + 300
        while "done" not in events and "error" not in events and \
                time.time() < deadline:
            time.sleep(0.01)
    finally:
        eng.stop()
    assert "error" not in events, events
    assert events["done"][0][0] == ref


def _run_sessions(params, spec, jobs, timeout=300):
    """Drive a DecodeEngine over ``jobs`` = [(sid, prompt, submit_kw)];
    returns ({sid: tokens}, stats, engine) — stats captured before
    stop, the (stopped) engine returned for cache introspection."""
    events = {sid: {"done": None, "error": None} for sid, _, _ in jobs}
    lock = threading.Lock()

    def emit(kind, sid, *rest):
        with lock:
            if kind == "done":
                events[sid]["done"] = rest[0]
            elif kind == "error":
                events[sid]["error"] = rest[0]

    eng = D.DecodeEngine(params, spec, emit)
    eng.start(timeout=timeout)
    try:
        for sid, prompt, kw in jobs:
            eng.submit(sid, prompt, **kw)
        deadline = time.time() + timeout
        while (any(e["done"] is None and e["error"] is None
                   for e in events.values()) and time.time() < deadline):
            time.sleep(0.01)
        stats = eng.stats()
    finally:
        eng.stop()
    for sid, ev in events.items():
        assert ev["error"] is None, (sid, ev["error"])
        assert ev["done"] is not None, (sid, "timed out")
    return {sid: ev["done"] for sid, ev in events.items()}, stats, eng


def test_parity_paged_equals_slot_equals_oracle_with_prefix_hits():
    """Gate (a): block-paged greedy decode — including trie-matched
    admissions that skip the shared prefill — is token-identical to the
    legacy slot-paged cache AND to a full-recompute greedy decode; the
    engine's paged cache leaks zero block references afterwards."""
    cfg = _cfg()
    params = _params(cfg)
    rng = np.random.default_rng(5)
    system = rng.integers(1, cfg.vocab_size, size=8).tolist()
    prompts = {"lead": system + [3, 5],
               "follow": system + [7, 11, 13],
               "cold": rng.integers(1, cfg.vocab_size, size=6).tolist()}
    jobs = [(sid, p, {}) for sid, p in prompts.items()]
    # slots=1 serializes admission, so "follow" provably arrives after
    # "lead" registered the shared prefix -> a guaranteed trie hit
    paged, pstats, eng = _run_sessions(
        params, D.DecodeSpec(cfg, slots=1, max_tokens=6, paged=True,
                             block_size=4), jobs)
    slotted, sstats, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=1, max_tokens=6, paged=False),
        jobs)
    for sid, p in prompts.items():
        ref = _oracle(params, p, cfg, max_tokens=6)
        assert paged[sid] == ref, (sid, paged[sid], ref)
        assert slotted[sid] == ref, (sid, slotted[sid], ref)
    assert pstats["paged"] is True and sstats["paged"] is False
    assert pstats["prefix_hits"] >= 1
    assert pstats["prefix_tokens_saved"] >= 8  # the whole system prompt
    # refcount lint: every retired session returned its blocks; only
    # trie-resident prefixes (reusable capacity) remain accounted
    cache = eng._cache
    assert cache.occupancy == 0
    assert cache.leaked_blocks() == []


def test_parity_seeded_sampling_replay_token_identical():
    """Gate (b): a seeded-sampled session replayed from scratch (what
    failover does after re-prefill) emits the identical token stream;
    a different seed provably diverges."""
    from tensorflowonspark_tpu.serving.decode import sampling
    cfg = _cfg()
    params = _params(cfg)
    prompt = [2, 3, 5, 7, 11]
    sp = sampling.make(temperature=0.8, top_k=12, seed=99)
    first, _, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=8, block_size=4),
        [("r1", prompt, {"sampling": sp})])
    replay, _, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=8, block_size=4),
        [("r2", prompt, {"sampling": sp})])
    assert first["r1"] == replay["r2"]
    other, _, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=8, block_size=4),
        [("r3", prompt,
          {"sampling": sampling.make(temperature=0.8, top_k=12,
                                     seed=100)})])
    assert other["r3"] != first["r1"]


def test_parity_speculative_equals_plain_same_seed():
    """Gate (c): speculative decoding (draft proposes, target verifies
    in one windowed step) returns token-identical output to the
    non-speculative engine for greedy AND seeded-sampled sessions; a
    draft that IS the target is always accepted (the speedup path)."""
    import jax

    from tensorflowonspark_tpu.models import transformer as T
    from tensorflowonspark_tpu.serving.decode import sampling

    cfg = _cfg()
    params = _params(cfg)
    dcfg = _cfg(dim=16, n_layers=1)
    dparams = T.init(jax.random.PRNGKey(7), dcfg)
    sp = sampling.make(temperature=0.9, top_k=16, seed=7)
    jobs = [("g", [3, 5, 7, 9, 11], {}),
            ("s", [4, 6, 8, 10], {"sampling": sp})]
    plain, _, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=7, block_size=4),
        jobs)
    assert plain["g"] == _oracle(params, [3, 5, 7, 9, 11], cfg,
                                 max_tokens=7)
    specd, st, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=7, block_size=4,
                             draft_params=dparams, draft_cfg=dcfg,
                             spec_window=3), jobs)
    assert specd == plain
    assert st["spec_proposed"] > 0
    # perfect draft (the target itself): every proposal accepted, output
    # still identical — multiple tokens really do land per fused step
    perfect, pt, _ = _run_sessions(
        params, D.DecodeSpec(cfg, slots=2, max_tokens=7, block_size=4,
                             draft_params=params, draft_cfg=cfg,
                             spec_window=3), jobs)
    assert perfect == plain
    assert pt["spec_accepted"] == pt["spec_proposed"] > 0
    with pytest.raises(ValueError):
        D.DecodeSpec(cfg, draft_params=dparams, draft_cfg=None)
    with pytest.raises(ValueError):
        D.DecodeSpec(cfg, paged=False, draft_params=dparams,
                     draft_cfg=dcfg)


# --- Server / HTTP e2e ------------------------------------------------------

def test_server_generate_and_http_roundtrip(tmp_path):
    import jax

    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    params = _params(cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=8))
    prompt = [2, 3, 5, 7]
    ref = _oracle(params, prompt, cfg, max_tokens=6)
    with S.Server(spec, num_replicas=1, request_timeout=300) as srv:
        out = srv.generate(prompt, max_tokens=6, timeout=300)
        assert out["tokens"] == ref
        assert out["ttft_ms"] >= 0
        # gaps only exist between adjacent streamed tokens
        assert len(out["token_ms"]) == len(ref) - 1
        # predict on a decode-only spec is a clear error, not a hang
        with pytest.raises(Exception):
            srv.predict({"x": np.ones(1)}, timeout=30)
        # oversized prompts are rejected driver-side before any replica
        # sees the session (no crash, no shed)
        with pytest.raises(ValueError):
            srv.generate(list(range(1, cfg.max_seq + 1)), max_tokens=2,
                         timeout=30)
        # seeded sampling through the full server stack is replayable
        s1 = srv.generate(prompt, max_tokens=6, timeout=300,
                          temperature=0.9, top_k=8, seed=5)
        s2 = srv.generate(prompt, max_tokens=6, timeout=300,
                          temperature=0.9, top_k=8, seed=5)
        assert s1["tokens"] == s2["tokens"]
        httpd = S.serve_http(srv, port=0, block=False)
        try:
            host, port = httpd.server_address
            req = urllib.request.Request(
                f"http://{host}:{port}/v1/generate",
                data=json.dumps({"prompt": prompt,
                                 "max_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read())
            assert doc["tokens"] == ref
            # malformed body -> 400, not a crash
            bad = urllib.request.Request(
                f"http://{host}:{port}/v1/generate",
                data=json.dumps({"nope": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=30)
            assert ei.value.code == 400
            # oversized prompt / invalid sampling params -> 400 too
            for body in ({"prompt": list(range(1, cfg.max_seq + 1))},
                         {"prompt": prompt, "temperature": -1.0}):
                r400 = urllib.request.Request(
                    f"http://{host}:{port}/v1/generate",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as ei2:
                    urllib.request.urlopen(r400, timeout=30)
                assert ei2.value.code == 400, body
        finally:
            httpd.shutdown()
        summ = srv.summary()
    dec = summ["decode"]
    assert dec["completed"] >= 2 and dec["ttft_p99_ms"] >= 0


class _GenShedStub:
    pool = None

    def generate(self, prompt, max_tokens=None, eos_id=None, timeout=None,
                 **sampling_kw):
        raise B.Overloaded(65, 64, retry_after=0.5)


def test_http_generate_overload_maps_to_503():
    httpd = S.serve_http(_GenShedStub(), port=0, block=False)
    try:
        host, port = httpd.server_address
        req = urllib.request.Request(
            f"http://{host}:{port}/v1/generate",
            data=json.dumps({"prompt": [1, 2]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) == pytest.approx(0.5)
    finally:
        httpd.shutdown()


# --- slow lane: replica SIGKILL mid-decode (satellite c) --------------------

@pytest.mark.slow
def test_replica_sigkill_mid_decode_zero_drop_zero_dup(tmp_path):
    """A 2-replica decode service survives one SIGKILLed replica with
    sessions in flight: orphans re-prefill on the survivor and the
    resolve-once ledger dedupes the replayed stream, so every session
    still returns the exact oracle tokens — zero dropped, zero
    duplicated."""
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    cfg = _cfg()
    params = _params(cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=4, max_tokens=24))
    rng = np.random.default_rng(11)
    with S.Server(spec, num_replicas=2, request_timeout=300) as srv:
        # warm both replicas' compile caches first so the kill lands
        # mid-stream, not mid-compile
        srv.generate([1, 2, 3], max_tokens=2, timeout=300)
        results, errors = {}, {}

        def one(i):
            p = rng.integers(0, cfg.vocab_size, size=3 + i % 5).tolist()
            try:
                results[i] = (p, srv.generate(p, max_tokens=20,
                                              timeout=300))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors[i] = e

        ts = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        deadline = time.time() + 120
        while srv.pool.outstanding_sessions() < 3 and \
                time.time() < deadline:
            time.sleep(0.01)
        pids = srv.pool.replica_pids()
        os.kill(pids[sorted(pids)[0]], 9)
        for t in ts:
            t.join()
        assert not errors, errors
        assert len(results) == 6
        for i, (p, out) in results.items():
            ref = _oracle(params, p, cfg, max_tokens=20)
            assert out["tokens"] == ref, (i, out["tokens"], ref)
