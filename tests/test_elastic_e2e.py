"""Elastic resize-and-reshard acceptance (slow lane): a SIGKILLed
executor with a ZERO respawn budget and ``min_executors=1`` must shrink
the cluster to the survivor, which resumes from a checkpoint written
under the 8-device fold on a 4-device mesh (accum 2x) with loss
continuity and exactly-once feed accounting (docs/elastic.md).

The rigid cousin (full-strength respawn recovery) is
test_fault_tolerance_e2e.py; this file is the path where healing is
impossible and the cluster re-forms over what survives.
"""

import glob
import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster as TFCluster
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.engine import LocalEngine
from tensorflowonspark_tpu.utils import faults, telemetry

pytestmark = [pytest.mark.slow, pytest.mark.elastic, pytest.mark.faults]

N_PART = 4
PER_PART = 320
CHUNK = 64  # 5 puts/partition; executor 1's 6th put = its 2nd partition
LOGICAL = 8  # virtual mesh: data=8, on 4*num_workers fake devices


def elastic_mnist_main(args, ctx):
    """MNIST CNN through the elastic runtime.  Each incarnation sees
    ``4 * num_workers`` of its executor's 8 fake CPU devices — 8 before
    the kill (accum 1), 4 after the shrink to one worker (accum 2) —
    for the SAME logical ``data=8`` mesh, and resumes through the
    resharding restore path."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    devices = jax.devices()[: 4 * ctx.num_workers]
    rt = ctx.elastic_runtime({"data": LOGICAL}, devices=devices)
    ckpt_dir = os.path.join(args["model_dir"], f"worker-{ctx.task_index}")
    log_path = os.path.join(args["model_dir"],
                            f"losses-{ctx.task_index}.jsonl")

    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.05, momentum=0.9)
    saved, start = ctx.restore_latest(
        ckpt_dir, target_shardings=lambda t: rt.fsdp_sharding(t))
    if saved is not None:
        params = saved["params"]  # fresh opt state after restart is fine
    else:
        params = rt.reshard(params)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    feed = ctx.get_data_feed(train_mode=True)
    step = start
    while not feed.should_stop():
        batch = feed.next_batch(32)
        if not batch:
            continue
        images = jax.device_put(
            np.stack([b[0] for b in batch]).astype(np.float32),
            rt.batch_sharding())
        labels = jax.device_put(
            np.asarray([b[1] for b in batch], dtype=np.int32),
            rt.batch_sharding())
        params, opt_state, loss, acc = step_fn(
            params, opt_state, images, labels)
        step += 1
        ckpt.save_checkpoint(
            ckpt_dir, {"params": params, "loss": jnp.asarray(float(loss))},
            step)
        with open(log_path, "a") as f:
            f.write(json.dumps({
                "epoch": ctx.epoch, "step": step, "loss": float(loss),
                "devices": rt.layout.n_physical,
                "accum": rt.layout.accum_steps,
            }) + "\n")


def _synthetic_records(n):
    rng = np.random.default_rng(0)
    images = rng.random((n, 28, 28, 1), dtype=np.float32)
    q = np.stack(
        [
            images[:, :14, :14, 0].mean((1, 2)),
            images[:, :14, 14:, 0].mean((1, 2)),
            images[:, 14:, :14, 0].mean((1, 2)),
            images[:, 14:, 14:, 0].mean((1, 2)),
        ],
        axis=-1,
    )
    labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
    return list(zip(list(images), list(labels)))


def _read_all(root):
    text = ""
    for path in glob.glob(os.path.join(str(root), "**", "*"), recursive=True):
        if os.path.isfile(path):
            with open(path, errors="replace") as f:
                text += f.read()
    return text


def test_kill_one_executor_resumes_on_smaller_mesh(tmp_path, monkeypatch):
    telemetry_dir = tmp_path / "telemetry"
    monkeypatch.setenv(telemetry.DIR_ENV, str(telemetry_dir))
    monkeypatch.chdir(tmp_path)
    # healing impossible: zero respawn budget (read by the DRIVER-side
    # engine at construction) forces the elastic shrink path
    monkeypatch.setenv("TFOS_EXECUTOR_RESPAWNS", "0")
    engine = LocalEngine(2, env={
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "",  # drop the TPU-tunnel site hook
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "TFOS_FEED_CHUNK": str(CHUNK),
        faults.PLAN_ENV: "feed.put:kill@6",
        faults.EXECUTOR_ENV: "1",
    })
    model_dir = tmp_path / "model"
    try:
        cluster = TFCluster.run(
            engine, elastic_mnist_main, {"model_dir": str(model_dir)},
            num_executors=2, input_mode=InputMode.SPARK,
            restarts=1, min_executors=1,
        )
        ds = engine.parallelize(_synthetic_records(N_PART * PER_PART), N_PART)
        cluster.train(ds, num_epochs=1, feed_timeout=240)

        assert cluster._restarts_used == 1
        # the cluster re-formed over the single survivor
        assert cluster.meta["cluster_template"] == {"worker": [0]}
        assert cluster.meta["num_executors"] == 1
        assert len(cluster.cluster_info) == 1
        # exactly-once feed accounting: every partition consumed exactly
        # once across both incarnations (the ledger re-fed only the
        # partitions the dead executor never finished)
        assert cluster.server.fed_partitions("input") == list(range(N_PART))
        cluster.shutdown(grace_secs=2)
    finally:
        engine.stop()
        for k in (telemetry.NODE_ENV, telemetry.ROLE_ENV,
                  telemetry.SPOOL_ENV):
            os.environ.pop(k, None)

    # the survivor trained in BOTH incarnations: epoch 0 on the 8-device
    # fold (accum 1), epoch 1 on the 4-device fold (accum 2), resuming
    # from the resharded checkpoint (step continuity) with its loss
    # continuing below the cold-start loss (value continuity)
    lines = [json.loads(ln) for ln in
             (model_dir / "losses-0.jsonl").read_text().splitlines()]
    e0 = [ln for ln in lines if ln["epoch"] == 0]
    e1 = [ln for ln in lines if ln["epoch"] == 1]
    assert e0 and e1, f"missing incarnation logs: {len(e0)}/{len(e1)}"
    assert all(ln["devices"] == 8 and ln["accum"] == 1 for ln in e0)
    assert all(ln["devices"] == 4 and ln["accum"] == 2 for ln in e1)
    assert e1[0]["step"] > 1, f"post-resize run restarted: {e1[0]}"
    assert e1[0]["loss"] < e0[0]["loss"], (
        f"loss continuity broken: resumed at {e1[0]['loss']:.4f} vs "
        f"cold start {e0[0]['loss']:.4f}")

    # resize is visible in telemetry: the cluster re-template, the
    # rendezvous requirement change, and the node-side runtime build
    raw = _read_all(telemetry_dir)
    for ev in ("cluster/resize", "rendezvous/resize", "elastic/from_context"):
        assert ev in raw, f"telemetry event {ev} missing from drained run"
