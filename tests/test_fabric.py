"""Pod-scale serving-fabric tests: consistent-hash ring + affinity LRU
units, the ServeAutoscaler hysteresis kernel (injected mode + env
knobs), cross-host predict/decode roundtrips through ``Server(...,
fabric=True)`` incl. the CPU parity gate (fabric-routed decode
token-identical to the single-replica oracle), route-id affinity
(miss -> hit -> rebind-on-failover), fault injection on the two fabric
chaos sites, loadgen route-id plumbing, and the elastic mirror's
reload-watermark acceptance (satellite: ElasticReplicaPool).  Slow
lane: SIGKILL of the affinity-target host mid-session (zero drop, zero
dup, fallback rebind) and an end-to-end autoscale-up under induced
queueing."""

import functools
import os
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import replicas as R
from tensorflowonspark_tpu.serving import server as S
from tensorflowonspark_tpu.serving.fabric import affinity as FA
from tensorflowonspark_tpu.serving.fabric import autoscale as FS
from tensorflowonspark_tpu.serving.fabric import router as FR
from tensorflowonspark_tpu.utils import faults

pytestmark = pytest.mark.serve


# --- probe predicts (module-level: shipped to executor processes) -----------

def _double_predict(params, inputs):
    x = np.asarray(inputs["x"])
    return {"y": x * params["scale"]}


def _slow_predict(params, inputs):
    x = np.asarray(inputs["x"])
    time.sleep(0.05)
    return {"y": x * 1.0}


def _cfg(**kw):
    from tensorflowonspark_tpu.models import transformer as T
    base = dict(vocab_size=61, dim=32, n_layers=2, n_heads=2, max_seq=32,
                dtype="float32", attn_impl="reference")
    base.update(kw)
    return T.Config(**base)


def _oracle(params, prompt, cfg, **kw):
    from tensorflowonspark_tpu import ops
    from tensorflowonspark_tpu.models import transformer as T
    return T.greedy_decode_reference(
        params, prompt, cfg,
        attn_fn=functools.partial(ops.mha_reference, causal=True), **kw)


def _export_decode_spec(tmp_path, slots=4, max_tokens=24):
    import jax

    from tensorflowonspark_tpu.models import transformer as T
    from tensorflowonspark_tpu.serving import decode as D
    from tensorflowonspark_tpu.utils import checkpoint as ckpt
    cfg = _cfg()
    params = T.init(jax.random.PRNGKey(0), cfg)
    export = str(tmp_path / "export")
    ckpt.export_model(export, params, metadata={})
    spec = R.ModelSpec(export_dir=export,
                       decode=D.DecodeSpec(cfg, slots=slots,
                                           max_tokens=max_tokens))
    return cfg, params, spec


# --- consistent-hash ring + affinity map units ------------------------------

def test_ring_deterministic_and_balanced():
    eps = [(h, r) for h in range(3) for r in range(2)]
    ring = FA.Ring(eps)
    picks = [ring.lookup(f"route-{i}") for i in range(600)]
    assert picks == [FA.Ring(eps).lookup(f"route-{i}") for i in range(600)]
    counts = {ep: picks.count(ep) for ep in eps}
    assert set(counts) == set(eps)
    # 64 vnodes/endpoint keeps the spread within a loose band
    assert min(counts.values()) > 20 and max(counts.values()) < 300


def test_ring_consistency_on_membership_change():
    before = FA.Ring([(h, 0) for h in range(4)])
    after = FA.Ring([(h, 0) for h in range(4) if h != 2])
    keys = [f"s{i}" for i in range(400)]
    moved = sum(1 for k in keys
                if before.lookup(k) != (2, 0)
                and before.lookup(k) != after.lookup(k))
    # consistent hashing: only keys owned by the removed endpoint move
    assert moved == 0
    with pytest.raises(ValueError):
        FA.Ring([])


def test_affinity_map_is_a_bounded_lru():
    m = FA.AffinityMap(capacity=3)
    for i in range(3):
        m.bind(f"s{i}", (i, 0))
    assert m.get("s0") == (0, 0)      # refreshes recency
    m.bind("s3", (3, 0))              # evicts s1 (oldest untouched)
    assert m.get("s1") is None
    assert m.get("s0") == (0, 0) and m.get("s3") == (3, 0)
    assert len(m) == 3
    assert m.pop("s3") == (3, 0) and m.get("s3") is None


# --- autoscaler kernel (injected mode) --------------------------------------

def _scaler(sig, plans, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("high", 2.0)
    kw.setdefault("low", 0.5)
    kw.setdefault("cooldown", 10.0)
    return FS.ServeAutoscaler(read_signal=lambda: sig,
                              apply_plan=plans.append, **kw)


def test_autoscaler_scales_up_emptiest_host_on_queueing():
    sig = {0: {"workers": 2, "depth": 9}, 1: {"workers": 1, "depth": 4}}
    plans = []
    sc = _scaler(sig, plans)
    assert sc.step(now=0.0) == "up"
    # ratio 13/3 > 2.0: one replica added to the emptiest host (spread
    # before stacking)
    assert plans == [{0: 2, 1: 2}]
    assert sc.scale_ups == 1
    # cooldown gates the next action; after it expires the (unchanged,
    # still collapsed) signal fires again
    assert sc.step(now=5.0) is None
    assert sc.step(now=11.0) == "up"
    assert plans[-1] == {0: 2, 1: 2}


def test_autoscaler_scales_down_fullest_host_and_clamps():
    sig = {0: {"workers": 3, "depth": 0}, 1: {"workers": 1, "depth": 0}}
    plans = []
    sc = _scaler(sig, plans)
    assert sc.step(now=0.0) == "down"
    # LIFO retirement target: the fullest host sheds one
    assert plans == [{0: 2, 1: 1}]
    # at the min everywhere: clamp holds (no plan published)
    quiet = {0: {"workers": 1, "depth": 0}}
    sc2 = _scaler(quiet, plans)
    assert sc2.step(now=0.0) is None
    # at the max everywhere under collapse: clamp holds too
    full = {0: {"workers": 3, "depth": 99}}
    sc3 = _scaler(full, plans)
    assert sc3.step(now=0.0) is None
    assert len(plans) == 1
    # band interior: no action
    band = {0: {"workers": 2, "depth": 2}}
    assert _scaler(band, plans).step(now=0.0) is None
    # no signal: sit still
    assert _scaler(None, plans).step(now=0.0) is None


def test_autoscaler_env_knobs_and_validation(monkeypatch):
    monkeypatch.setenv(FS.MIN_ENV, "2")
    monkeypatch.setenv(FS.MAX_ENV, "6")
    monkeypatch.setenv(FS.HIGH_ENV, "3.5")
    monkeypatch.setenv(FS.LOW_ENV, "0.1")
    monkeypatch.setenv(FS.COOLDOWN_ENV, "1.5")
    sc = FS.ServeAutoscaler()
    assert (sc.min_replicas, sc.max_replicas) == (2, 6)
    assert (sc.high, sc.low, sc.cooldown) == (3.5, 0.1, 1.5)
    with pytest.raises(ValueError):
        FS.ServeAutoscaler(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        FS.ServeAutoscaler(high=1.0, low=1.0)


# --- loadgen route ids (satellite: loadgen) ---------------------------------

def test_session_route_ids_and_affinity_aggregation():
    from tensorflowonspark_tpu.serving import decode as D
    ids = D.session_route_ids(32, sessions=4, seed=7)
    assert len(ids) == 32 and set(ids) <= {f"s{k}" for k in range(4)}
    assert ids == D.session_route_ids(32, sessions=4, seed=7)
    seen = []

    def request_fn(i, route_id):
        seen.append((i, route_id))
        return {"tokens": 1,
                "affinity": "hit" if i % 4 else "miss"}

    stats = D.run_open_loop(request_fn, rate_rps=2000, n_requests=8,
                            route_fn=ids.__getitem__)
    assert sorted(i for i, _ in seen) == list(range(8))
    assert all(rid == ids[i] for i, rid in seen)
    assert stats["affinity_hits"] == 6
    assert stats["affinity_misses"] == 2
    assert stats["affinity_fallbacks"] == 0
    assert stats["affinity_hit_rate"] == pytest.approx(6 / 8)
    # without routed results the affinity keys stay absent
    plain = D.run_open_loop(lambda i: None, rate_rps=2000, n_requests=3)
    assert "affinity_hit_rate" not in plain


# --- fabric predict roundtrip (tentpole: cross-host addressing) -------------

def test_fabric_predict_roundtrip_and_describe():
    spec = R.ModelSpec(predict=_double_predict, params={"scale": 3.0},
                       jit=False)
    with S.Server(spec, fabric=True, fabric_hosts=2, replicas_per_host=2,
                  max_batch=8, max_delay_ms=5) as srv:
        assert isinstance(srv.pool, FR.FabricRouter)
        c = srv.client()
        outs = [c.predict({"x": np.array([float(i)], np.float32)},
                          timeout=60) for i in range(6)]
        for i, out in enumerate(outs):
            assert out["y"] == pytest.approx([3.0 * i])
        assert sorted(srv.pool.live_replicas()) == [0, 1]
        desc = srv.pool.describe()
        assert desc["fabric"] and desc["live_hosts"] == 2
        assert desc["replicas"] == 4  # 2 hosts x 2 workers
        rows = FR.fabric_table()
        assert {r["host"] for r in rows} == {0, 1}
        assert all(r["alive"] and r["replicas"] == 2 for r in rows)
        # the rollup rides /statusz as the "pods" section (obs/http.py)
        # and renders as the tfos-top --pods pane (obs/top.py)
        from tensorflowonspark_tpu.obs import http as obs_http
        from tensorflowonspark_tpu.obs import top as obs_top
        obs = obs_http.ObsServer(cluster=None, port=0, interval=999)
        statusz = obs.render_statusz()
        assert {r["host"] for r in statusz["pods"]} == {0, 1}
        pane = obs_top.render_pods(statusz)
        assert "pods (serving/fabric/):" in pane
        assert pane.count("yes") == 2
        st = srv.pool.stats(timeout=30)
        assert set(st) == {0, 1}
        assert all(len(v["workers"]) == 2 for v in st.values())
    assert FR.fabric_table() == []  # stop() deregisters the router


def test_fabric_dispatch_and_route_fault_sites(monkeypatch):
    spec = R.ModelSpec(predict=_double_predict, params={"scale": 2.0},
                       jit=False)
    with S.Server(spec, fabric=True, fabric_hosts=1, max_batch=4,
                  max_delay_ms=5) as srv:
        c = srv.client()
        monkeypatch.setenv("TFOS_FAULT_PLAN", "serve.fabric_dispatch:exc@1")
        faults._reset_for_tests()
        try:
            with pytest.raises(Exception):
                c.predict({"x": np.ones(1, np.float32)}, timeout=60)
            out = c.predict({"x": np.ones(1, np.float32)}, timeout=60)
            assert out["y"] == pytest.approx([2.0])
        finally:
            monkeypatch.delenv("TFOS_FAULT_PLAN")
            faults._reset_for_tests()
    # the route site is armed the same way (it fires inside
    # dispatch_session; exercised without processes here)
    router = FR.FabricRouter(spec, num_hosts=1)
    monkeypatch.setenv("TFOS_FAULT_PLAN", "serve.fabric_route:exc@1")
    faults._reset_for_tests()
    try:
        with pytest.raises(RuntimeError):
            router._route_session("s1")
        assert router._route_session(None) == (None, None, None)
    finally:
        monkeypatch.delenv("TFOS_FAULT_PLAN")
        faults._reset_for_tests()


# --- decode parity gate + session affinity ----------------------------------

def test_fabric_decode_parity_and_affinity(tmp_path):
    """Acceptance (CPU parity gate): a decode session routed through
    the fabric is token-identical to the single-replica local pool at
    the same seed, and route-id affinity goes miss -> hit."""
    cfg, params, spec = _export_decode_spec(tmp_path)
    prompt = [2, 3, 5, 7]
    ref = _oracle(params, prompt, cfg, max_tokens=6)
    with S.Server(spec, num_replicas=1, request_timeout=300) as srv:
        local = srv.generate(prompt, max_tokens=6, timeout=300)
        local_seeded = srv.generate(prompt, max_tokens=6, timeout=300,
                                    temperature=0.9, top_k=8, seed=5)
    assert local["tokens"] == ref
    with S.Server(spec, fabric=True, fabric_hosts=2, replicas_per_host=2,
                  request_timeout=300) as srv:
        out1 = srv.generate(prompt, max_tokens=6, timeout=300,
                            route_id="alice")
        assert out1["tokens"] == ref == local["tokens"]
        assert out1["affinity"] == "miss"   # first sighting: ring place
        bound = srv.pool.affinity_binding("alice")
        assert bound is not None
        out2 = srv.generate(prompt, max_tokens=6, timeout=300,
                            route_id="alice")
        assert out2["tokens"] == ref
        assert out2["affinity"] == "hit"    # returning session: binding
        assert srv.pool.affinity_binding("alice") == bound
        # seeded sampling crosses the fabric wire token-identically too
        fs = srv.generate(prompt, max_tokens=6, timeout=300,
                          temperature=0.9, top_k=8, seed=5)
        assert fs["tokens"] == local_seeded["tokens"]
        # no route id -> least-loaded dispatch, no affinity outcome
        assert "affinity" not in srv.generate(prompt, max_tokens=4,
                                              timeout=300)
        counts = srv.pool.affinity_counts()
        assert counts["miss"] == 1 and counts["hit"] == 1


# --- elastic mirror watermark (satellite: ElasticReplicaPool) ---------------

def test_elastic_mirror_acceptance_uses_reload_watermark():
    from tensorflowonspark_tpu.serving import elastic as E
    spec = R.ModelSpec(predict=_double_predict, params={"scale": 1.0},
                       jit=False)
    pool = E.ElasticReplicaPool(spec, num_replicas=1)
    # no watermark of any kind: plain newest-wins
    assert pool._accept_mirror(5)
    pool._mirror_version = 5
    assert not pool._accept_mirror(4)
    # the hot-reload watermark now pins acceptance: a respawn that
    # cold-booted at a NEWER, never-broadcast checkpoint (7) must not
    # smuggle it into the mirror past the broadcast step (5)
    pool._reload_watermark = 5
    assert not pool._accept_mirror(7)
    assert pool._accept_mirror(5)
    # an explicit promotion watermark still takes precedence
    pool.set_watermark(9)
    assert pool._accept_mirror(7)
    pool._mirror_version = 7
    assert not pool._accept_mirror(6)


# --- slow lane: affinity-target SIGKILL + autoscale e2e ---------------------

@pytest.mark.slow
def test_fabric_host_sigkill_zero_drop_zero_dup(tmp_path):
    """Acceptance: SIGKILL the host an affinity-bound session targets
    while sessions are in flight — every session still returns the
    exact oracle tokens (zero drop, zero dup), the route rebinds to a
    survivor, and the host respawns."""
    cfg, params, spec = _export_decode_spec(tmp_path)
    rng = np.random.default_rng(11)
    with S.Server(spec, fabric=True, fabric_hosts=2, request_timeout=300,
                  decode_queue_max=64) as srv:
        srv.generate([1, 2, 3], max_tokens=2, timeout=300)  # warm compiles
        out = srv.generate([1, 2, 3], max_tokens=2, timeout=300,
                           route_id="victim")
        assert out["affinity"] == "miss"
        target = srv.pool.affinity_binding("victim")[0]
        results, errors = {}, {}

        def one(i, route_id=None):
            p = rng.integers(0, cfg.vocab_size, size=3 + i % 5).tolist()
            try:
                results[i] = (p, srv.generate(p, max_tokens=20,
                                              timeout=300,
                                              route_id=route_id))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors[i] = e

        ts = [threading.Thread(target=one, args=(i,),
                               kwargs={"route_id": "victim" if i == 0
                                       else None})
              for i in range(6)]
        for t in ts:
            t.start()
        deadline = time.time() + 120
        while srv.pool.outstanding_sessions() < 3 and \
                time.time() < deadline:
            time.sleep(0.01)
        os.kill(srv.pool.host_pids()[target], 9)
        for t in ts:
            t.join()
        assert not errors, errors
        assert len(results) == 6
        for i, (p, o) in results.items():
            assert o["tokens"] == _oracle(params, p, cfg, max_tokens=20), i
        # the bound session either rode out the kill on the other host
        # or was re-dispatched and rebound to the survivor
        bound = srv.pool.affinity_binding("victim")
        assert bound is not None
        # the killed host comes back (engine respawn) and serves again
        deadline = time.time() + 120
        while len(srv.pool.live_replicas()) < 2 and \
                time.time() < deadline:
            time.sleep(0.1)
        assert len(srv.pool.live_replicas()) == 2
        assert srv.pool.describe()["respawns"] >= 1
        after = srv.generate([3, 5, 7], max_tokens=6, timeout=300,
                             route_id="victim")
        assert after["tokens"] == _oracle(params, [3, 5, 7], cfg,
                                          max_tokens=6)


@pytest.mark.slow
def test_fabric_autoscaler_scales_up_under_load():
    """Acceptance: under sustained queueing collapse the supervised
    autoscaler publishes an up-plan and the router actuates it —
    replicas provably grow 1 -> N (telemetry-asserted via describe)."""
    spec = R.ModelSpec(predict=_slow_predict, params={}, jit=False)
    router = FR.FabricRouter(
        spec, num_hosts=2, replicas_per_host=1,
        autoscale={"min_replicas": 1, "max_replicas": 3, "high": 1.5,
                   "low": 0.0, "cooldown": 1.0, "tick_secs": 0.2})
    router.start()
    try:
        import itertools

        from tensorflowonspark_tpu.serving import batcher as B
        bid = itertools.count()

        def fire():
            router.dispatch(B.Batch(
                f"as-{next(bid)}", [],
                {"x": np.ones((2, 1), np.float32)}, 2, 0.0))

        # keep ~8 envelopes in flight against 2 single-worker hosts:
        # depth/worker >> high, so the kernel must publish an up-plan
        deadline = time.time() + 60
        while router.scale_ups < 1 and time.time() < deadline:
            while len(router._table) < 8:
                fire()
            time.sleep(0.05)
        assert router.scale_ups >= 1
        desc = router.describe()
        assert desc["scale_ups"] >= 1
        # the ack lands: some host reports >1 workers
        deadline = time.time() + 60
        while time.time() < deadline:
            if sum(router._live_workers().values()) > 2:
                break
            time.sleep(0.1)
        assert sum(router._live_workers().values()) > 2
    finally:
        router.stop()
