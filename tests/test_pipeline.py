"""Pipeline-layer tests (parity: reference test/test_pipeline.py).

- Namespace / Params / merge_args_params semantics (:48-87)
- full TFEstimator.fit (linear regression over 2 executor processes,
  DataFeed, chief-only export) -> TFModel.transform, prediction ==
  w1 + w2 to 2 decimals (:89-172)
"""

import argparse

import numpy as np
import pytest

from tensorflowonspark_tpu.pipeline import (
    Namespace,
    TFEstimator,
    TFModel,
    yield_batch,
)

W1, W2 = 3.14, 1.618


# -- Namespace / params unit tests ------------------------------------------

def test_namespace_from_dict():
    ns = Namespace({"a": 1, "b": "two"})
    assert ns.a == 1 and ns.b == "two"
    assert "a" in ns and "missing" not in ns
    assert dict(ns.items()) == {"a": 1, "b": "two"}


def test_namespace_from_argv_and_namespace():
    ns = Namespace(["--epochs", "3"])
    assert ns.argv == ["--epochs", "3"]
    ns2 = Namespace(Namespace({"x": 9}))
    assert ns2.x == 9
    ns3 = Namespace(argparse.Namespace(y=7))
    assert ns3.y == 7
    with pytest.raises(TypeError):
        Namespace(42)


def test_params_merge_args_params():
    est = TFEstimator(lambda a, c: None, {"batch_size": 17, "custom": "keep"})
    est.setBatchSize(64).setEpochs(5)
    args = est.merge_args_params()
    assert args.batch_size == 64      # param wins over arg
    assert args.epochs == 5
    assert args.custom == "keep"      # untouched user arg survives
    assert args.cluster_size == 1     # defaults fill in


def test_param_setters_getters_and_copy():
    m = TFModel({})
    m.setBatchSize("32")              # converter coerces strings
    assert m.getBatchSize() == 32
    m.setInputMapping({"x": "features"})
    assert m.getInputMapping() == {"x": "features"}
    with pytest.raises(TypeError):
        m.setInputMapping("not-a-dict")
    dup = m.copy()
    dup.setBatchSize(8)
    assert m.getBatchSize() == 32 and dup.getBatchSize() == 8


def test_copy_accepts_string_keys():
    est = TFEstimator(lambda a, c: None, {})
    dup = est.copy({"epochs": 7, "batch_size": "16"})
    assert dup.getEpochs() == 7
    assert dup.getBatchSize() == 16  # converter still applies
    assert est.getEpochs() == 1      # original untouched


def test_select_columns_rejects_unprojectable_rows():
    from tensorflowonspark_tpu.engine import LocalEngine
    from tensorflowonspark_tpu.pipeline import _select_columns

    engine = LocalEngine(1)
    try:
        ds = engine.parallelize([("only", "two")], 1)
        with pytest.raises(Exception) as e:
            _select_columns(ds, ["a", "b", "c"]).collect()
        assert "cannot project" in str(e.value)
        # matching arity passes through
        ok = _select_columns(engine.parallelize([(1, 2)], 1), ["a", "b"]).collect()
        assert ok == [(1, 2)]
    finally:
        engine.stop()


def test_yield_batch():
    batches = list(yield_batch(iter(range(10)), 4))
    assert [len(b) for b in batches] == [4, 4, 2]


def test_estimator_and_model_are_subclassable():
    """Subclasses with custom __init__ signatures must work; the base
    __init__ installs mixin defaults without reflectively re-invoking
    every MRO __init__ (regression: MRO loop crashed subclasses)."""

    class MyEstimator(TFEstimator):
        def __init__(self, fn):
            super().__init__(fn)
            self.extra = "yes"

    class MyModel(TFModel):
        def __init__(self):
            super().__init__({})

    est = MyEstimator(lambda a, c: None)
    assert est.extra == "yes"
    assert est.getBatchSize() == 128
    model = MyModel()
    assert model.getBatchSize() == 128


def test_model_cache_shared_across_pickled_closures(tmp_path, monkeypatch):
    """The partition closure must hit the module-level _model_cache, not a
    cloudpickle-copied closure global (regression: cache never shared)."""
    import cloudpickle

    from tensorflowonspark_tpu import pipeline as pl
    from tensorflowonspark_tpu.models import linear
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    export_dir = str(tmp_path / "export")
    ckpt.export_model(
        export_dir,
        linear.init_params(),
        None,
        metadata={"predict": "tensorflowonspark_tpu.models.linear:predict"},
    )

    args = Namespace({
        "export_dir": export_dir,
        "model_dir": None,
        "batch_size": 4,
        "input_mapping": {"x": "features"},
        "output_mapping": {"prediction": "preds"},
        "signature_def_key": None,
    })
    pl._model_cache.clear()
    loads = []
    real_load = pl._load_predictor
    monkeypatch.setattr(
        pl, "_load_predictor",
        lambda d, a: loads.append(d) or real_load(d, a),
    )

    rows = [([1.0, 1.0],)] * 4
    # two independently deserialized tasks, as the engine would produce
    for _ in range(2):
        closure = cloudpickle.loads(cloudpickle.dumps(pl._run_model(args)))
        out = closure(iter(rows))
        assert len(out) == 4
    assert len(loads) == 1, "model must load once per worker, not per task"
    assert len(pl._model_cache) == 1


# -- end-to-end fit -> transform --------------------------------------------

def linreg_main(args, ctx):
    """User main: trains y = w.x + b from the DataFeed, chief exports."""
    import jax
    import optax

    from tensorflowonspark_tpu.models import linear
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    feed = ctx.get_data_feed(train_mode=True, input_mapping=args.input_mapping)
    params = linear.init_params()
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)
    step = jax.jit(linear.make_train_step(opt))

    while not feed.should_stop():
        batch = feed.next_batch(args.batch_size)
        if not batch["features"]:
            continue
        x = np.asarray(batch["features"], dtype=np.float32)
        y = np.asarray(batch["label"], dtype=np.float32)
        params, opt_state, loss = step(params, opt_state, x, y)

    ckpt.export_model(
        args.export_dir,
        params,
        ctx,
        metadata={"predict": "tensorflowonspark_tpu.models.linear:predict"},
    )


@pytest.mark.slow
def test_estimator_fit_model_transform(tmp_path):
    from tensorflowonspark_tpu.engine import LocalEngine

    engine = LocalEngine(
        2,
        env={
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        },
    )
    try:
        rng = np.random.default_rng(42)
        x = rng.random((1024, 2)).astype(np.float32)
        y = x @ np.array([W1, W2], dtype=np.float32)
        rows = [{"x": list(map(float, xi)), "y": float(yi)} for xi, yi in zip(x, y)]
        ds = engine.parallelize(rows, 4)

        export_dir = str(tmp_path / "export")
        est = (
            TFEstimator(linreg_main, {})
            .setInputMapping({"x": "features", "y": "label"})
            .setClusterSize(2)
            .setMasterNode("chief")
            .setEpochs(12)
            .setBatchSize(32)
            .setExportDir(export_dir)
            .setGraceSecs(5)
        )
        model = est.fit(ds)
        assert isinstance(model, TFModel)

        preds_ds = (
            model.copy()
            .setInputMapping({"x": "features"})
            .setOutputMapping({"prediction": "preds"})
            .setBatchSize(16)
            .transform(engine.parallelize([{"x": [1.0, 1.0]}] * 8, 2))
        )
        preds = preds_ds.collect()
        assert len(preds) == 8
        expected = W1 + W2
        for row in preds:
            assert round(float(row["preds"]), 2) == round(expected, 2), preds
    finally:
        engine.stop()
