"""Dry-run the TPU perf-session scripts off-chip (VERDICT r3 next #6a).

The round-3 postmortem: every perf script was written while the tunnel
was dead, so its TPU-only branches (promote, config merge, refusal,
markdown writing) had never executed anywhere.  These tests run the REAL
scripts as subprocesses — tiny shapes via TFOS_SWEEP_TINY, a faked TPU
device identity via tests/fake_tpu_driver.py where the branch under test
demands one — so the first live chip claim is spent measuring, not
debugging.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "fake_tpu_driver.py")


def _env(cfg_path, **extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TFOS_")}
    env.update(
        PYTHONPATH="",  # drop any TPU-tunnel site hook
        JAX_PLATFORMS="cpu",
        TFOS_BENCH_CONFIG=str(cfg_path),
        TFOS_SWEEP_TINY="1",
        # explicit acknowledgement that promoting tiny results is the
        # POINT of these dry runs; without it the sweeps refuse (the
        # guard a leftover TFOS_SWEEP_TINY on a live claim relies on)
        TFOS_SWEEP_TINY_PROMOTE_OK="1",
    )
    env.update(extra)
    return env


def _run(args, env, timeout=600):
    proc = subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


def test_resnet_promote_writes_config_on_faked_tpu(tmp_path):
    cfg = tmp_path / "bench_config.json"
    out = _run(
        [DRIVER, "sweep_resnet", "faketpu",
         "--steps", "2", "--image", "32", "--promote"],
        _env(cfg, TFOS_SWEEP="b512_s2d_bnf"))
    assert "promoted" in out, out
    written = json.loads(cfg.read_text())
    assert written["winner"] == "b512_s2d_bnf"
    assert written["batch"] == 4 and written["image"] == 32
    assert written["stem_s2d"] is True
    assert "FakeTpuDevice" in written["device"]


def test_transformer_promote_merges_resnet_section(tmp_path):
    cfg = tmp_path / "bench_config.json"
    # pre-existing resnet winner must survive the transformer promote
    cfg.write_text(json.dumps(
        {"batch": 512, "stem_s2d": True, "remat": False,
         "winner": "b512_s2d_bnf", "image": 224}))
    out = _run(
        [DRIVER, "sweep_transformer", "faketpu",
         "--steps", "2", "--promote"],
        _env(cfg, TFOS_SWEEP="b16_q512_kv512"))
    assert "promoted" in out, out
    written = json.loads(cfg.read_text())
    assert written["winner"] == "b512_s2d_bnf"  # resnet section kept
    assert written["transformer"]["winner"] == "b16_q512_kv512"
    assert written["transformer"]["bwd"] == "xla"


def test_transformer_promote_records_seq(tmp_path):
    """The r5 long-seq configs carry a per-config seq; the promote path
    must record it so bench._transformer_bench sizes cfg.max_seq from
    the promoted winner (tiny mode rewrites long-seq to 2x the tiny
    base seq — the key's presence and round-trip is what's under test)."""
    cfg = tmp_path / "bench_config.json"
    out = _run(
        [DRIVER, "sweep_transformer", "faketpu",
         "--steps", "2", "--promote"],
        _env(cfg, TFOS_SWEEP="b16_s4096_remat_pbwd_bce"))
    assert "promoted" in out, out
    written = json.loads(cfg.read_text())["transformer"]
    assert written["winner"] == "b16_s4096_remat_pbwd_bce"
    assert written["seq"] == 512  # tiny base 256 x 2 for long-seq picks
    assert written["ce"] == "block"


def test_promote_refused_on_real_cpu(tmp_path):
    """Without the faked device the promote guard must refuse: a CPU run
    may never pin the TPU bench to toy shapes."""
    cfg = tmp_path / "bench_config.json"
    out = _run(
        [DRIVER, "sweep_resnet", "cpu",
         "--steps", "2", "--image", "32", "--promote"],
        _env(cfg, TFOS_SWEEP="b512_s2d_bnf"))
    assert "promote skipped" in out, out
    assert not cfg.exists()


def test_tiny_promote_refused_without_acknowledgement(tmp_path):
    """A leftover TFOS_SWEEP_TINY=1 during a live chip claim must not
    pin bench_config.json to batch-4 toy shapes: promote requires the
    explicit TFOS_SWEEP_TINY_PROMOTE_OK acknowledgement."""
    cfg = tmp_path / "bench_config.json"
    env = _env(cfg, TFOS_SWEEP="b512_s2d_bnf")
    env.pop("TFOS_SWEEP_TINY_PROMOTE_OK")
    out = _run(
        [DRIVER, "sweep_resnet", "faketpu",
         "--steps", "2", "--image", "32", "--promote"], env)
    assert "promote skipped" in out, out
    assert not cfg.exists()


def test_bench_reads_env_config_path(tmp_path, monkeypatch):
    """bench.py must pick up TFOS_BENCH_CONFIG so dry runs and tests
    never collide with the repo-root promoted config."""
    cfg = tmp_path / "bench_config.json"
    cfg.write_text(json.dumps({"batch": 123, "transformer": {"batch": 7}}))
    monkeypatch.setenv("TFOS_BENCH_CONFIG", str(cfg))
    sys.path.insert(0, REPO)
    try:
        import bench

        got = bench._promoted_config()
    finally:
        sys.path.remove(REPO)
    assert got["batch"] == 123 and got["transformer"]["batch"] == 7


def test_stress_fed_both_modes(tmp_path):
    """The fed consumer stress bench (scripts/stress_fed.py) must run
    both wire modes end-to-end: real feeder process -> shm ring ->
    DataFeed, correct shapes, non-zero throughput."""
    env = _env(tmp_path / "unused.json")
    out = _run([os.path.join(REPO, "scripts", "stress_fed.py"),
                "--batch", "32", "--image", "32", "--steps", "6"],
               env, timeout=300)
    lines = [json.loads(x) for x in out.strip().splitlines()
             if x.startswith("{")]
    by_mode = {r["mode"]: r for r in lines if "mode" in r}
    assert set(by_mode) == {"rows", "columnar"}, out
    for r in by_mode.values():
        assert r["records_per_sec"] > 0 and r["batches"] > 0, out


def test_round5_session_smoke(tmp_path):
    """The round-5 session entrypoint end-to-end on CPU: roofline,
    fwd/grad decomposition, resnet sweep, traffic, profile, transformer
    sweep — every step rc=0, benches skipped, and the smoke run must NOT
    write ROOFLINE.json/TRAFFIC.json at the repo root (CPU numbers must
    never pose as chip evidence)."""
    log = tmp_path / "session.log"
    breakdown = tmp_path / "breakdown.md"
    root_roof = os.path.join(REPO, "ROOFLINE.json")
    root_traffic = os.path.join(REPO, "TRAFFIC.json")
    had = {p: os.path.exists(p) for p in (root_roof, root_traffic)}
    env = _env(tmp_path / "bench_config.json",
               TFOS_SESSION_SMOKE="1",
               TFOS_SESSION_IMAGE="64",
               TFOS_SESSION_RESNET_STEPS="2",
               TFOS_SESSION_TRANSFORMER_STEPS="2",
               TFOS_SESSION_BREAKDOWN=str(breakdown),
               TFOS_PERF_LOG=str(log),
               # the r5 script sets TFOS_SWEEP per step itself — subset
               # via the session-level vars it actually honors
               TFOS_SESSION_RESNET_SWEEP="b512_s2d_bnf",
               TFOS_SESSION_TRANSFORMER_SWEEP="b16_q512_kv512")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "tpu_round5_session.sh")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    text = log.read_text()
    # roofline, fwd, grad, sweep, traffic(host), profile, transformer
    assert text.count("-- rc=0 --") >= 7, text[-3000:]
    assert "bench.py skipped (smoke mode)" in text
    assert "promote skipped" in text
    assert breakdown.exists()
    for p, existed in had.items():
        assert os.path.exists(p) == existed, f"smoke run touched {p}"


def test_full_session_smoke(tmp_path):
    """The exact entrypoint a live chip claim uses, end-to-end on CPU:
    sweep -> profile -> sweep -> (bench skipped), every step rc=0."""
    log = tmp_path / "session.log"
    breakdown = tmp_path / "breakdown.md"
    env = _env(tmp_path / "bench_config.json",
               TFOS_SESSION_SMOKE="1",
               TFOS_SESSION_IMAGE="64",
               TFOS_SESSION_RESNET_STEPS="2",
               TFOS_SESSION_TRANSFORMER_STEPS="2",
               TFOS_SESSION_BREAKDOWN=str(breakdown),
               TFOS_PERF_LOG=str(log),
               TFOS_SWEEP="b512_s2d_bnf,b16_q512_kv512")
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "tpu_perf_session.sh")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    text = log.read_text()
    # one rc=0 per step: resnet sweep, profile, transformer sweep
    assert text.count("-- rc=0 --") >= 3, text[-3000:]
    assert "bench.py skipped (smoke mode)" in text
    assert breakdown.exists() and "step-time breakdown" in breakdown.read_text()
    # smoke sweeps must not promote
    assert "promote skipped" in text