"""Inference CLI + schema parser (parity: Inference.scala +
SimpleTypeParserTest.scala)."""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu.utils import schema as schema_util


# -- SimpleTypeParser parity --------------------------------------------------

def test_parse_roundtrip_all_types():
    text = ("struct<a:bigint,b:float,c:string,d:binary,"
            "e:array<float>,f:array<bigint>>")
    parsed = schema_util.parse_schema(text)
    assert parsed == {
        "a": ("int64", False),
        "b": ("float", False),
        "c": ("string", False),
        "d": ("bytes", False),
        "e": ("float", True),
        "f": ("int64", True),
    }
    assert schema_util.parse_schema(schema_util.format_schema(parsed)) == parsed


def test_parse_widening_and_bare_list():
    assert schema_util.parse_schema("x:boolean,y:int,z:double") == {
        "x": ("int64", False), "y": ("int64", False), "z": ("float", False),
    }


@pytest.mark.parametrize("bad", ["struct<a:", "a:unknown", "a;int", "x:array<>"])
def test_parse_errors(bad):
    with pytest.raises(schema_util.SchemaParseError):
        schema_util.parse_schema(bad)


def test_merge_partial_hint():
    inferred = {"img": ("string", True), "label": ("int64", False)}
    hint = schema_util.parse_schema("img:array<binary>")
    assert schema_util.merge_schemas(inferred, hint) == {
        "img": ("bytes", True), "label": ("int64", False),
    }


# -- CLI end-to-end -----------------------------------------------------------

def test_inference_cli_end_to_end(tmp_path):
    """TFRecords -> CLI -> JSON predictions with a linear-model export."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu import dfutil, inference
    from tensorflowonspark_tpu.engine import LocalEngine
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    # rows: y = 2*x0 + 3*x1
    rows = [
        {"features": [float(i), float(2 * i)], "label": float(2 * i + 6 * i)}
        for i in range(20)
    ]
    data_dir = str(tmp_path / "data")
    dfutil.save_as_tfrecords(rows, data_dir)

    export_dir = str(tmp_path / "export")
    ckpt.export_model(
        export_dir,
        {"w": jnp.asarray([2.0, 3.0]), "b": jnp.asarray(0.0)},
        metadata={"predict": "tensorflowonspark_tpu.models.linear:predict"},
    )

    out_dir = str(tmp_path / "preds")
    args = inference.build_parser().parse_args([
        "--export_dir", export_dir,
        "--input", data_dir,
        "--output", out_dir,
        "--schema_hint", "struct<features:array<float>,label:float>",
        "--input_mapping", json.dumps({"features": "x"}),
        "--output_mapping", json.dumps({"prediction": "preds"}),
        "--batch_size", "4",
    ])

    engine = LocalEngine(num_executors=2)
    try:
        shards = inference.run(args, source=engine)
    finally:
        engine.stop()

    assert shards
    preds = []
    for path in sorted(os.listdir(out_dir)):
        with open(os.path.join(out_dir, path)) as f:
            preds.extend(json.loads(line) for line in f)
    assert len(preds) == 20
    got = sorted(p["preds"] for p in preds)
    want = sorted(2.0 * i + 3.0 * 2 * i for i in range(20))
    np.testing.assert_allclose(got, want, atol=1e-5)
