"""Pipeline parallelism + MoE expert parallelism on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu.models import layers as L
from tensorflowonspark_tpu.models import moe
from tensorflowonspark_tpu.parallel import (
    pipeline_apply,
    stack_stage_params,
    stage_sharding,
)


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stages(key, n, dim):
    ks = jax.random.split(key, n)
    return [
        {"w": jax.random.normal(k, (dim, dim)) / np.sqrt(dim),
         "b": jnp.zeros((dim,))}
        for k in ks
    ]


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(eight_devices, n_stages, n_micro):
    mesh = Mesh(np.array(eight_devices[:n_stages]), ("pp",))
    dim, batch = 16, 16
    stages = _stages(jax.random.PRNGKey(0), n_stages, dim)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))

    # sequential reference
    ref = x
    for p in stages:
        ref = _stage_fn(p, ref)

    stacked = stack_stage_params(stages)
    stacked = jax.device_put(stacked, stage_sharding(mesh, stacked))
    out = jax.jit(
        lambda sp, x: pipeline_apply(
            _stage_fn, sp, x, mesh=mesh, n_microbatches=n_micro
        )
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_is_differentiable(eight_devices):
    mesh = Mesh(np.array(eight_devices[:2]), ("pp",))
    dim = 8
    stages = _stages(jax.random.PRNGKey(0), 2, dim)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, dim))

    def loss(sp, x):
        y = pipeline_apply(_stage_fn, sp, x, mesh=mesh, n_microbatches=4)
        return jnp.sum(y ** 2)

    g = jax.jit(jax.grad(loss))(stacked, x)

    def loss_seq(sp, x):
        y = x
        for i in range(2):
            y = _stage_fn(jax.tree.map(lambda p: p[i], sp), y)
        return jnp.sum(y ** 2)

    g_ref = jax.grad(loss_seq)(stacked, x)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_moe_forward_and_balance_loss():
    params = moe.init(jax.random.PRNGKey(0), dim=16, hidden=32, num_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux = moe.apply(params, x)
    assert y.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0

    # gradients flow to router and experts
    def loss(p):
        y, aux = moe.apply(p, x)
        return jnp.sum(y ** 2) + aux

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["router"]).sum()) > 0
    assert float(jnp.abs(g["w1"]).sum()) > 0


def test_moe_expert_sharded_on_mesh(eight_devices):
    mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("data", "model"))
    params = moe.init(jax.random.PRNGKey(0), dim=16, hidden=64, num_experts=8)
    specs = jax.tree.map(
        lambda s: NamedSharding(mesh, s), moe.param_specs(ep_axis="model"),
        is_leaf=lambda s: isinstance(s, P),
    )
    sharded = jax.device_put(params, specs)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16)),
        NamedSharding(mesh, P("data")),
    )
    y, aux = jax.jit(moe.apply)(sharded, x)
    ref, _ = moe.apply(params, jax.device_get(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
