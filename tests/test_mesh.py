"""Mesh construction: axis canonicalization, ordering, -1 inference."""

import pytest

from tensorflowonspark_tpu.parallel import make_mesh
from tensorflowonspark_tpu.parallel.mesh import AXIS_ORDER, MeshSpec


def test_axis_order_uses_short_names():
    assert "pp" in AXIS_ORDER and "ep" in AXIS_ORDER
    assert "pipe" not in AXIS_ORDER and "expert" not in AXIS_ORDER


def test_aliases_canonicalize():
    sizes = MeshSpec({"pipe": 2, "expert": 4}).resolve(8)
    assert sizes == {"pp": 2, "ep": 4}


def test_alias_collision_rejected():
    with pytest.raises(ValueError, match="collide"):
        MeshSpec({"pipe": 2, "pp": 2}).resolve(4)


def test_make_mesh_orders_axes(eight_devices):
    mesh = make_mesh({"model": 2, "pp": 2, "data": 2}, devices=eight_devices)
    assert mesh.axis_names == ("pp", "data", "model")
    assert dict(mesh.shape) == {"pp": 2, "data": 2, "model": 2}


def test_make_mesh_accepts_aliases(eight_devices):
    mesh = make_mesh({"expert": 4, "pipe": 2}, devices=eight_devices)
    assert mesh.axis_names == ("pp", "ep")
    assert dict(mesh.shape) == {"pp": 2, "ep": 4}


def test_minus_one_absorbs_remainder(eight_devices):
    mesh = make_mesh({"model": 2, "data": -1}, devices=eight_devices)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
