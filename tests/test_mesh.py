"""Mesh construction: axis canonicalization, ordering, -1 inference."""

import pytest

from tensorflowonspark_tpu.parallel import make_mesh
from tensorflowonspark_tpu.parallel.mesh import AXIS_ORDER, MeshSpec


def test_axis_order_uses_short_names():
    assert "pp" in AXIS_ORDER and "ep" in AXIS_ORDER
    assert "pipe" not in AXIS_ORDER and "expert" not in AXIS_ORDER


def test_aliases_canonicalize():
    sizes = MeshSpec({"pipe": 2, "expert": 4}).resolve(8)
    assert sizes == {"pp": 2, "ep": 4}


def test_alias_collision_rejected():
    with pytest.raises(ValueError, match="collide"):
        MeshSpec({"pipe": 2, "pp": 2}).resolve(4)


def test_make_mesh_orders_axes(eight_devices):
    mesh = make_mesh({"model": 2, "pp": 2, "data": 2}, devices=eight_devices)
    assert mesh.axis_names == ("pp", "data", "model")
    assert dict(mesh.shape) == {"pp": 2, "data": 2, "model": 2}


def test_make_mesh_accepts_aliases(eight_devices):
    mesh = make_mesh({"expert": 4, "pipe": 2}, devices=eight_devices)
    assert mesh.axis_names == ("pp", "ep")
    assert dict(mesh.shape) == {"pp": 2, "ep": 4}


def test_minus_one_absorbs_remainder(eight_devices):
    mesh = make_mesh({"model": 2, "data": -1}, devices=eight_devices)
    assert dict(mesh.shape) == {"data": 4, "model": 2}


def test_resolve_rejects_multiple_minus_ones():
    with pytest.raises(ValueError, match="at most one"):
        MeshSpec({"data": -1, "model": -1}).resolve(8)


def test_resolve_rejects_non_divisible_remainder():
    # fixed axes product (3) does not divide the device count (8)
    with pytest.raises(ValueError, match="not divisible"):
        MeshSpec({"model": 3, "data": -1}).resolve(8)


def test_resolve_rejects_fixed_product_mismatch():
    with pytest.raises(ValueError, match="devices"):
        MeshSpec({"data": 4, "model": 4}).resolve(8)


def test_resolve_single_axis_degenerate():
    assert MeshSpec({"data": 1}).resolve(1) == {"data": 1}
    assert MeshSpec({"data": -1}).resolve(1) == {"data": 1}


def test_fsdp_sharding_small_leaves_replicate(eight_devices):
    import numpy as np

    from tensorflowonspark_tpu.parallel import fsdp_sharding

    mesh = make_mesh({"data": 4, "fsdp": 2}, devices=eight_devices)
    tree = {
        "tiny": np.zeros((8, 8), np.float32),        # < min_shard_elems
        "big": np.zeros((130, 64), np.float32),      # largest dim % 2 == 0
        "odd": np.zeros((65, 65), np.float32),       # no divisible dim
    }
    sh = fsdp_sharding(mesh, tree)
    assert sh["tiny"].spec == jax_pspec()
    assert sh["big"].spec == jax_pspec("fsdp", None)
    assert sh["odd"].spec == jax_pspec()


def test_fsdp_sharding_prefers_largest_divisible_dim(eight_devices):
    import numpy as np

    from tensorflowonspark_tpu.parallel import fsdp_sharding

    mesh = make_mesh({"fsdp": 8}, devices=eight_devices)
    # largest dim (100) is not divisible by 8; the smaller (64) is
    leaf = np.zeros((100, 64), np.float32)
    sh = fsdp_sharding(mesh, {"w": leaf}, min_shard_elems=1)
    assert sh["w"].spec == jax_pspec(None, "fsdp")


def jax_pspec(*entries):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*entries)
