"""Sphinx configuration (parity: reference docs/source/conf.py)."""

import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "tensorflowonspark_tpu"
author = "tensorflowonspark_tpu authors"
extensions = ["sphinx.ext.autodoc", "sphinx.ext.napoleon", "sphinx.ext.viewcode"]
autodoc_mock_imports = ["jax", "jaxlib", "optax", "numpy", "cloudpickle"]
html_theme = "alabaster"
