"""MNIST online serving: export a model, stand up a 2-replica service,
and hammer it with concurrent clients (no reference counterpart — the
reference delegates online serving to TF Serving; see docs/serving.md).

Self-contained: initializes untrained MNIST params, exports them with a
``serve_predict`` entry, then demonstrates

- dynamic micro-batching (concurrent single-example requests coalesce
  into power-of-two shape buckets, one jit compile per bucket),
- checkpoint hot-reload (a new checkpoint is picked up in-band while
  requests are in flight),
- live SLO stats (p50/p95/p99 latency, mean device batch, shed rate).

    JAX_PLATFORMS=cpu python examples/serving/mnist_serving.py

Add ``--http`` to also expose the stdlib HTTP frontend and poke it
(``tfos-serve`` is the standalone CLI for the same thing).
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num_replicas", type=int, default=2)
    p.add_argument("--clients", type=int, default=32)
    p.add_argument("--requests", type=int, default=4,
                   help="requests per client thread")
    p.add_argument("--max_batch", type=int, default=32)
    p.add_argument("--max_delay_ms", type=float, default=10.0)
    p.add_argument("--http", action="store_true",
                   help="also start the HTTP frontend and issue one POST")
    args = p.parse_args()

    import jax
    import numpy as np

    from tensorflowonspark_tpu import configure_logging, serving
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    configure_logging()
    workdir = tempfile.mkdtemp(prefix="tfos_serving_example_")
    export_dir = os.path.join(workdir, "export")
    ckpt_dir = os.path.join(workdir, "ckpts")

    params = mnist.init_params(jax.random.PRNGKey(0))
    ckpt.save_checkpoint(ckpt_dir, params, step=1)
    ckpt.export_model(export_dir, params, metadata={
        "predict": "tensorflowonspark_tpu.models.mnist:serve_predict",
    })

    spec = serving.ModelSpec(export_dir=export_dir, ckpt_dir=ckpt_dir)
    rng = np.random.default_rng(0)
    images = rng.random((args.clients, 28, 28, 1)).astype(np.float32)

    with serving.Server(spec, num_replicas=args.num_replicas,
                        max_batch=args.max_batch,
                        max_delay_ms=args.max_delay_ms) as srv:
        client = srv.client()
        print("warmup (first compile per shape bucket is the slow part)...")
        client.predict({"image": images[0]}, timeout=300)

        errors = []

        def burst(i):
            for _ in range(args.requests):
                try:
                    out = client.predict({"image": images[i]}, timeout=300)
                    assert out["logits"].shape == (10,)
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

        t0 = time.monotonic()
        threads = [threading.Thread(target=burst, args=(i,))
                   for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        n = args.clients * args.requests
        print(f"{n} requests from {args.clients} concurrent clients "
              f"in {wall:.2f}s ({n / wall:.0f} req/s), "
              f"errors={len(errors)}")

        # hot reload: write a new checkpoint; the pool watcher broadcasts
        # an in-band reload, so no request is dropped while params swap.
        ckpt.save_checkpoint(ckpt_dir, params, step=2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if set(srv.pool.versions().values()) == {2}:
                break
            time.sleep(0.2)
        print("hot-reload:", srv.pool.versions())

        if args.http:
            import urllib.request

            from tensorflowonspark_tpu.serving import server as S

            httpd = S.serve_http(srv, port=0, block=False)
            try:
                host, port = httpd.server_address
                req = urllib.request.Request(
                    f"http://{host}:{port}/v1/predict",
                    data=json.dumps(
                        {"inputs": {"image": images[0].tolist()}}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=300) as r:
                    body = json.loads(r.read())
                print("HTTP prediction:", body["outputs"]["prediction"])
            finally:
                httpd.shutdown()

        print("summary:", json.dumps(srv.summary(), default=str))


if __name__ == "__main__":
    main()
