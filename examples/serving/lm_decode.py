"""Autoregressive LM decode serving: export a decoder-only transformer,
stand up a continuous-batching decode service, and measure its SLOs
under open-loop Poisson load (no reference counterpart — the reference
has no generative path; see docs/serving.md "Autoregressive decode").

Self-contained: initializes untrained transformer params, exports them,
then demonstrates

- ``Server.generate``: one decode session, token-identical to a
  full-recompute greedy decode (the KV cache changes the math zero),
- seeded sampling: ``temperature=/top_k=/top_p=/seed=`` — every token
  is a pure function of (logits, seed, index), so the same seed
  reproduces the same stream (the property failover replay relies on),
- prefix sharing: sessions repeating a system prompt map the already-
  computed KV blocks instead of re-prefilling them (paged cache),
- continuous batching: concurrent mixed-length sessions share the
  replica's KV slots, newcomers admitted between decode steps,
- the open-loop load generator (``serving.run_open_loop``) reporting
  TTFT p50/p99, per-token-gap p50/p99 and tokens/s — the same harness
  the ``TFOS_BENCH_DECODE`` lane runs.

    JAX_PLATFORMS=cpu python examples/serving/lm_decode.py

Add ``--http`` to also expose the HTTP frontend and issue one
``POST /v1/generate``.
"""

import argparse
import functools
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num_replicas", type=int, default=2)
    p.add_argument("--slots", type=int, default=8,
                   help="KV slots (max concurrent sessions) per replica")
    p.add_argument("--sessions", type=int, default=16,
                   help="open-loop session count")
    p.add_argument("--rate", type=float, default=4.0,
                   help="offered session arrivals per second")
    p.add_argument("--max_tokens", type=int, default=16)
    p.add_argument("--http", action="store_true",
                   help="also start the HTTP frontend and issue one POST")
    args = p.parse_args()

    import jax
    import numpy as np

    from tensorflowonspark_tpu import configure_logging, ops, serving
    from tensorflowonspark_tpu.models import transformer as T
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    configure_logging()
    cfg = T.Config(vocab_size=257, dim=64, n_layers=2, n_heads=4,
                   max_seq=64, dtype="float32", attn_impl="reference")
    params = T.init(jax.random.PRNGKey(0), cfg)
    workdir = tempfile.mkdtemp(prefix="tfos_decode_example_")
    export_dir = os.path.join(workdir, "export")
    ckpt.export_model(export_dir, params, metadata={})

    spec = serving.ModelSpec(
        export_dir=export_dir,
        decode=serving.DecodeSpec(cfg, slots=args.slots,
                                  max_tokens=args.max_tokens))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in rng.integers(4, 25, size=args.sessions)]

    with serving.Server(spec, num_replicas=args.num_replicas,
                        request_timeout=300) as srv:
        print("warmup (first prefill/decode_step compiles are the slow "
              "part)...")
        out = srv.generate(prompts[0], max_tokens=args.max_tokens,
                           timeout=300)
        ref = T.greedy_decode_reference(
            params, prompts[0], cfg, max_tokens=args.max_tokens,
            attn_fn=functools.partial(ops.mha_reference, causal=True))
        assert out["tokens"] == ref, "KV-cached decode diverged from oracle"
        print(f"single session: {len(out['tokens'])} tokens, "
              f"ttft {out['ttft_ms']:.1f} ms — token-identical to "
              "full-recompute greedy decode")

        a = srv.generate(prompts[0], max_tokens=args.max_tokens,
                         temperature=0.8, top_k=40, seed=1234,
                         timeout=300)
        b = srv.generate(prompts[0], max_tokens=args.max_tokens,
                         temperature=0.8, top_k=40, seed=1234,
                         timeout=300)
        assert a["tokens"] == b["tokens"], "seeded sampling not reproducible"
        print(f"seeded sampling (T=0.8 top_k=40 seed=1234): "
              f"{a['tokens'][:8]}... — same seed, same stream")

        # same system prompt, different tails: followers map the shared
        # prefix blocks instead of re-prefilling them
        system = prompts[0][:16] if len(prompts[0]) >= 16 else prompts[0]
        for tail in ([7, 3, 9], [11, 2, 5], [4, 8, 6]):
            srv.generate(system * 2 + tail, max_tokens=4, timeout=300)
        reps = srv.summary(include_replicas=True)["replica_stats"]
        hits = sum(int(((r or {}).get("decode") or {}).get(
            "prefix_hits") or 0) for r in reps.values())
        saved = sum(int(((r or {}).get("decode") or {}).get(
            "prefix_tokens_saved") or 0) for r in reps.values())
        print(f"prefix sharing: hits={hits} prefill_tokens_saved={saved}")

        def session(i):
            o = srv.generate(prompts[i % len(prompts)],
                             max_tokens=args.max_tokens, timeout=300)
            return {"ttft_ms": o.get("ttft_ms"),
                    "token_ms": o.get("token_ms"),
                    "tokens": len(o.get("tokens") or ())}

        stats = serving.run_open_loop(
            session, rate_rps=args.rate, n_requests=args.sessions,
            seed=0, shed_exc=serving.Overloaded)
        print(f"open loop: offered {stats['offered_rps']} sessions/s, "
              f"completed {stats['completed']}/{stats['requests']} "
              f"(shed {stats['shed']}, errors {stats['errors']})")
        print(f"  ttft  p50 {stats.get('ttft_p50_ms')} ms   "
              f"p99 {stats.get('ttft_p99_ms')} ms")
        print(f"  token p50 {stats.get('tok_p50_ms')} ms   "
              f"p99 {stats.get('tok_p99_ms')} ms   "
              f"{stats.get('tokens_per_sec', 0)} tok/s")

        if args.http:
            import urllib.request

            from tensorflowonspark_tpu.serving import server as S

            httpd = S.serve_http(srv, port=0, block=False)
            try:
                host, port = httpd.server_address
                req = urllib.request.Request(
                    f"http://{host}:{port}/v1/generate",
                    data=json.dumps({"prompt": prompts[0],
                                     "max_tokens": 8}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=300) as r:
                    body = json.loads(r.read())
                print("HTTP generation:", body["tokens"])
            finally:
                httpd.shutdown()

        print("summary:", json.dumps(srv.summary()["decode"], default=str))


if __name__ == "__main__":
    main()
