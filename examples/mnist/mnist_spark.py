"""MNIST, InputMode.SPARK: engine partitions stream into distributed training.

Parity workload: reference examples/mnist/keras/mnist_spark.py — a driver
that starts a cluster, feeds partitioned records through DataFeed, trains
data-parallel, and lets the chief export.  The porting story holds: the
model/training code below is plain JAX; the cluster plumbing is ~10 lines.

Run (no Spark needed — built-in engine):
    python examples/mnist/mnist_spark.py --cluster_size 2 --steps 40

With pyspark installed, pass a SparkContext instead of LocalEngine.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    """Runs on every cluster node (the user's `map_fun`)."""
    import numpy as np
    import jax
    import optax

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.parallel import make_mesh, local_to_global
    from tensorflowonspark_tpu.utils import checkpoint as ckpt

    from tensorflowonspark_tpu import infeed
    from tensorflowonspark_tpu.utils import metrics as M

    env = ctx.jax_initialize()
    mesh = make_mesh({"data": -1})
    params = mnist.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(args["lr"], momentum=0.9)
    opt_state = opt.init(params)
    step_fn = jax.jit(mnist.make_train_step(opt))

    # double-buffered device staging + infeed-stall accounting: the
    # background thread collates/stages batch t+1 while t trains
    tm = M.TrainMetrics(window=10)
    feed = ctx.get_data_feed(train_mode=True, metrics=tm)
    per_proc = args["batch_size"] // max(env["num_processes"], 1)

    def collate(batch):
        images = np.stack([b[0] for b in batch]).astype(np.float32)
        labels = np.asarray([b[1] for b in batch], dtype=np.int32)
        return images, labels

    step = loss = acc = 0
    # synchronized: every process stops on the same step at end of feed
    # even with ragged tails (the reference's "90% of steps" trick,
    # mnist_spark.py:58-66, replaced by a principled global stop)
    for gi, gl in infeed.synchronized(infeed.device_feed(
        feed, per_proc, collate=collate,
        placement=lambda b: local_to_global(mesh, b),
    ), feed=feed):
        params, opt_state, loss, acc = step_fn(params, opt_state, gi, gl)
        tm.step(items=per_proc)
        step += 1
        if step % 10 == 0 and ctx.task_index == 0:
            print(f"step {step}: loss={float(loss):.4f} acc={float(acc):.3f} "
                  f"metrics={tm.report()}")

    if ckpt.is_chief(ctx):  # chief-only persistence (compat.py:10-17 parity)
        ckpt.save_checkpoint(os.path.join(args["model_dir"], "ckpt"), params, step)
        ckpt.export_model(os.path.join(args["model_dir"], "export"), params, ctx)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cluster_size", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--steps", type=int, default=40, help="steps of data to feed")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model_dir", default="/tmp/mnist_model")
    p.add_argument("--mnist_csv", default=None,
                   help="optional CSV dir (label,pix...); default synthetic")
    args = p.parse_args()

    import numpy as np

    from tensorflowonspark_tpu import cluster as TFCluster
    from tensorflowonspark_tpu.cluster import InputMode
    from tensorflowonspark_tpu.engine import LocalEngine
    from tensorflowonspark_tpu import configure_logging

    configure_logging()
    n = args.batch_size * args.steps
    rng = np.random.default_rng(0)
    if args.mnist_csv:
        rows = []
        for fname in sorted(os.listdir(args.mnist_csv)):
            with open(os.path.join(args.mnist_csv, fname)) as f:
                for line in f:
                    vals = np.fromstring(line, dtype=np.float32, sep=",")
                    rows.append((vals[1:].reshape(28, 28, 1) / 255.0, int(vals[0])))
        records = rows
    else:
        images = rng.random((n, 28, 28, 1), dtype=np.float32)
        q = np.stack(
            [images[:, :14, :14, 0].mean((1, 2)), images[:, :14, 14:, 0].mean((1, 2)),
             images[:, 14:, :14, 0].mean((1, 2)), images[:, 14:, 14:, 0].mean((1, 2))],
            axis=-1)
        labels = (np.argmax(q, axis=-1) * 2 + (q.sum(-1) > 2.0)).astype(np.int32)
        records = list(zip(list(images), list(labels)))

    engine = LocalEngine(
        args.cluster_size,
        env={"JAX_PLATFORMS": os.environ.get("TFOS_NODE_PLATFORM", "cpu"),
             "PYTHONPATH": "", "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    cluster = TFCluster.run(
        engine, main_fun,
        {"batch_size": args.batch_size, "lr": args.lr, "model_dir": args.model_dir},
        num_executors=args.cluster_size, input_mode=InputMode.SPARK,
        master_node="chief",
    )
    ds = engine.parallelize(records, args.cluster_size * 2)
    cluster.train(ds, num_epochs=args.epochs, feed_timeout=600)
    cluster.shutdown(grace_secs=5)
    engine.stop()
    print("export:", os.path.join(args.model_dir, "export"))


if __name__ == "__main__":
    main()
